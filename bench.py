#!/usr/bin/env python
"""Benchmark: DM x accel trials/sec/chip on tutorial.fil.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Baseline anchor (BASELINE.md): the reference's shipped 2014 run searched
59 DM trials x 3 accel trials in 0.3088 s of GPU searching time
=> 573.2 DM x accel trials/s. vs_baseline is our steady-state
trials/s/chip divided by that.

The search phase is timed steady-state (a first warm-up pass absorbs
XLA compilation, which is cached in-process).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

from peasoup_tpu.utils.cache import enable_compilation_cache

enable_compilation_cache()  # warm XLA compiles across bench processes

# resolve the peaks stripe-height verdict while the TPU is still free
# (subprocess-isolated probe; disk-cached — see ops/pallas/peaks.py)
import peasoup_tpu.ops.pallas.peaks  # noqa: E402,F401


def bench_fft(n: int = 1 << 23, iters: int = 50) -> int:
    """hcfft-equivalent micro-bench (reference src/hcfft.cpp:14-42):
    mean seconds per R2C+C2R round trip, N=2^23 when the backend
    supports it. Secondary mode, invoked explicitly with --fft.

    The first run is VALIDATED BY MATERIALISATION: on this backend a
    too-large FFT fails lazily — block_until_ready reports success and
    only the D2H transfer surfaces UNIMPLEMENTED — so without the
    np.asarray round trip the old code timed the enqueue of a
    computation that never executed (~0.02 ms/iter "results"). On
    failure the size halves until the round trip actually runs, and
    the achieved N is part of the record."""
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(0)
    while n >= (1 << 18):
        xn = rng.normal(size=n).astype(np.float32)
        x = jnp.asarray(xn)

        def roundtrip(v, _n=n):
            return jnp.fft.irfft(jnp.fft.rfft(v), n=_n)

        roundtrip = jax.jit(roundtrip)
        # retry the SAME size once before halving: the tunnel's
        # transient faults (worker restart, closed response body) must
        # not permanently degrade the recorded N
        for attempt in (1, 2):
            try:
                y0 = np.asarray(roundtrip(x))  # compile + EXECUTE + fetch
                if np.abs(y0 - xn).max() >= 1e-2:
                    raise RuntimeError("fft roundtrip is not the identity")
                n_ok = True
                break
            except RuntimeError:
                raise
            except Exception as exc:
                n_ok = False
                print(
                    f"fft roundtrip at N={n} attempt {attempt} failed "
                    f"({type(exc).__name__})", file=sys.stderr,
                )
                if attempt == 1:
                    time.sleep(10)
        if n_ok:
            break
        n //= 2
    else:
        print("no supported FFT size found", file=sys.stderr)
        return 1
    t0 = time.time()
    y = x
    for _ in range(iters):
        y = roundtrip(y)
    y.block_until_ready()
    per_iter = (time.time() - t0) / iters
    # materialise the final value UNCONDITIONALLY (not in an assert —
    # python -O must not strip it): surfaces any deferred error and
    # proves the timed chain really executed
    if not np.isfinite(np.asarray(y[:8])).all():
        raise RuntimeError("fft bench chain produced non-finite output")
    print(
        json.dumps(
            {
                "metric": "fft_r2c_c2r_roundtrip",
                "value": round(per_iter * 1e3, 3),
                "unit": f"ms/iter@2^{n.bit_length() - 1}",
                "vs_baseline": 0.0,  # reference harness recorded no number
            }
        )
    )
    return 0


def bench_recall() -> int:
    """Golden end-to-end recall vs the reference CUDA run (BASELINE.md's
    headline correctness metric): run tutorial.fil with the golden run's
    exact flags and match candidates against
    /root/reference/example_output/overview.xml.  vs_baseline is recall
    itself (1.0 = full parity with the CUDA candidate list)."""
    import tempfile

    from peasoup_tpu.cli.peasoup import main as peasoup_main
    from peasoup_tpu.tools.recall import match_golden

    fil_path = os.environ.get(
        "PEASOUP_BENCH_FIL", "/root/reference/example_data/tutorial.fil"
    )
    with tempfile.TemporaryDirectory() as outdir:
        rc = peasoup_main(
            [
                "-i", fil_path, "-o", outdir,
                "--dm_end", "250", "--acc_start", "-5", "--acc_end", "5",
                "--npdmp", "10",
            ]
        )
        if rc != 0:
            return rc
        rep = match_golden(os.path.join(outdir, "overview.xml"))
    print(rep.summary(), file=sys.stderr)
    print(
        json.dumps(
            {
                "metric": "golden_candidate_recall",
                "value": round(rep.recall, 4),
                "unit": "fraction of 10 golden candidates",
                "vs_baseline": round(rep.recall, 4),
            }
        )
    )
    return 0


SURVEY_FIL = os.environ.get("PEASOUP_SURVEY_FIL", "/tmp/peasoup_survey_r3.fil")
SURVEY_NCHANS = int(os.environ.get("PEASOUP_SURVEY_NCHANS", 1024))
SURVEY_NSAMPS = int(os.environ.get("PEASOUP_SURVEY_NSAMPS", (1 << 21) + 2048))
SURVEY_DM_END = float(os.environ.get("PEASOUP_SURVEY_DM_END", 100.0))


def _ensure_survey_fil(path: str) -> None:
    """Synthesize the survey-scale filterbank once: SURVEY_NCHANS chans
    x SURVEY_NSAMPS samples, 2-bit, with a dispersed P=50.03 ms pulsar
    at DM 60 buried in noise."""
    if os.path.exists(path):
        return
    from peasoup_tpu.io.sigproc import (
        Filterbank, SigprocHeader, write_filterbank,
    )
    from peasoup_tpu.plan.dm_plan import delay_table

    nchans, nsamps = SURVEY_NCHANS, SURVEY_NSAMPS
    tsamp, fch1 = 256e-6, 1500.0
    foff = -300.0 / nchans  # 300 MHz band regardless of channel count
    rng = np.random.default_rng(42)
    print(
        f"synthesizing survey filterbank {nsamps}x{nchans} -> {path}",
        file=sys.stderr,
    )
    delays = np.rint(
        np.float32(60.0) * np.abs(delay_table(fch1, foff, nchans, tsamp))
    ).astype(np.int64)
    P = 0.05003
    t = np.arange(nsamps, dtype=np.float64)
    pulse = ((t * tsamp / P) % 1.0) < 0.06
    # 2-bit noise ~ B(3, 0.5)-ish via sum of bits; pulse bumps by +1
    data = rng.integers(0, 3, size=(nsamps, nchans), dtype=np.uint8)
    for c in range(nchans):
        src = np.clip(t - delays[c], 0, nsamps - 1).astype(np.int64)
        data[:, c] += pulse[src]
    hdr = SigprocHeader(
        source_name="survey_synth", data_type=1, nchans=nchans, nbits=2,
        nifs=1, tsamp=tsamp, tstart=51000.0, fch1=fch1, foff=foff,
    )
    # atomic publish (see _ensure_big_fil): never leave a truncated
    # file a later run's exists() check would reuse
    tmp = path + ".tmp"
    write_filterbank(tmp, Filterbank(header=hdr, data=data))
    os.replace(tmp, path)


def bench_survey() -> int:
    """Survey-scale end-to-end (VERDICT r2 item 5): a SURVEY_NCHANS-chan
    x ~2^21-sample, few-hundred-DM search on the real chip exercising
    the production subband dedispersion, host-spilled trials (forced via
    a 1 GB HBM budget), and checkpoint save + resume. Emits the same
    one-JSON-line contract; vs_baseline is 0 (the reference records no
    survey-scale number — its 2014 artifact is tutorial-scale only)."""
    from peasoup_tpu.io import read_filterbank
    from peasoup_tpu.pipeline import PeasoupSearch, SearchConfig

    _ensure_survey_fil(SURVEY_FIL)
    fil = read_filterbank(SURVEY_FIL)
    import glob as _glob

    ckpt = SURVEY_FIL + ".ckpt.npz"
    for p in [ckpt] + _glob.glob(ckpt + ".dm*"):
        if os.path.exists(p):
            os.unlink(p)

    def cfg(**kw):
        return SearchConfig(
            dm_end=SURVEY_DM_END, acc_start=0.0, acc_end=0.0,
            nharmonics=4, npdmp=10, limit=100,
            subbands=32, subband_smear=1.0,
            hbm_bytes=1_000_000_000,  # forces the host-spill trials path
            checkpoint_file=ckpt, **kw,
        )

    search = PeasoupSearch(cfg())
    ndm = search.build_dm_plan(fil).ndm
    # Device anchor (VERDICT r4 item 2): trace the main run and split
    # device-busy seconds per phase by top-level jit name, so the
    # survey record stops encoding tunnel weather — the wall numbers
    # keep the old series (now measured WITH trace overhead; the trace
    # only collects device events, the dominant wall terms are still
    # upload + dispatch + compile)
    phase_dev: dict = {}
    survey_stages: dict = {}
    res = None
    t0 = time.time()
    try:
        import jax as _jax

        from peasoup_tpu.perf.roofline import stage_roofline
        from peasoup_tpu.tools.scope_trace import scope_trace

        with scope_trace() as tr:
            res = search.run(fil)
        phase_dev = tr.phase_seconds()
        phase_dev["total"] = tr.device_s
        # per-stage device-busy + roofline attribution from the SAME
        # trace (perf/roofline.py; fold FLOPs left null — the survey
        # roofline attributes the search phases)
        from peasoup_tpu.plan.fft_plan import choose_fft_size as _cfs

        survey_stages = stage_roofline(
            tr.stage_profile(),
            _search_stage_flops(
                ndm, fil.nchans, search.build_dm_plan(fil).out_nsamps,
                _cfs(fil.nsamps, 0), ndm, 4,
            ),
            str(_jax.local_devices()[0].device_kind),
        )
    except Exception as exc:  # tracing is best-effort
        print(f"survey device trace failed: {exc!r}", file=sys.stderr)
        if res is None:  # the SEARCH failed, not the trace parse:
            res = search.run(fil)  # rerun; a parse failure keeps res
        phase_dev = {}
    wall = time.time() - t0
    t_search = res.timers["searching"]
    t_dedisp = res.timers["dedispersion"]
    t_fold = res.timers.get("folding", 0.0)
    print(
        f"survey: {ndm} DM trials, dedisp {t_dedisp:.2f}s, search "
        f"{t_search:.2f}s, fold {t_fold:.2f}s (npdmp=10), wall "
        f"{wall:.2f}s (first run incl. compile)",
        file=sys.stderr,
    )
    if phase_dev:
        print(
            "survey device-busy (s): "
            + ", ".join(f"{k} {v:.3f}" for k, v in phase_dev.items()),
            file=sys.stderr,
        )
    # resume: a fresh driver restores every trial from the checkpoint
    t0 = time.time()
    res2 = PeasoupSearch(cfg()).run(fil)
    t_resume = res2.timers["searching"]
    t_fold_warm = res2.timers.get("folding", 0.0)
    print(
        f"survey resume: search {t_resume:.2f}s, fold {t_fold_warm:.2f}s "
        f"warm (restored from checkpoint; first search was "
        f"{t_search:.2f}s)",
        file=sys.stderr,
    )
    top = res.candidates[0]
    assert abs(1.0 / top.freq - 0.05003) / 0.05003 < 2e-3, 1.0 / top.freq
    # interbin quantization legitimately splits a smeared pulsar's DM
    # cluster (different DMs favour adjacent bins, outside freq_tol),
    # so the crowned candidate's DM can sit a cluster away — the
    # reference's distiller behaves identically
    assert abs(top.dm - 60.0) < 30.0, top.dm
    assert [
        (a.freq, a.snr, a.dm) for a in res.candidates
    ] == [(b.freq, b.snr, b.dm) for b in res2.candidates]
    value = ndm / (t_dedisp + t_search)
    print(
        json.dumps(
            {
                "metric": "survey_dm_trials_per_sec",
                "value": round(value, 2),
                "unit": (
                    f"DM trials/s @ {SURVEY_NCHANS}ch x {SURVEY_NSAMPS} "
                    "samples (subband+spill+checkpoint, dedisp+search)"
                ),
                "vs_baseline": 0.0,
                "detail": {
                    "ndm": ndm,
                    "dedisp_s": round(t_dedisp, 2),
                    "search_s": round(t_search, 2),
                    "fold_s": round(t_fold, 2),
                    "fold_warm_s": round(t_fold_warm, 2),
                    "wall_s": round(wall, 2),
                    "resume_search_s": round(t_resume, 2),
                    # device-anchored per-phase seconds (scope_trace
                    # classification; 'other' kept visible): the
                    # honest chip-work record — wall minus these is
                    # upload + dispatch + compile + tunnel
                    "dedisp_device_s": round(phase_dev.get("dedisp", 0.0), 3),
                    "search_device_s": round(phase_dev.get("search", 0.0), 3),
                    "fold_device_s": round(phase_dev.get("fold", 0.0), 3),
                    "other_device_s": round(phase_dev.get("other", 0.0), 3),
                    "total_device_s": round(phase_dev.get("total", 0.0), 3),
                    # at survey trace durations (20+ min) the profiler
                    # can drop per-op tf_op attribution, landing a
                    # phase's device time in 'other' — flag it so a
                    # zero phase under a large wall is never read as
                    # "no device work" (total_device_s stays honest).
                    # Complete = the trace exists AND every phase with
                    # substantial wall got SOME attributed device time.
                    "device_attrib_complete": bool(phase_dev) and all(
                        phase_dev.get(ph, 0.0) > 0.0 or wall_ph < 60.0
                        for ph, wall_ph in (
                            ("dedisp", t_dedisp),
                            ("search", t_search),
                            ("fold", t_fold),
                        )
                    ),
                    # per-stage device-busy + roofline attribution
                    # (perf/roofline.py taxonomy, shared with
                    # peasoup-perf bench's stage totals)
                    "stages": survey_stages,
                },
            }
        )
    )
    return 0


BIG_FIL = os.environ.get("PEASOUP_BIG_FIL", "/tmp/peasoup_big_r5.fil")


def _ensure_big_fil(path: str) -> None:
    """Synthesize the secondary pinned-grid filterbank once (BASELINE.md
    "Big grid, round 5"): 64 chans x 2^21+8192 samples, 2-bit, 64 us,
    with a P=31.4 ms pulsar at DM 10 — 16x the tutorial grid's series
    length, so the searching chain runs at a scale where the harness
    overhead of the 90 ms tutorial anchor no longer dominates.
    Small channel count keeps dedispersion/upload out of the way: this
    grid exists to measure the SEARCH chain."""
    if os.path.exists(path):
        return
    from peasoup_tpu.io.sigproc import (
        Filterbank, SigprocHeader, write_filterbank,
    )
    from peasoup_tpu.plan.dm_plan import delay_table

    nchans, nsamps = 64, (1 << 21) + 8192
    tsamp, fch1 = 64e-6, 1500.0
    foff = -300.0 / nchans
    rng = np.random.default_rng(7)
    print(f"synthesizing big-grid filterbank -> {path}", file=sys.stderr)
    delays = np.rint(
        np.float32(10.0) * np.abs(delay_table(fch1, foff, nchans, tsamp))
    ).astype(np.int64)
    P = 0.0314
    t = np.arange(nsamps, dtype=np.float64)
    pulse = ((t * tsamp / P) % 1.0) < 0.08
    data = rng.integers(0, 3, size=(nsamps, nchans), dtype=np.uint8)
    for c in range(nchans):
        src = np.clip(t - delays[c], 0, nsamps - 1).astype(np.int64)
        data[:, c] += pulse[src]
    hdr = SigprocHeader(
        source_name="big_grid_synth", data_type=1, nchans=nchans, nbits=2,
        nifs=1, tsamp=tsamp, tstart=51000.0, fch1=fch1, foff=foff,
    )
    # atomic publish: a mid-write failure must not leave a truncated
    # file for the retry (exists() would happily reuse it)
    tmp = path + ".tmp"
    write_filterbank(tmp, Filterbank(header=hdr, data=data))
    os.replace(tmp, path)


def _bench_big_grid(force_wall: bool) -> dict:
    """Secondary pinned grid (VERDICT r4 item 7): 2^21-sample series,
    54 DM x 43-accel dense grid, single chip, device-anchored, brute
    force (dedupe off) like the primary anchor. The tutorial grid at
    ~90 ms device is approaching harness-dominated; this grid gives
    future rounds headroom to differentiate while the r01-comparable
    grid stays unchanged. Fused-DFT is shape-gated OFF here (m = 2^20
    > the kernel's 2^17 VMEM gate) — the einsum + interbin-kernel
    chain is the measured path, which is exactly the production path
    at this scale."""
    from peasoup_tpu.io import read_filterbank
    from peasoup_tpu.pipeline import PeasoupSearch, SearchConfig

    _ensure_big_fil(BIG_FIL)
    fil = read_filterbank(BIG_FIL)
    search = PeasoupSearch(
        SearchConfig(
            dm_end=20.0, acc_start=-0.5, acc_end=0.5,
            acc_pulse_width=0.064, npdmp=0, limit=1000,
            dedupe_accel=False,
        )
    )
    search.run(fil)
    warm = search.run(fil)
    walls = sorted(search.run(fil).timers["searching"] for _ in range(3))
    if force_wall:
        dev = []
    else:
        dev = sorted(
            d
            for d in (
                _device_busy_seconds(lambda: search.run(fil))
                for _ in range(3)
            )
            if d > 0
        )
    device_s = _median(dev)
    top = warm.candidates[0]
    assert abs(1.0 / top.freq - 0.0314) / 0.0314 < 2e-3, 1.0 / top.freq
    n = warm.n_accel_trials
    return {
        "big_grid_trials": n,
        "big_grid_device_busy_s": round(device_s, 3),
        "big_grid_device_all_s": [round(d, 4) for d in dev],
        "big_grid_wall_median_s": round(_median(walls), 3),
        "big_grid_trials_per_sec_device": (
            round(n / device_s, 2) if device_s else 0.0
        ),
        "big_grid_trials_per_sec_min_wall": round(n / walls[0], 2),
    }


# the BENCH protocol and peasoup-perf share ONE measurement path
# (peasoup_tpu/perf/measure.py): median semantics, the median-of-k
# block_until_ready discipline, and the device-anchored trace parse —
# so the trajectory files and the CI ratchet can never drift apart
from peasoup_tpu.perf.measure import (  # noqa: E402
    device_busy_seconds as _device_busy_seconds,
    median as _median,
)


def _search_stage_flops(ndm, nchans, out_nsamps, size, n_accel, nharms):
    """Analytic per-stage FLOP estimates for one search run (the
    roofline numerator; device seconds and bytes are MEASURED from the
    trace). Conventions: one MAC = 2 FLOPs; the rfft counted at the
    familiar 2.5 N log2 N; harmonics as one add per level-bin; peaks
    as ~4 ops per bin per level (threshold, compare, select, count)."""
    import math as _math

    nbins = size // 2 + 1
    lg = _math.log2(max(2, size))
    return {
        "unpack": float(ndm and nchans * out_nsamps),  # shifts+masks
        "dedisperse": 2.0 * ndm * nchans * out_nsamps,
        "spectrum_chain": ndm * (2.5 * size * lg + 12.0 * nbins),
        "resample": 2.0 * n_accel * size + ndm * 2.5 * size * lg,
        "harmonics": float(nharms) * n_accel * nbins,
        "peaks": 4.0 * (nharms + 1) * n_accel * nbins,
    }


def _stage_record(run_fn, stage_flops) -> dict:
    """One traced run -> the BENCH ``stages`` section: per-stage
    device-busy seconds + measured bytes from the profiler trace,
    joined with analytic FLOPs into roofline fields
    (peasoup_tpu/perf/roofline.py). {} when tracing fails — absent
    attribution is visible, never faked."""
    try:
        import jax

        from peasoup_tpu.perf.roofline import stage_roofline
        from peasoup_tpu.tools.scope_trace import scope_trace

        with scope_trace() as tr:
            run_fn()
        if not tr.events:
            return {}
        kind = str(jax.local_devices()[0].device_kind)
        return stage_roofline(tr.stage_profile(), stage_flops, kind)
    except Exception as exc:  # tracing is best-effort
        print(f"stage roofline trace failed: {exc!r}", file=sys.stderr)
        return {}


def main() -> int:
    from peasoup_tpu.io import read_filterbank
    from peasoup_tpu.pipeline import PeasoupSearch, SearchConfig

    fil_path = os.environ.get(
        "PEASOUP_BENCH_FIL", "/root/reference/example_data/tutorial.fil"
    )
    fil = read_filterbank(fil_path)
    # FIXED dense-accel workload: 59 DM x ~44 accel trials (2832 padded)
    # over tutorial.fil.  acc_pulse_width=0.064 pins the accel grid that
    # rounds 1-2 unknowingly benched (their accel plan divided the pulse
    # width by 1e3; the plan now matches the golden binary's us
    # semantics, which would yield only 3 accels/DM — far too little
    # device work to amortise the tunnel's ~0.2 s of per-run syncs).
    # Keeping the historical grid keeps BENCH_r01/r02 comparable.
    # HEADLINE: identity-trial dedupe OFF so every accel trial is
    # physically dispatched, exactly like rounds 1-2 and the 2014 run —
    # the whole point of pinning this grid is comparability. The
    # production default (dedupe ON, bitwise-identical output, ~44x
    # less device work on this degenerate grid) is reported in the
    # dedupe_* fields below.
    grid = dict(
        dm_end=250.0, acc_start=-5.0, acc_end=5.0, acc_pulse_width=0.064,
        npdmp=0, limit=1000,
    )
    search = PeasoupSearch(SearchConfig(dedupe_accel=False, **grid))

    # Warm-up TWICE: the first run learns the adaptive compaction /
    # fetch sizes, which changes compiled shapes — the second run
    # compiles at the learned sizes, so the timed runs below are
    # compile-free (a single warm-up left a ~2 s XLA compile inside the
    # first timed run, profiled in r3). Telemetry around the warm-ups
    # splits compile cost out of the record: backend-compile count and
    # seconds, persistent-cache hits vs misses (a cache-served compile
    # is a disk deserialise, not XLA work — the trajectory should
    # distinguish compile-cache wins from kernel wins).
    from peasoup_tpu.obs.telemetry import (
        RunTelemetry,
        persistent_cache_counters,
    )

    tel = RunTelemetry()
    t0 = time.time()
    with tel.activate():
        search.run(fil)
        warm = search.run(fil)
    first_run_wall_s = time.time() - t0
    cache_hits, cache_misses = persistent_cache_counters(tel)
    compile_events = {
        k: v for k, v in tel.jit.items() if "backend_compile" in k
    }
    compile_count = int(sum(v[0] for v in compile_events.values()))
    compile_backend_s = float(sum(v[1] for v in compile_events.values()))

    # Steady-state timing: MEDIAN of 5 runs (the chip sits behind a
    # shared tunnel with +-20-30% wall-clock noise; r02's best-of-3
    # recorded a 1978 outlier against a measured ~2600 steady state).
    runs = [search.run(fil) for _ in range(5)]
    times = sorted(r.timers["searching"] for r in runs)
    searching = times[len(times) // 2]
    res = runs[0]
    print(f"searching times: {[round(t, 3) for t in times]}", file=sys.stderr)
    n_trials = res.n_accel_trials
    baseline = 59 * 3 / 0.3088  # 2014 golden run (BASELINE.md)

    # PRIMARY record: DEVICE-busy time of steady-state runs via
    # profiler traces — MEDIAN of 3 (VERDICT r4 item 6: one-sample
    # device rows are not statistically defensible; the spread is
    # recorded). The chip sits behind a shared tunnel whose sync
    # latency varies by the HOUR (r3 weather log: same code, wall
    # 0.98 -> 2.64 s over 8 h while device busy moved 0.7%), so wall
    # rates measure the tunnel, not the chip — BENCH_r01..r03 headline
    # values fell monotonically while the chip got faster. Per the
    # definition in BASELINE.md ("Official benchmark definition,
    # round 4"), `value` is the device-anchored rate, with min-wall
    # across the 5 timed runs as the fallback when tracing fails.
    # PEASOUP_BENCH_ANCHOR=wall forces the fallback path (used once to
    # archive a fallback-format record; trace overhead on device time
    # is nil — the profiler only collects device events).
    force_wall = os.environ.get("PEASOUP_BENCH_ANCHOR") == "wall"
    if force_wall:
        dev_samples = []
    else:
        dev_samples = sorted(
            d
            for d in (
                _device_busy_seconds(lambda: search.run(fil))
                for _ in range(3)
            )
            if d > 0
        )
    device_s = _median(dev_samples)

    # PRODUCTION configuration (first-class, BASELINE.md row): identity-
    # trial dedupe ON — the shipped default; bitwise-identical
    # candidates, only DISTINCT resamplings dispatched (this grid is one
    # identity class per DM, so ~44x less device work). Median of 5
    # device traces (VERDICT r4 item 6): the 21 ms device sample is
    # small, so the spread is part of the record.
    dsearch = PeasoupSearch(SearchConfig(**grid))
    dsearch.run(fil)
    dsearch.run(fil)
    dtimes = sorted(dsearch.run(fil).timers["searching"] for _ in range(3))
    dedupe_median = dtimes[1]
    if force_wall:
        ddev_samples = []
    else:
        ddev_samples = sorted(
            d
            for d in (
                _device_busy_seconds(lambda: dsearch.run(fil))
                for _ in range(5)
            )
            if d > 0
        )
    dedupe_device_s = _median(ddev_samples)

    # sanity: the search must still find the pulsar, else the number is void
    top = res.candidates[0]
    assert abs(1.0 / top.freq - 0.25) < 0.001 and top.snr > 80, (
        "benchmark run failed to recover the golden candidate"
    )

    # secondary pinned grid (2^21-sample series; BASELINE.md "Big
    # grid, round 5") — best-effort: a failure voids its fields, not
    # the primary record
    big: dict = {}
    if os.environ.get("PEASOUP_BENCH_BIG", "1") != "0":
        # one retry of its own: the tunnel's transient compile/IO
        # faults (observed: 'response body closed') would otherwise
        # silently drop the secondary record for the round
        for attempt in (1, 2):
            try:
                big = _bench_big_grid(force_wall)
                print(f"big grid: {big}", file=sys.stderr)
                break
            except Exception as exc:
                print(
                    f"big-grid bench attempt {attempt} failed: {exc!r}",
                    file=sys.stderr,
                )

    # dedispersion planner provenance (ISSUE 8): the auto-tuned plan
    # for this observation's shape bucket on THIS device, tuned into a
    # throwaway cache so the record carries real measured tuning time.
    # Best-effort: a failure voids these fields, not the record.
    plan_fields: dict = {}
    try:
        import tempfile

        from peasoup_tpu.perf.tuning import resolve_plan_for_filterbank

        t_tune = time.time()
        with tempfile.TemporaryDirectory() as td:
            dplan = resolve_plan_for_filterbank(
                fil, "search", SearchConfig(**grid),
                cache_path=os.path.join(td, "tuning_cache.json"),
            )
        plan_fields = {
            "dedisp_plan": dplan.summary(),
            "tuning_s": round(time.time() - t_tune, 3),
        }
        print(f"dedisp plan: {plan_fields}", file=sys.stderr)
    except Exception as exc:
        print(f"dedisp-plan tuning failed: {exc!r}", file=sys.stderr)

    # per-stage device-busy + roofline attribution (one extra traced
    # steady-state run; the same stage taxonomy as peasoup-perf bench,
    # perf/roofline.py — best-effort, {} when tracing fails)
    stages: dict = {}
    if not force_wall:
        from peasoup_tpu.plan.fft_plan import choose_fft_size

        dm_plan_b = search.build_dm_plan(fil)
        stages = _stage_record(
            lambda: search.run(fil),
            _search_stage_flops(
                dm_plan_b.ndm, fil.nchans, dm_plan_b.out_nsamps,
                choose_fft_size(fil.nsamps, 0), n_trials, 4,
            ),
        )

    # weather-proof primary (BASELINE.md "Official benchmark
    # definition, round 4"): the chip's brute-force rate by device-busy
    # time; min-wall fallback if the trace failed
    if device_s > 0:
        value = n_trials / device_s
        anchor = "device"
    else:
        value = n_trials / times[0]  # min of the 5 sorted walls
        anchor = "min_wall"
    wall_value = n_trials / searching

    print(
        json.dumps(
            {
                # metric RENAMED from r01-r03's dm_accel_trials_per_sec
                # _per_chip: the timing anchor moved from tunnel-wall to
                # device-busy (BASELINE.md "Official benchmark
                # definition, round 4"), so the series break is visible
                # in the core keys — suffixed by the ACTUAL anchor so a
                # min-wall fallback record can never pollute the
                # device-anchored series; wall_trials_per_sec continues
                # the old series
                "metric": f"dm_accel_trials_per_sec_per_chip_{anchor}",
                "value": round(value, 2),
                "unit": f"trials/s/chip ({anchor}-anchored)",
                "vs_baseline": round(value / baseline, 4),
                "value_anchor": anchor,
                "device_busy_s": round(device_s, 3),
                "device_busy_all_s": [round(d, 4) for d in dev_samples],
                "wall_median_s": round(searching, 3),
                "wall_all_s": [round(t, 3) for t in times],
                "wall_trials_per_sec": round(wall_value, 2),
                # compile/execute split (both warm-up runs): wall of
                # the warm-up phase vs the steady-state medians above,
                # backend-compile seconds by jax.monitoring, and the
                # persistent compilation cache's hit/miss tally (hits
                # deserialise from utils/cache.py's on-disk cache —
                # an AOT-warmed or second bench process shows ~all
                # hits and a collapsed warmup wall)
                "warmup_wall_s": round(first_run_wall_s, 3),
                "compile_programs": compile_count,
                "compile_backend_s": round(compile_backend_s, 3),
                "persistent_cache_hits": cache_hits,
                "persistent_cache_misses": cache_misses,
                "production_dedupe_wall_median_s": round(dedupe_median, 3),
                "production_dedupe_device_busy_s": round(dedupe_device_s, 3),
                "production_dedupe_device_all_s": [
                    round(d, 4) for d in ddev_samples
                ],
                "production_dedupe_trials_per_sec_effective": round(
                    n_trials / dedupe_median, 2
                ),
                "production_dedupe_trials_per_sec_device_effective": (
                    round(n_trials / dedupe_device_s, 2)
                    if dedupe_device_s
                    else 0.0
                ),
                "stages": stages,
                **plan_fields,
                **big,
            }
        )
    )
    return 0


def _with_retry(fn) -> int:
    """The axon tunnel's TPU worker can crash/restart mid-run (observed:
    UNAVAILABLE after a kernel fault; recovers in ~30 s). One retry in a
    fresh attempt keeps a transient runtime failure from voiding the
    round's benchmark record."""
    try:
        return fn()
    except Exception as exc:  # noqa: BLE001 - any runtime failure
        print(f"bench attempt failed ({type(exc).__name__}: {exc!s:.200}); "
              "retrying once in 30 s", file=sys.stderr)
        time.sleep(30)
        return fn()


if __name__ == "__main__":
    if "--fft" in sys.argv:
        sys.exit(_with_retry(bench_fft))
    if "--survey" in sys.argv:
        sys.exit(_with_retry(bench_survey))
    if "--recall" in sys.argv:
        sys.exit(_with_retry(bench_recall))
    sys.exit(_with_retry(main))
