#!/usr/bin/env bash
# Fast pre-commit gate: lint + the no-print contract + the quick test
# subset. The full tier-1 suite stays `pytest tests/ -m 'not slow'`.
set -euo pipefail
cd "$(dirname "$0")/.."

if command -v ruff >/dev/null 2>&1; then
    ruff check .
else
    echo "check.sh: ruff not installed; skipping lint" >&2
fi

# T201 equivalent that needs no tooling: library code never print()s
# (CLI and tools entry points own their stdout and are exempt)
if grep -rn "print(" peasoup_tpu --include='*.py' \
        | grep -vE "^peasoup_tpu/(cli|tools)/"; then
    echo "check.sh: print() found in library code — use the" \
         "peasoup_tpu logger (peasoup_tpu/obs/log.py)" >&2
    exit 1
fi

# fast subset: observability, aux units, output writers, scope-trace
JAX_PLATFORMS=cpu python -m pytest -q -p no:cacheprovider \
    tests/test_obs.py tests/test_scope_trace.py tests/test_aux.py \
    tests/test_output.py
echo "check.sh: OK"
