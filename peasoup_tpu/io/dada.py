"""PSRDADA header reader.

Reference: DadaHeader (include/data_types/header.hpp:52-161) — a
4096-byte text header of ``KEY value`` pairs at the start of a .dada
file, parsed by substring search. The reference class is unused by the
pipeline (the `accmap` tool that wanted it references a missing
data_types/dada.hpp); it is kept here for format parity so .dada
metadata can be inspected and converted.

Quirk preserved: the reference computes nsamples from the payload size
as filesize/nchan/nant/npol/2 (header.hpp:157) — the /2 assumes 8-bit
complex (NDIM=2) sampling regardless of NBIT/NDIM.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

import numpy as np

DADA_HDR_SIZE = 4096

# canonical ``KEY -> field`` mapping shared by the parser and the
# writer (order is the order keys are emitted by tofile/write_dada)
_DADA_KEYS: tuple[tuple[str, str], ...] = (
    ("HDR_VERSION", "header_version"),
    ("HDR_SIZE", "header_size"),
    ("BW", "bw"),
    ("FREQ", "freq"),
    ("NANT", "nant"),
    ("NCHAN", "nchan"),
    ("NDIM", "ndim"),
    ("NPOL", "npol"),
    ("NBIT", "nbit"),
    ("TSAMP", "tsamp"),
    ("OSAMP_RATIO", "osamp_ratio"),
    ("SOURCE", "source_name"),
    ("RA", "ra"),
    ("DEC", "dec"),
    ("PROC_FILE", "proc_file"),
    ("MODE", "mode"),
    ("OBSERVER", "observer"),
    ("PID", "pid"),
    ("OBS_OFFSET", "obs_offset"),
    ("TELESCOPE", "telescope"),
    ("INSTRUMENT", "instrument"),
    ("DSB", "dsb"),
    ("FILE_SIZE", "dada_filesize"),
    ("BYTES_PER_SECOND", "bytes_per_sec"),
    ("UTC_START", "utc_start"),
    ("ANT_ID", "ant_id"),
    ("FILE_NUMBER", "file_no"),
)


@dataclass
class DadaHeader:
    header_version: float = 0.0
    header_size: int = 0
    bw: float = 0.0
    freq: float = 0.0
    nant: int = 0
    nchan: int = 0
    ndim: int = 0
    npol: int = 0
    nbit: int = 0
    tsamp: float = 0.0
    osamp_ratio: float = 0.0
    source_name: str = ""
    ra: str = ""
    dec: str = ""
    proc_file: str = ""
    mode: str = ""
    observer: str = ""
    pid: str = ""
    obs_offset: int = 0
    telescope: str = ""
    instrument: str = ""
    dsb: int = 0
    filesize: int = 0
    dada_filesize: int = 0
    nsamples: int = 0
    bytes_per_sec: int = 0
    utc_start: str = ""
    ant_id: int = 0
    file_no: int = 0

    @classmethod
    def fromfile(cls, filename: str | os.PathLike) -> "DadaHeader":
        with open(filename, "rb") as f:
            raw = f.read(DADA_HDR_SIZE)
            f.seek(0, os.SEEK_END)
            payload = max(f.tell() - DADA_HDR_SIZE, 0)
        text = raw.decode("ascii", errors="replace")
        # PSRDADA headers allow '#'-prefixed comment lines; drop them
        # (and trailing NUL padding) before the substring search so a
        # commented-out key can never shadow the live one
        text = "\n".join(
            ln
            for ln in text.replace("\x00", "").splitlines()
            if not ln.lstrip().startswith("#")
        )

        def value(key: str) -> str:
            # substring search like the reference's get_value
            # (header.hpp:65-76): first occurrence, next whitespace token
            pos = text.find(key + " ")
            if pos < 0:
                return ""
            rest = text[pos + len(key) + 1 :]
            toks = rest.split()
            return toks[0] if toks else ""

        def fnum(key: str) -> float:
            v = value(key)
            try:
                return float(v)
            except ValueError:
                return 0.0

        def inum(key: str) -> int:
            v = value(key)
            try:
                return int(float(v))
            except ValueError:
                return 0

        h = cls(
            header_version=fnum("HDR_VERSION"),
            header_size=inum("HDR_SIZE"),
            bw=float(inum("BW")),  # reference uses atoi for BW (:132)
            freq=fnum("FREQ"),
            nant=inum("NANT"),
            nchan=inum("NCHAN"),
            ndim=inum("NDIM"),
            npol=inum("NPOL"),
            nbit=inum("NBIT"),
            tsamp=fnum("TSAMP"),
            osamp_ratio=fnum("OSAMP_RATIO"),
            source_name=value("SOURCE"),
            ra=value("RA"),
            dec=value("DEC"),
            proc_file=value("PROC_FILE"),
            mode=value("MODE"),
            observer=value("OBSERVER"),
            pid=value("PID"),
            obs_offset=inum("OBS_OFFSET"),
            telescope=value("TELESCOPE"),
            instrument=value("INSTRUMENT"),
            dsb=inum("DSB"),
            filesize=payload,
            dada_filesize=inum("FILE_SIZE"),
            bytes_per_sec=inum("BYTES_PER_SECOND"),
            utc_start=value("UTC_START"),
            ant_id=inum("ANT_ID"),
            file_no=inum("FILE_NUMBER"),
        )
        denom = max(h.nchan, 1) * max(h.nant, 1) * max(h.npol, 1) * 2
        h.nsamples = payload // denom
        return h

    def header_text(self) -> str:
        """The ``KEY value`` header block (no padding): every mapped
        field with a non-default value, in canonical key order.
        HDR_SIZE is always emitted (readers use it to find the
        payload)."""
        lines = []
        for key, field_name in _DADA_KEYS:
            v = getattr(self, field_name)
            if key == "HDR_SIZE":
                v = v or DADA_HDR_SIZE
            if v == 0 or v == 0.0 or v == "":
                if key != "HDR_SIZE":
                    continue
            if isinstance(v, float):
                v = f"{v:.12g}"
            lines.append(f"{key} {v}")
        return "\n".join(lines) + "\n"

    def tofile(
        self,
        filename: str | os.PathLike,
        payload: "np.ndarray | bytes | None" = None,
    ) -> None:
        """Write a .dada file: the header text NUL-padded to
        DADA_HDR_SIZE bytes, then the raw payload. Atomic
        (tmp + os.replace) so a tailing stream reader never sees a
        torn segment appear."""
        text = self.header_text().encode("ascii")
        if len(text) > DADA_HDR_SIZE:
            raise ValueError(
                f"header text ({len(text)} bytes) exceeds "
                f"DADA_HDR_SIZE={DADA_HDR_SIZE}"
            )
        body = b"" if payload is None else (
            payload if isinstance(payload, bytes)
            else np.ascontiguousarray(payload, dtype=np.uint8).tobytes()
        )
        tmp = os.fspath(filename) + ".tmp"
        with open(tmp, "wb") as f:
            f.write(text.ljust(DADA_HDR_SIZE, b"\x00"))
            f.write(body)
        os.replace(tmp, os.fspath(filename))


def write_dada(
    filename: str | os.PathLike,
    payload: "np.ndarray | bytes",
    **fields,
) -> DadaHeader:
    """Synthesise a valid .dada stream segment from header ``fields``
    (DadaHeader field names) + payload samples — the helper the replay
    source and the tests use to build PSRDADA-style streams."""
    h = DadaHeader(**fields)
    h.tofile(filename, payload)
    return h
