"""PSRDADA header reader.

Reference: DadaHeader (include/data_types/header.hpp:52-161) — a
4096-byte text header of ``KEY value`` pairs at the start of a .dada
file, parsed by substring search. The reference class is unused by the
pipeline (the `accmap` tool that wanted it references a missing
data_types/dada.hpp); it is kept here for format parity so .dada
metadata can be inspected and converted.

Quirk preserved: the reference computes nsamples from the payload size
as filesize/nchan/nant/npol/2 (header.hpp:157) — the /2 assumes 8-bit
complex (NDIM=2) sampling regardless of NBIT/NDIM.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

DADA_HDR_SIZE = 4096


@dataclass
class DadaHeader:
    header_version: float = 0.0
    header_size: int = 0
    bw: float = 0.0
    freq: float = 0.0
    nant: int = 0
    nchan: int = 0
    ndim: int = 0
    npol: int = 0
    nbit: int = 0
    tsamp: float = 0.0
    osamp_ratio: float = 0.0
    source_name: str = ""
    ra: str = ""
    dec: str = ""
    proc_file: str = ""
    mode: str = ""
    observer: str = ""
    pid: str = ""
    obs_offset: int = 0
    telescope: str = ""
    instrument: str = ""
    dsb: int = 0
    filesize: int = 0
    dada_filesize: int = 0
    nsamples: int = 0
    bytes_per_sec: int = 0
    utc_start: str = ""
    ant_id: int = 0
    file_no: int = 0

    @classmethod
    def fromfile(cls, filename: str | os.PathLike) -> "DadaHeader":
        with open(filename, "rb") as f:
            raw = f.read(DADA_HDR_SIZE)
            f.seek(0, os.SEEK_END)
            payload = max(f.tell() - DADA_HDR_SIZE, 0)
        text = raw.decode("ascii", errors="replace")

        def value(key: str) -> str:
            # substring search like the reference's get_value
            # (header.hpp:65-76): first occurrence, next whitespace token
            pos = text.find(key + " ")
            if pos < 0:
                return ""
            rest = text[pos + len(key) + 1 :]
            toks = rest.split()
            return toks[0] if toks else ""

        def fnum(key: str) -> float:
            v = value(key)
            try:
                return float(v)
            except ValueError:
                return 0.0

        def inum(key: str) -> int:
            v = value(key)
            try:
                return int(float(v))
            except ValueError:
                return 0

        h = cls(
            header_version=fnum("HDR_VERSION"),
            header_size=inum("HDR_SIZE"),
            bw=float(inum("BW")),  # reference uses atoi for BW (:132)
            freq=fnum("FREQ"),
            nant=inum("NANT"),
            nchan=inum("NCHAN"),
            ndim=inum("NDIM"),
            npol=inum("NPOL"),
            nbit=inum("NBIT"),
            tsamp=fnum("TSAMP"),
            osamp_ratio=fnum("OSAMP_RATIO"),
            source_name=value("SOURCE"),
            ra=value("RA"),
            dec=value("DEC"),
            proc_file=value("PROC_FILE"),
            mode=value("MODE"),
            observer=value("OBSERVER"),
            pid=value("PID"),
            obs_offset=inum("OBS_OFFSET"),
            telescope=value("TELESCOPE"),
            instrument=value("INSTRUMENT"),
            dsb=inum("DSB"),
            filesize=payload,
            dada_filesize=inum("FILE_SIZE"),
            bytes_per_sec=inum("BYTES_PER_SECOND"),
            utc_start=value("UTC_START"),
            ant_id=inum("ANT_ID"),
            file_no=inum("FILE_NUMBER"),
        )
        denom = max(h.nchan, 1) * max(h.nant, 1) * max(h.npol, 1) * 2
        h.nsamples = payload // denom
        return h
