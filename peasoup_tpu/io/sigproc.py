"""SIGPROC filterbank / time-series I/O.

Implements the keyword-tagged binary header format used by sigproc and
the reference pipeline (reference: include/data_types/header.hpp:339-403
for reading, header.hpp:222-308 for writing) plus bit unpacking of
1/2/4/8-bit filterbank data (done inside libdedisp in the reference).

All file I/O is host-side numpy; arrays are handed to JAX later.
"""

from __future__ import annotations

import io as _io
import os
import struct
from dataclasses import dataclass, field, asdict
from typing import BinaryIO, Optional

import numpy as np

# Header keys -> struct format. Mirrors the keyword set understood by the
# reference reader (header.hpp:351-391).
_INT_KEYS = {
    "nchans", "telescope_id", "machine_id", "data_type", "ibeam",
    "nbeams", "nbits", "barycentric", "pulsarcentric", "nbins",
    "nsamples", "nifs", "npuls",
}
_DOUBLE_KEYS = {
    "az_start", "za_start", "src_raj", "src_dej", "tstart", "tsamp",
    "period", "fch1", "foff", "refdm",
}
_STRING_KEYS = {"source_name", "rawdatafile"}
_CHAR_KEYS = {"signed"}


@dataclass
class SigprocHeader:
    """Sigproc header values (reference: header.hpp:171-212)."""

    source_name: str = ""
    rawdatafile: str = ""
    az_start: float = 0.0
    za_start: float = 0.0
    src_raj: float = 0.0
    src_dej: float = 0.0
    tstart: float = 0.0
    tsamp: float = 0.0
    period: float = 0.0
    fch1: float = 0.0
    foff: float = 0.0
    nchans: int = 0
    telescope_id: int = 0
    machine_id: int = 0
    data_type: int = 0
    ibeam: int = 0
    nbeams: int = 0
    nbits: int = 0
    barycentric: int = 0
    pulsarcentric: int = 0
    nbins: int = 0
    nsamples: int = 0
    nifs: int = 0
    npuls: int = 0
    refdm: float = 0.0
    signed_data: int = 0
    size: int = 0  # header size in bytes (set on read)

    @property
    def cfreq(self) -> float:
        """Centre frequency (reference: filterbank.hpp:189-195).

        The reference treats fch1 as the band edge and always moves
        nchans/2 channels toward the band centre (the foff>0 branch
        subtracts, keeping the result below fch1 for ascending bands —
        preserved verbatim for trial-grid parity).
        """
        if self.foff < 0:
            return self.fch1 + self.foff * self.nchans / 2
        return self.fch1 - self.foff * self.nchans / 2

    @property
    def bandwidth(self) -> float:
        """Total (absolute) bandwidth in MHz."""
        return abs(self.foff) * self.nchans

    @property
    def tobs(self) -> float:
        return self.nsamples * self.tsamp

    def to_dict(self) -> dict:
        return asdict(self)


def _read_string(stream: BinaryIO) -> Optional[str]:
    raw = stream.read(4)
    if len(raw) < 4:
        return None
    (length,) = struct.unpack("<i", raw)
    if length <= 0 or length >= 80:
        return None
    return stream.read(length).decode("latin-1")


def read_sigproc_header(stream: BinaryIO) -> SigprocHeader:
    """Read a sigproc header from an open binary stream.

    Computes ``nsamples`` from the file size when the keyword is absent,
    like the reference (header.hpp:394-401).
    """
    hdr = SigprocHeader()
    start = _read_string(stream)
    if start != "HEADER_START":
        raise ValueError("not a sigproc file: missing HEADER_START")
    while True:
        key = _read_string(stream)
        if key is None:
            raise ValueError("unterminated sigproc header")
        if key == "HEADER_END":
            break
        if key in _STRING_KEYS:
            value = _read_string(stream)
            setattr(hdr, key, value or "")
        elif key in _INT_KEYS:
            (value,) = struct.unpack("<i", stream.read(4))
            setattr(hdr, key, value)
        elif key in _DOUBLE_KEYS:
            (value,) = struct.unpack("<d", stream.read(8))
            setattr(hdr, key, value)
        elif key in _CHAR_KEYS:
            (value,) = struct.unpack("<B", stream.read(1))
            hdr.signed_data = value
        else:
            # Unknown keyword: warn and continue, like the reference
            # (header.hpp:390-391). We cannot skip its value (length is
            # keyword-dependent), so the next string read resynchronises
            # or fails; warn either way.
            import warnings

            warnings.warn(f"read_sigproc_header: unknown parameter {key!r}")
    hdr.size = stream.tell()
    if hdr.nsamples == 0 and hdr.nchans > 0 and hdr.nbits > 0:
        pos = stream.tell()
        stream.seek(0, _io.SEEK_END)
        total = stream.tell()
        hdr.nsamples = (total - hdr.size) // hdr.nchans * 8 // hdr.nbits
        stream.seek(pos, _io.SEEK_SET)
    return hdr


def _write_string(stream: BinaryIO, s: str) -> None:
    b = s.encode("latin-1")
    stream.write(struct.pack("<i", len(b)))
    stream.write(b)


def write_sigproc_header(stream: BinaryIO, hdr: SigprocHeader) -> None:
    """Write a sigproc header (reference: header.hpp:222-308)."""
    _write_string(stream, "HEADER_START")
    if hdr.source_name:
        _write_string(stream, "source_name")
        _write_string(stream, hdr.source_name)
    if hdr.rawdatafile:
        _write_string(stream, "rawdatafile")
        _write_string(stream, hdr.rawdatafile)
    for key in sorted(_DOUBLE_KEYS):
        _write_string(stream, key)
        stream.write(struct.pack("<d", getattr(hdr, key)))
    for key in sorted(_INT_KEYS):
        if key == "nsamples":
            continue  # recomputed from file size on read, like sigproc
        _write_string(stream, key)
        stream.write(struct.pack("<i", getattr(hdr, key)))
    _write_string(stream, "signed")
    stream.write(struct.pack("<B", hdr.signed_data))
    _write_string(stream, "HEADER_END")


# ---------------------------------------------------------------------------
# Bit packing/unpacking.
#
# Sigproc packs sub-byte samples LSB-first within each byte, channel index
# running fastest. The reference delegates unpacking to libdedisp's
# sub-word extraction; we unpack to u8 on the host once and keep the
# (nsamps, nchans) array.
# ---------------------------------------------------------------------------

def unpack_bits(raw: np.ndarray, nbits: int) -> np.ndarray:
    """Unpack a u8 byte array into individual samples (LSB-first).

    Uses the native C++ runtime when available (peasoup_tpu.native);
    numpy fallback below is the behavioural oracle.
    """
    raw = np.ascontiguousarray(raw, dtype=np.uint8)
    if nbits in (1, 2, 4):
        from .. import native

        out = native.unpack_bits(raw, nbits)
        if out is not None:
            return out
    if nbits == 8:
        return raw
    if nbits == 4:
        out = np.empty(raw.size * 2, dtype=np.uint8)
        out[0::2] = raw & 0x0F
        out[1::2] = raw >> 4
        return out
    if nbits == 2:
        out = np.empty(raw.size * 4, dtype=np.uint8)
        for k in range(4):
            out[k::4] = (raw >> (2 * k)) & 0x03
        return out
    if nbits == 1:
        out = np.empty(raw.size * 8, dtype=np.uint8)
        for k in range(8):
            out[k::8] = (raw >> k) & 0x01
        return out
    raise ValueError(f"unsupported nbits: {nbits}")


def pack_bits(samples: np.ndarray, nbits: int) -> np.ndarray:
    """Inverse of :func:`unpack_bits` (used for writing test fixtures)."""
    samples = np.ascontiguousarray(samples, dtype=np.uint8).ravel()
    if nbits == 8:
        return samples
    per_byte = 8 // nbits
    if samples.size % per_byte:
        raise ValueError("sample count not a multiple of samples-per-byte")
    out = np.zeros(samples.size // per_byte, dtype=np.uint8)
    mask = (1 << nbits) - 1
    for k in range(per_byte):
        out |= (samples[k::per_byte] & mask) << (nbits * k)
    return out


class Filterbank:
    """A filterbank in host RAM: header + samples.

    Like the reference (filterbank.hpp:207-250, whose dedisp call
    consumes the PACKED bytes and unpacks on the GPU), the packed
    ``raw`` bytes are the primary storage when the file had sub-byte
    samples: the dedispersion engine uploads them as-is and unpacks on
    device — a 4x (2-bit) smaller host->device transfer. ``data``
    unpacks lazily for host-side consumers.
    """

    header: SigprocHeader
    _data: np.ndarray | None = None  # (nsamps, nchans) uint8, lazy
    raw: np.ndarray | None = None  # packed file bytes (None if 8-bit)

    def __init__(self, header, data=None, raw=None):
        self.header = header
        self._data = data
        self.raw = raw
        if data is None and raw is None:
            raise ValueError("Filterbank needs data or raw")

    @property
    def data(self) -> np.ndarray:
        if self._data is None:
            self._data = unpack_bits(self.raw, self.header.nbits).reshape(
                self.header.nsamples, self.header.nchans
            )
        return self._data

    @property
    def nsamps(self) -> int:
        return self.header.nsamples if self._data is None else self._data.shape[0]

    @property
    def nchans(self) -> int:
        return self.header.nchans

    @property
    def tsamp(self) -> float:
        return self.header.tsamp

    @property
    def cfreq(self) -> float:
        return self.header.cfreq

    @property
    def foff(self) -> float:
        return self.header.foff

    @property
    def fch1(self) -> float:
        return self.header.fch1

    @property
    def nbits(self) -> int:
        return self.header.nbits


def _read_filterbank_once(path: str | os.PathLike) -> Filterbank:
    from ..resilience import TransientIOError, faults

    faults.fire("fil.read", context=str(path))
    with open(path, "rb") as f:
        hdr = read_sigproc_header(f)
        nbytes = hdr.nsamples * hdr.nbits * hdr.nchans // 8
        f.seek(hdr.size, _io.SEEK_SET)
        raw = np.frombuffer(f.read(nbytes), dtype=np.uint8)
    if raw.size < nbytes:
        # short read: a recorder still appending, an NFS cache burp, or
        # a torn copy — transient from the retry policy's point of view
        # (a truly truncated file exhausts the budget and fails the job
        # into the normal retry/quarantine path)
        raise TransientIOError(
            None,
            f"{path}: short read ({raw.size}/{nbytes} payload bytes)",
        )
    if hdr.nbits == 8:
        return Filterbank(
            header=hdr, data=raw.reshape(hdr.nsamples, hdr.nchans)
        )
    return Filterbank(header=hdr, raw=raw.copy())


def read_filterbank(path: str | os.PathLike) -> Filterbank:
    """Read a sigproc filterbank file fully into host RAM.

    Transient failures (EIO/EAGAIN, short reads, injected ``fil.read``
    faults) retry under the shared bounded-backoff policy
    (resilience/policy.py); malformed headers and other fatal errors
    raise immediately."""
    from ..resilience import IO_RETRY

    return IO_RETRY.call(
        _read_filterbank_once, path, site="fil.read", context=str(path)
    )


def write_filterbank(path: str | os.PathLike, fil: Filterbank) -> None:
    with open(path, "wb") as f:
        write_sigproc_header(f, fil.header)
        f.write(pack_bits(fil.data.ravel(), fil.header.nbits).tobytes())


def read_timeseries(path: str | os.PathLike) -> tuple[SigprocHeader, np.ndarray]:
    """Read a sigproc .tim file: header + float32 samples
    (reference: timeseries.hpp:137-160)."""
    with open(path, "rb") as f:
        hdr = read_sigproc_header(f)
        f.seek(hdr.size, _io.SEEK_SET)
        data = np.frombuffer(f.read(), dtype=np.float32)
    return hdr, data
