from .sigproc import (
    SigprocHeader,
    read_sigproc_header,
    write_sigproc_header,
    Filterbank,
    read_filterbank,
    read_timeseries,
    write_filterbank,
    unpack_bits,
    pack_bits,
)
from .masks import read_killfile, read_zapfile
