"""Streaming block sources: fixed-shape ingest for the real-time search.

The streaming driver (peasoup_tpu/stream/) consumes an endless
filterbank stream as a sequence of FIXED-SIZE :class:`StreamBlock`\\ s
— every block has the same (block_samples, nchans) shape, so every
downstream device program compiles once and is reused for the life of
the stream (the zero-steady-state-recompile contract). Three sources
implement the same iterator protocol:

* :class:`ReplaySource` — replays a recorded, fully-read filterbank at
  a configurable real-time factor (``rate=2`` releases data twice as
  fast as the observation's sampling clock; ``rate=0`` releases as
  fast as the consumer drains). The deterministic test/benchmark
  source, and the CLI's ``--replay`` mode.
* :class:`FileTailSource` — tails a GROWING sigproc filterbank on
  disk (a recorder process appends payload while we read). End of
  stream is signalled by a ``<path>.complete`` marker file or by the
  file going idle for ``idle_timeout_s``.
* :class:`DadaStreamSource` — PSRDADA-style ring-buffer reader built
  on :mod:`peasoup_tpu.io.dada`: consumes the numbered ``*.dada``
  segment files a PSRDADA file writer dumps (each a 4096-byte
  ``KEY value`` header + payload), in ``FILE_NUMBER`` order, tailing
  the directory for new segments until an ``obs.complete`` marker or
  idle timeout. TSAMP follows the PSRDADA convention (microseconds);
  the band is reconstructed from FREQ (centre) + BW as a
  descending-frequency filterbank.

All sources zero-pad the final partial block to the fixed shape and
mark it with ``nvalid < block_samples`` + ``final=True``; the driver
masks the padding out of the search.
"""

from __future__ import annotations

import glob
import os
import time
from dataclasses import dataclass, field

import numpy as np

from ..obs import get_logger
from ..resilience import IO_RETRY, faults, is_transient
from .dada import DADA_HDR_SIZE, DadaHeader
from .sigproc import read_sigproc_header, unpack_bits

log = get_logger("io.stream_source")


@dataclass(frozen=True)
class StreamFormat:
    """The per-stream metadata a DM plan needs (one source = one
    contiguous band/sampling configuration)."""

    nchans: int
    nbits: int
    tsamp: float  # seconds
    fch1: float  # MHz, first channel centre
    foff: float  # MHz, channel step (negative = descending band)
    source_name: str = ""
    tstart: float = 0.0  # MJD where known


@dataclass
class StreamBlock:
    """One fixed-shape slab of the stream."""

    seq: int
    start_sample: int  # absolute sample index of row 0
    data: np.ndarray  # (block_samples, nchans) uint8, zero-padded tail
    nvalid: int  # leading valid rows (== block_samples mid-stream)
    t_arrival_s: float = field(
        default_factory=time.perf_counter
    )  # host receipt time (perf_counter clock)
    final: bool = False  # no further blocks will follow


class StreamSource:
    """Iterator protocol shared by every source: ``format`` metadata
    plus a ``blocks()`` generator of :class:`StreamBlock`."""

    format: StreamFormat
    block_samples: int

    def blocks(self):
        raise NotImplementedError

    def close(self) -> None:
        pass


def _blocks_from_array(
    data: np.ndarray, block_samples: int, start_seq: int = 0
):
    """Chop an (nsamps, nchans) array into fixed StreamBlocks (the
    final partial block zero-padded + flagged)."""
    nsamps = data.shape[0]
    nblocks = max(1, -(-nsamps // block_samples))
    for k in range(nblocks):
        lo = k * block_samples
        chunk = data[lo : lo + block_samples]
        nvalid = chunk.shape[0]
        if nvalid < block_samples:
            chunk = np.concatenate(
                [
                    chunk,
                    np.zeros(
                        (block_samples - nvalid, data.shape[1]),
                        dtype=data.dtype,
                    ),
                ]
            )
        yield StreamBlock(
            seq=start_seq + k,
            start_sample=lo,
            data=np.ascontiguousarray(chunk, dtype=np.uint8),
            nvalid=nvalid,
            final=(k == nblocks - 1),
        )


class ReplaySource(StreamSource):
    """Replay a recorded filterbank at ``rate`` x real time.

    ``rate > 0`` paces block k's release to
    ``t0 + (k+1) * block_samples * tsamp / rate`` — the wall-clock a
    live recorder running ``rate`` times faster than the observation
    would deliver it; ``rate = 0`` releases blocks as fast as the
    consumer drains them (bounded-queue backpressure still applies).
    """

    def __init__(self, fil, block_samples: int, rate: float = 0.0):
        self.fil = fil
        self.block_samples = int(block_samples)
        self.rate = float(rate)
        h = fil.header
        self.format = StreamFormat(
            nchans=fil.nchans, nbits=fil.nbits, tsamp=fil.tsamp,
            fch1=fil.fch1, foff=fil.foff,
            source_name=h.source_name, tstart=h.tstart,
        )

    def blocks(self):
        t0 = time.perf_counter()
        data = self.fil.data  # unpacks sub-byte payloads once
        for blk in _blocks_from_array(data, self.block_samples):
            # fault seam: a replayed recording is RAM-resident, so a
            # "flaky read" here costs nothing to redo — the retry
            # policy absorbs the injection and the stream continues
            # (the chaos soak's transient-read drill for streaming)
            IO_RETRY.call(
                faults.fire, "fil.read", f"replay:seq{blk.seq}",
                site="fil.read", context=f"replay:seq{blk.seq}",
            )
            if self.rate > 0:
                release = t0 + (
                    (blk.seq + 1) * self.block_samples * self.fil.tsamp
                ) / self.rate
                delay = release - time.perf_counter()
                if delay > 0:
                    time.sleep(delay)
            blk.t_arrival_s = time.perf_counter()
            yield blk


class FileTailSource(StreamSource):
    """Tail a growing sigproc filterbank file.

    The header must be complete on disk before ``blocks()`` yields
    anything (we poll for it); payload bytes are then consumed as they
    are appended. The stream ends when ``<path>.complete`` exists and
    every remaining byte has been read, or when the file stops growing
    for ``idle_timeout_s`` seconds.
    """

    def __init__(
        self,
        path: str,
        block_samples: int,
        poll_s: float = 0.05,
        idle_timeout_s: float = 10.0,
        complete_marker: str | None = None,
    ):
        self.path = path
        self.block_samples = int(block_samples)
        self.poll_s = float(poll_s)
        self.idle_timeout_s = float(idle_timeout_s)
        self.complete_marker = complete_marker or (path + ".complete")
        self._hdr = self._wait_for_header()
        h = self._hdr
        self.format = StreamFormat(
            nchans=h.nchans, nbits=h.nbits, tsamp=h.tsamp,
            fch1=h.fch1, foff=h.foff,
            source_name=h.source_name, tstart=h.tstart,
        )

    def _wait_for_header(self):
        deadline = time.perf_counter() + self.idle_timeout_s
        while True:
            try:
                with open(self.path, "rb") as f:
                    return read_sigproc_header(f)
            except Exception:  # truncated header mid-write, or absent
                if time.perf_counter() > deadline:
                    raise TimeoutError(
                        f"no complete sigproc header at {self.path} "
                        f"after {self.idle_timeout_s}s"
                    )
                time.sleep(self.poll_s)

    def _ended(self) -> bool:
        return os.path.exists(self.complete_marker)

    def blocks(self):
        h = self._hdr
        row_bits = h.nchans * h.nbits
        # consume whole bit-packing groups so unpack_bits sees complete
        # bytes: with sub-byte samples a row is still whole bytes when
        # nchans*nbits % 8 == 0 (every real filterbank we read)
        row_bytes = row_bits // 8
        if row_bits % 8:
            raise ValueError(
                f"cannot tail {self.path}: nchans*nbits={row_bits} is "
                "not byte-aligned"
            )
        blk_bytes = row_bytes * self.block_samples
        offset = h.size
        seq = 0
        start = 0
        last_growth = time.perf_counter()
        pending = b""
        while True:
            try:
                faults.fire(
                    "fil.read", context=f"tail:{self.path}@{offset}"
                )
                size = os.path.getsize(self.path)
                avail = size - offset
                if avail > 0:
                    take = min(avail, 4 * blk_bytes)
                    with open(self.path, "rb") as f:
                        f.seek(offset)
                        pending += f.read(take)
                    offset += take
                    last_growth = time.perf_counter()
            except OSError as exc:
                # a tailed file can vanish briefly (recorder rotating /
                # re-linking) or throw EIO on a flaky mount; both are
                # transient AT THIS SEAM — keep polling, bounded by the
                # idle timeout (last_growth stops advancing). Anything
                # else is a real error.
                if not (
                    is_transient(exc) or isinstance(exc, FileNotFoundError)
                ):
                    raise
                log.warning(
                    "transient tail-read failure on %s (%s: %.200s); "
                    "retrying", self.path, type(exc).__name__, exc,
                )
                time.sleep(self.poll_s)
            if self._ended():
                # re-stat: the final append may have landed between our
                # read and the completion marker (stat failure defers
                # the decision to the next poll)
                try:
                    ended = offset >= os.path.getsize(self.path)
                except OSError:
                    ended = False
            else:
                ended = False
            idle = (
                time.perf_counter() - last_growth > self.idle_timeout_s
            )
            while len(pending) >= blk_bytes:
                raw = np.frombuffer(pending[:blk_bytes], dtype=np.uint8)
                pending = pending[blk_bytes:]
                data = unpack_bits(raw, h.nbits).reshape(
                    self.block_samples, h.nchans
                )
                more = len(pending) >= blk_bytes or not (ended or idle)
                yield StreamBlock(
                    seq=seq, start_sample=start, data=data,
                    nvalid=self.block_samples,
                    final=not more and not pending,
                )
                seq += 1
                start += self.block_samples
            if ended or idle:
                if idle and not ended:
                    log.warning(
                        "%s idle for %.1fs without a completion marker; "
                        "ending the stream", self.path, self.idle_timeout_s,
                    )
                break
            time.sleep(self.poll_s)
        nrows = len(pending) // row_bytes
        if nrows:
            raw = np.frombuffer(
                pending[: nrows * row_bytes], dtype=np.uint8
            )
            data = unpack_bits(raw, h.nbits).reshape(nrows, h.nchans)
            for blk in _blocks_from_array(
                data, self.block_samples, start_seq=seq
            ):
                blk.start_sample += start
                blk.t_arrival_s = time.perf_counter()
                yield blk


class DadaStreamSource(StreamSource):
    """Read a PSRDADA-style segment stream: ``*.dada`` files in one
    directory (or a single file), each DADA_HDR_SIZE header bytes +
    an 8-bit (nsamps, nchan) payload, consumed in name order and
    tailed for new segments."""

    def __init__(
        self,
        path: str,
        block_samples: int,
        poll_s: float = 0.05,
        idle_timeout_s: float = 10.0,
        complete_marker: str | None = None,
    ):
        self.path = path
        self.block_samples = int(block_samples)
        self.poll_s = float(poll_s)
        self.idle_timeout_s = float(idle_timeout_s)
        self._dir = path if os.path.isdir(path) else None
        self.complete_marker = complete_marker or (
            os.path.join(path, "obs.complete")
            if self._dir
            else path + ".complete"
        )
        first = self._segments()
        deadline = time.perf_counter() + idle_timeout_s
        while not first:
            if time.perf_counter() > deadline:
                raise TimeoutError(f"no .dada segments under {path}")
            time.sleep(poll_s)
            first = self._segments()
        h = DadaHeader.fromfile(first[0])
        if h.nbit not in (0, 8):
            raise ValueError(
                f"DadaStreamSource reads 8-bit payloads; {first[0]} "
                f"has NBIT {h.nbit}"
            )
        nchan = max(1, h.nchan)
        bw = abs(h.bw)
        foff = -(bw / nchan) if bw else -1.0
        # FREQ is the band centre: channel 0 sits half the band above
        # it (descending-frequency convention, like our filterbanks)
        fch1 = h.freq + (bw - abs(foff)) / 2.0 if bw else h.freq
        self.header = h
        self.format = StreamFormat(
            nchans=nchan, nbits=8,
            tsamp=h.tsamp * 1e-6,  # PSRDADA TSAMP is microseconds
            fch1=fch1, foff=foff, source_name=h.source_name,
        )

    def _segments(self) -> list[str]:
        if self._dir is None:
            return [self.path] if os.path.exists(self.path) else []
        return sorted(glob.glob(os.path.join(self._dir, "*.dada")))

    def _ended(self) -> bool:
        return os.path.exists(self.complete_marker)

    def blocks(self):
        nchan = self.format.nchans
        blk_bytes = nchan * self.block_samples
        consumed: set[str] = set()
        pending = b""
        seq = 0
        start = 0
        last_growth = time.perf_counter()
        while True:
            segs = [s for s in self._segments() if s not in consumed]
            for seg in segs:
                try:
                    faults.fire("fil.read", context=f"dada:{seg}")
                    with open(seg, "rb") as f:
                        f.seek(DADA_HDR_SIZE)
                        pending += f.read()
                except OSError as exc:
                    # a segment mid-rename or a flaky mount: leave it
                    # unconsumed and re-poll (idle timeout bounds this)
                    if not (
                        is_transient(exc)
                        or isinstance(exc, FileNotFoundError)
                    ):
                        raise
                    log.warning(
                        "transient segment read failure on %s "
                        "(%s: %.200s); retrying", seg,
                        type(exc).__name__, exc,
                    )
                    break
                consumed.add(seg)
                last_growth = time.perf_counter()
            ended = self._ended() and not [
                s for s in self._segments() if s not in consumed
            ]
            idle = (
                time.perf_counter() - last_growth > self.idle_timeout_s
            )
            while len(pending) >= blk_bytes:
                raw = np.frombuffer(pending[:blk_bytes], dtype=np.uint8)
                pending = pending[blk_bytes:]
                more = len(pending) >= blk_bytes or not (ended or idle)
                yield StreamBlock(
                    seq=seq, start_sample=start,
                    data=raw.reshape(self.block_samples, nchan),
                    nvalid=self.block_samples,
                    final=not more and not pending,
                )
                seq += 1
                start += self.block_samples
            if ended or idle:
                if idle and not ended:
                    log.warning(
                        "%s idle for %.1fs without a completion marker; "
                        "ending the stream", self.path,
                        self.idle_timeout_s,
                    )
                break
            time.sleep(self.poll_s)
        nrows = len(pending) // nchan
        if nrows:
            raw = np.frombuffer(pending[: nrows * nchan], dtype=np.uint8)
            for blk in _blocks_from_array(
                raw.reshape(nrows, nchan), self.block_samples,
                start_seq=seq,
            ):
                blk.start_sample += start
                blk.t_arrival_s = time.perf_counter()
                yield blk
