"""Output writers: candidates.peasoup binary + overview.xml (+ the
single-pulse ``.singlepulse`` text table and XML section).

Reference: include/utils/output_stats.hpp. The binary format per
candidate (output_stats.hpp:237-270):
  [optional] b"FOLD" + nbins(i32) + nints(i32) + fold(f32 x nbins*nints)
  ndets(i32) + ndets x CandidatePOD(24 bytes)
with a byte-offset map recorded for the XML. The XML mirrors the
reference's section set: misc_info, header_parameters,
search_parameters, dedispersion_trials, acceleration_trials, device
info, candidates, execution_times.

Single-pulse output (no reference equivalent): a whitespace-delimited
``.singlepulse`` table — the de-facto text format of single-pulse
tooling (PRESTO's first five columns, extended with the cluster
footprint) — plus a ``<single_pulse_search>`` overview.xml section.
Both round-trip through peasoup_tpu.tools.parsers.
"""

from __future__ import annotations

import getpass
import os
import struct
import time
from typing import Iterable, Sequence

import numpy as np

from ..core.candidates import Candidate
from .sigproc import SigprocHeader
from .xml_writer import Element


class CandidateFileWriter:
    def __init__(self, output_dir: str):
        self.output_dir = output_dir
        os.makedirs(output_dir, exist_ok=True)
        self.byte_mapping: dict[int, int] = {}

    def write_binary(
        self, candidates: Sequence[Candidate], filename: str = "candidates.peasoup"
    ) -> str:
        path = os.path.join(self.output_dir, filename)
        with open(path, "wb") as fo:
            for ii, cand in enumerate(candidates):
                self.byte_mapping[ii] = fo.tell()
                self._write_one(fo, cand)
        return path

    def write_binaries(self, candidates: Sequence[Candidate]) -> dict[int, str]:
        """One file per candidate (output_stats.hpp:272-307)."""
        filenames = {}
        for ii, cand in enumerate(candidates):
            period = 1.0 / cand.freq if cand.freq else float("inf")
            name = (
                f"cand_{ii:04d}_{period:.5f}_{cand.dm:.1f}_{cand.acc:.1f}"
                ".peasoup"
            )
            path = os.path.join(self.output_dir, name)
            with open(path, "wb") as fo:
                self._write_one(fo, cand)
            filenames[ii] = os.path.abspath(path)
        return filenames

    @staticmethod
    def _write_one(fo, cand: Candidate) -> None:
        if cand.fold is not None and cand.fold.size > 0:
            nints, nbins = cand.fold.shape
            fo.write(b"FOLD")
            fo.write(struct.pack("<ii", nbins, nints))
            fo.write(np.asarray(cand.fold, dtype="<f4").tobytes())
        pods = cand.collect_pods()
        fo.write(struct.pack("<i", len(pods)))
        fo.write(pods.tobytes())


# .singlepulse column order: PRESTO's five, then the cluster footprint
SINGLEPULSE_COLUMNS = (
    "dm", "snr", "time_s", "sample", "width",
    "width_idx", "dm_idx", "members",
    "sample_lo", "sample_hi", "dm_idx_lo", "dm_idx_hi",
    "width_lo", "width_hi",
)


def write_singlepulse(path: str, candidates: Sequence) -> str:
    """Write SinglePulseCandidates as a whitespace-delimited text
    table (one row per cluster, sorted as given). The leading '#'
    header names every column so the table self-describes; parse it
    back with peasoup_tpu.tools.parsers.read_singlepulse."""
    with open(path, "w", encoding="ascii") as f:
        f.write("# " + " ".join(SINGLEPULSE_COLUMNS) + "\n")
        for c in candidates:
            f.write(
                f"{c.dm:.6f} {c.snr:.4f} {c.time_s:.9f} {c.sample:d} "
                f"{c.width:d} {c.width_idx:d} {c.dm_idx:d} {c.members:d} "
                f"{c.sample_lo:d} {c.sample_hi:d} {c.dm_idx_lo:d} "
                f"{c.dm_idx_hi:d} {c.width_lo:d} {c.width_hi:d}\n"
            )
    return path


# .ffa column order: the FFACandidate fields, self-describing like the
# .singlepulse table
FFA_COLUMNS = ("period", "dm", "snr", "width", "duty_cycle")


def write_ffa_candidates(path: str, candidates: Sequence) -> str:
    """Write FFACandidates as a whitespace-delimited text table (one
    row per period-collapsed candidate, sorted as given)."""
    with open(path, "w", encoding="ascii") as f:
        f.write("# " + " ".join(FFA_COLUMNS) + "\n")
        for c in candidates:
            f.write(
                f"{c.period:.9f} {c.dm:.6f} {c.snr:.4f} {c.width:d} "
                f"{c.dc:.6f}\n"
            )
    return path


# .fdas column order: periodicity fields plus the Fourier-domain
# provenance, self-describing like the .singlepulse/.ffa tables
FDAS_COLUMNS = ("period", "dm", "acc", "fdot", "fddot", "z", "w",
                "nh", "snr")


def write_fdas_candidates(path: str, candidates: Sequence) -> str:
    """Write FdasCandidates as a whitespace-delimited text table (one
    row per distilled candidate, sorted as given). ``acc`` is the
    equivalent line-of-sight acceleration -fdot*c/f."""
    with open(path, "w", encoding="ascii") as f:
        f.write("# " + " ".join(FDAS_COLUMNS) + "\n")
        for c in candidates:
            f.write(
                f"{c.period:.12g} {c.dm:.6f} {c.acc:.6f} "
                f"{c.fdot:.9g} {c.fddot:.9g} {c.z:.3f} {c.w:.3f} "
                f"{c.nh:d} {c.snr:.4f}\n"
            )
    return path


class OutputFileWriter:
    def __init__(self):
        self.root = Element("peasoup_search")

    def to_string(self) -> str:
        return self.root.to_string(header=True)

    def to_file(self, filename: str) -> None:
        with open(filename, "w", encoding="latin-1") as f:
            f.write(self.to_string())

    def add_misc_info(self) -> None:
        info = self.root.append(Element("misc_info"))
        try:
            user = getpass.getuser()
        except Exception:
            user = "unknown"
        info.append(Element("username", user))
        info.append(Element("local_datetime", time.strftime("%Y-%m-%d-%H:%M")))
        info.append(
            Element("utc_datetime", time.strftime("%Y-%m-%d-%H:%M", time.gmtime()))
        )

    def add_header(self, hdr: SigprocHeader) -> None:
        h = self.root.append(Element("header_parameters"))
        h.append(Element("source_name", hdr.source_name))
        h.append(Element("rawdatafile", hdr.rawdatafile))
        h.append(Element("az_start", hdr.az_start))
        h.append(Element("za_start", hdr.za_start))
        h.append(Element("src_raj", hdr.src_raj))
        h.append(Element("src_dej", hdr.src_dej))
        h.append(Element("tstart", hdr.tstart))
        h.append(Element("tsamp", hdr.tsamp))
        h.append(Element("period", hdr.period))
        h.append(Element("fch1", hdr.fch1))
        h.append(Element("foff", hdr.foff))
        h.append(Element("nchans", hdr.nchans))
        h.append(Element("telescope_id", hdr.telescope_id))
        h.append(Element("machine_id", hdr.machine_id))
        h.append(Element("data_type", hdr.data_type))
        h.append(Element("ibeam", hdr.ibeam))
        h.append(Element("nbeams", hdr.nbeams))
        h.append(Element("nbits", hdr.nbits))
        h.append(Element("barycentric", hdr.barycentric))
        h.append(Element("pulsarcentric", hdr.pulsarcentric))
        h.append(Element("nbins", hdr.nbins))
        h.append(Element("nsamples", hdr.nsamples))
        h.append(Element("nifs", hdr.nifs))
        h.append(Element("npuls", hdr.npuls))
        h.append(Element("refdm", hdr.refdm))
        h.append(Element("signed", int(hdr.signed_data)))

    def add_search_parameters(self, cfg, infilename: str) -> None:
        s = self.root.append(Element("search_parameters"))
        s.append(Element("infilename", infilename))
        s.append(Element("outdir", cfg.outdir))
        s.append(Element("killfilename", cfg.killfilename))
        s.append(Element("zapfilename", cfg.zapfilename))
        s.append(Element("max_num_threads", cfg.max_num_threads))
        s.append(Element("size", cfg.size))
        s.append(Element("dm_start", float(np.float32(cfg.dm_start))))
        s.append(Element("dm_end", float(np.float32(cfg.dm_end))))
        s.append(Element("dm_tol", float(np.float32(cfg.dm_tol))))
        s.append(Element("dm_pulse_width", float(np.float32(cfg.dm_pulse_width))))
        s.append(Element("acc_start", float(np.float32(cfg.acc_start))))
        s.append(Element("acc_end", float(np.float32(cfg.acc_end))))
        s.append(Element("acc_tol", float(np.float32(cfg.acc_tol))))
        s.append(Element("acc_pulse_width", float(np.float32(cfg.acc_pulse_width))))
        s.append(Element("boundary_5_freq", float(np.float32(cfg.boundary_5_freq))))
        s.append(Element("boundary_25_freq", float(np.float32(cfg.boundary_25_freq))))
        s.append(Element("nharmonics", cfg.nharmonics))
        s.append(Element("npdmp", cfg.npdmp))
        s.append(Element("min_snr", float(np.float32(cfg.min_snr))))
        s.append(Element("min_freq", float(np.float32(cfg.min_freq))))
        s.append(Element("max_freq", float(np.float32(cfg.max_freq))))
        s.append(Element("max_harm", cfg.max_harm))
        s.append(Element("freq_tol", float(np.float32(cfg.freq_tol))))
        s.append(Element("verbose", cfg.verbose))
        s.append(Element("progress_bar", cfg.progress_bar))

    def add_dm_list(self, dms: Iterable[float]) -> None:
        dms = list(dms)
        trials = self.root.append(Element("dedispersion_trials"))
        trials.add_attribute("count", len(dms))
        for ii, dm in enumerate(dms):
            t = Element("trial", float(dm))
            t.add_attribute("id", ii)
            trials.append(t)

    def add_acc_list(self, accs: Iterable[float], dm: float = 0) -> None:
        accs = list(accs)
        trials = self.root.append(Element("acceleration_trials"))
        trials.add_attribute("count", len(accs))
        trials.add_attribute("DM", int(dm))
        for ii, acc in enumerate(accs):
            t = Element("trial", float(acc))
            t.add_attribute("id", ii)
            trials.append(t)

    def add_device_info(self) -> None:
        """TPU stand-in for the reference's cuda_device_parameters
        (output_stats.hpp:124-142)."""
        info = self.root.append(Element("tpu_device_parameters"))
        try:
            import jax

            info.append(Element("backend", jax.default_backend()))
            for ii, dev in enumerate(jax.devices()):
                d = Element("tpu_device")
                d.add_attribute("id", ii)
                d.append(Element("name", str(dev.device_kind)))
                d.append(Element("platform", str(dev.platform)))
                info.append(d)
        except Exception as exc:  # device info must never fail the run
            info.append(Element("error", str(exc)))

    def add_candidates(
        self, candidates: Sequence[Candidate], byte_map: dict[int, int]
    ) -> None:
        cands = self.root.append(Element("candidates"))
        for ii, c in enumerate(candidates):
            e = Element("candidate")
            e.add_attribute("id", ii)
            e.append(Element("period", 1.0 / c.freq if c.freq else float("inf")))
            e.append(Element("opt_period", c.opt_period))
            e.append(Element("dm", float(np.float32(c.dm))))
            e.append(Element("acc", float(np.float32(c.acc))))
            e.append(Element("nh", c.nh))
            e.append(Element("snr", float(np.float32(c.snr))))
            e.append(Element("folded_snr", float(np.float32(c.folded_snr))))
            e.append(Element("is_adjacent", c.is_adjacent))
            e.append(Element("is_physical", c.is_physical))
            e.append(Element("ddm_count_ratio", float(np.float32(c.ddm_count_ratio))))
            e.append(Element("ddm_snr_ratio", float(np.float32(c.ddm_snr_ratio))))
            e.append(Element("nassoc", c.count_assoc()))
            e.append(Element("byte_offset", byte_map.get(ii, 0)))
            cands.append(e)

    def add_ffa_section(
        self, cfg, infilename: str, candidates: Sequence
    ) -> None:
        """FFA search parameters + candidates. The ``<candidates>``
        entries carry the periodicity field set (period/opt_period/
        dm/acc/nh/snr/folded_snr — acc and nh vacuous for an FFA
        detection) so tools.parsers.OverviewFile and the campaign DB
        ingest read FFA jobs through the existing periodicity path,
        plus the FFA-specific width/duty_cycle extras."""
        s = self.root.append(Element("ffa_search_parameters"))
        s.append(Element("infilename", infilename))
        s.append(Element("outdir", cfg.outdir))
        s.append(Element("killfilename", cfg.killfilename))
        s.append(Element("dm_start", float(np.float32(cfg.dm_start))))
        s.append(Element("dm_end", float(np.float32(cfg.dm_end))))
        s.append(Element("dm_tol", float(np.float32(cfg.dm_tol))))
        s.append(
            Element("dm_pulse_width", float(np.float32(cfg.dm_pulse_width)))
        )
        s.append(Element("p_start", float(np.float32(cfg.p_start))))
        s.append(Element("p_end", float(np.float32(cfg.p_end))))
        s.append(Element("min_dc", float(np.float32(cfg.min_dc))))
        s.append(Element("min_snr", float(np.float32(cfg.min_snr))))
        cands = self.root.append(Element("candidates"))
        for ii, c in enumerate(candidates):
            e = Element("candidate")
            e.add_attribute("id", ii)
            e.append(Element("period", float(c.period)))
            e.append(Element("opt_period", float(c.period)))
            e.append(Element("dm", float(np.float32(c.dm))))
            e.append(Element("acc", 0.0))
            e.append(Element("nh", 0))
            e.append(Element("snr", float(np.float32(c.snr))))
            e.append(Element("folded_snr", 0.0))
            e.append(Element("width", int(c.width)))
            e.append(Element("duty_cycle", float(np.float32(c.dc))))
            cands.append(e)

    def add_fdas_section(self, cfg, zs: Iterable[float],
                         ws: Iterable[float]) -> None:
        """The ``<fdas_search>`` element: FDAS search parameters plus
        the (z, w) template trial ladders. Candidates are written by
        :meth:`add_candidates` at top level in the periodicity field
        set (an FdasCandidate's ``acc`` is the equivalent line-of-sight
        acceleration), extended with per-candidate <fdot>/<fddot> so
        tools.parsers.OverviewFile and the campaign DB ingest read FDAS
        jobs through the existing periodicity path while keeping the
        native Fourier-domain provenance."""
        sec = self.root.append(Element("fdas_search"))
        params = sec.append(Element("search_parameters"))
        params.append(Element("outdir", cfg.outdir))
        params.append(Element("killfilename", cfg.killfilename))
        params.append(Element("zapfilename", cfg.zapfilename))
        params.append(Element("size", cfg.size))
        params.append(Element("dm_start", float(np.float32(cfg.dm_start))))
        params.append(Element("dm_end", float(np.float32(cfg.dm_end))))
        params.append(Element("dm_tol", float(np.float32(cfg.dm_tol))))
        params.append(
            Element("dm_pulse_width", float(np.float32(cfg.dm_pulse_width)))
        )
        params.append(Element("zmax", float(np.float32(cfg.zmax))))
        params.append(Element("zstep", float(np.float32(cfg.zstep))))
        params.append(Element("wmax", float(np.float32(cfg.wmax))))
        params.append(Element("wstep", float(np.float32(cfg.wstep))))
        params.append(Element("nharmonics", cfg.nharmonics))
        params.append(Element("min_snr", float(np.float32(cfg.min_snr))))
        params.append(Element("min_freq", float(np.float32(cfg.min_freq))))
        params.append(Element("max_freq", float(np.float32(cfg.max_freq))))
        params.append(Element("max_harm", cfg.max_harm))
        params.append(Element("freq_tol", float(np.float32(cfg.freq_tol))))
        ztr = sec.append(Element("fdot_trials"))
        zs = [float(z) for z in zs]
        ztr.add_attribute("count", len(zs))
        ztr.add_attribute("unit", "bins")
        for ii, z in enumerate(zs):
            t = Element("trial", z)
            t.add_attribute("id", ii)
            ztr.append(t)
        wtr = sec.append(Element("fddot_trials"))
        ws = [float(w) for w in ws]
        wtr.add_attribute("count", len(ws))
        wtr.add_attribute("unit", "bins")
        for ii, w in enumerate(ws):
            t = Element("trial", w)
            t.add_attribute("id", ii)
            wtr.append(t)

    def add_candidates_fdas(
        self, candidates: Sequence[Candidate], byte_map: dict[int, int]
    ) -> None:
        """Top-level <candidates> in the periodicity layout plus the
        FDAS provenance extras (fdot Hz/s, fddot Hz/s^2, z/w in bins);
        name-based parsers skip unknown children, so everything that
        reads add_candidates output reads this too."""
        cands = self.root.append(Element("candidates"))
        for ii, c in enumerate(candidates):
            e = Element("candidate")
            e.add_attribute("id", ii)
            e.append(Element("period", 1.0 / c.freq if c.freq else float("inf")))
            e.append(Element("opt_period", c.opt_period))
            e.append(Element("dm", float(np.float32(c.dm))))
            e.append(Element("acc", float(np.float32(c.acc))))
            e.append(Element("nh", c.nh))
            e.append(Element("snr", float(np.float32(c.snr))))
            e.append(Element("folded_snr", float(np.float32(c.folded_snr))))
            e.append(Element("fdot", float(np.float32(getattr(c, "fdot", 0.0)))))
            e.append(Element("fddot", float(np.float32(getattr(c, "fddot", 0.0)))))
            e.append(Element("z", float(np.float32(getattr(c, "z", 0.0)))))
            e.append(Element("w", float(np.float32(getattr(c, "w", 0.0)))))
            e.append(Element("nassoc", c.count_assoc()))
            e.append(Element("byte_offset", byte_map.get(ii, 0)))
            cands.append(e)

    def add_single_pulse_section(
        self,
        cfg,
        infilename: str,
        widths: Iterable[int],
        candidates: Sequence,
    ) -> None:
        """The single-pulse twin of search_parameters + trials +
        candidates, nested under ONE <single_pulse_search> element so a
        combined periodicity + single-pulse overview stays unambiguous.
        Round-trips via tools.parsers.OverviewFile (sp_* attributes).
        """
        sp = self.root.append(Element("single_pulse_search"))
        params = sp.append(Element("search_parameters"))
        params.append(Element("infilename", infilename))
        params.append(Element("outdir", cfg.outdir))
        params.append(Element("killfilename", cfg.killfilename))
        params.append(Element("dm_start", float(np.float32(cfg.dm_start))))
        params.append(Element("dm_end", float(np.float32(cfg.dm_end))))
        params.append(Element("dm_tol", float(np.float32(cfg.dm_tol))))
        params.append(
            Element("dm_pulse_width", float(np.float32(cfg.dm_pulse_width)))
        )
        params.append(Element("min_snr", float(np.float32(cfg.min_snr))))
        params.append(Element("n_widths", cfg.n_widths))
        params.append(Element("max_events", cfg.max_events))
        params.append(Element("decimate", cfg.decimate))
        params.append(Element("time_link", float(np.float32(cfg.time_link))))
        params.append(Element("dm_link", cfg.dm_link))
        widths = [int(w) for w in widths]
        trials = sp.append(Element("width_trials"))
        trials.add_attribute("count", len(widths))
        for ii, w in enumerate(widths):
            t = Element("trial", w)
            t.add_attribute("id", ii)
            trials.append(t)
        cands = sp.append(Element("candidates"))
        cands.add_attribute("count", len(candidates))
        for ii, c in enumerate(candidates):
            e = Element("candidate")
            e.add_attribute("id", ii)
            e.append(Element("dm", float(np.float32(c.dm))))
            e.append(Element("dm_idx", c.dm_idx))
            e.append(Element("snr", float(np.float32(c.snr))))
            e.append(Element("time_s", float(c.time_s)))
            e.append(Element("sample", c.sample))
            e.append(Element("width", c.width))
            e.append(Element("width_idx", c.width_idx))
            e.append(Element("members", c.members))
            e.append(Element("sample_lo", c.sample_lo))
            e.append(Element("sample_hi", c.sample_hi))
            e.append(Element("dm_idx_lo", c.dm_idx_lo))
            e.append(Element("dm_idx_hi", c.dm_idx_hi))
            e.append(Element("width_lo", c.width_lo))
            e.append(Element("width_hi", c.width_hi))
            cands.append(e)

    def add_timing_info(self, timers: dict[str, float]) -> None:
        times = self.root.append(Element("execution_times"))
        for key in sorted(timers):
            times.append(Element(key, float(timers[key])))
