"""Kill-file (channel mask) and zap-file (birdie list) parsing.

Reference: killfile = one 0/1 per channel line (dedisperser.hpp:71-95);
zapfile = two columns "freq width" in Hz (birdiezapper.hpp:35-59).
"""

from __future__ import annotations

import os

import numpy as np


def read_killfile(path: str | os.PathLike, nchans: int) -> np.ndarray:
    """Return an int killmask of shape (nchans,) with 1 = keep.

    Like the reference, a size mismatch degrades to an all-pass mask with
    a warning rather than an error (dedisperser.hpp:86-93).
    """
    values = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            values.append(int(float(line.split()[0])))
            if len(values) >= nchans:
                break
    if len(values) != nchans:
        import warnings

        warnings.warn(
            f"killmask is not the same size as nchans ({len(values)} != {nchans}); ignoring"
        )
        return np.ones(nchans, dtype=np.int32)
    return np.asarray(values, dtype=np.int32)


def read_zapfile(path: str | os.PathLike) -> tuple[np.ndarray, np.ndarray]:
    """Return (freqs, widths) float arrays parsed from a birdie list."""
    freqs, widths = [], []
    with open(path) as f:
        for line in f:
            parts = line.split()
            if len(parts) >= 2:
                freqs.append(float(parts[0]))
                widths.append(float(parts[1]))
    return np.asarray(freqs, dtype=np.float64), np.asarray(widths, dtype=np.float64)
