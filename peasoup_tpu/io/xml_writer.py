"""Minimal XML element tree matching the reference's formatting.

Reference: include/utils/xml_util.hpp — single-quoted attributes,
2-space indentation, 15-significant-digit numeric formatting
(std::setprecision(15) default-float notation == printf %.15g), bools
as 1/0, leaf text inline.
"""

from __future__ import annotations

from typing import Union

import numpy as np

Scalar = Union[str, int, float, bool, np.floating, np.integer]


def fmt(value: Scalar) -> str:
    if isinstance(value, (bool, np.bool_)):
        return "1" if value else "0"
    if isinstance(value, (int, np.integer)):
        return str(int(value))
    if isinstance(value, (float, np.floating)):
        return f"{float(value):.15g}"
    # escape markup characters so filenames/source names with &, <, '
    # cannot corrupt the document (the reference writes them raw, which
    # is why its own tools need a <username> cleanup workaround)
    return (
        str(value)
        .replace("&", "&amp;")
        .replace("<", "&lt;")
        .replace(">", "&gt;")
        .replace("'", "&apos;")
    )


class Element:
    def __init__(self, name: str, value: Scalar | None = None):
        self.name = name
        self.text = "" if value is None else fmt(value)
        self.attributes: dict[str, str] = {}
        self.children: list[Element] = []

    def append(self, child: "Element") -> "Element":
        self.children.append(child)
        return child

    def add_attribute(self, key: str, value: Scalar) -> None:
        self.attributes[key] = fmt(value)

    def set_text(self, value: Scalar) -> None:
        self.text = fmt(value)

    def to_string(self, header: bool = False, level: int = 0) -> str:
        out = []
        if header:
            out.append("<?xml version='1.0' encoding='ISO-8859-1'?>\n")
        indent = "  " * level
        attrs = "".join(f" {k}='{v}'" for k, v in self.attributes.items())
        out.append(f"{indent}<{self.name}{attrs}>")
        if not self.children:
            out.append(self.text)
        else:
            out.append("\n")
            for child in self.children:
                out.append(child.to_string(False, level + 1))
            out.append(indent)
        out.append(f"</{self.name}>\n")
        return "".join(out)
