"""Device-mesh construction.

The reference's parallelism is one pthread per GPU pulling DM-trial
indices from a mutex-protected dispenser (src/pipeline_multi.cu:33-81).
TPU-native equivalent: a `jax.sharding.Mesh` whose axes shard the trial
grid — 'dm' for DM trials within a pod (ICI), 'beam' for multibeam
ensembles (DCN across pods). Work assignment is static round-robin
(deterministic) instead of the reference's dynamic mutex dealing.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh


def device_count() -> int:
    return len(jax.devices())


def make_mesh(axes: dict[str, int] | None = None, devices=None) -> Mesh:
    """Build a mesh; default is all devices on one 'dm' axis.

    ``axes`` maps axis name -> size, e.g. {'beam': 2, 'dm': 4}. Sizes
    must multiply to the device count (-1 means "the rest").
    """
    devices = list(devices if devices is not None else jax.devices())
    if axes is None:
        axes = {"dm": len(devices)}
    names = list(axes)
    sizes = [axes[n] for n in names]
    if -1 in sizes:
        known = int(np.prod([s for s in sizes if s != -1]))
        sizes[sizes.index(-1)] = len(devices) // known
    if int(np.prod(sizes)) != len(devices):
        raise ValueError(
            f"mesh axes {dict(zip(names, sizes))} do not cover {len(devices)} devices"
        )
    dev_array = np.asarray(devices).reshape(sizes)
    return Mesh(dev_array, tuple(names))
