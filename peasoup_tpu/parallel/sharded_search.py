"""DM-trial-sharded acceleration search over a device mesh.

The reference scales by running one share-nothing worker per GPU over a
dynamically-dealt DM list (src/pipeline_multi.cu:33-81,342-359). Here a
BLOCK of DM trials is laid out on the mesh's 'dm' axis with
``shard_map``: each chip runs the identical jitted per-trial program on
its local trials; there is no cross-chip communication in the search
itself (trial grid parallelism rides on data placement, not
collectives), and the fixed-size peak arrays gather back to the host
for distilling — the analogue of the reference's per-worker candidate
merge on join (pipeline_multi.cu:356-359).
"""

from __future__ import annotations

from functools import lru_cache, partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..obs import get_logger
from ..obs.telemetry import current as current_telemetry
from ..pipeline.accel_search import AccelSearchPeaks, search_block_core

log = get_logger("parallel.sharded_search")


@lru_cache(maxsize=None)
def make_sharded_search_fn(
    mesh: Mesh,
    threshold: float,
    axis: str = "dm",
    pallas_block: int = 0,
    select_smax: int = 0,
    pallas_peaks: bool = False,
    fused_interbin: bool = False,
    mega_harm: bool = False,
    fused_dft: bool = False,
):
    """Jitted (D, ...) -> (D, ...) search with D sharded over ``axis``.

    D must be a multiple of the mesh axis size (pad the trial block and
    the afs rows; padded rows are searched but discarded by the host).
    Each chip runs the block-batched core on its local trials; with
    ``pallas_block`` > 0 the Pallas resample kernel runs per chip.
    Cached (mesh/threshold/axis/block are hashable) so repeat runs reuse
    the compiled executable like make_batched_search_fn.
    """
    log.debug(
        "building sharded search: %d-chip '%s' mesh, pallas_block=%d, "
        "pallas_peaks=%s", mesh.shape[axis], axis, pallas_block,
        pallas_peaks,
    )
    current_telemetry().event(
        "sharded_search_built", n_chips=int(mesh.shape[axis]), axis=axis,
        pallas_block=int(pallas_block), pallas_peaks=bool(pallas_peaks),
        mega_harm=bool(mega_harm), fused_dft=bool(fused_dft),
        process_index=int(jax.process_index()),
    )

    @partial(
        jax.jit,
        static_argnames=("size", "nsamps_valid", "nharms", "max_peaks",
                         "pos5", "pos25"),
    )
    def sharded_search(
        tims: jax.Array,  # (D, >=size) u8 trials, sharded over axis
        afs: jax.Array,  # (D, A) f32 per-trial accel factors
        zapmask: jax.Array,  # (size//2+1,) bool, replicated
        windows: jax.Array,  # (nharms+1, 2) i32, replicated
        *,
        size: int,
        nsamps_valid: int,
        nharms: int,
        max_peaks: int,
        pos5: int,
        pos25: int,
    ) -> AccelSearchPeaks:
        def local(tims_l, afs_l, zap_l, win_l):
            return search_block_core(
                tims_l, afs_l, zap_l, win_l,
                threshold=threshold, size=size, nsamps_valid=nsamps_valid,
                nharms=nharms, max_peaks=max_peaks, pos5=pos5, pos25=pos25,
                pallas_block=pallas_block, select_smax=select_smax,
                pallas_peaks=pallas_peaks, fused_interbin=fused_interbin,
                mega_harm=mega_harm, fused_dft=fused_dft,
            )

        return jax.shard_map(
            local,
            mesh=mesh,
            in_specs=(P(axis), P(axis), P(), P()),
            out_specs=AccelSearchPeaks(
                idxs=P(axis), snrs=P(axis), counts=P(axis), ccounts=P(axis)
            ),
        )(tims, afs, zapmask, windows)

    return sharded_search


def place_trials(mesh: Mesh, trials, axis: str = "dm"):
    """Device-put a (D, N) trial block sharded along the mesh axis."""
    return jax.device_put(trials, NamedSharding(mesh, P(axis)))
