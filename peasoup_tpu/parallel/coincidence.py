"""Multibeam coincidence over a (possibly sharded) beam axis.

Reference: src/coincidencer.cpp + kernels.cu:1073-1100 — an offline
binary looping over beam device pointers on one GPU. TPU-native: beams
are a leading array axis; per-beam baselining vmaps, and when beams are
sharded across chips the exceed-count reduces with ``psum`` over the
mesh's 'beam' axis (ICI within a pod, DCN across pods).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..ops.coincidence import coincidence_mask
from ..ops.rednoise import whiten_fseries
from ..ops.spectrum import form_interpolated, normalise, spectrum_stats


@partial(jax.jit, static_argnames=("size", "pos5", "pos25"))
def baseline_beam(
    tim: jax.Array, *, size: int, pos5: int, pos25: int
) -> tuple[jax.Array, jax.Array]:
    """One beam's zero-DM baselining (coincidencer.cpp:163-180).

    Returns (normalised interbin spectrum (size//2+1,), normalised
    dereddened time series (size,)).
    """
    fser = whiten_fseries(tim[:size], pos5=pos5, pos25=pos25)
    spec = form_interpolated(fser)
    mean, _, std = spectrum_stats(spec)
    spec = normalise(spec, mean, std)
    xd = jnp.fft.irfft(fser, n=size)
    tmean, _, tstd = spectrum_stats(xd)
    xd = normalise(xd, tmean, tstd)
    return spec, xd


def sharded_coincidence(
    mesh: Mesh,
    beams: jax.Array,  # (B, N) with B sharded over the 'beam' axis
    thresh: float,
    beam_thresh: int,
    axis: str = "beam",
) -> jax.Array:
    """(N,) keep-mask: 1.0 where fewer than beam_thresh beams exceed
    thresh. Cross-chip exceed-counts ride a psum over the beam axis."""

    def local(beams_l):
        return coincidence_mask(beams_l, thresh, beam_thresh, axis_name=axis)

    fn = jax.shard_map(
        local, mesh=mesh, in_specs=(P(axis),), out_specs=P(None)
    )
    return fn(beams)
