"""DM-trial-sharded dedispersion over a device mesh.

The reference dedisperses across ALL GPUs in one node
(`dedisp_create_plan_multi`, reference include/transforms/
dedisperser.hpp:25-31).  Round 1 of this framework instead dedispersed
the whole trial set on one chip while the mesh's other chips idled.
Here the DM-trial axis of the shift-and-sum engine is laid out on the
mesh's ``dm`` axis with ``shard_map``: the (channel-blocked, masked)
filterbank is replicated to every chip, each chip scans its local slice
of the delay table, and the (ndm, out_nsamps) trial block materialises
ALREADY SHARDED the way the search consumes it — trial rows then move
chip-to-chip only as u8 over ICI when a search chunk regroups them
(make_row_gather), never through the host.

Bitwise identical to ops.dedisperse.dedisperse_device's jnp scan:
channel sums of <=8-bit samples are exact in f32 so the per-chip
accumulation order cannot change the result.
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.dedisperse import _dedisperse_core, _pad_blocks


@lru_cache(maxsize=None)
def _make_sharded_dd(
    mesh: Mesh,
    axis: str,
    out_nsamps: int,
    quantize: bool,
    scale: float,
    block: int,
    per_dev: int,
):
    def local_fn(x_cb, delays):
        # delays: (per_dev, C) — this chip's slice of the trial table.
        # Python loop over fixed-size blocks bounds the live f32 carry
        # exactly like dedisperse_device's blocked scan.
        outs = [
            _dedisperse_core(
                x_cb, delays[s : s + block],
                out_nsamps=out_nsamps, quantize=quantize, scale=scale,
            )
            for s in range(0, per_dev, block)
        ]
        return outs[0] if len(outs) == 1 else jnp.concatenate(outs, axis=0)

    # check_vma off: the local body is collective-free, and the scan
    # carry inside _dedisperse_core starts unvarying (created from
    # jnp.zeros) while the delays are device-varying — the check would
    # demand a pvary cast inside shared single-device code
    try:
        fn = jax.shard_map(
            local_fn,
            mesh=mesh,
            in_specs=(P(), P(axis, None)),
            out_specs=P(axis, None),
            check_vma=False,
        )
    except TypeError:  # older jax spells it check_rep
        fn = jax.shard_map(
            local_fn,
            mesh=mesh,
            in_specs=(P(), P(axis, None)),
            out_specs=P(axis, None),
            check_rep=False,
        )
    return jax.jit(fn)


def dedisperse_sharded(
    fil_tc,
    delays: np.ndarray,
    killmask: np.ndarray,
    out_nsamps: int,
    mesh: Mesh,
    *,
    axis: str = "dm",
    quantize: bool = True,
    scale: float = 1.0,
    block: int = 16,
):
    """Dedisperse all DM trials with the trial axis sharded over ``mesh``.

    Returns a GLOBAL (ndm_padded, out_nsamps) array laid out
    ``P(axis, None)`` — ndm is padded up to a multiple of the mesh axis
    size by repeating the last trial row; callers index rows < ndm only
    (the search's chunk dispatch does exactly that).
    """
    n_dev = mesh.shape[axis]
    delays = np.asarray(delays, dtype=np.int32)
    ndm = delays.shape[0]
    per_dev = -(-ndm // n_dev)
    ndm_pad = per_dev * n_dev
    if ndm_pad > ndm:
        delays = np.concatenate(
            [delays, np.tile(delays[-1:], (ndm_pad - ndm, 1))], axis=0
        )

    # Preprocessing (identical to dedisperse_block's front half:
    # pad/block the time axis, mask channels, f32) runs ONCE on the
    # default device, then the finished blocked tensor replicates to the
    # mesh — eager ops on an already-replicated array would execute on
    # every device (8x the work), and on TPU the one broadcast rides ICI.
    x = _pad_blocks(jnp.asarray(fil_tc))
    x = x.astype(jnp.float32).T * jnp.asarray(
        np.asarray(killmask), dtype=jnp.float32
    )[:, None]
    x_cb = jax.device_put(
        x.reshape(x.shape[0], -1, 128), NamedSharding(mesh, P())
    )  # (C, T/128, 128) replicated

    fn = _make_sharded_dd(
        mesh, axis, out_nsamps, quantize, float(scale), block, per_dev
    )
    delays_dev = jax.device_put(
        delays, NamedSharding(mesh, P(axis, None))
    )
    return fn(x_cb, delays_dev)


@lru_cache(maxsize=None)
def make_row_gather(mesh: Mesh, axis: str, tim_len: int):
    """Jitted (trials, idx) -> (len(idx), tim_len) row regroup with the
    output pinned to ``P(axis, None)``: XLA moves exactly the u8 rows a
    chunk needs between chips over ICI — no host hop, no full-array
    migration (replaces the eager take + device_put in the search's
    chunk dispatch)."""
    sh = NamedSharding(mesh, P(axis, None))

    @jax.jit
    def gather(trials, idx):
        rows = jnp.take(trials, idx, axis=0)[:, :tim_len]
        return jax.lax.with_sharding_constraint(rows, sh)

    return gather
