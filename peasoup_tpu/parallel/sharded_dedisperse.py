"""DM-trial-sharded dedispersion over a device mesh.

The reference dedisperses across ALL GPUs in one node
(`dedisp_create_plan_multi`, reference include/transforms/
dedisperser.hpp:25-31).  Round 1 of this framework instead dedispersed
the whole trial set on one chip while the mesh's other chips idled.
Here the DM-trial axis of the shift-and-sum engine is laid out on the
mesh's ``dm`` axis with ``shard_map``: the (channel-blocked, masked)
filterbank is replicated to every chip, each chip scans its local slice
of the delay table, and the (ndm, out_nsamps) trial block materialises
ALREADY SHARDED the way the search consumes it — trial rows then move
chip-to-chip only as u8 over ICI when a search chunk regroups them
(make_row_gather), never through the host.

Bitwise identical to ops.dedisperse.dedisperse_device's jnp scan:
channel sums of <=8-bit samples are exact in f32 so the per-chip
accumulation order cannot change the result.
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.dedisperse import _dedisperse_core, _pad_blocks


def _shard_map_nocheck(local_fn, mesh, in_specs, out_specs):
    # check_vma off: the local bodies are collective-free, and values
    # created inside (scan carries, iotas) start unvarying while the
    # delays are device-varying — the check would demand pvary casts
    # inside shared single-device code
    try:
        return jax.shard_map(
            local_fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False,
        )
    except TypeError:  # older jax spells it check_rep
        return jax.shard_map(
            local_fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=False,
        )


@lru_cache(maxsize=None)
def _make_sharded_dd(
    mesh: Mesh,
    axis: str,
    out_nsamps: int,
    quantize: bool,
    scale: float,
    block: int,
    per_dev: int,
):
    def local_fn(x_cb, delays):
        # delays: (per_dev, C) — this chip's slice of the trial table.
        # Python loop over fixed-size blocks bounds the live f32 carry
        # exactly like dedisperse_device's blocked scan.
        outs = [
            _dedisperse_core(
                x_cb, delays[s : s + block],
                out_nsamps=out_nsamps, quantize=quantize, scale=scale,
            )
            for s in range(0, per_dev, block)
        ]
        return outs[0] if len(outs) == 1 else jnp.concatenate(outs, axis=0)

    return jax.jit(
        _shard_map_nocheck(
            local_fn, mesh, (P(), P(axis, None)), P(axis, None)
        )
    )


@lru_cache(maxsize=None)
def _make_sharded_dd_pallas(
    mesh: Mesh,
    axis: str,
    t_out: int,
    cpad: int,
    b: int,
    spread: int,
    quantize: bool,
    scale: float,
    per_dev: int,
    out_nsamps: int,
    interpret: bool,
):
    """Per-shard Pallas blocked-roll kernel (ops/pallas/dedisperse.py):
    each chip runs the 13x kernel on ITS slice of the delay table — the
    multi-chip analogue of dedisp_create_plan_multi with dedisp's GPU
    kernel on every device."""
    from ..ops.pallas.dedisperse import _build

    fn = _build(per_dev, t_out, cpad, b, spread, interpret)

    def local_fn(xp, delays):
        out = fn(delays, xp).reshape(per_dev, t_out)[:, :out_nsamps]
        if scale != 1.0:
            out = out * jnp.float32(scale)
        if quantize:
            out = jnp.clip(jnp.rint(out), 0, 255).astype(jnp.uint8)
        return out

    return jax.jit(
        _shard_map_nocheck(
            local_fn, mesh, (P(), P(axis, None)), P(axis, None)
        )
    )


def dedisperse_sharded(
    fil_tc,
    delays: np.ndarray,
    killmask: np.ndarray,
    out_nsamps: int,
    mesh: Mesh,
    *,
    axis: str = "dm",
    quantize: bool = True,
    scale: float = 1.0,
    block: int = 16,
    use_pallas: bool | None = None,
    interpret: bool = False,
):
    """Dedisperse all DM trials with the trial axis sharded over ``mesh``.

    Returns a GLOBAL (ndm_padded, out_nsamps) array laid out
    ``P(axis, None)`` — ndm is padded up to a multiple of the mesh axis
    size by repeating the last trial row; callers index rows < ndm only
    (the search's chunk dispatch does exactly that).

    ``use_pallas`` None = auto: on TPU backends that pass the kernel
    probe (and monotone delay tables), each shard runs the blocked-roll
    Pallas kernel; elsewhere the jnp channel scan. Both bitwise equal.
    """
    n_dev = mesh.shape[axis]
    delays = np.asarray(delays, dtype=np.int32)
    ndm = delays.shape[0]

    if use_pallas is None:
        from ..ops.pallas import probe_pallas_dedisperse

        use_pallas = (
            not interpret
            and probe_pallas_dedisperse()
            and bool(np.all(np.diff(delays, axis=0) >= 0))
        )

    if use_pallas:
        from ..ops.pallas.dedisperse import (
            _CC, _DT, _QUANT, _tr_rows, plan_spread,
        )

        # per-shard trial count must hit the kernel's 8-trial quantum;
        # shard boundaries at multiples of 8 keep the global 8-chunk
        # walk of plan_spread aligned with every shard's local chunks
        per_dev = -(-(-(-ndm // n_dev)) // _DT) * _DT
        ndm_pad = per_dev * n_dev
        c = delays.shape[1]
        cpad = -(-c // _CC) * _CC
        if ndm_pad > ndm:
            delays = np.concatenate(
                [delays, np.tile(delays[-1:], (ndm_pad - ndm, 1))], axis=0
            )
        if cpad > c:
            delays = np.concatenate(
                [delays, np.tile(delays[:, -1:], (1, cpad - c))], axis=1
            )
        t_in = fil_tc.shape[0]
        b = min(16384, max(_QUANT, -(-out_nsamps // _QUANT) * _QUANT))
        t_out = -(-out_nsamps // b) * b
        spread = plan_spread(delays)
        k_max = (127 + spread) // 128
        tr = _tr_rows(t_in, b // 128, k_max)
        x = jnp.asarray(fil_tc).astype(jnp.float32) * jnp.asarray(
            np.asarray(killmask), dtype=jnp.float32
        )[None, :]
        xp = jax.device_put(
            jnp.pad(x.T, ((0, cpad - c), (0, tr * 128 - t_in))).reshape(
                cpad, tr, 128
            ),
            NamedSharding(mesh, P()),
        )
        fn = _make_sharded_dd_pallas(
            mesh, axis, t_out, cpad, b, spread, quantize, float(scale),
            per_dev, out_nsamps, interpret,
        )
        delays_dev = jax.device_put(
            delays, NamedSharding(mesh, P(axis, None))
        )
        return fn(xp, delays_dev)

    per_dev = -(-ndm // n_dev)
    ndm_pad = per_dev * n_dev
    if ndm_pad > ndm:
        delays = np.concatenate(
            [delays, np.tile(delays[-1:], (ndm_pad - ndm, 1))], axis=0
        )

    # Preprocessing (identical to dedisperse_block's front half:
    # pad/block the time axis, mask channels, f32) runs ONCE on the
    # default device, then the finished blocked tensor replicates to the
    # mesh — eager ops on an already-replicated array would execute on
    # every device (8x the work), and on TPU the one broadcast rides ICI.
    x = _pad_blocks(jnp.asarray(fil_tc))
    x = x.astype(jnp.float32).T * jnp.asarray(
        np.asarray(killmask), dtype=jnp.float32
    )[:, None]
    x_cb = jax.device_put(
        x.reshape(x.shape[0], -1, 128), NamedSharding(mesh, P())
    )  # (C, T/128, 128) replicated

    fn = _make_sharded_dd(
        mesh, axis, out_nsamps, quantize, float(scale), block, per_dev
    )
    delays_dev = jax.device_put(
        delays, NamedSharding(mesh, P(axis, None))
    )
    return fn(x_cb, delays_dev)


@lru_cache(maxsize=None)
def make_row_gather(mesh: Mesh, axis: str, tim_len: int):
    """Jitted (trials, idx) -> (len(idx), tim_len) row regroup with the
    output pinned to ``P(axis, None)``: XLA moves exactly the u8 rows a
    chunk needs between chips over ICI — no host hop, no full-array
    migration (replaces the eager take + device_put in the search's
    chunk dispatch)."""
    sh = NamedSharding(mesh, P(axis, None))

    @jax.jit
    def gather(trials, idx):
        rows = jnp.take(trials, idx, axis=0)[:, :tim_len]
        return jax.lax.with_sharding_constraint(rows, sh)

    return gather
