"""Multi-host (multi-process) execution over ICI + DCN.

The reference scales no further than one node: pthread workers over the
local GPUs (src/pipeline_multi.cu:33-81), no MPI/NCCL. This module is
the TPU framework's distributed communication backend: JAX's
coordinator-based multi-process runtime, with XLA collectives riding
ICI within a pod slice and DCN between pods/hosts. The search itself
needs no new code for multi-host — `shard_map` programs built on a
global mesh (parallel/sharded_search.py, parallel/coincidence.py,
parallel/distributed_fft.py) run unchanged; only device discovery and
data placement change.

Deployment pattern (one process per host):

    from peasoup_tpu.parallel import multihost
    multihost.initialize(coordinator="host0:8476",
                         num_processes=4, process_id=RANK)
    mesh = multihost.global_mesh({"beam": 4, "dm": -1},
                                 dcn_axis="beam")
    # beams land one per pod (DCN between them), DM trials shard the
    # pod's chips (ICI); psum over 'beam' crosses DCN, collectives
    # over 'dm' stay on ICI.

On a single process (no coordinator), everything degrades to the
local-device behaviour used throughout this repo.
"""

from __future__ import annotations

import os
import socket

import jax
from jax.sharding import Mesh

from ..obs import get_logger
from ..obs.telemetry import current as current_telemetry
from ..obs.trace import flow_id_for, job_span
from ..resilience import TransientIOError, faults
from .mesh import make_mesh

log = get_logger("parallel.multihost")

# message fragments that identify a *distributed-runtime* failure (a
# peer died at the barrier, the coordinator timed out, a DCN link
# dropped) as opposed to a programming error inside the collective.
# jaxlib raises one runtime-error type for every status code, so the
# contract available is the ABSL status text.
_COLLECTIVE_TRANSIENT_TOKENS = (
    "DEADLINE_EXCEEDED",
    "UNAVAILABLE",
    "ABORTED",
    "connection",
    "heartbeat",
    "barrier",
    "coordination service",
    "shutting down",
)


def _classify_collective_error(exc: Exception, context: str) -> None:
    """Re-raise a collective failure as TRANSIENT when it carries a
    distributed-runtime signature: a host dying at the allgather
    barrier must fail the step fast — classified transient so the
    campaign attempt budget retries it — never hang or read as a
    programming error. Anything else propagates unchanged."""
    msg = str(exc)
    low = msg.lower()
    if any(t.lower() in low for t in _COLLECTIVE_TRANSIENT_TOKENS):
        import errno as _errno

        raise TransientIOError(
            _errno.ECONNRESET,
            f"multihost collective failed ({context}): {msg:.300}",
        ) from exc
    raise exc


def initialize(
    coordinator: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> None:
    """Initialise the multi-process JAX runtime.

    With no arguments, reads the standard env (JAX_COORDINATOR_ADDRESS,
    JAX_NUM_PROCESSES, JAX_PROCESS_ID — or their cloud-TPU equivalents
    auto-detected by jax.distributed). Safe no-op when already
    initialised or when running single-process.
    """
    coordinator = coordinator or os.environ.get("JAX_COORDINATOR_ADDRESS")
    if num_processes is None:
        env = os.environ.get("JAX_NUM_PROCESSES")
        num_processes = int(env) if env else None
    if process_id is None:
        env = os.environ.get("JAX_PROCESS_ID")
        process_id = int(env) if env else None
    if coordinator is None and num_processes in (None, 1):
        return  # single-process: nothing to do
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=num_processes,
            process_id=process_id,
        )
    except RuntimeError as exc:
        msg = str(exc)
        if (
            "already initialized" not in msg
            and "should only be called once" not in msg
        ):
            raise


def global_mesh(
    axes: dict[str, int], dcn_axis: str | None = None
) -> Mesh:
    """Build a mesh over ALL processes' devices (jax.devices() is
    global after initialize()).

    ``dcn_axis`` names the axis that should map to the slowest link
    (across hosts/pods): it is laid out as the LEADING mesh dimension
    so consecutive devices along every other axis stay within one
    process's slice (ICI), and only the named axis crosses process
    boundaries (DCN). With ``-1`` sizes resolved as in make_mesh.
    """
    devices = jax.devices()
    if dcn_axis is not None and dcn_axis in axes:
        names = [dcn_axis] + [n for n in axes if n != dcn_axis]
        axes = {n: axes[n] for n in names}
    return make_mesh(axes, devices=devices)


def dm_slice_for_process(
    ndm: int, num_processes: int, process_id: int
) -> tuple[int, int]:
    """Contiguous, balanced [lo, hi) slice of the global DM-trial list
    for one process (the multi-host analogue of the reference's
    DMDispenser dealing trials to per-GPU workers,
    pipeline_multi.cu:54-74 — static dealing keeps it deterministic)."""
    base, extra = divmod(ndm, num_processes)
    lo = process_id * base + min(process_id, extra)
    return lo, lo + base + (1 if process_id < extra else 0)


def _allgather_pickled(payload: bytes, context: str = "") -> list[bytes]:
    """Exchange one pickled blob per process; returns every process's
    blob in process order. Single-process: identity.

    ``multihost.barrier`` is this collective's fault seam: a scheduled
    injection (or a real peer death surfacing as a distributed-runtime
    error) raises TRANSIENT here, so the step fails fast into the
    campaign retry budget instead of hanging at the barrier."""
    faults.fire("multihost.barrier", context=context)
    if jax.process_count() == 1:
        return [payload]
    import numpy as np
    from jax.experimental import multihost_utils

    try:
        # fixed-size exchange: lengths first, then the padded arrays
        n = np.frombuffer(payload, dtype=np.uint8)
        lens = multihost_utils.process_allgather(
            np.asarray([n.size], dtype=np.int64)
        ).reshape(-1)
        padded = np.zeros(int(lens.max()), dtype=np.uint8)
        padded[: n.size] = n
        blobs = multihost_utils.process_allgather(padded)
        return [bytes(blobs[i, : int(lens[i])]) for i in range(len(lens))]
    except TransientIOError:
        raise
    except Exception as exc:
        _classify_collective_error(exc, context or "allgather")
        raise  # unreachable (classify always raises); keeps mypy honest


class GangComm:
    """File-backed allgather for gang-scheduled campaign jobs: N worker
    PROCESSES without a JAX distributed runtime exchange pickled blobs
    through a shared gang directory (one per claim epoch under the
    job's directory), so the multi-host drivers below run unchanged —
    same slice/partial/merge/finalize code, same ``multihost.barrier``
    and ``multihost.merge`` fault seams — with this object supplying
    ``nprocs``/``rank``/``allgather`` instead of the JAX collectives.

    Each collective round writes ``r<round>.rank<k>`` (tmp + atomic
    rename) and waits for every rank's blob. A member that dies —
    SIGKILL, crash, or a peer aborting via :meth:`abort` — surfaces as
    a ``TransientIOError`` at the next barrier (never a hang), so the
    gang fails TRANSIENT as one unit and the job requeues as a single
    consumed attempt.
    """

    def __init__(
        self,
        gang_dir: str,
        nprocs: int,
        rank: int,
        timeout_s: float = 600.0,
        poll_s: float = 0.05,
        heartbeat=None,
    ) -> None:
        self.gang_dir = os.path.abspath(gang_dir)
        self.nprocs = int(nprocs)
        self.rank = int(rank)
        self.timeout_s = float(timeout_s)
        self.poll_s = float(poll_s)
        self._heartbeat = heartbeat  # called during waits (registry beat)
        self._round = 0
        os.makedirs(self.gang_dir, exist_ok=True)

    def _blob_path(self, rnd: int, rank: int) -> str:
        return os.path.join(self.gang_dir, f"r{rnd:03d}.rank{rank}")

    def abort(self, reason: str) -> None:
        """Mark the gang aborted so peers fail fast at their next
        barrier instead of running out the full timeout."""
        try:
            with open(
                os.path.join(self.gang_dir, f"abort.rank{self.rank}"), "w"
            ) as f:
                f.write(f"{reason}\n")
        except OSError:
            pass  # the timeout remains the backstop

    def _aborted(self) -> str | None:
        try:
            for name in os.listdir(self.gang_dir):
                if name.startswith("abort."):
                    return name
        except FileNotFoundError:
            return "gang directory removed"
        return None

    def allgather(
        self,
        payload: bytes,
        context: str = "",
        timeout_s: float | None = None,
    ) -> list[bytes]:
        """Exchange one blob per member; returns every member's blob in
        rank order. The ``multihost.barrier`` fault seam fires here,
        exactly as it does for the JAX-collective path."""
        import time as _time

        faults.fire("multihost.barrier", context=context)
        rnd = self._round
        self._round += 1
        tmp = self._blob_path(rnd, self.rank) + ".w"
        with open(tmp, "wb") as f:
            f.write(payload)
        os.replace(tmp, self._blob_path(rnd, self.rank))
        deadline = _time.monotonic() + (
            self.timeout_s if timeout_s is None else float(timeout_s)
        )
        last_beat = 0.0
        # the barrier wait is a span in the job's connected trace (a
        # no-op when the campaign runner has no tracer active): gang
        # stragglers become visible as long gang_barrier spans. Every
        # rank derives the SAME flow id from shared coordinates, so
        # Perfetto draws arrows linking the leader's barrier wait to
        # each member's span for the same round.
        with job_span(
            "gang_barrier", cat="sched",
            flow_id=flow_id_for(
                os.path.basename(self.gang_dir), context or "barrier", rnd
            ),
            context=context or "barrier", round=rnd, rank=self.rank,
        ):
            return self._await_round(rnd, context, deadline, last_beat)

    def _await_round(
        self, rnd: int, context: str, deadline: float, last_beat: float
    ) -> list[bytes]:
        import errno as _errno
        import time as _time

        while True:
            aborted = self._aborted()
            if aborted:
                raise TransientIOError(
                    _errno.ECONNRESET,
                    f"gang aborted ({aborted}) at {context or 'barrier'} "
                    f"round {rnd}",
                )
            try:
                present = [
                    os.path.exists(self._blob_path(rnd, k))
                    for k in range(self.nprocs)
                ]
            except OSError:
                present = [False]
            if all(present):
                out = []
                for k in range(self.nprocs):
                    try:
                        with open(self._blob_path(rnd, k), "rb") as f:
                            out.append(f.read())
                    except OSError as exc:
                        raise TransientIOError(
                            _errno.EIO,
                            f"gang blob unreadable at {context!r} round "
                            f"{rnd} rank {k}: {exc}",
                        ) from exc
                return out
            if _time.monotonic() > deadline:
                missing = [k for k, p in enumerate(present) if not p]
                raise TransientIOError(
                    _errno.ETIMEDOUT,
                    f"gang member(s) rank {missing} missing at "
                    f"{context or 'barrier'} round {rnd} (peer dead or "
                    "never assembled)",
                )
            now = _time.monotonic()
            if self._heartbeat is not None and now - last_beat > 0.5:
                last_beat = now
                try:
                    self._heartbeat()
                except Exception:
                    pass  # liveness beats are best-effort
            _time.sleep(self.poll_s)


def _unpickle_all(blobs: list[bytes], context: str = "") -> list:
    """Deserialise every process's blob — the merge step shared by the
    search/single-pulse/survey-fold drivers, and the ``multihost.merge``
    fault seam: a torn or injected failure while combining per-host
    results classifies TRANSIENT (the step re-runs whole)."""
    import pickle

    faults.fire("multihost.merge", context=context)
    try:
        return [pickle.loads(b) for b in blobs]
    except TransientIOError:
        raise
    except Exception as exc:
        _classify_collective_error(exc, context or "merge")
        raise


def _comm_topology(comm: "GangComm | None") -> tuple[int, int, "object"]:
    """(nprocs, rank, gather) for a driver: the JAX multi-process
    runtime by default, or a :class:`GangComm` when the campaign gang
    path supplies one (N worker processes coordinating through the
    shared filesystem instead of a coordinator)."""
    if comm is not None:
        return comm.nprocs, comm.rank, comm.allgather
    initialize()
    return (
        jax.process_count(),
        jax.process_index(),
        _allgather_pickled,
    )


def run_search(fil, config, comm: "GangComm | None" = None):
    """Multi-host `peasoup` search: DM-trial data parallelism across
    processes. Each process dedisperses + searches its contiguous slice
    of the global DM list on its LOCAL chips (share-nothing, like the
    reference's per-GPU workers), then per-DM candidates are allgathered
    over DCN and every process runs the identical global
    distill/score/fold finalize — folds are computed by the trial's
    owner process and exchanged, so the final candidate list is
    identical (and deterministic) on every process. With ``comm`` (a
    gang-scheduled campaign job) the same driver runs over the
    file-backed exchange instead of the JAX collectives.

    Single-process: exactly PeasoupSearch(config).run(fil).
    """
    import pickle

    from ..pipeline.search import PartialSearchResult, PeasoupSearch

    # topology first: jax.distributed.initialize() must run before
    # the search constructor touches the backend (device discovery)
    nproc, rank, gather = _comm_topology(comm)
    search = PeasoupSearch(config)
    if nproc == 1:
        return search.run(fil)

    plan = search.build_dm_plan(fil)
    lo, hi = dm_slice_for_process(plan.ndm, nproc, rank)
    log.info(
        "multi-host search: process %d/%d owns DM trials [%d, %d) of %d",
        rank, nproc, lo, hi, plan.ndm,
    )
    # tag this host's telemetry so its manifest shard self-identifies
    # (tools/report.py --merge keys hosts on process_index/hostname)
    tel = current_telemetry()
    tel.set_context(
        process_index=int(rank),
        process_count=int(nproc),
        hostname=socket.gethostname(),
        dm_slice=[int(lo), int(hi)],
    )
    tel.event(
        "multihost_slice", processes=nproc,
        process=rank, dm_lo=lo, dm_hi=hi,
        ndm=int(plan.ndm),
    )
    part = search.run(fil, dm_slice=(lo, hi), finalize=False)

    blobs = gather(
        pickle.dumps((part.cands, part.n_accel_trials)),
        context="search:candidates",
    )
    merged_cands, n_trials = [], 0
    # process order == ascending DM slices
    for cands, n in _unpickle_all(blobs, context="search:candidates"):
        merged_cands.extend(cands)
        n_trials += n
    merged = PartialSearchResult(
        cands=merged_cands,
        trials=part.trials,
        trials_nsamps=part.trials_nsamps,
        dm_offset=part.dm_offset,
        dm_list=plan.dm_list,  # global
        acc_list_dm0=part.acc_list_dm0,
        timers=part.timers,
        nsamps=part.nsamps,
        size=part.size,
        n_accel_trials=n_trials,
        t_total_start=part.t_total_start,
    )

    def fold_exchange(outcomes: list[dict]) -> list[dict]:
        out = []
        blobs = gather(
            pickle.dumps(outcomes), context="search:folds"
        )
        for piece in _unpickle_all(blobs, context="search:folds"):
            out.extend(piece)
        return out

    return search.finalize(fil, merged, fold_exchange=fold_exchange)


def run_fdas_search(fil, config, comm: "GangComm | None" = None):
    """Multi-host `peasoup-fdas`: DM-trial data parallelism across
    processes, mirroring :func:`run_search`. Each process dedisperses +
    correlation-searches its contiguous slice of the global DM list on
    its LOCAL chips (the template bank is identical everywhere — it
    depends only on the (zmax, wmax) geometry), the per-DM distilled
    candidates (GLOBAL dm_idx) are allgathered, and every process runs
    the identical global distill/score finalize, so the final list is
    deterministic on every process; the CLI's rank 0 writes it. With
    ``comm`` (a gang-scheduled campaign job) the same driver runs over
    the file-backed exchange. No fold exchange: FDAS does not fold.

    Single-process: exactly FdasSearch(config).run(fil).
    """
    import pickle

    from ..pipeline.fdas import FdasSearch, PartialFdasResult

    # topology first: jax.distributed.initialize() must run before
    # the search constructor touches the backend (device discovery)
    nproc, rank, gather = _comm_topology(comm)
    search = FdasSearch(config)
    if nproc == 1:
        return search.run(fil)

    plan = search.build_dm_plan(fil)
    lo, hi = dm_slice_for_process(plan.ndm, nproc, rank)
    log.info(
        "multi-host FDAS: process %d/%d owns DM trials [%d, %d) of %d",
        rank, nproc, lo, hi, plan.ndm,
    )
    tel = current_telemetry()
    tel.set_context(
        process_index=int(rank),
        process_count=int(nproc),
        hostname=socket.gethostname(),
        dm_slice=[int(lo), int(hi)],
    )
    tel.event(
        "multihost_slice", processes=nproc,
        process=rank, dm_lo=lo, dm_hi=hi,
        ndm=int(plan.ndm),
    )
    part = search.run(fil, dm_slice=(lo, hi), finalize=False)

    blobs = gather(
        pickle.dumps((part.cands, part.n_trials)),
        context="fdas:candidates",
    )
    merged_cands, n_trials = [], 0
    # process order == ascending DM slices
    for cands, n in _unpickle_all(blobs, context="fdas:candidates"):
        merged_cands.extend(cands)
        n_trials += n
    merged = PartialFdasResult(
        cands=merged_cands,
        dm_offset=part.dm_offset,
        dm_list=plan.dm_list,  # global
        zs=part.zs,
        ws=part.ws,
        timers=part.timers,
        nsamps=part.nsamps,
        size=part.size,
        n_templates=part.n_templates,
        n_trials=n_trials,
        t_total_start=part.t_total_start,
    )
    return search.finalize(fil, merged)


def run_single_pulse_search(fil, config, comm: "GangComm | None" = None):
    """Multi-host `spsearch`: DM-trial data parallelism across
    processes, mirroring :func:`run_search`. Each process dedisperses +
    boxcar-searches its contiguous slice of the global DM list on its
    LOCAL chips, the raw above-threshold events (GLOBAL dm_idx) are
    allgathered over DCN, and every process runs the identical global
    friends-of-friends clustering — so a pulse whose DM footprint
    spans a slice boundary still clusters as ONE candidate, and the
    final list is identical (and deterministic) on every process; the
    CLI's rank 0 writes it. With ``comm`` (a gang-scheduled campaign
    job) the same driver runs over the file-backed exchange.

    Single-process: exactly SinglePulseSearch(config).run(fil).
    """
    import pickle

    from ..pipeline.single_pulse import (
        PartialSinglePulseResult,
        SinglePulseSearch,
    )

    # topology first: jax.distributed.initialize() must run before
    # the search constructor touches the backend (device discovery)
    nproc, rank, gather = _comm_topology(comm)
    search = SinglePulseSearch(config)
    if nproc == 1:
        return search.run(fil)

    plan = search.build_dm_plan(fil)
    lo, hi = dm_slice_for_process(plan.ndm, nproc, rank)
    log.info(
        "multi-host spsearch: process %d/%d owns DM trials [%d, %d) "
        "of %d", rank, nproc, lo, hi, plan.ndm,
    )
    tel = current_telemetry()
    tel.set_context(
        process_index=int(rank),
        process_count=int(nproc),
        hostname=socket.gethostname(),
        dm_slice=[int(lo), int(hi)],
    )
    tel.event(
        "multihost_slice", processes=nproc,
        process=rank, dm_lo=lo, dm_hi=hi,
        ndm=int(plan.ndm),
    )
    part = search.run(fil, dm_slice=(lo, hi), finalize=False)

    # the event allgather: tiny payloads (<= max_events per trial),
    # process order == ascending DM slices so the merged set is
    # deterministic
    import numpy as np

    blobs = gather(
        pickle.dumps((part.events, part.n_overflowed)),
        context="spsearch:events",
    )
    all_events, n_overflowed = [], 0
    for ev, novf in _unpickle_all(blobs, context="spsearch:events"):
        all_events.append(ev)
        n_overflowed += int(novf)
    merged = PartialSinglePulseResult(
        events=np.concatenate(all_events),
        dm_list=plan.dm_list,  # global
        widths=part.widths,
        timers=part.timers,
        nsamps=part.nsamps,
        n_overflowed=n_overflowed,
        t_total_start=part.t_total_start,
    )
    return search.finalize(fil, merged)


def run_survey_fold(observations, folder) -> list[dict]:
    """Multi-host survey folding (peasoup_tpu/sift/fold.py):
    observation-level data parallelism. Observations are dealt
    round-robin to processes (coarse but deterministic balancing — the
    fold cost of an observation scales with its candidate count, which
    round-robin spreads), each process batch-folds its share on LOCAL
    chips, and the outcome dicts are allgathered over DCN in process
    order so every process returns the identical full outcome list.

    Single-process: exactly ``folder.fold_outcomes(observations)``.
    """
    import pickle

    initialize()
    nproc = jax.process_count()
    if nproc == 1:
        return folder.fold_outcomes(observations)
    rank = jax.process_index()
    mine = observations[rank::nproc]
    log.info(
        "multi-host survey fold: process %d/%d folds %d of %d "
        "observations", rank, nproc, len(mine), len(observations),
    )
    current_telemetry().event(
        "multihost_fold", processes=nproc, process=rank,
        observations=len(mine), total=len(observations),
    )
    outcomes = folder.fold_outcomes(mine)
    merged: list[dict] = []
    blobs = _allgather_pickled(
        pickle.dumps(outcomes), context="survey_fold:outcomes"
    )
    for piece in _unpickle_all(blobs, context="survey_fold:outcomes"):
        merged.extend(piece)
    return merged


def process_local_slice(mesh: Mesh, axis: str) -> tuple[int, int]:
    """The [start, stop) block of ``axis`` whose shards live on THIS
    process — the host-side work partition for feeding per-process
    data (e.g. which DM trials this host should stage).

    Derived from the mesh's ACTUAL device layout: an axis index is
    local when any device in its hyperplane belongs to this process
    (an axis that does not cross processes is therefore fully local
    on every host). Requires the local indices to be contiguous,
    which the leading-DCN-axis layout of global_mesh guarantees."""
    import numpy as np

    pid = jax.process_index()
    axis_pos = mesh.axis_names.index(axis)
    planes = np.moveaxis(mesh.devices, axis_pos, 0)
    local = np.asarray(
        [
            any(d.process_index == pid for d in np.ravel(plane))
            for plane in planes
        ]
    )
    idxs = np.nonzero(local)[0]
    if idxs.size == 0:
        return 0, 0
    lo, hi = int(idxs[0]), int(idxs[-1]) + 1
    if idxs.size != hi - lo:
        raise ValueError(
            f"axis {axis!r} is not contiguous across this process; "
            "lay the cross-process axis leading (global_mesh dcn_axis)"
        )
    return lo, hi
