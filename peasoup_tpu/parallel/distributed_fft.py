"""Distributed FFT over a TPU mesh axis (sequence parallelism).

The reference never splits a time series: each GPU holds the whole
series (up to 2^23 samples, SURVEY.md §5 "long-context analogue") and
scaling is across the trial grid only. On TPU the equivalent limit is
one chip's HBM; this module removes it with a four-step (Bailey)
decomposition of the DFT across the mesh's sequence axis, turning the
cross-chip data movement into ONE all-to-all over ICI:

  x viewed as (N1, N2), n = n1*N2 + n2, sharded over n2 (columns):
    1. local FFT over n1 (each chip holds all rows of its columns)
    2. local twiddle multiply  exp(-2*pi*i * n2 * k1 / N)
    3. all-to-all transpose: shards of k1 rows replace shards of n2
    4. local FFT over n2
  giving X[k2*N1 + k1] laid out as rows k1 (sharded), columns k2.

A real-input transform packs even/odd samples into a complex series of
half the length (the classic R2C doubling trick), re-shards the
shuffled output to natural frequency order with a second all-to-all,
and untangles the conjugate-symmetric halves with two ppermutes (the
mirrored blocks + the one-element seam). Total cross-chip traffic for
an rfft: two all_to_alls + two ppermutes, all over ICI.

These functions are meant to be called INSIDE shard_map (they use
axis_index/all_to_all/ppermute); `distributed_fft`/`distributed_rfft`
wrap them for whole-array use on a mesh.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P


def _fft_local_steps(x_cols: jax.Array, n1: int, n2: int, axis: str):
    """Steps 1-4 on one chip's column block (n1, n2/P) -> row block
    (n1/P, n2) of X[k2*n1 + k1]."""
    p = jax.lax.axis_size(axis)
    me = jax.lax.axis_index(axis)
    cols = n2 // p

    # 1. local FFT along n1 (columns fully resident)
    w = jnp.fft.fft(x_cols, axis=0)  # rows now k1
    # 2. twiddle exp(-2i pi n2 k1 / N); n2 are this chip's global columns
    k1 = jnp.arange(n1)[:, None]
    n2_global = me * cols + jnp.arange(cols)[None, :]
    tw = jnp.exp((-2j * jnp.pi / (n1 * n2)) * (k1 * n2_global))
    w = w * tw.astype(w.dtype)
    # 3. all-to-all transpose: k1 blocks out, n2 blocks in
    w = jax.lax.all_to_all(w, axis, split_axis=0, concat_axis=1, tiled=True)
    # 4. local FFT along n2 (now fully resident)
    return jnp.fft.fft(w, axis=1)  # (n1/p, n2): rows k1 block, cols k2


def fft_sharded(x_cols: jax.Array, n: int, axis: str) -> jax.Array:
    """C2C DFT of a length-``n`` series inside shard_map.

    Args:
      x_cols: this chip's (n1, n2/P) column block of x viewed as
        (n1, n2) row-major with n1 = P (one row block per chip).
      n: total length (= n1*n2).
      axis: mesh axis name to decompose over.

    Returns this chip's (1, n2) row block of X arranged [k1, k2] with
    flat index k = k2*n1 + k1 (use unshuffle_fft_order for natural
    order).
    """
    n1 = x_cols.shape[0]
    return _fft_local_steps(x_cols, n1, n // n1, axis)


def unshuffle_fft_order(x_rows: np.ndarray) -> np.ndarray:
    """Host helper: gathered (n1, n2) [k1, k2] layout -> natural X[k]
    (k = k2*n1 + k1 means natural order is the column-major flatten)."""
    return np.asarray(x_rows).T.reshape(-1)


@partial(jax.jit, static_argnames=("mesh", "axis"))
def distributed_fft(x: jax.Array, mesh: Mesh, axis: str = "seq") -> jax.Array:
    """C2C FFT of a 1-D complex array over a mesh axis.

    The array is laid out (n1=P, n2) row-major and sharded by columns;
    output is the (n1, n2) [k1, k2] matrix sharded by rows (flat index
    k = k2*n1 + k1). One all_to_all crosses chips.
    """
    p = mesh.shape[axis]
    n = x.shape[-1]
    if n % (p * p):
        raise ValueError(f"n={n} must be divisible by P^2={p*p}")
    x2 = x.reshape(p, n // p).astype(jnp.complex64)
    fn = jax.shard_map(
        partial(fft_sharded, n=n, axis=axis),
        mesh=mesh,
        in_specs=P(None, axis),
        out_specs=P(axis, None),
    )
    return fn(x2)


def rfft_sharded(z_cols: jax.Array, n: int, axis: str) -> jax.Array:
    """R2C DFT inside shard_map via the even/odd packing trick.

    Args:
      z_cols: (n1, m2/P) column block of z[j] = x[2j] + i*x[2j+1]
        viewed as (n1, m2) with m = n/2 = n1*m2.
      n: REAL series length.

    Returns this chip's (m/P,) block of the half-spectrum X[0:m], where
    m = n/2, in NATURAL frequency order sharded contiguously over chips.
    (The rfft's bin m is X[m] = Re(Z[0]) - Im(Z[0]) if needed; bins
    m+1..n-1 are the conjugate mirror.)
    """
    p = jax.lax.axis_size(axis)
    me = jax.lax.axis_index(axis)
    n1 = z_cols.shape[0]
    m = n // 2
    m2 = m // n1

    zf = _fft_local_steps(z_cols, n1, m2, axis)  # (n1/p, m2) [k1, k2]
    # natural-order contiguous block: k = k2*n1 + k1 for k1 in my row
    # block — NOT contiguous. Re-shard to contiguous blocks of Z with an
    # all_to_all on k2: Z block b holds k in [b*m/p, (b+1)*m/p).
    # zf[k1_local, k2] -> flat k = k2*n1 + (me*n1/p + k1_local).
    # Split k2 into p chunks of m2/p -> chunk c covers k in
    # [c*(m/p) ... ) interleaved by k1; after all_to_all each chip has
    # all k1 for its k2 chunk -> transpose locally to natural order.
    za = jax.lax.all_to_all(zf, axis, split_axis=1, concat_axis=0, tiled=True)
    # za: (n1, m2/p) = all k1 rows, my k2 chunk
    z_nat = za.T.reshape(-1)  # flat k = k2_local*n1 + k1, k2 ascending

    # untangle R2C: X[k] = (Z[k] + conj(Z[(m-k) mod m]))/2
    #                     - (i/2) e^{-2 pi i k/n} (Z[k] - conj(Z[(m-k) mod m]))
    # need the mirrored block Z[(m-k) mod m]: for my k block
    # [me*L, me*L+L), mirrors live in blocks (p-1-me) shifted by one
    # sample -> one ppermute + local roll, plus Z[0]'s special seam.
    L = m // p
    # chip me's k block [me*L, me*L+L) needs mirrors (m-k) mod m for
    # t = k - me*L >= 1: these are k' = b*L + (L-t) for b = p-1-me, so
    # block b's whole tail — ppermute source j -> dest p-1-j
    mirror = jax.lax.ppermute(
        z_nat, axis, [(j, p - 1 - j) for j in range(p)]
    )
    # the t=0 seam element is Z[(m - me*L) mod m] = Z[j*L] for
    # j = (p-me) % p, i.e. chip j's FIRST element -> second ppermute
    first = jax.lax.ppermute(
        z_nat[:1], axis, [(j, (p - j) % p) for j in range(p)]
    )
    # conj(Z[(m-k) mod m]) for k = me*L + t:
    #   t=0 -> 'first'; t>=1 -> mirror[L-t] = flip(mirror)[t-1]
    zm = jnp.concatenate([first, jnp.flip(mirror)[: L - 1]])
    zmc = jnp.conj(zm)

    k_global = me * L + jnp.arange(L)
    even = 0.5 * (z_nat + zmc)
    odd = -0.5j * (z_nat - zmc)
    wk = jnp.exp((-2j * jnp.pi / n) * k_global)
    xk = even + wk * odd
    # k = 0 must be Re(Z[0]) + Im(Z[0]) (whole-series DC): the formula
    # above already gives it since Z[(m-0)%m]=Z[0]; no special case.
    return xk


@partial(jax.jit, static_argnames=("mesh", "axis"))
def distributed_rfft(x: jax.Array, mesh: Mesh, axis: str = "seq") -> jax.Array:
    """First n/2 bins of rfft(x) for real x, sharded contiguously.

    Output matches jnp.fft.rfft(x)[: n//2] (the Nyquist bin is dropped;
    the search pipeline never uses it on its own).
    """
    p = mesh.shape[axis]
    n = x.shape[-1]
    m = n // 2
    if n % 2 or m % (p * p):
        raise ValueError(f"n={n}: n/2 must be divisible by P^2={p*p}")
    z = x[0::2] + 1j * x[1::2].astype(jnp.float32)
    z2 = z.reshape(p, m // p).astype(jnp.complex64)
    fn = jax.shard_map(
        partial(rfft_sharded, n=n, axis=axis),
        mesh=mesh,
        in_specs=P(None, axis),
        out_specs=P(axis),
    )
    return fn(z2)
