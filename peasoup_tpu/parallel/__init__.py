from .mesh import make_mesh, device_count
from . import multihost
from .sharded_search import make_sharded_search_fn
from .coincidence import baseline_beam, sharded_coincidence
from .distributed_fft import (
    distributed_fft,
    distributed_rfft,
    unshuffle_fft_order,
)
