"""`peasoup-stream` — streaming real-time single-pulse search CLI.

The batch CLIs are jobs; this is the pipeline as a long-lived service
(ROADMAP "streaming real-time mode"): ingest an endless filterbank /
voltage stream in fixed chunks, dedisperse + boxcar-search each with
carried-over state, and emit triggers within a latency budget. Three
source modes:

  # replay a recorded filterbank at 4x real time (deterministic
  # testing / capacity qualification; --rate 0 = as fast as possible)
  python -m peasoup_tpu.cli.stream --replay data.fil --rate 4 -o out/

  # tail a growing .fil a recorder is appending to
  python -m peasoup_tpu.cli.stream --tail /data/live.fil -o out/

  # consume PSRDADA-style .dada segment files from a ring dump dir
  python -m peasoup_tpu.cli.stream --dada /data/ring/ -o out/

Outputs (all updated live, not at exit):
  triggers.jsonl           one JSON line per confirmed trigger
  candidates.singlepulse   rolling top-N table (batch format)
  telemetry.json           run manifest with a "streaming" section
  status.json (--status-json) heartbeat with live latency/queue/drop
                           fields — tail with python -m
                           peasoup_tpu.tools.watch
"""

from __future__ import annotations

import argparse
import os
import sys
import time

from . import (
    add_observability_args,
    add_version_arg,
    init_observability,
    live_observability,
)


def default_outdir() -> str:
    return time.strftime("./%Y-%m-%d-%H:%M_stream/", time.gmtime())


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="peasoup-stream",
        description="Peasoup-TPU streaming real-time single-pulse "
        "search - bounded-latency chunked ingest with backpressure "
        "and live triggers",
    )
    src = p.add_mutually_exclusive_group(required=True)
    src.add_argument(
        "--replay", metavar="FIL",
        help="replay a recorded filterbank (deterministic testing)",
    )
    src.add_argument(
        "--tail", metavar="FIL",
        help="tail a growing sigproc filterbank file",
    )
    src.add_argument(
        "--dada", metavar="PATH",
        help="consume PSRDADA-style .dada segments (file or directory)",
    )
    p.add_argument(
        "--rate", type=float, default=1.0,
        help="replay real-time factor (--replay only): 2 = twice real "
        "time, 0 = as fast as the search drains (default 1)",
    )
    p.add_argument("-o", "--outdir", default=None,
                   help="The output directory")
    p.add_argument("-k", "--killfile", default="", help="Channel mask file")
    p.add_argument("--dm_start", type=float, default=0.0)
    p.add_argument("--dm_end", type=float, default=100.0)
    p.add_argument("--dm_tol", type=float, default=1.10,
                   help="DM smearing tolerance (1.11=10%%)")
    p.add_argument("--dm_pulse_width", type=float, default=64.0,
                   help="Minimum pulse width (us) for which dm_tol is valid")
    p.add_argument("-m", "--min_snr", type=float, default=6.0,
                   help="single-pulse S/N threshold")
    p.add_argument(
        "--n_widths", type=int, default=12,
        help="number of octave-spaced boxcar widths (1..2^(n-1) samples)",
    )
    p.add_argument(
        "--max_width", type=int, default=0,
        help="cap on the widest boxcar (samples; 0 = n_widths and "
        "quarter-chunk caps only)",
    )
    p.add_argument(
        "--max_events", type=int, default=256,
        help="static per-DM-trial per-chunk event-compaction size",
    )
    p.add_argument(
        "--decimate", type=int, default=32,
        help="best-plane max-decimation factor (chunk and hold must "
        "be multiples of this)",
    )
    p.add_argument(
        "--time_link", type=float, default=1.0,
        help="friends-of-friends time tolerance in units of the wider "
        "member's boxcar width",
    )
    p.add_argument(
        "--dm_link", type=int, default=2,
        help="friends-of-friends DM-trial adjacency tolerance",
    )
    p.add_argument("--limit", type=int, default=1000,
                   help="rolling candidates.singlepulse table size")
    g = p.add_argument_group("streaming")
    g.add_argument(
        "--chunk", dest="chunk_samples", type=int, default=16384,
        help="dedispersed samples per search chunk (default 16384)",
    )
    g.add_argument(
        "--hold", dest="hold_samples", type=int, default=0,
        help="carried-tail samples across chunk boundaries (0 = auto "
        "from the widest boxcar)",
    )
    g.add_argument(
        "--block-samples", dest="block_samples", type=int, default=0,
        help="source block size in samples (default chunk/4)",
    )
    g.add_argument(
        "--queue-blocks", dest="queue_blocks", type=int, default=8,
        help="bounded ingest queue capacity in blocks (default 8)",
    )
    g.add_argument(
        "--policy", choices=("block", "drop_oldest"), default="block",
        help="backpressure policy when the queue fills: block the "
        "reader (lossless, falls behind) or drop_oldest (bounded "
        "latency, accounted sensitivity loss)",
    )
    g.add_argument(
        "--latency-slo", dest="latency_slo_s", type=float, default=2.0,
        help="per-chunk arrival->trigger latency budget in seconds "
        "(misses are counted + evented, never fatal; default 2)",
    )
    g.add_argument(
        "--max-chunks", dest="max_chunks", type=int, default=0,
        help="stop after N chunks (0 = run to stream end)",
    )
    g.add_argument(
        "--metrics-jsonl", dest="metrics_jsonl", default="",
        help="append-only time-series metrics file (chunk latency, "
        "queue depth, trigger counts; obs/metrics.py — read with "
        "`peasoup-campaign metrics` tooling or Prometheus); default "
        "off",
    )
    g.add_argument(
        "--no-warmup", dest="no_warmup", action="store_true",
        help="skip the AOT warmup of the chunk programs before ingest",
    )
    g.add_argument(
        "--idle-timeout", dest="idle_timeout_s", type=float, default=10.0,
        help="tail/dada modes: end the stream after this many seconds "
        "without new data (default 10)",
    )
    p.add_argument("-v", "--verbose", action="store_true")
    add_version_arg(p)
    add_observability_args(p)
    return p


def make_source(args, block_samples: int):
    """Resolve the source mode into a StreamSource."""
    from ..io.stream_source import (
        DadaStreamSource,
        FileTailSource,
        ReplaySource,
    )

    if args.replay:
        from ..io.sigproc import read_filterbank

        return ReplaySource(
            read_filterbank(args.replay), block_samples, rate=args.rate
        )
    if args.tail:
        return FileTailSource(
            args.tail, block_samples,
            idle_timeout_s=args.idle_timeout_s,
        )
    return DadaStreamSource(
        args.dada, block_samples, idle_timeout_s=args.idle_timeout_s
    )


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    outdir = (args.outdir or default_outdir()).rstrip("/")
    from .peasoup import apply_platform_env

    apply_platform_env()
    tel = init_observability(args)
    tel.set_context(
        command="stream", outdir=outdir,
        source=args.replay or args.tail or args.dada,
        mode="replay" if args.replay else
        "tail" if args.tail else "dada",
    )
    manifest_path = args.metrics_json or os.path.join(
        outdir, "telemetry.json"
    )

    # Heavy imports after arg parsing so --help/--version stay fast
    from ..stream import StreamConfig, StreamingSearch

    block_samples = args.block_samples or max(
        args.decimate, args.chunk_samples // 4
    )
    cfg = StreamConfig(
        outdir=outdir,
        killfilename=args.killfile,
        dm_start=args.dm_start,
        dm_end=args.dm_end,
        dm_tol=args.dm_tol,
        dm_pulse_width=args.dm_pulse_width,
        min_snr=args.min_snr,
        n_widths=args.n_widths,
        max_width=args.max_width,
        max_events=args.max_events,
        decimate=args.decimate,
        time_link=args.time_link,
        dm_link=args.dm_link,
        limit=args.limit,
        chunk_samples=args.chunk_samples,
        hold_samples=args.hold_samples,
        queue_blocks=args.queue_blocks,
        policy=args.policy,
        latency_slo_s=args.latency_slo_s,
        max_chunks=args.max_chunks,
        warmup=not args.no_warmup,
        metrics_jsonl=args.metrics_jsonl,
    )
    os.makedirs(outdir, exist_ok=True)
    with tel.activate(), live_observability(
        tel, args, outdir, manifest_path
    ):
        source = make_source(args, block_samples)
        result = StreamingSearch(cfg).run(source)
        tel.merge_timers(result.timers)
        tel.gauge("candidates.written", len(result.candidates))
        tel.set_stage("done")
        tel.write(manifest_path)
    if args.verbose:
        lat = result.latency
        print(
            f"Stream drained: {result.n_chunks} chunks, "
            f"{result.n_triggers} triggers -> {outdir} "
            f"(p95 latency "
            f"{(lat.get('p95') or 0.0) * 1e3:.0f} ms vs SLO "
            f"{cfg.latency_slo_s * 1e3:.0f} ms; "
            f"{result.drops.get('blocks', 0)} dropped blocks; "
            f"{result.jit_programs_steady} steady-state recompiles)"
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
