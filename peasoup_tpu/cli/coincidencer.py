"""`coincidencer` CLI: build multibeam RFI masks/birdie lists by
coincidence-matching zero-DM time series and spectra across beams.

Reference: src/coincidencer.cpp. Per beam: dedisperse at DM=0,
deredden + normalise the spectrum AND the time series; then count, per
sample/bin, how many beams exceed a threshold — samples firing in >=
beam_thresh beams are multibeam RFI. Outputs a 0/1 sample mask and a
(freq, width) birdie list derived from zero-runs of the spectral mask
(include/transforms/coincidencer.hpp:42-78).

TPU design: beams stack on a leading axis; per-beam baselining is one
vmapped jitted program, and the coincidence count is a beam-axis
reduction (psum over a mesh axis when beams are sharded across chips —
see peasoup_tpu.parallel.coincidence).
"""

from __future__ import annotations

import argparse
import sys

import numpy as np

from . import (
    add_observability_args,
    add_version_arg,
    init_observability,
    live_observability,
)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="coincidencer",
        description="Peasoup-TPU multibeam coincidence RFI detector",
    )
    p.add_argument("filterbanks", nargs="+", help="File names")
    p.add_argument("--o", dest="samp_outfilename", default="rfi.eb_mask",
                   help="Sample mask output filename")
    p.add_argument("--o2", dest="spec_outfilename", default="birdies.txt",
                   help="Birdie list output filename")
    p.add_argument("-l", "--boundary_5_freq", type=float, default=0.05)
    p.add_argument("-a", "--boundary_25_freq", type=float, default=0.5)
    p.add_argument("-n", "--nharmonics", type=int, default=4)
    p.add_argument("--thresh", type=float, default=4.0,
                   help="S/N threshold for coincidence matching")
    p.add_argument("--beam_thresh", type=int, default=4,
                   help="Beams a candidate must appear in to be multibeam")
    p.add_argument("-L", "--min_freq", type=float, default=0.1)
    p.add_argument("-H", "--max_freq", type=float, default=1100.0)
    p.add_argument("-b", "--max_harm", type=int, default=16)
    p.add_argument("-f", "--freq_tol", type=float, default=0.0001)
    p.add_argument("-v", "--verbose", action="store_true")
    add_version_arg(p)
    add_observability_args(p)
    return p


def write_samp_mask(mask: np.ndarray, filename: str) -> None:
    with open(filename, "w") as fo:
        fo.write("#0 1\n")
        for v in mask:
            fo.write(f"{int(v)}\n")


def birdies_from_mask(mask: np.ndarray, bin_width: float) -> list[tuple[float, float]]:
    """Zero-runs of the spectral mask -> (freq, width) rows
    (coincidencer.hpp:53-72)."""
    birdies = []
    ii = 0
    size = len(mask)
    while ii < size:
        if mask[ii] == 0:
            count = 0
            while ii < size and mask[ii] == 0:
                count += 1
                ii += 1
            birdies.append((((ii - 1) - count / 2.0) * bin_width, count * bin_width))
        else:
            ii += 1
    return birdies


def write_birdie_list(
    mask: np.ndarray, bin_width: float, filename: str
) -> None:
    with open(filename, "w") as fo:
        for freq, width in birdies_from_mask(mask, bin_width):
            fo.write(f"{freq:.9f}\t{width:.6f}\n")


def main(argv: list[str] | None = None) -> int:
    import os

    args = build_parser().parse_args(argv)

    from .peasoup import apply_platform_env

    apply_platform_env()
    tel = init_observability(args)
    tel.set_context(
        command="coincidencer", n_beams=len(args.filterbanks)
    )
    workdir = (
        os.path.dirname(args.metrics_json or args.samp_outfilename)
        or "."
    )
    manifest_path = args.metrics_json or os.path.join(
        workdir, "telemetry.json"
    )

    import jax.numpy as jnp

    from ..io.sigproc import read_filterbank
    from ..ops.coincidence import coincidence_mask
    from ..parallel.coincidence import baseline_beam
    from ..plan.dm_plan import DMPlan

    with tel.activate(), live_observability(
        tel, args, workdir,
        manifest_path if (args.metrics_json or args.status_json) else None,
    ):
        tims = []
        tsamp = None
        n_beams = len(args.filterbanks)
        with tel.stage("reading"):
            for i, path in enumerate(args.filterbanks):
                if args.verbose:
                    print(f"Reading and dedispersing {path}")
                tel.set_progress(i, n_beams, unit="beams")
                fil = read_filterbank(path)
                plan = DMPlan.create(
                    nsamps=fil.nsamps, nchans=fil.nchans, tsamp=fil.tsamp,
                    fch1=fil.fch1, foff=fil.foff, dm_start=0.0, dm_end=0.0,
                    pulse_width=0.4, tol=1.1,
                )
                from ..ops.dedisperse import dedisperse, output_scale

                trial = dedisperse(
                    fil.data, plan.delay_samples(), plan.killmask,
                    plan.out_nsamps,
                    scale=output_scale(fil.nbits, fil.nchans),
                )[0]
                tims.append(trial)
                tsamp = fil.tsamp
        sizes = {len(t) for t in tims}
        if len(sizes) != 1:
            raise SystemExit("Not all filterbanks the same length")
        # the reference uses the FULL dedispersed length, not a power of
        # two (coincidencer.cpp:136); jnp.fft handles arbitrary sizes
        size = sizes.pop()
        tobs = size * tsamp
        bin_width = 1.0 / tobs
        pos5 = int(args.boundary_5_freq / bin_width)
        pos25 = int(args.boundary_25_freq / bin_width)

        specs, series = [], []
        with tel.device_capture():
            with tel.stage("baselining"):
                for i, t in enumerate(tims):
                    if args.verbose:
                        print("Baselining beam")
                    tel.set_progress(n_beams + i, 2 * n_beams, unit="beams")
                    spec, tim = baseline_beam(
                        jnp.asarray(t[:size]), size=size,
                        pos5=pos5, pos25=pos25,
                    )
                    specs.append(np.asarray(spec))
                    series.append(np.asarray(tim))

            if args.verbose:
                print("Performing cross beam coincidence matching")
            with tel.stage("coincidence"):
                samp_mask = np.asarray(
                    coincidence_mask(
                        jnp.asarray(np.stack(series)), args.thresh,
                        args.beam_thresh,
                    )
                )
                spec_mask = np.asarray(
                    coincidence_mask(
                        jnp.asarray(np.stack(specs)), args.thresh,
                        args.beam_thresh,
                    )
                )
        tel.set_progress(2 * n_beams, 2 * n_beams, unit="beams")
    write_samp_mask(samp_mask, args.samp_outfilename)
    write_birdie_list(spec_mask, bin_width, args.spec_outfilename)
    tel.gauge("mask.samples_flagged", int((samp_mask == 0).sum()))
    tel.gauge("mask.bins_flagged", int((spec_mask == 0).sum()))
    if args.metrics_json:
        tel.write(args.metrics_json)
    if args.verbose:
        print(f"Wrote {args.samp_outfilename} and {args.spec_outfilename}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
