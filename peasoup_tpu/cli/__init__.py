"""CLI entry points.

Shared observability wiring: every CLI (`peasoup`, `peasoup-ffa`,
`coincidencer`) grows the same flags — ``--log-level`` (stderr library
logging), ``--metrics-json`` (the telemetry.json run manifest),
``--capture-device-trace`` (per-scope device attribution folded into
the manifest), ``--status-json`` / ``--heartbeat-interval`` (the live
status.json heartbeat + stall watchdog), ``--no-flight-recorder``
(the crash flight recorder is ON by default) — resolved here so flag
names and semantics can't drift between tools.
"""

from __future__ import annotations

import argparse
import contextlib
import os


class _VersionAction(argparse.Action):
    """--version for every CLI: package version, JAX version, and the
    active backend — the first three facts every bug report needs.
    Imports stay lazy so ``--help`` never pays for a backend init."""

    def __call__(self, parser, namespace, values, option_string=None):
        from .. import __version__

        try:
            import jax

            jax_version = jax.__version__
            try:
                backend = jax.default_backend()
            except Exception as exc:  # no usable backend is still a fact
                backend = f"unavailable ({type(exc).__name__})"
        except Exception:
            jax_version = backend = "unavailable"
        print(
            f"peasoup_tpu {__version__} (jax {jax_version}, "
            f"backend {backend})"
        )
        parser.exit(0)


def add_version_arg(p) -> None:
    """Wire the shared --version flag (see _VersionAction)."""
    p.add_argument(
        "--version", action=_VersionAction, nargs=0,
        help="print package version, JAX version, and active backend, "
        "then exit",
    )


def add_observability_args(p) -> None:
    g = p.add_argument_group("observability")
    g.add_argument(
        "--log-level", dest="log_level", default=None,
        choices=["debug", "info", "warning", "error"],
        help="library log threshold (messages go to stderr; default "
        "warning, or info with -v; PEASOUP_LOG_LEVEL also works)",
    )
    g.add_argument(
        "--metrics-json", dest="metrics_json", default=None,
        help="path for the telemetry.json run manifest (peasoup "
        "defaults to <outdir>/telemetry.json; the other tools write "
        "one only when this flag is given). Render/diff with "
        "python -m peasoup_tpu.tools.report",
    )
    g.add_argument(
        "--capture-device-trace", dest="capture_device_trace",
        action="store_true",
        help="profile the run with jax.profiler and fold per-scope "
        "device-time/bytes attribution into the manifest (opt-in: "
        "tracing costs wall time and memory)",
    )
    g.add_argument(
        "--status-json", dest="status_json", default=None,
        help="write a live status.json heartbeat here (current stage, "
        "progress/rate/ETA, memory gauges, event tail), atomically "
        "rewritten every --heartbeat-interval seconds. Tail it with "
        "python -m peasoup_tpu.tools.watch",
    )
    g.add_argument(
        "--heartbeat-interval", dest="heartbeat_interval", type=float,
        default=5.0,
        help="seconds between status.json heartbeats (default 5); the "
        "stall watchdog fires after PEASOUP_STALL_TIMEOUT (default "
        "300) seconds without progress",
    )
    g.add_argument(
        "--no-flight-recorder", dest="no_flight_recorder",
        action="store_true",
        help="disable the crash flight recorder (on by default: "
        "SIGTERM/SIGINT/fatal exceptions dump flight.json plus a "
        "partial telemetry manifest marked aborted)",
    )


def init_observability(args):
    """Configure the library logger from parsed flags and return the
    run's RunTelemetry (activate it around the pipeline call)."""
    from ..obs import RunTelemetry, configure_logging

    configure_logging(args.log_level, getattr(args, "verbose", False))
    return RunTelemetry(
        capture_device_trace=getattr(args, "capture_device_trace", False)
    )


@contextlib.contextmanager
def live_observability(tel, args, workdir, manifest_path=None):
    """Arm the live layer around a pipeline call: install the crash
    flight recorder (unless ``--no-flight-recorder``) and start the
    status.json heartbeat (when ``--status-json``).

    The flight recorder is installed BEFORE the heartbeat's first
    snapshot, so an external watcher that waits for status.json to
    appear can rely on abort forensics being armed. A propagating
    exception dumps flight.json + the partial manifest before the
    stack unwinds; a clean exit writes neither (the heartbeat's final
    ``"done": true`` snapshot is the only trace left behind)."""
    from ..obs.flight import FlightRecorder
    from ..obs.heartbeat import Heartbeat

    recorder = None
    heartbeat = None
    workdir = workdir or "."
    if not getattr(args, "no_flight_recorder", False):
        recorder = FlightRecorder(
            tel,
            os.path.join(workdir, "flight.json"),
            manifest_path=manifest_path,
        ).install()
    if getattr(args, "status_json", None):
        stall = float(os.environ.get("PEASOUP_STALL_TIMEOUT", 300.0))
        heartbeat = Heartbeat(
            tel,
            args.status_json,
            interval=getattr(args, "heartbeat_interval", 5.0),
            stall_timeout=stall,
        ).start()
    try:
        yield
    except BaseException as exc:
        if recorder is not None and not isinstance(exc, GeneratorExit):
            import traceback

            recorder.dump(
                f"exception:{type(exc).__name__}",
                exception="".join(
                    traceback.format_exception_only(type(exc), exc)
                ).strip(),
            )
        raise
    finally:
        if heartbeat is not None:
            heartbeat.stop()
        if recorder is not None:
            recorder.close()
