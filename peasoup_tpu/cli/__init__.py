"""CLI entry points.

Shared observability wiring: every CLI (`peasoup`, `peasoup-ffa`,
`coincidencer`) grows the same three flags — ``--log-level`` (stderr
library logging), ``--metrics-json`` (the telemetry.json run manifest),
``--capture-device-trace`` (per-scope device attribution folded into
the manifest) — resolved here so flag names and semantics can't drift
between tools.
"""

from __future__ import annotations


def add_observability_args(p) -> None:
    g = p.add_argument_group("observability")
    g.add_argument(
        "--log-level", dest="log_level", default=None,
        choices=["debug", "info", "warning", "error"],
        help="library log threshold (messages go to stderr; default "
        "warning, or info with -v; PEASOUP_LOG_LEVEL also works)",
    )
    g.add_argument(
        "--metrics-json", dest="metrics_json", default=None,
        help="path for the telemetry.json run manifest (peasoup "
        "defaults to <outdir>/telemetry.json; the other tools write "
        "one only when this flag is given). Render/diff with "
        "python -m peasoup_tpu.tools.report",
    )
    g.add_argument(
        "--capture-device-trace", dest="capture_device_trace",
        action="store_true",
        help="profile the run with jax.profiler and fold per-scope "
        "device-time/bytes attribution into the manifest (opt-in: "
        "tracing costs wall time and memory)",
    )


def init_observability(args):
    """Configure the library logger from parsed flags and return the
    run's RunTelemetry (activate it around the pipeline call)."""
    from ..obs import RunTelemetry, configure_logging

    configure_logging(args.log_level, getattr(args, "verbose", False))
    return RunTelemetry(
        capture_device_trace=getattr(args, "capture_device_trace", False)
    )
