"""`accmap` — cross-beam delay-finder demo CLI.

Reference: src/accmap.cpp (32 LoC) builds a `DelayFinder` over a set of
beam recordings and prints per-baseline correlation peaks. The
reference program does not compile as shipped (it includes
data_types/dada.hpp, which is absent from its tree); this is the
working equivalent over SIGPROC filterbanks (channel-summed to zero-DM
series) or .tim time series, using the batched one-FFT-per-beam
correlator (ops/correlate.py).
"""

from __future__ import annotations

import argparse
import sys

import numpy as np


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="accmap", description="Cross-beam delay finder"
    )
    p.add_argument("files", nargs="+", help="Beam files (.fil or .tim)")
    p.add_argument("-d", "--max_delay", type=int, default=600,
                   help="Maximum lag to search (samples)")
    from . import add_version_arg

    add_version_arg(p)
    return p


def _load_series(path: str) -> np.ndarray:
    from ..io import read_filterbank
    from ..io.sigproc import read_timeseries

    if path.endswith(".tim"):
        return read_timeseries(path)[1].astype(np.float32)
    fil = read_filterbank(path)
    return fil.data.sum(axis=1, dtype=np.float32)  # zero-DM series


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    from .peasoup import apply_platform_env

    apply_platform_env()
    import jax.numpy as jnp

    from ..ops.correlate import find_delays

    series = [_load_series(f) for f in args.files]
    n = min(len(s) for s in series)
    beams = jnp.asarray(np.stack([s[:n] for s in series]))
    res = find_delays(beams, args.max_delay)
    pairs = np.asarray(res.pairs)
    distance = np.asarray(res.distance)
    lag = np.asarray(res.lag)
    power = np.asarray(res.power)
    for k in range(pairs.shape[0]):
        ii, jj = pairs[k]
        # reference prints "<ii> <jj> Distance: <argmax>"
        # (correlator.hpp:85-86); the signed lag is the useful number
        print(
            f"{args.files[ii]} {args.files[jj]} "
            f"Distance: {int(distance[k])} "
            f"(lag {int(lag[k])} samples, power {float(power[k]):.3g})"
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
