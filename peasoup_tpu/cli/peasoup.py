"""`peasoup` CLI: flag-compatible with the reference binary
(reference: include/utils/cmdline.hpp:69-209 TCLAP spec).

Usage mirrors the CUDA original:
  peasoup -i data.fil --dm_end 250 --acc_start -5 --acc_end 5 --npdmp 10 -p
"""

from __future__ import annotations

import argparse
import os
import sys
import time

from . import (
    add_observability_args,
    add_version_arg,
    init_observability,
    live_observability,
)


def default_outdir() -> str:
    return time.strftime("./%Y-%m-%d-%H:%M_peasoup/", time.gmtime())


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="peasoup",
        description="Peasoup-TPU - a TPU pulsar search pipeline",
    )
    p.add_argument("-i", "--inputfile", required=True, help="File to process (.fil)")
    p.add_argument("-o", "--outdir", default=None, help="The output directory")
    p.add_argument("-k", "--killfile", default="", help="Channel mask file")
    p.add_argument("-z", "--zapfile", default="", help="Birdie list file")
    p.add_argument(
        "-t", "--num_threads", type=int, default=14,
        help="Number of device workers (reference: number of GPUs)",
    )
    p.add_argument("--limit", type=int, default=1000,
                   help="upper limit on number of candidates to write out")
    p.add_argument("--fft_size", type=int, default=0,
                   help="Transform size to use (defaults to lower power of two)")
    p.add_argument("--dm_start", type=float, default=0.0)
    p.add_argument("--dm_end", type=float, default=100.0)
    p.add_argument("--dm_tol", type=float, default=1.10,
                   help="DM smearing tolerance (1.11=10%%)")
    p.add_argument("--dm_pulse_width", type=float, default=64.0,
                   help="Minimum pulse width (us) for which dm_tol is valid")
    p.add_argument("--acc_start", type=float, default=0.0)
    p.add_argument("--acc_end", type=float, default=0.0)
    p.add_argument("--acc_tol", type=float, default=1.10)
    p.add_argument("--acc_pulse_width", type=float, default=64.0)
    p.add_argument("--boundary_5_freq", type=float, default=0.05)
    p.add_argument("--boundary_25_freq", type=float, default=0.5)
    p.add_argument("-n", "--nharmonics", type=int, default=4)
    p.add_argument("--npdmp", type=int, default=0,
                   help="Number of candidates to fold and pdmp")
    p.add_argument("-m", "--min_snr", type=float, default=9.0)
    p.add_argument("--min_freq", type=float, default=0.1)
    p.add_argument("--max_freq", type=float, default=1100.0)
    p.add_argument("--max_harm_match", type=int, default=16, dest="max_harm")
    p.add_argument("--freq_tol", type=float, default=0.0001)
    p.add_argument("-v", "--verbose", action="store_true")
    p.add_argument("-p", "--progress_bar", action="store_true")
    p.add_argument(
        "--subbands", type=int, default=0,
        help="two-stage subband dedispersion with N subbands "
        "(~sqrt(nchans)-fold less arithmetic at high channel counts; "
        "0 = direct, exact)",
    )
    p.add_argument(
        "--subband_smear", type=float, default=1.0,
        help="max extra smear (samples) allowed per DM-trial group "
        "when --subbands is set (0 = exact)",
    )
    p.add_argument(
        "--dedisp_engine", default="", choices=("", "exact", "matmul"),
        help="force one dedispersion engine: the gather channel scan "
        "(exact) or the MXU banded matmul (matmul) — bitwise-equal "
        "outputs; default lets the plan/tuner decide (subband is "
        "forced via --subbands)",
    )
    p.add_argument(
        "--tune", action=argparse.BooleanOptionalAction, default=False,
        help="auto-select exact-vs-subband dedispersion and load "
        "per-device tuned shape knobs from the tuning cache "
        "(plan/dedisp_plan.py + perf/tuning.py); an explicit "
        "--subbands overrides the planner",
    )
    p.add_argument(
        "--tuning-cache", default="",
        help="tuning_cache.json path (default: the per-user cache, "
        "or PEASOUP_TUNING_CACHE)",
    )
    p.add_argument(
        "--checkpoint", default="",
        help="Checkpoint file for resumable searches (TPU extension; "
        "the reference has no checkpointing)",
    )
    p.add_argument(
        "--hbm_bytes", type=int, default=0,
        help="device memory budget in bytes (0 = ask the device; set "
        "on chips that report no limit — also PEASOUP_HBM_BYTES)",
    )
    p.add_argument(
        "--no_accel_dedupe", action="store_true",
        help="dispatch every accel trial even when trials provably "
        "share their entire rounded resample-shift map (the dedupe is "
        "bitwise-output-equal; this flag exists for timing comparisons)",
    )
    add_version_arg(p)
    add_observability_args(p)
    return p


def apply_platform_env() -> None:
    """Honor JAX_PLATFORMS even when the ambient interpreter setup
    (e.g. a sitecustomize registering a TPU plugin) overrode the
    platform via jax.config after env parsing. Also enables JAX's
    persistent compilation cache (fresh CLI invocations would
    otherwise pay the full XLA compile every run — measured 10x on
    repeat FFA searches)."""
    import os

    platforms = os.environ.get("JAX_PLATFORMS")
    if platforms:
        import jax

        jax.config.update("jax_platforms", platforms)
    from ..utils.cache import enable_compilation_cache

    enable_compilation_cache()


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    outdir = args.outdir or default_outdir()
    apply_platform_env()
    tel = init_observability(args)
    tel.set_context(
        command="peasoup", inputfile=args.inputfile, outdir=outdir
    )
    manifest_path = args.metrics_json or os.path.join(
        outdir.rstrip("/"), "telemetry.json"
    )

    # Resolve the peaks-kernel stripe height BEFORE anything creates
    # this process's jax client: the subprocess-isolated _SUB=24 probe
    # (ops/pallas/peaks.py) needs the TPU free to validate the fast
    # default on single-client runtimes; once resolved the verdict is
    # disk-cached and this import is free
    from ..ops.pallas import peaks as _peaks

    tel.event("pallas_peaks_sub", **_peaks.SUB_RESOLUTION)

    # Heavy imports after arg parsing so --help stays fast
    from ..io.output import CandidateFileWriter, OutputFileWriter
    from ..io.sigproc import read_filterbank
    from ..pipeline.search import SearchConfig

    cfg = SearchConfig(
        outdir=outdir,
        killfilename=args.killfile,
        zapfilename=args.zapfile,
        max_num_threads=args.num_threads,
        limit=args.limit,
        size=args.fft_size,
        dm_start=args.dm_start,
        dm_end=args.dm_end,
        dm_tol=args.dm_tol,
        dm_pulse_width=args.dm_pulse_width,
        acc_start=args.acc_start,
        acc_end=args.acc_end,
        acc_tol=args.acc_tol,
        acc_pulse_width=args.acc_pulse_width,
        boundary_5_freq=args.boundary_5_freq,
        boundary_25_freq=args.boundary_25_freq,
        nharmonics=args.nharmonics,
        npdmp=args.npdmp,
        min_snr=args.min_snr,
        min_freq=args.min_freq,
        max_freq=args.max_freq,
        max_harm=args.max_harm,
        freq_tol=args.freq_tol,
        verbose=args.verbose,
        progress_bar=args.progress_bar,
        checkpoint_file=args.checkpoint,
        hbm_bytes=args.hbm_bytes,
        dedupe_accel=not args.no_accel_dedupe,
        subbands=args.subbands,
        subband_smear=args.subband_smear,
        dedisp_engine=args.dedisp_engine,
        tune=args.tune,
        tuning_cache=args.tuning_cache,
    )
    # multi-host aware (JAX_COORDINATOR_ADDRESS & co.): each process
    # searches its DM slice; single-process this is PeasoupSearch.run
    from ..parallel.multihost import run_search

    with tel.activate(), live_observability(
        tel, args, outdir, manifest_path
    ):
        t0 = time.perf_counter()
        tel.set_stage("reading")
        if args.progress_bar:
            print(f"Reading data from {args.inputfile}")
        fil = read_filterbank(args.inputfile)
        reading = time.perf_counter() - t0

        with tel.device_capture():
            result = run_search(fil, cfg)
        result.timers["reading"] = reading
        tel.merge_timers(result.timers)

        import jax

        if jax.process_count() > 1:
            # per-host manifest shard (stage timers here are this
            # host's own): telemetry.procN.json next to the main
            # manifest, merged with `tools.report --merge`
            base, ext = os.path.splitext(manifest_path)
            tel.write(f"{base}.proc{jax.process_index()}{ext or '.json'}")
        if jax.process_index() != 0:
            return 0  # every process holds the identical result; rank 0 writes

        tel.set_stage("writing")
        t0 = time.perf_counter()
        writer = CandidateFileWriter(outdir)
        writer.write_binary(result.candidates, "candidates.peasoup")
        result.timers["writing"] = time.perf_counter() - t0
        tel.add_timer("writing", result.timers["writing"])

        stats = OutputFileWriter()
        stats.add_misc_info()
        stats.add_header(fil.header)
        stats.add_search_parameters(cfg, args.inputfile)
        stats.add_dm_list(result.dm_list)
        stats.add_acc_list(result.acc_list_dm0)
        stats.add_device_info()
        stats.add_candidates(result.candidates, writer.byte_mapping)
        stats.add_timing_info(result.timers)
        stats.to_file(f"{outdir.rstrip('/')}/overview.xml")

        # the machine-readable twin of overview.xml, written beside it
        # unless --metrics-json redirects it
        tel.gauge("candidates.written", len(result.candidates))
        tel.set_stage("done")
        tel.write(manifest_path)
    if args.verbose or args.progress_bar:
        print(
            f"Done: {len(result.candidates)} candidates -> {outdir} "
            f"(total {result.timers['total']:.2f}s)"
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
