"""`peasoup-fdas` — Fourier-domain acceleration-search CLI.

The FDAS twin of the main `peasoup` binary: the same input/DM-plan/
spectrum flags, with the time-domain acc_start/acc_end trial range
replaced by the PRESTO-style --zmax/--wmax template-bank bounds
(f-dot and f-ddot extent in DFT bins over the observation). One
dereddened spectrum per DM trial is correlated against the whole
template bank in batched fixed-shape device programs
(peasoup_tpu/ops/fdas.py); candidates carry (f, f-dot[, f-ddot])
provenance into overview.xml and candidates.peasoup.

Usage:
  peasoup-fdas -i data.fil --dm_end 250 --zmax 128 -p
"""

from __future__ import annotations

import argparse
import os
import sys
import time

from . import (
    add_observability_args,
    add_version_arg,
    init_observability,
    live_observability,
)


def default_outdir() -> str:
    return time.strftime("./%Y-%m-%d-%H:%M_peasoup_fdas/", time.gmtime())


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="peasoup-fdas",
        description="Peasoup-TPU Fourier-domain acceleration search",
    )
    p.add_argument("-i", "--inputfile", required=True,
                   help="File to process (.fil)")
    p.add_argument("-o", "--outdir", default=None,
                   help="The output directory")
    p.add_argument("-k", "--killfile", default="", help="Channel mask file")
    p.add_argument("-z", "--zapfile", default="", help="Birdie list file")
    p.add_argument("--limit", type=int, default=1000,
                   help="upper limit on number of candidates to write out")
    p.add_argument("--fft_size", type=int, default=0,
                   help="Transform size to use (defaults to lower power "
                   "of two)")
    p.add_argument("--dm_start", type=float, default=0.0)
    p.add_argument("--dm_end", type=float, default=100.0)
    p.add_argument("--dm_tol", type=float, default=1.10,
                   help="DM smearing tolerance (1.11=10%%)")
    p.add_argument("--dm_pulse_width", type=float, default=64.0,
                   help="Minimum pulse width (us) for which dm_tol is valid")
    p.add_argument("--zmax", type=float, default=64.0,
                   help="f-dot search extent in DFT bins over the "
                   "observation (PRESTO -z; 0 = pure periodicity)")
    p.add_argument("--zstep", type=float, default=2.0,
                   help="f-dot template spacing in bins")
    p.add_argument("--wmax", type=float, default=0.0,
                   help="f-ddot (jerk) search extent in bins (PRESTO -w; "
                   "0 = jerk plane off)")
    p.add_argument("--wstep", type=float, default=20.0,
                   help="f-ddot template spacing in bins")
    p.add_argument("--boundary_5_freq", type=float, default=0.05)
    p.add_argument("--boundary_25_freq", type=float, default=0.5)
    p.add_argument("-n", "--nharmonics", type=int, default=4)
    p.add_argument("-m", "--min_snr", type=float, default=9.0)
    p.add_argument("--min_freq", type=float, default=0.1)
    p.add_argument("--max_freq", type=float, default=1100.0)
    p.add_argument("--max_harm_match", type=int, default=16, dest="max_harm")
    p.add_argument("--freq_tol", type=float, default=0.0001)
    p.add_argument("--segment", type=int, default=0,
                   help="overlap-save FFT length (0 = auto from template "
                   "width)")
    p.add_argument("--template_block", type=int, default=0,
                   help="template rows per device dispatch (0 = auto)")
    p.add_argument("--dm_block", type=int, default=0,
                   help="DM trials per device dispatch (0 = auto from "
                   "memory budget)")
    p.add_argument(
        "--checkpoint", default="",
        help="Checkpoint file for resumable searches",
    )
    p.add_argument("-v", "--verbose", action="store_true")
    p.add_argument("-p", "--progress_bar", action="store_true")
    add_version_arg(p)
    add_observability_args(p)
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    outdir = args.outdir or default_outdir()
    from .peasoup import apply_platform_env

    apply_platform_env()
    tel = init_observability(args)
    tel.set_context(
        command="peasoup-fdas", inputfile=args.inputfile, outdir=outdir
    )
    manifest_path = args.metrics_json or os.path.join(
        outdir.rstrip("/"), "telemetry.json"
    )

    # Heavy imports after arg parsing so --help stays fast
    from ..io.output import (
        CandidateFileWriter,
        OutputFileWriter,
        write_fdas_candidates,
    )
    from ..io.sigproc import read_filterbank
    from ..pipeline.fdas import FdasConfig

    cfg = FdasConfig(
        outdir=outdir,
        killfilename=args.killfile,
        zapfilename=args.zapfile,
        limit=args.limit,
        size=args.fft_size,
        dm_start=args.dm_start,
        dm_end=args.dm_end,
        dm_tol=args.dm_tol,
        dm_pulse_width=args.dm_pulse_width,
        zmax=args.zmax,
        zstep=args.zstep,
        wmax=args.wmax,
        wstep=args.wstep,
        boundary_5_freq=args.boundary_5_freq,
        boundary_25_freq=args.boundary_25_freq,
        nharmonics=args.nharmonics,
        min_snr=args.min_snr,
        min_freq=args.min_freq,
        max_freq=args.max_freq,
        max_harm=args.max_harm,
        freq_tol=args.freq_tol,
        verbose=args.verbose,
        progress_bar=args.progress_bar,
        segment=args.segment,
        template_block=args.template_block,
        dm_block=args.dm_block,
        checkpoint_file=args.checkpoint,
    )
    # multi-host aware (JAX_COORDINATOR_ADDRESS & co.): each process
    # searches its DM slice; single-process this is FdasSearch.run
    from ..parallel.multihost import run_fdas_search

    with tel.activate(), live_observability(
        tel, args, outdir, manifest_path
    ):
        t0 = time.perf_counter()
        tel.set_stage("reading")
        if args.progress_bar:
            print(f"Reading data from {args.inputfile}")
        fil = read_filterbank(args.inputfile)
        reading = time.perf_counter() - t0

        with tel.device_capture():
            result = run_fdas_search(fil, cfg)
        result.timers["reading"] = reading
        tel.merge_timers(result.timers)

        import jax

        if jax.process_count() > 1:
            base, ext = os.path.splitext(manifest_path)
            tel.write(f"{base}.proc{jax.process_index()}{ext or '.json'}")
        if jax.process_index() != 0:
            return 0  # every process holds the identical result; rank 0 writes

        tel.set_stage("writing")
        t0 = time.perf_counter()
        writer = CandidateFileWriter(outdir)
        writer.write_binary(result.candidates, "candidates.peasoup")
        write_fdas_candidates(
            os.path.join(outdir.rstrip("/"), "candidates.fdas"),
            result.candidates,
        )
        result.timers["writing"] = time.perf_counter() - t0
        tel.add_timer("writing", result.timers["writing"])

        stats = OutputFileWriter()
        stats.add_misc_info()
        stats.add_header(fil.header)
        stats.add_fdas_section(cfg, result.zs, result.ws)
        stats.add_dm_list(result.dm_list)
        stats.add_device_info()
        stats.add_candidates_fdas(result.candidates, writer.byte_mapping)
        stats.add_timing_info(result.timers)
        stats.to_file(f"{outdir.rstrip('/')}/overview.xml")

        tel.gauge("candidates.written", len(result.candidates))
        tel.set_stage("done")
        tel.write(manifest_path)
    if args.verbose or args.progress_bar:
        print(
            f"Done: {len(result.candidates)} candidates -> {outdir} "
            f"(total {result.timers['total']:.2f}s)"
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
