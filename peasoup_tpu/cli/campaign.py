"""`peasoup-campaign` — fault-tolerant multi-observation orchestration.

Run the pipelines over a manifest (or directory) of filterbanks as one
long-lived worker process; start the same command on N hosts/terminals
for N workers — they coordinate through the campaign directory alone
(file-backed queue with atomic claims, lease expiry, retry/backoff and
quarantine; see peasoup_tpu/campaign/).

    # start (or join) a campaign: one worker per invocation
    python -m peasoup_tpu.cli.campaign run -w camp/ --manifest obs.txt \\
        --pipeline spsearch --config '{"dm_end": 250, "min_snr": 7}'

    # live view (also: python -m peasoup_tpu.tools.watch camp/)
    python -m peasoup_tpu.cli.campaign status -w camp/

    # operator controls
    python -m peasoup_tpu.cli.campaign quarantine-list -w camp/
    python -m peasoup_tpu.cli.campaign retry -w camp/ --all
    python -m peasoup_tpu.cli.campaign ingest -w camp/

Campaign layout: ``campaign.json`` (config, first writer wins),
``queue/`` (job records, claims, done + quarantine markers),
``jobs/<id>/`` (each job's outputs + its own status.json heartbeat,
flight recorder and telemetry manifest), ``candidates.sqlite`` (the
survey candidate database) and ``campaign_status.json`` (the rollup).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import time

from . import add_version_arg


def _load_config_arg(text: str | None) -> dict:
    """--config accepts inline JSON or @path-to-json-file."""
    if not text:
        return {}
    if text.startswith("@"):
        with open(text[1:]) as f:
            return json.load(f)
    return json.loads(text)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="peasoup-campaign",
        description="Peasoup-TPU campaign orchestration - run the "
        "pipelines over many observations with a fault-tolerant "
        "multi-worker queue and a survey candidate database",
    )
    add_version_arg(p)
    sub = p.add_subparsers(dest="cmd", required=True)

    run = sub.add_parser(
        "run", help="enqueue observations (idempotent) and work the "
        "queue until the campaign drains",
    )
    run.add_argument("-w", "--workdir", required=True,
                     help="campaign directory (shared by all workers)")
    run.add_argument("--manifest", default=None,
                     help="observation list: one .fil path per line, or "
                     "JSON lines {'input': ..., 'config': {...}}")
    run.add_argument("--data-dir", default=None,
                     help="enqueue every *.fil under this directory "
                     "instead of (or in addition to) --manifest")
    run.add_argument("--pipeline", default="spsearch",
                     choices=["search", "spsearch", "ffa"],
                     help="which pipeline each job runs (default spsearch)")
    run.add_argument("--priority", type=int, default=0,
                     help="priority class for the observations enqueued "
                     "by THIS invocation (higher claims sooner — and may "
                     "preempt a running lower-priority claim; a "
                     "per-entry 'priority' in a JSON manifest line "
                     "overrides; default 0)")
    run.add_argument("--nprocs", type=int, default=1,
                     help="gang-schedule the observations enqueued by "
                     "THIS invocation across N worker processes of one "
                     "--group (search/spsearch pipelines; a per-entry "
                     "'nprocs' in a JSON manifest line overrides; "
                     "default 1 = no gang)")
    run.add_argument("--group", default=None,
                     help="process-group name for gang-scheduled jobs: "
                     "workers sharing a --group form one gang pool (the "
                     "lexicographically-first live member leads claims)")
    run.add_argument("--config", default=None,
                     help="pipeline config overrides as inline JSON or "
                     "@file.json (keys = SearchConfig/SinglePulseConfig "
                     "fields)")
    run.add_argument("--lease", type=float, default=60.0,
                     help="claim lease seconds; a worker dead past this "
                     "loses its job to the reaper (default 60)")
    run.add_argument("--max-attempts", type=int, default=3,
                     help="failures before quarantine (default 3)")
    run.add_argument("--backoff", type=float, default=2.0,
                     help="retry backoff base seconds, doubled per "
                     "attempt (default 2)")
    run.add_argument("--bucket-nsamps", default=None,
                     help="comma-separated explicit nsamps bucket ladder "
                     "(default: powers of two and 3*2^(k-1))")
    run.add_argument("--warmup", action=argparse.BooleanOptionalAction,
                     default=True,
                     help="AOT-compile each new bucket's programs on a "
                     "background thread before its first job touches "
                     "data (default on; --no-warmup disables)")
    run.add_argument("--tune", action=argparse.BooleanOptionalAction,
                     default=False,
                     help="auto-tuned dedispersion plans: each new "
                     "bucket resolves exact-vs-subband + per-device "
                     "shape knobs on the warmup thread and persists "
                     "the winner in the campaign tuning cache "
                     "(warm buckets re-measure nothing)")
    run.add_argument("--tuning-cache", default="",
                     help="tuning_cache.json path (default: "
                     "<workdir>/tuning_cache.json, shared by all "
                     "workers)")
    run.add_argument("--warmup-mode", default="dryrun",
                     choices=["dryrun", "aot"],
                     help="dryrun = run the pipeline once over a "
                     "synthetic bucket-shaped observation (exact, "
                     "costs one observation's device work); aot = "
                     "lower+compile the registry at bucket shapes only "
                     "(cheap, approximate) (default dryrun)")
    run.add_argument("--max-jobs", type=int, default=None,
                     help="stop this worker after N jobs (default: run "
                     "until the campaign drains)")
    run.add_argument("--no-drain", action="store_true",
                     help="exit when nothing is immediately claimable "
                     "instead of waiting for running/backoff jobs")
    run.add_argument("--worker-id", default=None,
                     help="override the worker identity (default "
                     "hostname-pid)")
    run.add_argument("--poll", type=float, default=1.0,
                     help="seconds between queue polls while waiting "
                     "(default 1)")
    run.add_argument("--metrics", action=argparse.BooleanOptionalAction,
                     default=True,
                     help="per-worker time-series metrics under "
                     "queue/workers/ (obs/metrics.py; read with "
                     "`peasoup-campaign metrics`; default on)")
    run.add_argument("--trace", action=argparse.BooleanOptionalAction,
                     default=True,
                     help="per-job trace span files under jobs/<id>/ "
                     "(obs/trace.py; export with `peasoup-campaign "
                     "trace`; default on)")
    run.add_argument("--log-level", dest="log_level", default=None,
                     choices=["debug", "info", "warning", "error"])
    run.add_argument("-v", "--verbose", action="store_true")

    st = sub.add_parser("status", help="print the campaign rollup")
    st.add_argument("-w", "--workdir", required=True)
    st.add_argument("--json", action="store_true",
                    help="print the raw campaign_status.json document")

    rt = sub.add_parser(
        "retry", help="re-queue quarantined jobs (reset attempts)"
    )
    rt.add_argument("-w", "--workdir", required=True)
    rt.add_argument("job_ids", nargs="*", help="job ids to re-queue")
    rt.add_argument("--all", action="store_true",
                    help="re-queue every quarantined job")

    ql = sub.add_parser(
        "quarantine-list", help="list quarantined jobs with last errors"
    )
    ql.add_argument("-w", "--workdir", required=True)

    ing = sub.add_parser(
        "ingest", help="(re)ingest every completed job's outputs into "
        "the sqlite candidate database",
    )
    ing.add_argument("-w", "--workdir", required=True)

    pe = sub.add_parser(
        "preempt", help="revoke a running claim: the victim worker "
        "checkpoints at the next DM-block boundary and releases the "
        "job with zero attempts consumed (it resumes later, "
        "bitwise-equal); a victim unresponsive past the grace "
        "deadline is escalated to the lease reaper",
    )
    pe.add_argument("-w", "--workdir", required=True)
    pe.add_argument("job_id", help="the job whose claim to revoke")
    pe.add_argument("--grace", type=float, default=60.0,
                    help="seconds before an unresponsive victim is "
                    "reaped (default 60)")

    asc = sub.add_parser(
        "autoscale", help="run the fleet autoscale controller: spawn "
        "real workers when the backlog outruns the fleet, retire idle "
        "ones when it drains — bounded by --min/--max with a cooldown, "
        "decisions logged into campaign_status.json",
    )
    asc.add_argument("-w", "--workdir", required=True)
    asc.add_argument("--min", type=int, default=1, dest="min_workers")
    asc.add_argument("--max", type=int, default=4, dest="max_workers")
    asc.add_argument("--cooldown", type=float, default=60.0)
    asc.add_argument("--backlog-per-worker", type=float, default=2.0)
    asc.add_argument("--poll", type=float, default=5.0)
    asc.add_argument("--max-runtime", type=float, default=None,
                     help="stop the controller after N seconds "
                     "(default: run until the campaign drains)")
    asc.add_argument("--spawn-arg", action="append", default=[],
                     help="extra argument forwarded to each spawned "
                     "`peasoup-campaign run` (repeatable, e.g. "
                     "--spawn-arg=--no-warmup)")

    me = sub.add_parser(
        "metrics", help="aggregate every worker's time-series metrics "
        "(queue/workers/*.metrics.jsonl) and print the Prometheus text "
        "exposition; --serve exposes it on a stdlib HTTP endpoint",
    )
    me.add_argument("-w", "--workdir", required=True)
    me.add_argument("--json", action="store_true",
                    help="print the raw samples (one JSON object per "
                    "worker) instead of the exposition")
    me.add_argument("--serve", action="store_true",
                    help="serve GET /metrics forever (Prometheus "
                    "scrape target; ctrl-C to stop)")
    me.add_argument("--port", type=int, default=9099)
    me.add_argument("--host", default="127.0.0.1")

    tr = sub.add_parser(
        "trace", help="export one or more jobs' cross-process trace "
        "spans as Chrome trace-event JSON (load at ui.perfetto.dev): "
        "a preempted-and-resumed job or an N-member gang renders as "
        "ONE connected timeline, one track per worker",
    )
    tr.add_argument("-w", "--workdir", required=True)
    tr.add_argument("job_ids", nargs="*",
                    help="jobs to export (default: every job with "
                    "trace files)")
    tr.add_argument("-o", "--output", default=None,
                    help="output trace JSON path (default: "
                    "<workdir>/trace.json)")
    tr.add_argument("--no-autoscale", action="store_true",
                    help="omit the autoscale decision instants from "
                    "the campaign track")

    pf = sub.add_parser(
        "profile", help="request a bounded on-demand jax.profiler "
        "capture from a LIVE worker: a profile.request file lands "
        "beside its registry entry, the worker observes it on its "
        "next beat and captures into <workdir>/profiles/ (guarded "
        "no-op on the CPU backend)",
    )
    pf.add_argument("-w", "--workdir", required=True)
    pf.add_argument("worker_id", help="the worker to profile (see "
                    "`peasoup-campaign status` fleet view)")
    pf.add_argument("--seconds", type=float, default=5.0,
                    help="capture duration (bounded at 60s; default 5)")

    pr = sub.add_parser(
        "prune", help="delete accumulated campaign artifacts: "
        "*.corrupt quarantine forensics (--corrupt) and on-demand "
        "jax.profiler capture directories (--profiles) — both grow "
        "forever otherwise",
    )
    pr.add_argument("-w", "--workdir", required=True)
    pr.add_argument("--corrupt", action="store_true",
                    help="prune *.corrupt quarantine files (the flag "
                    "keeps the verb explicit)")
    pr.add_argument("--profiles", action="store_true",
                    help="prune on-demand device-profile capture "
                    "directories under <workdir>/profiles/ "
                    "(peasoup-campaign profile output; counted in the "
                    "rollup's profiles section)")
    pr.add_argument("--journals", action="store_true",
                    help="rotate the append-only journals (alerts, "
                    "per-tenant alert routes, submissions) down to a "
                    "size cap, keeping the newest complete lines; "
                    "restart-safe — alert state lives in the snapshot, "
                    "not the journal")
    pr.add_argument("--max-bytes", type=int, default=1 << 20,
                    help="journal size cap for --journals (rotate when "
                    "larger, keep roughly half; default 1 MiB)")
    pr.add_argument("--older-than-days", type=float, default=0.0,
                    help="only prune artifacts older than N days "
                    "(default 0 = all)")
    pr.add_argument("--dry-run", action="store_true",
                    help="list what would be deleted without deleting")

    sv = sub.add_parser(
        "serve", help="serve the per-campaign live status portal "
        "(stdlib HTTP, read-only): /metrics (Prometheus exposition "
        "incl. the ALERTS series), /status, /alerts, /jobs/<id>, the "
        "sift report and bowtie plot",
    )
    sv.add_argument("-w", "--workdir", required=True)
    sv.add_argument("--port", type=int, default=9100)
    sv.add_argument("--host", default="127.0.0.1")
    sv.add_argument("--max-requests", type=int, default=None,
                    help="serve N requests then exit (for tests/gates; "
                    "default: serve forever)")
    sv.add_argument("--data-root", action="append", default=[],
                    dest="data_roots", metavar="DIR",
                    help="allow POST /submit inputs under DIR "
                    "(repeatable); a tenant's own watch_dir is always "
                    "allowed, anything else is rejected 403")

    al = sub.add_parser(
        "alerts", help="print the campaign's alerts snapshot "
        "(obs/alerts.py); --evaluate runs one evaluation round of the "
        "default SLO/data-quality/sentinel rules first",
    )
    al.add_argument("-w", "--workdir", required=True)
    al.add_argument("--evaluate", action="store_true",
                    help="evaluate the rules against the current "
                    "metrics before printing (workers also do this "
                    "continuously while running)")
    al.add_argument("--json", action="store_true",
                    help="print the raw alerts.json snapshot")

    se = sub.add_parser(
        "sentinel", help="enqueue a synthetic-pulsar injection "
        "sentinel at low priority: the campaign searches it like any "
        "observation, and the alert engine pages when the known "
        "candidate is NOT recovered — an end-to-end scientific "
        "validity probe",
    )
    se.add_argument("-w", "--workdir", required=True)
    se.add_argument("--check", action="store_true",
                    help="report recovery status of existing sentinels "
                    "instead of enqueueing a new one")
    se.add_argument("--min-snr", type=float, default=7.0,
                    help="S/N the recovered candidate must reach "
                    "(default 7)")
    se.add_argument("--dm-tol", type=float, default=5.0,
                    help="DM match tolerance in pc/cm^3 (default 5)")
    se.add_argument("--time-tol", type=float, default=0.05,
                    help="arrival-time match tolerance in seconds "
                    "(default 0.05)")
    se.add_argument("--nsamps", type=int, default=1 << 12,
                    help="synthetic observation length (default 4096)")

    te = sub.add_parser(
        "tenant", help="manage the multi-tenant registry "
        "(queue/tenants/<name>.json): add mints a bearer token, list "
        "shows quotas and live throttle state, rotate-token mints a "
        "replacement secret (the old token is rejected immediately), "
        "set-quota edits only the quota flags given — both admin "
        "actions are journaled to queue/submissions.jsonl",
    )
    te.add_argument("-w", "--workdir", required=True)
    te.add_argument("action", choices=["add", "list", "show", "remove",
                                       "rotate-token", "set-quota"])
    te.add_argument("name", nargs="?", default="",
                    help="tenant name (all actions except list)")
    te.add_argument("--token", default="",
                    help="bearer token (default: minted)")
    te.add_argument("--max-queued", type=int, default=None,
                    help="max non-terminal jobs (0 = unlimited)")
    te.add_argument("--max-running", type=int, default=None,
                    help="max concurrent running jobs (0 = unlimited)")
    te.add_argument("--device-seconds", type=float, default=None,
                    help="device-seconds budget per rolling window "
                    "(0 = unlimited)")
    te.add_argument("--window-s", type=float, default=None,
                    help="rolling budget window (default 3600)")
    te.add_argument("--priority-max", type=int, default=None,
                    help="priority ceiling; higher submissions are "
                    "clamped (default: none; set-quota: -1 clears "
                    "the ceiling)")
    te.add_argument("--watch-dir", default=None,
                    help="folder polled by `ingest-folder`; dropped "
                    ".fil/.fbk files are auto-submitted")

    sm = sub.add_parser(
        "submit", help="submit one observation as a tenant: "
        "quota-checked admission, journaled append-only to "
        "queue/submissions.jsonl whether accepted or rejected",
    )
    sm.add_argument("-w", "--workdir", required=True)
    sm.add_argument("tenant", help="tenant name")
    sm.add_argument("input", help="observation file (.fil/.fbk)")
    sm.add_argument("--priority", type=int, default=0)
    sm.add_argument("--pipeline", default="spsearch")
    sm.add_argument("--config", default=None,
                    help="per-job config overrides (JSON or @file)")

    inf = sub.add_parser(
        "ingest-folder", help="poll every tenant's watch folder once "
        "and submit fresh .fil/.fbk drops through the same "
        "quota-checked admission as HTTP/CLI submissions",
    )
    inf.add_argument("-w", "--workdir", required=True)
    inf.add_argument("--pipeline", default="spsearch")
    inf.add_argument("--poll", type=float, default=0.0,
                     help="keep polling every N seconds (default 0 = "
                     "one pass)")
    inf.add_argument("--max-runtime", type=float, default=None,
                     help="stop polling after N seconds")
    return p


def _cmd_run(args) -> int:
    from ..campaign.queue import JobQueue
    from ..campaign.rollup import write_status
    from ..campaign.runner import (
        CampaignConfig,
        enqueue_entries,
        parse_manifest,
        run_worker,
        save_campaign_config,
    )
    from ..obs import configure_logging
    from .peasoup import apply_platform_env

    configure_logging(args.log_level, args.verbose)
    apply_platform_env()
    ladder = (
        [int(x) for x in args.bucket_nsamps.split(",")]
        if args.bucket_nsamps else None
    )
    campaign = save_campaign_config(
        args.workdir,
        CampaignConfig(
            pipeline=args.pipeline,
            config=_load_config_arg(args.config),
            lease_s=args.lease,
            max_attempts=args.max_attempts,
            backoff_base_s=args.backoff,
            bucket_nsamps=ladder,
            warmup=args.warmup,
            warmup_mode=args.warmup_mode,
            tune=args.tune,
            tuning_cache=args.tuning_cache,
            metrics=args.metrics,
            trace=args.trace,
        ),
    )
    queue = JobQueue(
        args.workdir,
        lease_s=campaign.lease_s,
        max_attempts=campaign.max_attempts,
        backoff_base_s=campaign.backoff_base_s,
    )
    entries = []
    if args.manifest:
        entries.extend(parse_manifest(args.manifest))
    if args.data_dir:
        entries.extend(
            {"input": p}
            for p in sorted(
                glob.glob(os.path.join(args.data_dir, "**", "*.fil"),
                          recursive=True)
            )
        )
    added = enqueue_entries(
        queue, entries, campaign.pipeline, campaign.bucket_nsamps,
        priority=args.priority, nprocs=args.nprocs,
    )
    counts = queue.counts()
    print(
        f"campaign {os.path.abspath(args.workdir)}: enqueued {added} new "
        f"of {len(entries)} listed ({counts['total']} total jobs)"
    )
    if counts["total"] == 0:
        print("nothing to do (empty campaign)")
        return 1
    worker_id = args.worker_id or JobQueue.default_worker_id()
    tally = run_worker(
        args.workdir,
        worker_id=worker_id,
        max_jobs=args.max_jobs,
        drain=not args.no_drain,
        poll_s=args.poll,
        group=args.group,
    )
    status = write_status(args.workdir, queue)
    q = status["queue"]
    print(
        f"worker {worker_id}: {tally['done']} done, "
        f"{tally['failed']} failed, {tally['quarantined']} quarantined "
        f"(campaign: {q['done']}/{q['total']} done, "
        f"{q['quarantined']} quarantined)"
    )
    return 0 if q["quarantined"] == 0 and q["done"] == q["total"] else 2


def _cmd_status(args) -> int:
    from ..campaign.rollup import write_status
    from ..tools.watch import render_campaign_status

    doc = write_status(args.workdir)
    if args.json:
        print(json.dumps(doc, indent=2))
    else:
        sys.stdout.write(render_campaign_status(doc))
    return 0


def _cmd_retry(args) -> int:
    from ..campaign.queue import JobQueue
    from ..campaign.rollup import write_status
    from ..campaign.runner import load_campaign_config

    campaign = load_campaign_config(args.workdir)
    queue = JobQueue(
        args.workdir,
        lease_s=campaign.lease_s,
        max_attempts=campaign.max_attempts,
        backoff_base_s=campaign.backoff_base_s,
    )
    ids = list(args.job_ids)
    if args.all:
        ids.extend(
            q["job_id"] for q in queue.quarantined()
            if q.get("job_id") not in ids
        )
    if not ids:
        print("nothing to retry (no job ids given; use --all?)")
        return 1
    n = 0
    for jid in ids:
        if queue.retry(jid):
            print(f"re-queued {jid}")
            n += 1
        else:
            print(f"{jid}: not quarantined, skipping")
    write_status(args.workdir, queue)
    return 0 if n else 1


def _cmd_quarantine_list(args) -> int:
    from ..campaign.queue import JobQueue

    queue = JobQueue(args.workdir)
    rows = queue.quarantined()
    if not rows:
        print("quarantine is empty")
        return 0
    for q in rows:
        print(
            f"{q.get('job_id')}  attempts={q.get('attempts')}  "
            f"input={q.get('input')}\n    {q.get('last_error')}"
        )
    return 0


def _cmd_ingest(args) -> int:
    from ..campaign.db import DB_FILENAME, CandidateDB
    from ..campaign.queue import JobQueue

    queue = JobQueue(args.workdir)
    done = queue.done_records()
    if not done:
        print("no completed jobs to ingest")
        return 1
    total = {"periodicity": 0, "single_pulse": 0}
    with CandidateDB(os.path.join(args.workdir, DB_FILENAME)) as db:
        for rec in done:
            jid = rec["job_id"]
            job_dir = os.path.join(args.workdir, "jobs", jid)
            try:
                counts = db.ingest_job(jid, job_dir, rec.get("input", ""))
            except Exception as exc:
                print(f"{jid}: ingest failed: {exc}")
                continue
            for k, v in counts.items():
                total[k] += v
        summary = db.counts()
    print(
        f"ingested {len(done)} jobs: {total['periodicity']} periodicity "
        f"+ {total['single_pulse']} single-pulse candidates "
        f"({summary['observations']} observations in the database)"
    )
    return 0


def _cmd_preempt(args) -> int:
    from ..campaign.queue import JobQueue
    from ..campaign.rollup import write_status

    queue = JobQueue(args.workdir)
    if not queue.request_preempt(
        args.job_id, requester="operator", grace_s=args.grace
    ):
        print(
            f"{args.job_id}: no live claim to preempt "
            f"(state: {queue.state(args.job_id)})"
        )
        return 1
    write_status(args.workdir, queue)
    print(
        f"preempt requested on {args.job_id} (grace {args.grace:g}s); "
        "the victim will checkpoint and release"
    )
    return 0


def _cmd_autoscale(args) -> int:
    from ..campaign.autoscale import AutoscaleController, AutoscalePolicy
    from ..campaign.rollup import write_status

    try:
        controller = AutoscaleController(
            args.workdir,
            AutoscalePolicy(
                min_workers=args.min_workers,
                max_workers=args.max_workers,
                cooldown_s=args.cooldown,
                backlog_per_worker=args.backlog_per_worker,
            ),
            extra_args=args.spawn_arg,
        )
    except ValueError as exc:
        print(f"autoscale: {exc}", file=sys.stderr)
        return 2
    decisions = controller.run(
        poll_s=args.poll, max_runtime_s=args.max_runtime
    )
    write_status(args.workdir)
    ups = sum(1 for d in decisions if d["action"] == "up")
    print(
        f"autoscale: {ups} scale-up(s), {len(decisions) - ups} "
        f"retirement(s); decision log in "
        f"{os.path.join(args.workdir, 'autoscale.json')}"
    )
    return 0


def _cmd_metrics(args) -> int:
    from ..obs.metrics import (
        fleet_samples,
        metrics_paths,
        prometheus_exposition,
        serve_metrics,
    )

    if args.serve:
        try:
            serve_metrics(args.workdir, port=args.port, host=args.host)
        except KeyboardInterrupt:
            pass
        return 0
    if not metrics_paths(args.workdir):
        print(
            f"no metrics files under {args.workdir}/queue/workers/ "
            "(campaign never ran, or ran with --no-metrics)",
            file=sys.stderr,
        )
        return 1
    samples = fleet_samples(args.workdir)
    if args.json:
        print(json.dumps(samples, indent=2))
    else:
        sys.stdout.write(prometheus_exposition(samples))
    return 0


def _cmd_trace(args) -> int:
    from ..campaign.autoscale import load_autoscale_log
    from ..obs.trace import (
        export_chrome_trace,
        load_spans,
        trace_paths,
        trace_summary,
    )

    jobs_dir = os.path.join(args.workdir, "jobs")
    job_ids = list(args.job_ids)
    if not job_ids and os.path.isdir(jobs_dir):
        job_ids = sorted(
            j for j in os.listdir(jobs_dir)
            if trace_paths(os.path.join(jobs_dir, j))
        )
    spans = []
    for jid in job_ids:
        spans.extend(load_spans(trace_paths(os.path.join(jobs_dir, jid))))
    if not spans:
        print(
            f"no trace spans under {jobs_dir} "
            "(campaign never ran, or ran with --no-trace)",
            file=sys.stderr,
        )
        return 1
    extra = None
    if not args.no_autoscale:
        scale = load_autoscale_log(args.workdir) or {}
        extra = [
            {
                "name": f"autoscale:{d.get('action')}",
                "ts_unix": float(d.get("unix", 0.0)),
                "args": {
                    "worker_id": d.get("worker_id"),
                    "reason": d.get("reason"),
                },
            }
            for d in scale.get("decisions") or []
        ]
    doc = export_chrome_trace(spans, extra_instants=extra)
    out = args.output or os.path.join(args.workdir, "trace.json")
    # atomic publish: the default path lands inside the campaign dir,
    # where a watcher (or a second trace invocation) may read it while
    # a soak is still running (PSP101)
    from ..campaign.queue import _atomic_write_json

    _atomic_write_json(out, doc)
    for jid in job_ids:
        summ = trace_summary(
            load_spans(trace_paths(os.path.join(jobs_dir, jid)))
        )
        flag = "" if summ["connected"] else "  *** DISCONNECTED ***"
        print(
            f"{jid}: {summ['n_spans']} spans across "
            f"{len(summ['workers'])} worker(s) "
            f"[{', '.join(summ['workers'])}]"
            f"  trace_id={','.join(summ['trace_ids'])}{flag}"
        )
    print(
        f"exported {len(doc['traceEvents'])} trace events -> {out}\n"
        "view: open https://ui.perfetto.dev and load the file "
        "(or chrome://tracing)"
    )
    return 0


def _cmd_profile(args) -> int:
    from ..campaign.registry import WorkerRegistry

    registry = WorkerRegistry(args.workdir)
    live = {e.get("worker_id") for e in registry.live()}
    if args.worker_id not in live:
        print(
            f"{args.worker_id}: not a live worker "
            f"(live: {sorted(w for w in live if w)})",
            file=sys.stderr,
        )
        return 1
    registry.request_profile(
        args.worker_id, seconds=args.seconds, requester="operator"
    )
    print(
        f"profile requested for {args.worker_id} ({args.seconds:g}s); "
        f"the capture lands under "
        f"{os.path.join(args.workdir, 'profiles')}/ and is announced "
        "in the worker's metrics stream (profile_captures_total)"
    )
    return 0


def _cmd_prune(args) -> int:
    import shutil

    if not args.corrupt and not args.profiles and not args.journals:
        print(
            "prune: nothing selected (pass --corrupt for *.corrupt "
            "quarantine files, --profiles for device-profile capture "
            "directories, and/or --journals to rotate the append-only "
            "journals)"
        )
        return 1
    root = os.path.abspath(args.workdir)
    if args.journals:
        from ..obs.metrics import rotate_journal

        qdir = os.path.join(root, "queue")
        paths = [
            os.path.join(qdir, "alerts.jsonl"),
            os.path.join(qdir, "submissions.jsonl"),
        ]
        paths.extend(sorted(
            glob.glob(os.path.join(qdir, "alerts.*.jsonl"))
        ))
        for path in paths:
            if not os.path.exists(path):
                continue
            before = os.path.getsize(path)
            if args.dry_run:
                if before > args.max_bytes:
                    print(
                        f"prune: would rotate {path} "
                        f"({before} > {args.max_bytes} bytes)"
                    )
                continue
            if rotate_journal(path, args.max_bytes):
                print(
                    f"prune: rotated {path} "
                    f"({before} -> {os.path.getsize(path)} bytes)"
                )
        if not args.corrupt and not args.profiles:
            return 0
    now_unix = time.time()
    cutoff = now_unix - args.older_than_days * 86400.0
    selected: list[tuple[str, bool]] = []  # (path, is_dir)
    if args.corrupt:
        for path in sorted(
            glob.glob(os.path.join(root, "**", "*.corrupt"),
                      recursive=True)
        ):
            try:
                mtime = os.path.getmtime(path)
            except OSError:
                continue  # pruned by a racing invocation
            if mtime <= cutoff:
                selected.append((path, False))
    if args.profiles:
        pdir = os.path.join(root, "profiles")
        for name in sorted(os.listdir(pdir)) if os.path.isdir(
            pdir
        ) else []:
            path = os.path.join(pdir, name)
            if not os.path.isdir(path):
                continue
            try:
                mtime = os.path.getmtime(path)
            except OSError:
                continue
            if mtime <= cutoff:
                selected.append((path, True))
    verb = "would delete" if args.dry_run else "deleted"
    pruned = 0
    for path, is_dir in selected:
        if not args.dry_run:
            try:
                if is_dir:
                    shutil.rmtree(path)
                else:
                    os.unlink(path)
            except OSError as exc:
                print(f"prune: {path}: {exc}")
                continue
        pruned += 1
        print(f"prune: {verb} {path}")
    print(
        f"prune: {verb} {pruned} artifact(s)"
        + (
            f" older than {args.older_than_days:g} day(s)"
            if args.older_than_days else ""
        )
    )
    return 0


def _cmd_serve(args) -> int:
    from ..obs.portal import serve_portal

    try:
        serve_portal(
            args.workdir,
            port=args.port,
            host=args.host,
            max_requests=args.max_requests,
            data_roots=args.data_roots,
        )
    except KeyboardInterrupt:
        pass
    return 0


def _cmd_alerts(args) -> int:
    from ..obs.alerts import evaluate_campaign, load_alerts

    if args.evaluate:
        snap = evaluate_campaign(args.workdir)
    else:
        snap = load_alerts(args.workdir)
    if args.json:
        print(json.dumps(snap, indent=2))
        return 0
    alerts = snap.get("alerts") or []
    if not alerts:
        print("no alerts (campaign healthy, or never evaluated)")
        return 0
    firing = 0
    for a in alerts:
        labels = a.get("labels") or {}
        lbl = " ".join(f"{k}={v}" for k, v in sorted(labels.items()))
        if a.get("state") == "firing":
            firing += 1
        line = (
            f"[{a.get('state'):>8}] {a.get('severity', '?'):<4} "
            f"{a.get('rule')}"
        )
        if lbl:
            line += f"  {lbl}"
        if a.get("message"):
            line += f"  {a['message']}"
        print(line)
    return 2 if firing else 0


def _cmd_sentinel(args) -> int:
    from ..obs.health import enqueue_sentinel, sentinel_status

    if args.check:
        rows = sentinel_status(args.workdir)
        if not rows:
            print("no sentinels enqueued")
            return 0
        missed = 0
        for r in rows:
            if r["status"] == "missed":
                missed += 1
            print(
                f"[{r['status']:>9}] {r['job_id']}  "
                f"dm={r.get('dm', 0):g} t={r.get('time_s', 0):g}s  "
                f"{r.get('detail', '')}"
            )
        return 2 if missed else 0
    doc = enqueue_sentinel(
        args.workdir,
        min_snr=args.min_snr,
        dm_tol=args.dm_tol,
        time_tol_s=args.time_tol,
        nsamps=args.nsamps,
    )
    print(
        f"sentinel enqueued as {doc['job_id']} (priority -1): "
        f"injected DM {doc['dm']:g} at t={doc['time_s']:g}s; recovery "
        "is checked after the job completes and ingests "
        "(`peasoup-campaign sentinel --check`, or the "
        "sentinel_unrecovered alert)"
    )
    return 0


def _tenant_audit(workdir: str, action: str, tenant: str, **extra) -> None:
    """Journal a tenant admin action to queue/submissions.jsonl — the
    same append-only audit trail as submissions, so `who changed what
    when` reads off one file. Secrets never land in the journal: token
    rotation records only a correlation suffix."""
    import time as _time

    from ..campaign.ingest import append_submission

    entry = {
        "t_unix": round(_time.time(), 3),
        "via": "cli",
        "kind": "tenant_admin",
        "action": action,
        "tenant": tenant,
    }
    entry.update(extra)
    append_submission(workdir, entry)


def _cmd_tenant(args) -> int:
    import dataclasses

    from ..campaign.tenants import Tenant, TenantRegistry, throttle_map

    reg = TenantRegistry(args.workdir)
    if args.action != "list" and not args.name:
        print(f"tenant {args.action}: a tenant name is required",
              file=sys.stderr)
        return 2
    if args.action == "add":
        try:
            t = reg.create(Tenant(
                name=args.name,
                token=args.token,
                max_queued=args.max_queued or 0,
                max_running=args.max_running or 0,
                device_seconds=args.device_seconds or 0.0,
                window_s=(
                    3600.0 if args.window_s is None else args.window_s
                ),
                priority_max=args.priority_max,
                watch_dir=args.watch_dir or "",
            ))
        except FileExistsError:
            print(f"tenant add: {args.name!r} already exists",
                  file=sys.stderr)
            return 1
        except ValueError as exc:
            print(f"tenant add: {exc}", file=sys.stderr)
            return 2
        print(f"tenant {t.name} created; token: {t.token}")
        return 0
    if args.action == "rotate-token":
        import uuid

        t = reg.get(args.name)
        if t is None:
            print(f"tenant rotate-token: no such tenant {args.name!r}",
                  file=sys.stderr)
            return 1
        new_token = args.token or uuid.uuid4().hex
        reg.update(dataclasses.replace(t, token=new_token))
        # the registry record is the single source of truth for
        # by_token, so the old secret stops authenticating the moment
        # the atomic rewrite lands
        _tenant_audit(
            args.workdir, "rotate-token", t.name,
            token_suffix=new_token[-6:],
        )
        print(f"tenant {t.name} token rotated; new token: {new_token}")
        print("(the previous token is invalid immediately)")
        return 0
    if args.action == "set-quota":
        t = reg.get(args.name)
        if t is None:
            print(f"tenant set-quota: no such tenant {args.name!r}",
                  file=sys.stderr)
            return 1
        changes: dict = {}
        if args.max_queued is not None:
            changes["max_queued"] = int(args.max_queued)
        if args.max_running is not None:
            changes["max_running"] = int(args.max_running)
        if args.device_seconds is not None:
            changes["device_seconds"] = float(args.device_seconds)
        if args.window_s is not None:
            changes["window_s"] = float(args.window_s)
        if args.priority_max is not None:
            changes["priority_max"] = (
                None if args.priority_max < 0 else int(args.priority_max)
            )
        if args.watch_dir is not None:
            changes["watch_dir"] = args.watch_dir
        if not changes:
            print("tenant set-quota: no quota flags given (nothing to "
                  "change)", file=sys.stderr)
            return 2
        reg.update(dataclasses.replace(t, **changes))
        _tenant_audit(args.workdir, "set-quota", t.name, changes=changes)
        print(f"tenant {t.name} quota updated: " + ", ".join(
            f"{k}={v}" for k, v in sorted(changes.items())
        ))
        return 0
    if args.action == "remove":
        if reg.remove(args.name):
            print(f"tenant {args.name} removed (historical usage and "
                  "done records keep their stamp)")
            return 0
        print(f"tenant remove: no such tenant {args.name!r}",
              file=sys.stderr)
        return 1
    if args.action == "show":
        t = reg.get(args.name)
        if t is None:
            print(f"tenant show: no such tenant {args.name!r}",
                  file=sys.stderr)
            return 1
        print(json.dumps(t.to_doc(), indent=2))
        return 0
    throttles = throttle_map(args.workdir)
    entries = reg.entries()
    if not entries:
        print("no tenants (peasoup-campaign tenant add <name> ...)")
        return 0
    for t in entries:
        quota = ", ".join(
            f"{k}={v}" for k, v in sorted(t.quota_doc().items())
            if v not in (0, 0.0, None) or k == "window_s"
        )
        line = f"{t.name}  {quota or 'unlimited'}"
        thr = throttles.get(t.name)
        if thr:
            line += f"  *** THROTTLED: {thr['reason']} ***"
        print(line)
    return 0


def _cmd_submit(args) -> int:
    from ..campaign.ingest import submit_observation
    from .peasoup import apply_platform_env

    apply_platform_env()
    entry = submit_observation(
        args.workdir,
        args.tenant,
        args.input,
        priority=args.priority,
        config=_load_config_arg(args.config) or None,
        pipeline=args.pipeline,
        via="cli",
    )
    if entry["accepted"]:
        print(f"submitted {entry['job_id']} for tenant {args.tenant}"
              + ("  (priority clamped to tenant ceiling)"
                 if entry.get("priority_capped") else ""))
        return 0
    print(f"submit rejected: {entry['reason']}", file=sys.stderr)
    return 1


def _cmd_ingest_folder(args) -> int:
    from ..campaign.ingest import ingest_watch_folders
    from .peasoup import apply_platform_env

    apply_platform_env()
    t0 = time.perf_counter()
    while True:
        entries = ingest_watch_folders(
            args.workdir, pipeline=args.pipeline
        )
        for e in entries:
            state = "accepted" if e["accepted"] else (
                f"rejected ({e['reason']})"
            )
            print(f"ingest-folder: {e['tenant']}: {e['input']} {state}")
        if not args.poll:
            return 0
        if (
            args.max_runtime is not None
            and time.perf_counter() - t0 >= args.max_runtime
        ):
            return 0
        time.sleep(args.poll)


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    return {
        "run": _cmd_run,
        "status": _cmd_status,
        "retry": _cmd_retry,
        "quarantine-list": _cmd_quarantine_list,
        "ingest": _cmd_ingest,
        "preempt": _cmd_preempt,
        "autoscale": _cmd_autoscale,
        "metrics": _cmd_metrics,
        "trace": _cmd_trace,
        "profile": _cmd_profile,
        "prune": _cmd_prune,
        "serve": _cmd_serve,
        "alerts": _cmd_alerts,
        "sentinel": _cmd_sentinel,
        "tenant": _cmd_tenant,
        "submit": _cmd_submit,
        "ingest-folder": _cmd_ingest_folder,
    }[args.cmd](args)


if __name__ == "__main__":
    sys.exit(main())
