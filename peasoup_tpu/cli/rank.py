"""`peasoup-rank` — train, apply, and gate the candidate scorer.

    # retrain the artifact from the injection machinery (deterministic
    # from the seed; same seed -> same fingerprint)
    python -m peasoup_tpu.cli.rank train -o model.json --seed 42

    # re-score a sifted campaign DB in place (fold products + DM
    # curves are stored in the sift rows, so no raw data is needed)
    python -m peasoup_tpu.cli.rank score -w camp/

    # the CI gate: ROC AUC on a held-out injected ground-truth set
    python -m peasoup_tpu.cli.rank eval --min-auc 0.95

``eval`` exits 2 when the shipped (or ``--model``) artifact scores
below ``--min-auc`` on the held-out injection set — a regression in
the features, the artifact, or the calibration fails CI loudly.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from . import (
    add_observability_args,
    add_version_arg,
    init_observability,
    live_observability,
)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="peasoup-rank",
        description="Peasoup-TPU candidate ranking - batched feature "
        "extraction over sift fold products, a calibrated pure-JAX "
        "scorer trained on the injection machinery, and the ROC gate "
        "CI holds it to",
    )
    add_version_arg(p)
    sub = p.add_subparsers(dest="cmd", required=True)

    tr = sub.add_parser(
        "train", help="train + calibrate the scorer on injected "
        "ground truth and write the model artifact",
    )
    tr.add_argument("-o", "--output", default="model.json",
                    help="artifact output path (default model.json)")
    tr.add_argument("--seed", type=int, default=42,
                    help="training seed (deterministic: same seed, "
                    "same artifact, same fingerprint)")
    tr.add_argument("--examples", type=int, default=1200,
                    help="injected training examples (default 1200)")
    tr.add_argument("--steps", type=int, default=400,
                    help="gradient steps (default 400)")
    tr.add_argument("--hidden", type=int, default=16,
                    help="hidden units (default 16)")
    tr.add_argument("--lr", type=float, default=0.05,
                    help="learning rate (default 0.05)")
    tr.add_argument("--batch", type=int, default=64,
                    help="feature-extraction batch width (default 64)")
    tr.add_argument("-v", "--verbose", action="store_true")
    add_observability_args(tr)

    sc = sub.add_parser(
        "score", help="re-score a sifted campaign database in place "
        "from its stored fold products",
    )
    sc.add_argument("-w", "--workdir", required=True,
                    help="campaign directory (holds candidates.sqlite)")
    sc.add_argument("--db", default="",
                    help="explicit candidates.sqlite path")
    sc.add_argument("--model", default="",
                    help="model artifact (default: the checked-in one)")
    sc.add_argument("--batch", type=int, default=64,
                    help="scoring batch width (default 64)")
    sc.add_argument("-v", "--verbose", action="store_true")
    add_observability_args(sc)

    ev = sub.add_parser(
        "eval", help="ROC/AUC gate on a held-out injected set (exit 2 "
        "below --min-auc)",
    )
    ev.add_argument("--model", default="",
                    help="model artifact (default: the checked-in one)")
    ev.add_argument("--min-auc", type=float, default=0.95,
                    help="minimum held-out ROC AUC (default 0.95)")
    ev.add_argument("--examples", type=int, default=600,
                    help="held-out injected examples (default 600)")
    ev.add_argument("--seed", type=int, default=20260806,
                    help="held-out injection seed (distinct from any "
                    "training seed)")
    ev.add_argument("--json", dest="json_out", default=None,
                    help="also write the evaluation document here")
    ev.add_argument("-v", "--verbose", action="store_true")
    add_observability_args(ev)
    return p


def _cmd_train(args) -> int:
    from ..rank.model import save_model_doc
    from ..rank.train import train_model
    from .peasoup import apply_platform_env

    apply_platform_env()
    tel = init_observability(args)
    tel.set_context(command="rank-train", seed=args.seed)
    workdir = os.path.dirname(os.path.abspath(args.output))
    with tel.activate(), live_observability(
        tel, args, workdir, args.metrics_json
    ):
        doc = train_model(
            seed=args.seed, n_examples=args.examples,
            steps=args.steps, hidden=args.hidden, lr=args.lr,
            batch=args.batch,
        )
        save_model_doc(doc, args.output)
        if args.metrics_json:
            tel.write(args.metrics_json)
    print(
        f"peasoup-rank train: {args.output} "
        f"({doc['fingerprint']}, train AUC {doc['train']['auc']:.4f})"
    )
    return 0


def _cmd_score(args) -> int:
    import numpy as np

    from ..campaign.db import DB_FILENAME, CandidateDB
    from ..rank.model import RankModel, score_tier
    from ..rank.score import neutral_dm_curve, score_fold_products
    from .peasoup import apply_platform_env

    apply_platform_env()
    db_path = args.db or os.path.join(args.workdir, DB_FILENAME)
    if not os.path.exists(db_path):
        print(
            f"peasoup-rank: no database at {db_path}", file=sys.stderr
        )
        return 2
    tel = init_observability(args)
    tel.set_context(command="rank-score", db=db_path)
    with tel.activate(), live_observability(
        tel, args, args.workdir, args.metrics_json
    ):
        model = RankModel.from_file(args.model or None)
        with CandidateDB(db_path) as db:
            rows = [
                r for r in db.sift_catalogue()
                if r.get("fold_json")
            ]
            if not rows:
                print(
                    "peasoup-rank score: no sift rows with fold "
                    "products (run peasoup-sift first)"
                )
                return 0
            stamps = [json.loads(r["fold_json"]) for r in rows]
            prof = np.asarray(
                [s["prof"] for s in stamps], dtype=np.float32
            )
            subints = np.asarray(
                [s["subints"] for s in stamps], dtype=np.float32
            )
            dm_curve = neutral_dm_curve(len(rows))
            for i, s in enumerate(stamps):
                if s.get("dm_curve") is not None:
                    dm_curve[i] = np.asarray(
                        s["dm_curve"], dtype=np.float32
                    )
            _feats, scores = score_fold_products(
                model, prof, subints, dm_curve, batch=args.batch
            )
            scored = [
                {
                    "id": r["id"],
                    "score": round(float(p), 6),
                    "score_tier": score_tier(float(p)),
                    "model_fp": model.fingerprint,
                }
                for r, p in zip(rows, scores)
            ]
            db.update_sift_scores(scored)
        tel.event(
            "rank_scored", rows=len(scored),
            model_fp=model.fingerprint,
        )
        if args.metrics_json:
            tel.write(args.metrics_json)
    tiers = [s["score_tier"] for s in scored]
    print(
        f"peasoup-rank score: {len(scored)} rows re-scored with "
        f"{model.fingerprint} "
        f"(tier1={tiers.count(1)}, tier2={tiers.count(2)}, "
        f"tier3={tiers.count(3)})"
    )
    return 0


def _cmd_eval(args) -> int:
    from ..rank.model import RankModel
    from ..rank.train import evaluate_model
    from .peasoup import apply_platform_env

    apply_platform_env()
    tel = init_observability(args)
    tel.set_context(command="rank-eval", seed=args.seed)
    with tel.activate(), live_observability(
        tel, args, ".", args.metrics_json
    ):
        model = RankModel.from_file(args.model or None)
        ev = evaluate_model(
            model, seed=args.seed, n_examples=args.examples
        )
        tel.event("rank_eval", **ev)
        if args.metrics_json:
            tel.write(args.metrics_json)
    if args.json_out:
        with open(args.json_out, "w") as f:
            json.dump(ev, f, indent=1, sort_keys=True)
            f.write("\n")
    ok = ev["auc"] >= args.min_auc
    print(
        f"peasoup-rank eval: AUC {ev['auc']:.4f} over "
        f"{ev['n_examples']} injected examples ({ev['n_pulsar']} "
        f"pulsars, {ev['n_foil']} RFI foils) with {ev['fingerprint']}; "
        f"pulsar tier-1 fraction {ev['pulsar_tier1_frac']:.2f}, "
        f"foil tier-1 fraction {ev['foil_tier1_frac']:.2f} -> "
        f"{'OK' if ok else f'BELOW --min-auc {args.min_auc}'}"
    )
    return 0 if ok else 2


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    return {
        "train": _cmd_train, "score": _cmd_score, "eval": _cmd_eval,
    }[args.cmd](args)


if __name__ == "__main__":
    sys.exit(main())
