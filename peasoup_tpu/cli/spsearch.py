"""`peasoup-spsearch` — single-pulse search CLI.

No reference equivalent: the CUDA peasoup searches periodicity only,
so surveys pair it with a second tool (Heimdall / GSP) over the same
dedispersed data. Here the single-pulse search is a first-class
workload of the same framework:

  python -m peasoup_tpu.cli.spsearch -i data.fil --dm_end 250 -m 7

Outputs land in the output directory:
  candidates.singlepulse   whitespace table (tools.parsers reads it)
  overview.xml             with a <single_pulse_search> section
  telemetry.json           the machine-readable run manifest

The live-observability stack (--status-json heartbeat, crash flight
recorder, telemetry manifest) is wired exactly like the periodicity
CLIs, so `python -m peasoup_tpu.tools.watch` and `tools.report` work
on single-pulse runs unchanged.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

from . import (
    add_observability_args,
    add_version_arg,
    init_observability,
    live_observability,
)


def default_outdir() -> str:
    return time.strftime("./%Y-%m-%d-%H:%M_spsearch/", time.gmtime())


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="peasoup-spsearch",
        description="Peasoup-TPU single-pulse search - matched-filter "
        "transient detection over the DM-time plane",
    )
    p.add_argument("-i", "--inputfile", required=True,
                   help="File to process (.fil)")
    p.add_argument("-o", "--outdir", default=None,
                   help="The output directory")
    p.add_argument("-k", "--killfile", default="", help="Channel mask file")
    p.add_argument(
        "-t", "--num_threads", type=int, default=14,
        help="Number of device workers (reference: number of GPUs)",
    )
    p.add_argument("--limit", type=int, default=1000,
                   help="upper limit on number of candidates to write out")
    p.add_argument("--dm_start", type=float, default=0.0)
    p.add_argument("--dm_end", type=float, default=100.0)
    p.add_argument("--dm_tol", type=float, default=1.10,
                   help="DM smearing tolerance (1.11=10%%)")
    p.add_argument("--dm_pulse_width", type=float, default=64.0,
                   help="Minimum pulse width (us) for which dm_tol is valid")
    p.add_argument("-m", "--min_snr", type=float, default=6.0,
                   help="single-pulse S/N threshold")
    p.add_argument(
        "--n_widths", type=int, default=12,
        help="number of octave-spaced boxcar widths (1..2^(n-1) samples)",
    )
    p.add_argument(
        "--max_width", type=int, default=0,
        help="cap on the widest boxcar (samples; 0 = n_widths and "
        "trial-length caps only)",
    )
    p.add_argument(
        "--max_events", type=int, default=256,
        help="static per-DM-trial event-compaction size",
    )
    p.add_argument(
        "--time_link", type=float, default=1.0,
        help="friends-of-friends time tolerance in units of the wider "
        "member's boxcar width",
    )
    p.add_argument(
        "--dm_link", type=int, default=2,
        help="friends-of-friends DM-trial adjacency tolerance",
    )
    p.add_argument(
        "--checkpoint", default="",
        help="Checkpoint file for resumable searches",
    )
    p.add_argument(
        "--hbm_bytes", type=int, default=0,
        help="device memory budget in bytes (0 = ask the device; also "
        "PEASOUP_HBM_BYTES)",
    )
    p.add_argument(
        "--dm_block", type=int, default=0,
        help="DM trials per device call (0 = auto from the HBM budget)",
    )
    p.add_argument(
        "--tune", action=argparse.BooleanOptionalAction, default=False,
        help="load per-device tuned dedispersion shape knobs from the "
        "tuning cache (perf/tuning.py), measuring once per new shape "
        "bucket",
    )
    p.add_argument(
        "--tuning-cache", default="",
        help="tuning_cache.json path (default: the per-user cache, "
        "or PEASOUP_TUNING_CACHE)",
    )
    p.add_argument("-v", "--verbose", action="store_true")
    p.add_argument("-p", "--progress_bar", action="store_true")
    add_version_arg(p)
    add_observability_args(p)
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    outdir = args.outdir or default_outdir()
    from .peasoup import apply_platform_env

    apply_platform_env()
    tel = init_observability(args)
    tel.set_context(
        command="spsearch", inputfile=args.inputfile, outdir=outdir
    )
    manifest_path = args.metrics_json or os.path.join(
        outdir.rstrip("/"), "telemetry.json"
    )

    # Heavy imports after arg parsing so --help/--version stay fast
    from ..io.output import OutputFileWriter, write_singlepulse
    from ..io.sigproc import read_filterbank
    from ..pipeline.single_pulse import SinglePulseConfig

    # multi-host aware (JAX_COORDINATOR_ADDRESS & co.): each process
    # searches its DM slice, events are allgathered and clustered
    # globally; single-process this is SinglePulseSearch.run
    from ..parallel.multihost import run_single_pulse_search

    cfg = SinglePulseConfig(
        outdir=outdir,
        killfilename=args.killfile,
        limit=args.limit,
        dm_start=args.dm_start,
        dm_end=args.dm_end,
        dm_tol=args.dm_tol,
        dm_pulse_width=args.dm_pulse_width,
        min_snr=args.min_snr,
        n_widths=args.n_widths,
        max_width=args.max_width,
        max_events=args.max_events,
        time_link=args.time_link,
        dm_link=args.dm_link,
        verbose=args.verbose,
        progress_bar=args.progress_bar,
        max_num_threads=args.num_threads,
        dm_block=args.dm_block,
        hbm_bytes=args.hbm_bytes,
        checkpoint_file=args.checkpoint,
        tune=args.tune,
        tuning_cache=args.tuning_cache,
    )
    os.makedirs(outdir.rstrip("/"), exist_ok=True)
    with tel.activate(), live_observability(
        tel, args, outdir, manifest_path
    ):
        t0 = time.perf_counter()
        tel.set_stage("reading")
        if args.progress_bar:
            print(f"Reading data from {args.inputfile}")
        fil = read_filterbank(args.inputfile)
        reading = time.perf_counter() - t0

        with tel.device_capture():
            result = run_single_pulse_search(fil, cfg)
        result.timers["reading"] = reading
        tel.merge_timers(result.timers)

        import jax

        if jax.process_count() > 1:
            # per-host manifest shard (stage timers here are this
            # host's own slice): telemetry.procN.json, merged with
            # `tools.report --merge`
            base, ext = os.path.splitext(manifest_path)
            tel.write(f"{base}.proc{jax.process_index()}{ext or '.json'}")
        if jax.process_index() != 0:
            # the merged+clustered result is identical on every
            # process; rank 0 writes
            return 0

        tel.set_stage("writing")
        t0 = time.perf_counter()
        write_singlepulse(
            os.path.join(outdir.rstrip("/"), "candidates.singlepulse"),
            result.candidates,
        )
        result.timers["writing"] = time.perf_counter() - t0
        tel.add_timer("writing", result.timers["writing"])

        stats = OutputFileWriter()
        stats.add_misc_info()
        stats.add_header(fil.header)
        stats.add_dm_list(result.dm_list)
        stats.add_device_info()
        stats.add_single_pulse_section(
            cfg, args.inputfile, result.widths, result.candidates
        )
        stats.add_timing_info(result.timers)
        stats.to_file(f"{outdir.rstrip('/')}/overview.xml")

        tel.gauge("candidates.written", len(result.candidates))
        tel.set_stage("done")
        tel.write(manifest_path)
    if args.verbose or args.progress_bar:
        print(
            f"Done: {len(result.candidates)} single-pulse candidates -> "
            f"{outdir} (total {result.timers['total']:.2f}s)"
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
