"""`peasoup-ffa` — FFA pulsar-search pipeline CLI.

Flag-compatible with the reference's FFA spec
(read_ffa_cmdline_options, include/utils/cmdline.hpp:211-292:
-i/-o/-k/-t/--nstreams/--dm_start/--dm_end/--dm_tol/--dm_pulse_width/
--p_start/--p_end/--min_dc/-v/-p with the same defaults), whose
implementing source (`ffa_pipeline.cu`, Makefile:41) is absent from
the reference tree — here the search is implemented for real
(ops/ffa.py). --nstreams and -t are accepted for compatibility; work
scheduling is XLA's, not CUDA streams'.
"""

from __future__ import annotations

import argparse
import sys
import time

import numpy as np

from . import (
    add_observability_args,
    add_version_arg,
    init_observability,
    live_observability,
)


def get_default_ffa_output_filename() -> str:
    """UTC-stamped default like the reference's search CLI
    (cmdline.hpp:53-59)."""
    return time.strftime("./%Y-%m-%d-%H:%M_peasoup_ffa.xml", time.gmtime())


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="peasoup-ffa",
        description="Peasoup/FFAster extension - a TPU FFA pulsar "
        "search pipeline",
    )
    p.add_argument("-i", "--inputfile", required=True,
                   help="File to process (.fil)")
    p.add_argument("-o", "--outfilename",
                   default=None, help="The output filename")
    p.add_argument("-k", "--killfile", default="", help="Channel mask file")
    p.add_argument("-t", "--num_threads", type=int, default=14,
                   help="The number of chips to use")
    p.add_argument("--nstreams", type=int, default=16,
                   help="(compatibility) stream count; scheduling is XLA's")
    p.add_argument("--dm_start", type=float, default=0.0,
                   help="First DM to dedisperse to")
    p.add_argument("--dm_end", type=float, default=100.0,
                   help="Last DM to dedisperse to")
    p.add_argument("--dm_tol", type=float, default=1.10,
                   help="DM smearing tolerance (1.11=10%%)")
    p.add_argument("--dm_pulse_width", type=float, default=64.0,
                   help="Minimum pulse width (us) for which dm_tol is valid")
    p.add_argument("--p_start", type=float, default=0.8,
                   help="Start period for FFA search (s)")
    p.add_argument("--p_end", type=float, default=20.0,
                   help="End period for FFA search (s)")
    p.add_argument("--min_dc", type=float, default=0.001,
                   help="Minimum duty cycle (fraction)")
    p.add_argument("--min_snr", type=float, default=8.0,
                   help="Candidate S/N threshold")
    p.add_argument("--limit", type=int, default=1000,
                   help="Maximum candidates to write")
    p.add_argument("-v", "--verbose", action="store_true")
    p.add_argument("-p", "--progress_bar", action="store_true")
    add_version_arg(p)
    add_observability_args(p)
    return p


def main(argv=None) -> int:
    import os

    args = build_parser().parse_args(argv)
    out = args.outfilename or get_default_ffa_output_filename()
    from .peasoup import apply_platform_env

    apply_platform_env()
    tel = init_observability(args)
    tel.set_context(
        command="peasoup-ffa", inputfile=args.inputfile, outfile=out
    )
    workdir = os.path.dirname(args.metrics_json or out) or "."
    manifest_path = args.metrics_json or os.path.join(
        workdir, "telemetry.json"
    )

    from ..io import read_filterbank
    from ..io.masks import read_killfile
    from ..io.xml_writer import Element
    from ..ops.dedisperse import dedisperse, fil_to_device, output_scale
    from ..ops.ffa import ffa_search_block
    from ..plan.dm_plan import DMPlan
    from ..utils import ProgressBar

    t0 = time.perf_counter()
    with tel.activate(), live_observability(
        tel, args, workdir,
        manifest_path if (args.metrics_json or args.status_json) else None,
    ):
        with tel.stage("reading"):
            fil = read_filterbank(args.inputfile)
        killmask = (
            read_killfile(args.killfile, fil.nchans)
            if args.killfile else None
        )
        dm_plan = DMPlan.create(
            nsamps=fil.nsamps, nchans=fil.nchans, tsamp=fil.tsamp,
            fch1=fil.fch1, foff=fil.foff, dm_start=args.dm_start,
            dm_end=args.dm_end, pulse_width=args.dm_pulse_width,
            tol=args.dm_tol, killmask=killmask,
        )
        tel.gauge("search.n_dm_trials", int(dm_plan.ndm))
        if args.verbose:
            print(f"FFA search: {dm_plan.ndm} DM trials, periods "
                  f"{args.p_start}-{args.p_end} s, min_dc {args.min_dc}")
        # trials are consumed on the host (one FFA per DM trial), so use
        # the host-resident dedisperse variant: HBM holds one block at a
        # time (packed upload + on-device unpack still apply)
        with tel.device_capture():
            with tel.stage("dedispersion"):
                trials = dedisperse(
                    fil_to_device(fil), dm_plan.delay_samples(),
                    dm_plan.killmask, dm_plan.out_nsamps,
                    scale=output_scale(
                        fil.nbits, int(dm_plan.killmask.sum())
                    ),
                )
            tel.capture_device_memory("dedispersion")

            progress = ProgressBar() if args.progress_bar else None
            if progress:
                progress.start()
            if progress:
                inner_progress = progress.update
            elif args.verbose:
                inner_progress = lambda f: print(
                    f"FFA octaves: {f * 100:5.1f}% done"
                )
            else:
                inner_progress = None

            def on_progress(f, _inner=inner_progress):
                # feeds the heartbeat's rate/ETA as well as the bar
                tel.set_progress(round(f * 100.0, 3), 100.0, unit="%")
                if _inner is not None:
                    _inner(f)

            # every octave folds the whole DM-trial block in a handful
            # of batched dispatches (ops/ffa.py: ffa_search_block)
            with tel.stage("ffa_search"):
                cands = ffa_search_block(
                    trials, fil.tsamp, args.p_start, args.p_end,
                    args.min_dc, dm_plan.dm_list, snr_min=args.min_snr,
                    progress=on_progress,
                )
            tel.capture_device_memory("ffa_search")
        tel.set_stage("writing")
    if progress:
        progress.stop()
    if args.verbose:
        print(f"{len(cands)} period-collapsed candidates")

    # ffa_search_block returns the cross-DM period-collapsed list
    unique = cands[: args.limit]

    root = Element("ffa_search")
    params = root.append(Element("search_parameters"))
    for k in ("p_start", "p_end", "min_dc", "dm_start", "dm_end",
              "dm_tol", "dm_pulse_width", "min_snr"):
        params.append(Element(k, getattr(args, k)))
    dm_el = root.append(Element("dedispersion_trials"))
    dm_el.add_attribute("count", dm_plan.ndm)
    cands_el = root.append(Element("candidates"))
    for i, c in enumerate(unique):
        el = cands_el.append(Element("candidate"))
        el.add_attribute("id", i)
        el.append(Element("period", c.period))
        el.append(Element("dm", c.dm))
        el.append(Element("snr", c.snr))
        el.append(Element("width", c.width))
        el.append(Element("duty_cycle", c.dc))
    total = time.perf_counter() - t0
    tel.add_timer("total", total)
    tel.gauge("candidates.final", len(unique))
    times = root.append(Element("execution_times"))
    for key in sorted(tel.timers):
        times.append(Element(key, float(tel.timers[key])))
    with open(out, "w") as f:
        f.write(root.to_string(header=True))
    if args.metrics_json:
        tel.write(args.metrics_json)
    print(f"Done: {len(unique)} FFA candidates -> {out} "
          f"(total {total:.2f}s)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
