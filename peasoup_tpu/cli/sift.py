"""`peasoup-sift` — survey-scale candidate sifting over a campaign DB.

The post-campaign pass: batch-fold every database candidate across
observations, cross-match against a known-pulsar catalogue, veto
multi-beam RFI, merge harmonic duplicates campaign-wide, associate
repeat single pulses (RRAT period inference), and render the survey
report.

    # sift a finished (or still-running) campaign
    python -m peasoup_tpu.cli.sift run -w camp/

    # the survey report: self-contained HTML + schema-valid JSON
    python -m peasoup_tpu.cli.sift report -w camp/ \\
        -o camp/sift/report.html --json camp/sift/report.json

``run`` writes the ``sift_*`` tables into ``candidates.sqlite``
(latest run replaces the previous product wholesale) and the usual
live-observability artefacts under ``<workdir>/sift/`` — status.json
heartbeat with a ``sift`` section, crash flight recorder, telemetry
manifest — so ``peasoup-watch`` and ``peasoup-report`` work on sift
runs unchanged.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from . import (
    add_observability_args,
    add_version_arg,
    init_observability,
    live_observability,
)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="peasoup-sift",
        description="Peasoup-TPU survey sifting - batched folding, "
        "known-source cross-match, campaign-level dedup, multi-beam "
        "vetoing and repeat single-pulse association over the "
        "campaign candidate database",
    )
    add_version_arg(p)
    sub = p.add_subparsers(dest="cmd", required=True)

    run = sub.add_parser(
        "run", help="sift the campaign database end to end and write "
        "the sift_* tables",
    )
    run.add_argument("-w", "--workdir", required=True,
                     help="campaign directory (holds candidates.sqlite)")
    run.add_argument("--db", default="",
                     help="explicit candidates.sqlite path (default "
                     "<workdir>/candidates.sqlite)")
    run.add_argument("--config", default=None,
                     help="SiftConfig overrides as inline JSON or "
                     "@file.json")
    run.add_argument("--catalogue", default="",
                     help="known-pulsar catalogue JSON (default: the "
                     "checked-in convenience catalogue)")
    run.add_argument("--no-fold", action="store_true",
                     help="skip the batched survey folding pass "
                     "(cross-match/dedup then use the search periods)")
    run.add_argument("--incremental", action="store_true",
                     help="no-op (exit 0) unless new observations "
                          "landed in the campaign DB since the last "
                          "sift run's watermark")
    run.add_argument("--fold-batch", type=int, default=None,
                     help="candidates per fixed fold batch "
                     "(default 64)")
    run.add_argument("--tenant", default="",
                     help="sift only observations stamped with this "
                     "tenant (the multi-tenant submission stamp)")
    run.add_argument("-v", "--verbose", action="store_true")
    add_observability_args(run)

    rep = sub.add_parser(
        "report", help="render the survey report from the sifted "
        "database (+ campaign rollup when present)",
    )
    rep.add_argument("-w", "--workdir", required=True)
    rep.add_argument("--db", default="")
    rep.add_argument("-o", "--html", default=None,
                     help="self-contained HTML output path (default "
                     "<workdir>/sift/report.html)")
    rep.add_argument("--json", dest="json_out", default=None,
                     help="schema-validated JSON report path (default "
                     "<workdir>/sift/report.json)")
    rep.add_argument("--limit", type=int, default=50,
                     help="catalogue rows included (default 50)")
    rep.add_argument("--tenant", default="",
                     help="report only rows touching this tenant's "
                     "observations (a filtered view of the sifted "
                     "product; the bowtie honours it too)")
    rep.add_argument("--print-summary", action="store_true",
                     help="also print the tally to stdout")
    return p


def _load_config_arg(text: str | None) -> dict:
    if not text:
        return {}
    if text.startswith("@"):
        with open(text[1:]) as f:
            return json.load(f)
    return json.loads(text)


def _cmd_run(args) -> int:
    import dataclasses

    from ..sift.service import SiftConfig, SiftRun
    from .peasoup import apply_platform_env

    apply_platform_env()
    overrides = _load_config_arg(args.config)
    names = {f.name for f in dataclasses.fields(SiftConfig)}
    unknown = set(overrides) - names
    if unknown:
        print(
            f"peasoup-sift: unknown SiftConfig keys {sorted(unknown)}",
            file=sys.stderr,
        )
        return 2
    overrides["workdir"] = args.workdir
    if args.db:
        overrides["db_path"] = args.db
    if args.catalogue:
        overrides["catalogue"] = args.catalogue
    if args.no_fold:
        overrides["fold"] = False
    if args.fold_batch:
        overrides["fold_batch"] = args.fold_batch
    if args.tenant:
        overrides["tenant"] = args.tenant
    cfg = SiftConfig(**overrides)

    if args.incremental:
        # Before any side effect (makedirs, telemetry): if no new
        # observations landed since the last run's watermark, exit 0
        # without touching anything.
        import json as _json

        from ..campaign.db import CandidateDB

        db_path = cfg.resolved_db()
        if os.path.exists(db_path):
            with CandidateDB(db_path) as db:
                latest = db.latest_sift_run()
                prev_wm = None
                if latest:
                    try:
                        prev_wm = _json.loads(
                            latest.get("config") or "{}"
                        ).get("watermark_rowid")
                    except ValueError:
                        prev_wm = None
                if (
                    prev_wm is not None
                    and db.max_observation_rowid() <= int(prev_wm)
                ):
                    print(
                        "peasoup-sift run: no new observations since "
                        f"run {latest['run_id']} (watermark rowid "
                        f"{int(prev_wm)}); nothing to do"
                    )
                    return 0

    sift_dir = os.path.join(args.workdir, "sift")
    os.makedirs(sift_dir, exist_ok=True)
    if not getattr(args, "status_json", None):
        args.status_json = os.path.join(sift_dir, "status.json")
    manifest_path = args.metrics_json or os.path.join(
        sift_dir, "telemetry.json"
    )
    tel = init_observability(args)
    tel.set_context(
        command="sift", workdir=os.path.abspath(args.workdir),
        db=cfg.resolved_db(),
    )
    with tel.activate(), live_observability(
        tel, args, sift_dir, manifest_path
    ):
        summary = SiftRun(cfg).run()
        tel.write(manifest_path)
    print(
        f"peasoup-sift run {summary['run_id']}: "
        f"{summary['n_folded']} folded, "
        f"{summary['n_catalogue']} catalogue rows "
        f"({summary['n_known']} known, {summary['n_rfi']} rfi), "
        f"{summary['n_sp_sources']} repeat single-pulse source(s) "
        f"over {summary['observations']} observations "
        f"in {summary['duration_s']:.1f}s"
    )
    return 0


def _cmd_report(args) -> int:
    from ..campaign.db import DB_FILENAME, CandidateDB
    from ..sift.report import build_report, write_report

    db_path = args.db or os.path.join(args.workdir, DB_FILENAME)
    if not os.path.exists(db_path):
        print(
            f"peasoup-sift: no database at {db_path}", file=sys.stderr
        )
        return 2
    campaign_status = None
    status_path = os.path.join(args.workdir, "campaign_status.json")
    if os.path.exists(status_path):
        try:
            from ..campaign.rollup import load_campaign_status

            campaign_status = load_campaign_status(status_path)
        except Exception as exc:
            print(
                f"peasoup-sift: ignoring unreadable rollup "
                f"{status_path}: {exc}", file=sys.stderr,
            )
    sift_dir = os.path.join(args.workdir, "sift")
    html_path = args.html or os.path.join(sift_dir, "report.html")
    json_path = args.json_out or os.path.join(sift_dir, "report.json")
    with CandidateDB(db_path) as db:
        doc = build_report(
            db, campaign_status, limit=args.limit,
            tenant=args.tenant or None,
        )
    # the DM-time bowtie diagnostic rides beside the report and is
    # linked from it (a missing/empty SP table renders an empty plot;
    # a failure only loses the plot, never the report)
    bowtie_href = None
    try:
        from ..tools.plotting import bowtie_from_db

        svg = bowtie_from_db(db_path, tenant=args.tenant or None)
        os.makedirs(sift_dir, exist_ok=True)
        bowtie_path = os.path.join(sift_dir, "bowtie.svg")
        tmp = bowtie_path + ".tmp"
        with open(tmp, "w") as f:
            f.write(svg)
        os.replace(tmp, bowtie_path)
        bowtie_href = "bowtie.svg"
    except Exception as exc:
        print(
            f"peasoup-sift: bowtie plot skipped: {exc}", file=sys.stderr
        )
    write_report(doc, json_path, html_path, bowtie_href=bowtie_href)
    print(f"peasoup-sift report: {json_path} + {html_path}")
    if args.print_summary:
        run = doc["run"]
        print(
            f"  run {run['run_id']}: {run['n_catalogue']} catalogue "
            f"rows, {run['n_known']} known, {run['n_rfi']} rfi, "
            f"{run['n_sp_sources']} repeat SP source(s); tiers "
            + ", ".join(
                f"t{k}={v}" for k, v in sorted(doc["tiers"].items())
            )
        )
    return 0


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    return {"run": _cmd_run, "report": _cmd_report}[args.cmd](args)


if __name__ == "__main__":
    sys.exit(main())
