"""Debug buffer dumps.

Reference: ``Utils::dump_device_buffer`` / ``dump_host_buffer``
(include/utils/utils.hpp:62-80) copy a device buffer to the host and
write its raw bytes to a file for offline numpy comparison — the
reference's test programs (e.g. src/rednoise_test.cpp:90-102) rely on
it. Here any array-like (device or host) dumps the same way; read back
with ``np.fromfile(path, dtype=...)``.
"""

from __future__ import annotations

import numpy as np


def dump_buffer(arr, path: str) -> None:
    """Write the raw little-endian bytes of ``arr`` (device or host) to
    ``path`` — same on-disk format as the reference's dumps."""
    host = np.asarray(arr)
    if host.dtype.byteorder == ">":
        host = host.astype(host.dtype.newbyteorder("<"))
    with open(path, "wb") as f:
        f.write(host.tobytes())
