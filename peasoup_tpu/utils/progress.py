"""Search progress reporting.

Reference: ``ProgressBar`` spawns a detached pthread that polls a shared
completion fraction every 100 ms and prints percentage + ETA
(include/utils/progress_bar.hpp:7-44), fed by the DMDispenser
(src/pipeline_multi.cu:57-68).

Here progress is event-driven instead of polled: the search driver owns
the loop over DM blocks, so it can update the bar after each device
step without a thread. Output format (percent + ETA) matches the
reference's. Frames go to **stderr** by default — the reference writes
``\\r`` frames to stdout, which corrupts piped/machine-readable output;
stdout stays reserved for data.
"""

from __future__ import annotations

import sys
import time


class ProgressBar:
    def __init__(self, stream=None, min_interval: float = 0.1) -> None:
        self._stream = stream if stream is not None else sys.stderr
        self._min_interval = min_interval
        self._t0 = 0.0
        self._last = 0.0
        self._active = False
        self._done = False

    def start(self) -> None:
        self._t0 = time.perf_counter()
        self._last = 0.0
        self._active = True
        self._done = False

    def update(self, fraction: float) -> None:
        """fraction in [0, 1]; rate-limited like the 100 ms poll. The
        final (100%) frame bypasses the rate limit — it must always
        render — but renders exactly once however many times completion
        is reported."""
        if not self._active:
            return
        now = time.perf_counter()
        if fraction >= 1.0:
            if self._done:
                return
            self._done = True
        elif now - self._last < self._min_interval:
            return
        self._last = now
        elapsed = now - self._t0
        if fraction > 0:
            eta = elapsed / fraction * (1.0 - fraction)
            eta_str = f"{eta:.1f} s"
        else:
            eta_str = "..."
        self._stream.write(
            f"\rComplete: {100.0 * fraction:.1f}%  ETA: {eta_str}   "
        )
        self._stream.flush()

    def stop(self) -> None:
        if not self._active:
            return
        self.update(1.0)
        self._stream.write("\n")
        self._stream.flush()
        self._active = False
