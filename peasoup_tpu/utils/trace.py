"""Tracing and phase timing.

Reference: NVTX ranges via PUSH_NVTX_RANGE/POP_NVTX_RANGE macros
(include/utils/nvtx.hpp:8-24) around the "Dedisperse", "DM-Loop",
"Acceleration-Loop" and "Harmonic summing" spans, plus a gettimeofday
``Stopwatch`` accumulator (include/utils/stopwatch.hpp:9-144) feeding
the overview.xml <execution_times> table.

TPU equivalent: ``trace_span`` emits a ``jax.profiler.TraceAnnotation``
(visible in TensorBoard/perfetto traces captured with
``jax.profiler.trace``) and the same span names are used by the search
driver; ``Stopwatch`` keeps the reference's accumulate-across-starts
semantics for the XML timing table.
"""

from __future__ import annotations

import time
from contextlib import contextmanager

import jax


class Stopwatch:
    """Accumulating monotonic timer (stopwatch.hpp:9-144 semantics:
    stop() adds to the running total; reset() clears). Durations come
    from ``perf_counter``, not the wall clock — NOTES.md documents 2-3x
    tunnel wall-clock swings that would corrupt accumulated times.

    Also a context manager: ``with sw:`` is start()/stop(). An optional
    ``name`` labels the span in error messages — stopping a stopwatch
    that is not running (e.g. a second stop()) raises naming it, so a
    mispaired timer points at the span that broke, not a bare
    traceback."""

    def __init__(self, name: str | None = None) -> None:
        self.name = name
        self._total = 0.0
        self._t0: float | None = None

    def _label(self) -> str:
        return f" {self.name!r}" if self.name else ""

    def start(self) -> None:
        self._t0 = time.perf_counter()

    def stop(self) -> None:
        if self._t0 is None:
            raise RuntimeError(
                f"Stopwatch{self._label()} stopped while not running: "
                "start() it first (each stop() needs its own start(); "
                "a second stop() on the same span is a bug)"
            )
        self._total += time.perf_counter() - self._t0
        self._t0 = None

    def reset(self) -> None:
        self._total = 0.0
        self._t0 = None

    def getTime(self) -> float:  # noqa: N802 - reference method name
        return self._total

    @property
    def elapsed(self) -> float:
        return self._total

    def __enter__(self) -> "Stopwatch":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.stop()


@contextmanager
def trace_span(name: str, stopwatch: Stopwatch | None = None):
    """Profiler span named like the reference's NVTX ranges, optionally
    accumulating into a Stopwatch for the XML timing table."""
    if stopwatch is not None:
        if stopwatch.name is None:
            stopwatch.name = name  # label mispair errors with the span
        with jax.profiler.TraceAnnotation(name), stopwatch:
            yield
    else:
        with jax.profiler.TraceAnnotation(name):
            yield
