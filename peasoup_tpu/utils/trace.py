"""Tracing and phase timing.

Reference: NVTX ranges via PUSH_NVTX_RANGE/POP_NVTX_RANGE macros
(include/utils/nvtx.hpp:8-24) around the "Dedisperse", "DM-Loop",
"Acceleration-Loop" and "Harmonic summing" spans, plus a gettimeofday
``Stopwatch`` accumulator (include/utils/stopwatch.hpp:9-144) feeding
the overview.xml <execution_times> table.

TPU equivalent: ``trace_span`` emits a ``jax.profiler.TraceAnnotation``
(visible in TensorBoard/perfetto traces captured with
``jax.profiler.trace``) and the same span names are used by the search
driver; ``Stopwatch`` keeps the reference's accumulate-across-starts
semantics for the XML timing table.
"""

from __future__ import annotations

import time
from contextlib import contextmanager

import jax


class Stopwatch:
    """Accumulating monotonic timer (stopwatch.hpp:9-144 semantics:
    stop() adds to the running total; reset() clears). Durations come
    from ``perf_counter``, not the wall clock — NOTES.md documents 2-3x
    tunnel wall-clock swings that would corrupt accumulated times."""

    def __init__(self) -> None:
        self._total = 0.0
        self._t0: float | None = None

    def start(self) -> None:
        self._t0 = time.perf_counter()

    def stop(self) -> None:
        if self._t0 is None:
            raise RuntimeError("Stopwatch stopped before being started")
        self._total += time.perf_counter() - self._t0
        self._t0 = None

    def reset(self) -> None:
        self._total = 0.0
        self._t0 = None

    def getTime(self) -> float:  # noqa: N802 - reference method name
        return self._total

    @property
    def elapsed(self) -> float:
        return self._total


@contextmanager
def trace_span(name: str, stopwatch: Stopwatch | None = None):
    """Profiler span named like the reference's NVTX ranges, optionally
    accumulating into a Stopwatch for the XML timing table."""
    if stopwatch is not None:
        stopwatch.start()
    with jax.profiler.TraceAnnotation(name):
        try:
            yield
        finally:
            if stopwatch is not None:
                stopwatch.stop()
