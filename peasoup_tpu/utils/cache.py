"""Persistent XLA compilation cache wiring.

At survey scale a fresh process pays minutes of XLA compiles (~70 s per
subband-stage shape, ~30 s for the fold phase at 2^21 samples —
NOTES.md); the persistent cache amortises them across processes. Every
entry point (the CLIs via apply_platform_env, bench.py) calls
:func:`enable_compilation_cache` before building programs.
``JAX_COMPILATION_CACHE_DIR`` overrides the location."""

from __future__ import annotations

import os


def default_cache_dir() -> str:
    return os.environ.get(
        "JAX_COMPILATION_CACHE_DIR",
        os.path.join(
            os.environ.get("XDG_CACHE_HOME", os.path.expanduser("~/.cache")),
            "peasoup_tpu", "jax",
        ),
    )


def enable_compilation_cache() -> str | None:
    """Point jax at the persistent on-disk compilation cache and return
    its path (None when it could not be enabled). Safe to call
    repeatedly, before or after backend init; failures are non-fatal
    (an uncached run is just slower)."""
    cache = default_cache_dir()
    try:
        os.makedirs(cache, exist_ok=True)
        import jax

        jax.config.update("jax_compilation_cache_dir", cache)
        # cache everything (default floor would skip fast compiles),
        # unless the operator set their own floor via the env var
        if "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS" not in os.environ:
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 0.0
            )
        return cache
    except Exception:  # read-only home etc.: run without the cache
        return None


def cache_entry_paths(cache_dir: str | None = None) -> list[str]:
    """The persistent cache's entry files (quarantined ``*.corrupt``
    forensics excluded). Empty when the cache dir is absent."""
    d = cache_dir or default_cache_dir()
    try:
        names = os.listdir(d)
    except OSError:
        return []
    return sorted(
        p
        for n in names
        if not n.endswith(".corrupt")
        for p in (os.path.join(d, n),)
        if os.path.isfile(p)
    )


def quarantine_cache_entries(cache_dir: str | None = None) -> list[str]:
    """Move every persistent-cache entry aside to ``*.corrupt`` (rename,
    never delete — the torn bytes are the post-mortem) so the next
    compile repopulates the cache from scratch instead of crashing on a
    garbled deserialisation. The cache is a pure optimisation: losing
    all of it costs recompiles, never correctness — which is why a
    single suspect entry quarantines the lot (XLA's entry filenames are
    opaque hashes; the damaged one cannot be singled out from outside).
    Returns the quarantine paths."""
    from ..resilience import STATS, quarantine_artifact

    out = []
    entries = cache_entry_paths(cache_dir)
    for path in entries:
        q = quarantine_artifact(path)
        if q:
            out.append(q)
    if entries:
        STATS.corrupt_artifact("xla cache")
        try:
            from ..obs.telemetry import current

            current().event(
                "corrupt_artifact", artifact="xla cache",
                path=cache_dir or default_cache_dir(),
                quarantined_to=f"{len(out)} entries",
            )
        except Exception:
            pass  # telemetry must never mask the recovery itself
    return out
