"""Persistent XLA compilation cache wiring.

At survey scale a fresh process pays minutes of XLA compiles (~70 s per
subband-stage shape, ~30 s for the fold phase at 2^21 samples —
NOTES.md); the persistent cache amortises them across processes. Every
entry point (the CLIs via apply_platform_env, bench.py) calls
:func:`enable_compilation_cache` before building programs.
``JAX_COMPILATION_CACHE_DIR`` overrides the location."""

from __future__ import annotations

import os


def default_cache_dir() -> str:
    return os.environ.get(
        "JAX_COMPILATION_CACHE_DIR",
        os.path.join(
            os.environ.get("XDG_CACHE_HOME", os.path.expanduser("~/.cache")),
            "peasoup_tpu", "jax",
        ),
    )


def enable_compilation_cache() -> str | None:
    """Point jax at the persistent on-disk compilation cache and return
    its path (None when it could not be enabled). Safe to call
    repeatedly, before or after backend init; failures are non-fatal
    (an uncached run is just slower)."""
    cache = default_cache_dir()
    try:
        os.makedirs(cache, exist_ok=True)
        import jax

        jax.config.update("jax_compilation_cache_dir", cache)
        # cache everything (default floor would skip fast compiles),
        # unless the operator set their own floor via the env var
        if "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS" not in os.environ:
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 0.0
            )
        return cache
    except Exception:  # read-only home etc.: run without the cache
        return None
