from .trace import Stopwatch, trace_span
from .progress import ProgressBar
