from .trace import Stopwatch, trace_span
from .progress import ProgressBar
from .debug import dump_buffer
