"""``peasoup-audit`` — the static-analysis gate.

Runs the five engines over the repo — AST JAX-hazard lints (PSA),
jitted-program contracts at representative AND campaign-bucket-ladder
shapes (PSC), concurrency/file-protocol lints (PSP), Pallas kernel
contracts (PSK), and protocol model checking (PSM: the real
queue/registry/tenants/alerts code explored under exhaustive
interleavings and crash points against a virtual filesystem) —
applies the baseline ratchet, prints a human report and optionally
writes the versioned ``audit.json``.

Exit codes (scripts/check.sh relies on these):

* ``0`` — clean: no findings outside the baseline
* ``1`` — new findings (or, with ``--strict-resolved``, stale baseline
  entries that should be ratcheted down)
* ``2`` — internal error (engine crash, unreadable baseline, bad args)

Usage::

    python -m peasoup_tpu.tools.audit --baseline audit_baseline.json
    python -m peasoup_tpu.tools.audit --write-baseline   # accept debt
    python -m peasoup_tpu.tools.audit --list-rules
"""

from __future__ import annotations

import argparse
import os
import sys
import traceback


def _repo_root() -> str:
    # tools/ -> peasoup_tpu/ -> repo root
    return os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="peasoup-audit",
        description=(
            "JAX-hazard static analysis: AST lints + jitted-program "
            "jaxpr/StableHLO contract checks"
        ),
    )
    p.add_argument(
        "--root",
        default=_repo_root(),
        help="repo root to audit (default: the installed tree)",
    )
    p.add_argument(
        "--baseline",
        default=None,
        help="ratchet baseline JSON (missing file = empty baseline)",
    )
    p.add_argument(
        "--write-baseline",
        action="store_true",
        help="rewrite --baseline from the current findings and exit 0",
    )
    p.add_argument(
        "--json",
        dest="json_path",
        default=None,
        metavar="PATH",
        help="write the versioned audit.json report here",
    )
    p.add_argument(
        "--rules",
        default=None,
        help="comma-separated rule IDs to run (default: all)",
    )
    p.add_argument(
        "--no-contracts",
        action="store_true",
        help="skip engine 2 (program contract checks, ladder included)",
    )
    p.add_argument(
        "--no-ast",
        action="store_true",
        help="skip engine 1 (AST lints; also disables the PSP/PSK "
        "static rules)",
    )
    p.add_argument(
        "--no-protocol",
        action="store_true",
        help="skip engine 3 (PSP concurrency/file-protocol rules)",
    )
    p.add_argument(
        "--no-kernels",
        action="store_true",
        help="skip engine 4 (PSK Pallas kernel rules + registry "
        "contract checks)",
    )
    p.add_argument(
        "--no-mc",
        action="store_true",
        help="skip engine 5 (PSM protocol model checking: exhaustive "
        "interleaving + crash-point exploration of the file-backed "
        "protocols)",
    )
    p.add_argument(
        "--mc-scenarios",
        default=None,
        metavar="NAMES",
        help="comma-separated mc scenario names to run "
        "(default: the whole library)",
    )
    p.add_argument(
        "--mc-budget",
        type=int,
        default=None,
        metavar="N",
        help="max schedules explored per mc scenario (default 400)",
    )
    p.add_argument(
        "--no-ladder",
        action="store_true",
        help="skip the bucket-ladder contract pass (representative "
        "shapes still checked)",
    )
    p.add_argument(
        "--ladder-rungs",
        type=int,
        default=None,
        metavar="N",
        help="number of bucket-ladder rungs to trace (default 2)",
    )
    p.add_argument(
        "--max-const-bytes",
        type=int,
        default=None,
        help="baked-in constant size threshold (default 1 MiB)",
    )
    p.add_argument(
        "--strict-resolved",
        action="store_true",
        help="fail (exit 1) when baseline entries no longer match",
    )
    p.add_argument(
        "-v",
        "--verbose",
        action="store_true",
        help="print baselined findings in full",
    )
    p.add_argument(
        "--list-rules",
        action="store_true",
        help="print the rule table and exit",
    )
    return p


def _list_rules() -> int:
    from peasoup_tpu.analysis.astlint import rule_classes

    for rule_id, cls in sorted(rule_classes().items()):
        print(f"{rule_id}  [{cls.severity:7s}]  {cls.title}")
        if cls.fix_hint:
            print(f"        hint: {cls.fix_hint}")
    print(
        "PSC101-PSC106 (contract engine): f64 ops, host callbacks / "
        "unexpected custom calls, oversized baked-in constants, "
        "donation mismatch, trace failure, missing bucket-ladder "
        "coverage (representative + ladder-rung shapes)"
    )
    print(
        "PSK202/PSK203/PSK208 (kernel engine, dynamic): registry "
        "drift (deleted probe / unreferenced twin), interpret-mode "
        "lowering failure, Mosaic lowering failure (TPU toolchains)"
    )
    print(
        "PSM300-PSM308 (mc engine, dynamic): protocol model checking "
        "— scenario invariant violations found by exhaustive "
        "interleaving + crash-point exploration of the real "
        "queue/registry/tenants/alerts code over a virtual "
        "filesystem. PSM300 internal (task crash/deadlock), PSM301 "
        "exactly-once claim/complete, PSM302 crash-recovery reap, "
        "PSM303 renew/release-vs-reap ownership, PSM304 preemption "
        "handoff, PSM305 gang assembly, PSM306 registry liveness, "
        "PSM307 tenant throttling, PSM308 alerts lock/journal. Each "
        "finding embeds its minimized schedule; replay with "
        "peasoup_tpu.analysis.mc.replay for a bit-identical trace"
    )
    return 0


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.list_rules:
        return _list_rules()
    try:
        from peasoup_tpu.analysis.findings import Baseline
        from peasoup_tpu.analysis.runner import (
            render_text,
            run_audit,
            write_report,
        )

        rule_ids = None
        if args.rules:
            rule_ids = [r.strip() for r in args.rules.split(",") if r.strip()]
        mc_names = None
        if args.mc_scenarios:
            mc_names = [
                n.strip()
                for n in args.mc_scenarios.split(",")
                if n.strip()
            ]
        result = run_audit(
            args.root,
            rule_ids=rule_ids,
            ast_engine=not args.no_ast,
            contracts=not args.no_contracts,
            protocol=not args.no_protocol,
            kernels=not args.no_kernels,
            ladder=not args.no_ladder,
            ladder_rung_count=args.ladder_rungs,
            baseline_path=args.baseline,
            max_const_bytes=args.max_const_bytes,
            mc=not args.no_mc,
            mc_scenarios=mc_names,
            mc_budget=args.mc_budget,
        )
        if args.write_baseline:
            if not args.baseline:
                print(
                    "peasoup-audit: --write-baseline requires --baseline",
                    file=sys.stderr,
                )
                return 2
            Baseline.from_findings(result.findings).save(args.baseline)
            print(
                f"peasoup-audit: baseline written to {args.baseline} "
                f"({len(result.findings)} finding(s) tolerated)"
            )
            return 0
        if args.json_path:
            write_report(result, args.json_path)
        print(render_text(result, verbose=args.verbose))
        if result.new:
            return 1
        if args.strict_resolved and result.resolved:
            return 1
        return 0
    except Exception:
        traceback.print_exc()
        print("peasoup-audit: internal error (exit 2)", file=sys.stderr)
        return 2


if __name__ == "__main__":
    sys.exit(main())
