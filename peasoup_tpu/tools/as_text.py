"""Dump an overview.xml candidate table as text
(reference: tools/peasoup_as_text.py)."""

from __future__ import annotations

import argparse
import sys


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="peasoup-as-text")
    p.add_argument("overview", help="path to overview.xml")
    args = p.parse_args(argv)
    from .parsers import OverviewFile

    ov = OverviewFile(args.overview)
    cols = ("period", "opt_period", "dm", "acc", "nh", "snr", "folded_snr",
            "is_adjacent", "is_physical", "ddm_count_ratio", "ddm_snr_ratio",
            "nassoc")
    print("#" + "\t".join(cols))
    for row in ov.candidates:
        print("\t".join(str(row[c]) for c in cols))
    return 0


if __name__ == "__main__":
    sys.exit(main())
