"""Tail/render a live ``status.json`` heartbeat.

    python -m peasoup_tpu.tools.watch run/status.json
    python -m peasoup_tpu.tools.watch run/status.json --once

The heartbeat (peasoup_tpu/obs/heartbeat.py, enabled per run with
``--status-json``) atomically rewrites the snapshot every few seconds;
this tool polls it and prints one compact line-block per NEW snapshot
(keyed on ``seq``), so it composes with ``tee``/log collectors instead
of fighting the terminal. It exits when the run reports ``done`` (or
immediately with ``--once``), and flags a heartbeat whose
``updated_unix`` has gone stale — the difference between a run that is
slow and a process that is gone.
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def _bar(frac: float, width: int = 24) -> str:
    filled = int(round(max(0.0, min(1.0, frac)) * width))
    return "#" * filled + "." * (width - filled)


def render_status(st: dict, stale_after: float = 0.0) -> str:
    """One compact text block for a status snapshot."""
    prog = st.get("progress") or {}
    head = (
        f"run {st.get('run_id', '?')}  "
        f"p{st.get('pid', '?')}@{st.get('hostname', '?')}  "
        f"stage={st.get('stage') or '-'}  "
        f"up {st.get('uptime_s', 0.0):.1f}s  seq={st.get('seq', '?')}"
    )
    lines = [head]
    total = prog.get("total")
    if prog:
        frac = prog.get("frac")
        rate = prog.get("rate_per_s")
        eta = prog.get("eta_s")
        unit = prog.get("unit") or ""
        bits = []
        if frac is not None:
            bits.append(f"[{_bar(frac)}] {frac * 100.0:5.1f}%")
        bits.append(
            f"{prog.get('done', 0):g}"
            + (f"/{total:g}" if total else "")
            + (f" {unit}" if unit else "")
        )
        if rate:
            bits.append(f"{rate:.3g} {unit or 'units'}/s")
        if eta is not None:
            bits.append(f"ETA {eta:.1f}s")
        lines.append("  " + "  ".join(bits))
    mem = (st.get("gauges") or {}).get("memory.peak_bytes")
    if mem:
        lines.append(f"  device memory high-water: {mem / 1e9:.2f} GB")
    if st.get("stalled"):
        lines.append(
            f"  *** STALLED: no progress for "
            f"{st.get('last_progress_age_s', 0.0):.0f}s ***"
        )
    age = time.time() - st.get("updated_unix", time.time())
    if stale_after and age > stale_after:
        lines.append(
            f"  *** heartbeat STALE: last update {age:.0f}s ago — "
            f"process dead or wedged? ***"
        )
    for rec in (st.get("events_tail") or [])[-3:]:
        extra = " ".join(
            f"{k}={v}"
            for k, v in rec.items()
            if k not in ("t", "kind")
        )
        lines.append(
            f"  [{rec.get('t', 0.0):9.3f}s] {rec.get('kind', '?')}  "
            f"{extra}"
        )
    if st.get("done"):
        lines.append("  run complete.")
    return "\n".join(lines) + "\n"


def _read(path: str) -> dict | None:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None  # not yet written, or mid-replace on exotic fs


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="peasoup-watch",
        description="Tail/render a live status.json heartbeat",
    )
    p.add_argument("status", help="path to the run's status.json")
    p.add_argument(
        "--interval", type=float, default=1.0,
        help="poll interval in seconds (default 1)",
    )
    p.add_argument(
        "--once", action="store_true",
        help="render the current snapshot once and exit",
    )
    p.add_argument(
        "--timeout", type=float, default=0.0,
        help="give up after this many seconds without a snapshot "
        "appearing (default: wait forever)",
    )
    args = p.parse_args(argv)

    t0 = time.monotonic()
    last_seq = None
    stale_after = max(10.0, 5 * args.interval)
    while True:
        st = _read(args.status)
        if st is None:
            if args.once or (
                args.timeout and time.monotonic() - t0 > args.timeout
            ):
                sys.stderr.write(f"no status at {args.status}\n")
                return 1
            time.sleep(args.interval)
            continue
        if st.get("seq") != last_seq or args.once:
            last_seq = st.get("seq")
            sys.stdout.write(render_status(st, stale_after=stale_after))
            sys.stdout.flush()
        if args.once or st.get("done"):
            return 0
        try:
            time.sleep(args.interval)
        except KeyboardInterrupt:
            return 0


if __name__ == "__main__":
    raise SystemExit(main())
