"""Tail/render a live ``status.json`` heartbeat or a campaign rollup.

    python -m peasoup_tpu.tools.watch run/status.json
    python -m peasoup_tpu.tools.watch run/status.json --once
    python -m peasoup_tpu.tools.watch campaign_dir/          # rollup
    python -m peasoup_tpu.tools.watch campaign_dir/campaign_status.json

The heartbeat (peasoup_tpu/obs/heartbeat.py, enabled per run with
``--status-json``) atomically rewrites the snapshot every few seconds;
this tool polls it and prints one compact line-block per NEW snapshot
(keyed on ``seq``), so it composes with ``tee``/log collectors instead
of fighting the terminal. It exits when the run reports ``done`` (or
immediately with ``--once``), and flags a heartbeat whose
``updated_unix`` has gone stale — the difference between a run that is
slow and a process that is gone.

Campaign mode: pointed at a campaign directory (or its
``campaign_status.json``) it renders the survey-level rollup instead —
queue depths, the running jobs with each one's live stage/progress,
throughput/ETA and the failure/quarantine tallies (the file is
rewritten by every worker after each state transition; see
peasoup_tpu/campaign/rollup.py). The two snapshot kinds are told apart
by their ``schema`` key, so one watch invocation works on both.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def _bar(frac: float, width: int = 24) -> str:
    filled = int(round(max(0.0, min(1.0, frac)) * width))
    return "#" * filled + "." * (width - filled)


def _fmt_s(v) -> str:
    return f"{v * 1e3:.0f}ms" if isinstance(v, (int, float)) else "-"


def render_streaming(sec: dict) -> list[str]:
    """Lines for a status snapshot's ``streaming`` section (written by
    peasoup_tpu/stream/driver.py; schema-dispatched on the key like
    the campaign rollup view)."""
    lines = []
    rate = sec.get("input_rate_sps")
    bits = [
        f"  stream: chunk {sec.get('chunks_done', 0)}  "
        f"triggers={sec.get('triggers', 0)}  "
        f"events={sec.get('events', 0)}"
    ]
    if rate:
        bits.append(f"in {rate:,.0f} samp/s")
    lines.append("  ".join(bits))
    depth = sec.get("queue_depth_blocks")
    if depth is not None:
        lines.append(
            f"  queue {depth}/{sec.get('queue_capacity_blocks', '?')} "
            f"blocks ({sec.get('policy', '?')})  "
            f"{sec.get('chunks_behind', 0):g} chunks behind real-time"
        )
    lat = sec.get("latency_s") or {}
    slo = lat.get("slo")
    misses = lat.get("misses", 0)
    line = (
        f"  latency p50 {_fmt_s(lat.get('p50'))}  "
        f"p95 {_fmt_s(lat.get('p95'))}  "
        f"max {_fmt_s(lat.get('max'))}"
        + (f"  SLO {_fmt_s(slo)}" if slo is not None else "")
    )
    if misses:
        line += f"  *** {misses} SLO MISS{'ES' if misses > 1 else ''} ***"
    lines.append(line)
    drops = sec.get("drops") or {}
    dropped = drops.get("blocks", 0)
    gaps = sec.get("gap_samples", 0)
    if dropped or gaps:
        lines.append(
            f"  *** DROPPED {dropped} blocks "
            f"({drops.get('samples', 0)} samples); "
            f"{gaps} samples zero-filled ***"
        )
    steady = sec.get("jit_programs_steady", 0)
    if steady:
        lines.append(
            f"  *** {steady} steady-state recompile(s): a shape leaked ***"
        )
    return lines


def render_resilience(sec: dict) -> list[str]:
    """Lines for a status snapshot's ``resilience`` section (written by
    peasoup_tpu/resilience/stats.py): only what differs from a clean
    run is shown, so a healthy process renders nothing."""
    lines = []

    def _total(table: str) -> int:
        return sum((sec.get(table) or {}).values())

    bits = []
    for table, label in (
        ("retries", "retries"),
        ("recoveries", "recovered"),
        ("degradations", "degradations"),
        ("corrupt_artifacts", "quarantined artifacts"),
    ):
        n = _total(table)
        if n:
            bits.append(f"{label}={n}")
    faults = sec.get("faults_injected") or {}
    if faults:
        bits.append(
            "faults injected: "
            + " ".join(f"{k}x{v}" for k, v in sorted(faults.items()))
        )
    if bits:
        lines.append("  resilience: " + "  ".join(bits))
    crashes = sec.get("thread_crashes") or {}
    if crashes:
        lines.append(
            "  *** DEGRADED: background thread crash(es): "
            + " ".join(f"{k}x{v}" for k, v in sorted(crashes.items()))
            + " ***"
        )
    giveups = sec.get("giveups") or {}
    if giveups:
        lines.append(
            "  *** retry budget exhausted at: "
            + " ".join(f"{k}x{v}" for k, v in sorted(giveups.items()))
            + " ***"
        )
    return lines


def render_sift(sec: dict) -> list[str]:
    """Lines for a status snapshot's ``sift`` section (written by
    peasoup_tpu/sift/service.py): the current pass and whichever
    tallies exist yet."""
    bits = [f"pass={sec.get('stage', '?')}"]
    for key, label in (
        ("observations", "obs"),
        ("periodicity", "periodicity"),
        ("single_pulse", "single-pulse"),
        ("folded", "folded"),
        ("known", "known"),
        ("catalogue", "catalogue"),
        ("n_sp_sources", "repeat-SP"),
    ):
        if sec.get(key) is not None:
            bits.append(f"{label}={sec[key]}")
    return ["  sift: " + "  ".join(bits)]


def render_status(st: dict, stale_after: float = 0.0) -> str:
    """One compact text block for a status snapshot."""
    prog = st.get("progress") or {}
    head = (
        f"run {st.get('run_id', '?')}  "
        f"p{st.get('pid', '?')}@{st.get('hostname', '?')}  "
        f"stage={st.get('stage') or '-'}  "
        f"up {st.get('uptime_s', 0.0):.1f}s  seq={st.get('seq', '?')}"
    )
    lines = [head]
    total = prog.get("total")
    if prog:
        frac = prog.get("frac")
        rate = prog.get("rate_per_s")
        eta = prog.get("eta_s")
        unit = prog.get("unit") or ""
        bits = []
        if frac is not None:
            bits.append(f"[{_bar(frac)}] {frac * 100.0:5.1f}%")
        bits.append(
            f"{prog.get('done', 0):g}"
            + (f"/{total:g}" if total else "")
            + (f" {unit}" if unit else "")
        )
        if rate:
            bits.append(f"{rate:.3g} {unit or 'units'}/s")
        if eta is not None:
            bits.append(f"ETA {eta:.1f}s")
        lines.append("  " + "  ".join(bits))
    mem = (st.get("gauges") or {}).get("memory.peak_bytes")
    if mem:
        lines.append(f"  device memory high-water: {mem / 1e9:.2f} GB")
    if isinstance(st.get("streaming"), dict):
        lines.extend(render_streaming(st["streaming"]))
    if isinstance(st.get("sift"), dict):
        lines.extend(render_sift(st["sift"]))
    if isinstance(st.get("resilience"), dict):
        lines.extend(render_resilience(st["resilience"]))
    if st.get("stalled"):
        lines.append(
            f"  *** STALLED: no progress for "
            f"{st.get('last_progress_age_s', 0.0):.0f}s ***"
        )
    # audit: ignore[PSA006] -- staleness vs an on-disk epoch stamp
    age = time.time() - st.get("updated_unix", time.time())
    if stale_after and age > stale_after:
        lines.append(
            f"  *** heartbeat STALE: last update {age:.0f}s ago — "
            f"process dead or wedged? ***"
        )
    for rec in (st.get("events_tail") or [])[-3:]:
        extra = " ".join(
            f"{k}={v}"
            for k, v in rec.items()
            if k not in ("t", "kind")
        )
        lines.append(
            f"  [{rec.get('t', 0.0):9.3f}s] {rec.get('kind', '?')}  "
            f"{extra}"
        )
    if st.get("done"):
        lines.append("  run complete.")
    return "\n".join(lines) + "\n"


def render_alerts(sec: dict) -> list[str]:
    """Lines for a campaign rollup's ``alerts`` section (written by
    peasoup_tpu/obs/alerts.py via the rollup): active alerts loud,
    resolved as a tally, nothing when the campaign is healthy."""
    lines: list[str] = []
    if sec.get("invalid"):
        return [f"  *** alerts snapshot invalid: {sec['invalid']} ***"]
    firing = sec.get("firing", 0)
    pending = sec.get("pending", 0)
    resolved = sec.get("resolved", 0)
    if firing or pending or resolved:
        lines.append(
            f"  alerts: {firing} firing  {pending} pending  "
            f"{resolved} resolved"
        )
    for a in sec.get("active") or []:
        labels = a.get("labels") or {}
        lbl = " ".join(f"{k}={v}" for k, v in sorted(labels.items()))
        mark = "***" if a.get("state") == "firing" else "  -"
        line = (
            f"  {mark} [{a.get('severity', '?')}] {a.get('rule', '?')}"
            f" ({a.get('state')})"
        )
        if lbl:
            line += f"  {lbl}"
        if a.get("message"):
            line += f": {a['message']}"
        lines.append(line)
    return lines


def render_data_quality(sec: dict) -> list[str]:
    """Lines for a campaign rollup's ``data_quality`` section
    (obs/health.py summaries): baselines + outliers + injection
    sentinel tallies; quiet when there is nothing to say."""
    lines: list[str] = []
    base = sec.get("baselines") or {}
    if base and sec.get("jobs"):
        bits = [f"  data quality over {sec['jobs']} job(s):"]
        for metric, rec in sorted(base.items()):
            bits.append(
                f"{metric} med {rec.get('median', 0):.3g}"
            )
        lines.append("  ".join(bits))
    outliers = sec.get("outliers") or []
    for o in outliers:
        labels = o.get("labels") or {}
        lines.append(
            f"  *** DQ outlier: job {labels.get('job', '?')} "
            f"{labels.get('metric', '?')} z={o.get('value', '?')} ***"
        )
    sent = sec.get("sentinels") or {}
    if sent.get("total"):
        line = (
            f"  sentinels: {sent.get('recovered', 0)} recovered  "
            f"{sent.get('pending', 0)} pending"
        )
        if sent.get("missed"):
            line += f"  *** {sent['missed']} MISSED ***"
        lines.append(line)
    return lines


def render_tenants(
    sec: dict, usage: dict | None = None, alerts: dict | None = None
) -> list[str]:
    """Lines for a campaign rollup's ``tenants`` section: one row per
    tenant (queued/running/throttled, device-seconds vs budget, firing
    alerts), throttled tenants loud.  Tolerant of pre-tenant rollup
    schemas — every field is optional."""
    if not sec:
        return []
    usage = usage or {}
    firing: dict[str, int] = {}
    for a in (alerts or {}).get("active") or []:
        t = (a.get("labels") or {}).get("tenant")
        if t and a.get("state") == "firing":
            firing[t] = firing.get(t, 0) + 1
    lines = [f"  tenants: {len(sec)}"]
    for name in sorted(sec):
        rec = sec[name] if isinstance(sec[name], dict) else {}
        bits = [
            f"    {name}  q={rec.get('queued', 0)}"
            f" run={rec.get('running', 0)}"
            f" thr={rec.get('throttled', 0)}"
            f" done={rec.get('done', 0)}"
        ]
        wdev = rec.get("window_device_s")
        budget = rec.get("device_s_budget")
        if wdev is not None:
            bits.append(
                f"dev-s {wdev:.1f}/{budget:.0f}"
                if budget else f"dev-s {wdev:.1f}"
            )
        u = usage.get(name) or {}
        if u.get("jobs_failed"):
            bits.append(f"failed={u['jobs_failed']}")
        if firing.get(name):
            bits.append(f"{firing[name]} alert(s) firing")
        if rec.get("throttle"):
            bits.append(f"*** THROTTLED: {rec['throttle']} ***")
        lines.append("  ".join(bits))
    return lines


def render_campaign_status(st: dict, stale_after: float = 0.0) -> str:
    """One compact text block for a campaign_status.json rollup."""
    q = st.get("queue") or {}
    total = q.get("total", 0)
    done = q.get("done", 0)
    head = (
        f"campaign {st.get('root', '?')}\n"
        f"  [{_bar(done / total if total else 0.0)}] "
        f"{done}/{total} done  "
        f"running={q.get('running', 0)}  pending={q.get('pending', 0)}"
        f"+{q.get('backoff', 0)} backing off  "
        f"stale={q.get('stale', 0)}  quarantined={q.get('quarantined', 0)}"
    )
    if q.get("throttled"):
        head += f"  throttled={q['throttled']}"
    lines = [head]
    thr = st.get("throughput_jobs_per_s")
    if thr:
        eta = st.get("eta_s")
        lines.append(
            f"  throughput {thr * 3600.0:.3g} jobs/h"
            + (f"  ETA {eta:.0f}s" if eta is not None else "")
        )
    if st.get("candidates_total"):
        lines.append(f"  candidates so far: {st['candidates_total']}")
    fleet = st.get("fleet") or {}
    live = fleet.get("live") or []
    if live:
        lines.append(f"  fleet: {len(live)} worker(s) live")
        per_worker = fleet.get("workers") or {}
        for w in live:
            wid = w.get("worker_id", "?")
            rate = (per_worker.get(wid) or {}).get("jobs_per_h")
            bits = [
                f"    {wid}  host={w.get('hostname', '?')}"
                f"  done={w.get('jobs_done', 0)}"
            ]
            if rate is not None:
                bits.append(f"{rate:.3g} jobs/h")
            if w.get("current_job"):
                bits.append(f"on {w['current_job']}")
            lines.append("  ".join(bits))
    pre = st.get("preemptions") or {}
    if pre.get("jobs") or pre.get("outstanding_requests"):
        lat = pre.get("latency_s") or {}
        bits = [
            f"  preemptions: {pre.get('total', 0)} revoke(s) over "
            f"{pre.get('jobs', 0)} job(s)"
        ]
        if lat.get("mean") is not None:
            bits.append(
                f"latency mean {lat['mean']:.3g}s max {lat['max']:.3g}s"
            )
        if pre.get("outstanding_requests"):
            bits.append(f"{pre['outstanding_requests']} in flight")
        lines.append("  ".join(bits))
    if st.get("gang_jobs"):
        lines.append(f"  gang jobs done: {st['gang_jobs']}")
    scale = st.get("autoscale") or {}
    if scale.get("decisions"):
        last = scale["decisions"][-1]
        ups = sum(1 for d in scale["decisions"] if d.get("action") == "up")
        downs = len(scale["decisions"]) - ups
        lines.append(
            f"  autoscale: {ups} up / {downs} down; last "
            f"{last.get('action')} {last.get('worker_id')} "
            f"({last.get('reason')})"
        )
    if st.get("degraded_jobs"):
        lines.append(
            f"  *** {st['degraded_jobs']} job(s) completed DEGRADED "
            "(OOM fall-through / crashed helper thread) ***"
        )
    if st.get("corrupt_artifact_files"):
        lines.append(
            f"  {st['corrupt_artifact_files']} quarantined *.corrupt "
            "artifact(s) (prune: peasoup-campaign prune --corrupt)"
        )
    if st.get("warmup_total_s") or st.get("tuning_total_s"):
        lines.append(
            f"  warmup {st.get('warmup_total_s', 0.0):.1f}s over "
            f"{st.get('warmup_jobs', 0)} jobs"
            + (
                f"  tuning {st['tuning_total_s']:.1f}s"
                if st.get("tuning_total_s") else ""
            )
        )
    for key, rec in sorted((st.get("warm_buckets") or {}).items()):
        plan = rec.get("plan") or {}
        if plan:
            lines.append(
                f"  bucket {key}: {rec.get('done', 0)} done, plan "
                f"{plan.get('engine', '?')}"
                + (
                    f"(nsub={plan.get('subbands')})"
                    if plan.get("engine") == "subband" else ""
                )
                + f" block={plan.get('dedisp_block', '?')} "
                f"[{plan.get('source', '?')}]"
            )
    if isinstance(st.get("tenants"), dict) and st["tenants"]:
        lines.extend(render_tenants(
            st["tenants"],
            usage=st.get("usage") if isinstance(st.get("usage"), dict)
            else None,
            alerts=st.get("alerts") if isinstance(st.get("alerts"), dict)
            else None,
        ))
    if isinstance(st.get("alerts"), dict):
        lines.extend(render_alerts(st["alerts"]))
    if isinstance(st.get("data_quality"), dict):
        lines.extend(render_data_quality(st["data_quality"]))
    if isinstance(st.get("resilience"), dict) and st["resilience"]:
        lines.extend(render_resilience(st["resilience"]))
    for rj in st.get("running_jobs") or []:
        prog = rj.get("progress") or {}
        frac = prog.get("frac")
        bits = [f"  run {rj.get('job_id')}  "
                f"worker={rj.get('worker_id', '?')}  "
                f"stage={rj.get('stage') or '-'}"]
        if frac is not None:
            bits.append(f"{frac * 100.0:5.1f}%")
        if rj.get("stalled"):
            bits.append("*** STALLED ***")
        lines.append("  ".join(bits))
    for fl in st.get("failures") or []:
        lines.append(
            f"  retrying {fl.get('job_id')} (attempt {fl.get('attempts')},"
            f" in {fl.get('retry_in_s', 0):.0f}s): {fl.get('last_error')}"
        )
    for ql in st.get("quarantined") or []:
        lines.append(
            f"  QUARANTINED {ql.get('job_id')} after "
            f"{ql.get('attempts')} attempts: {ql.get('last_error')}"
        )
    # audit: ignore[PSA006] -- staleness vs an on-disk epoch stamp
    age = time.time() - st.get("updated_unix", time.time())
    if stale_after and age > stale_after:
        lines.append(
            f"  *** rollup STALE: last update {age:.0f}s ago — "
            f"no worker alive? ***"
        )
    if st.get("done"):
        lines.append("  campaign complete.")
    return "\n".join(lines) + "\n"


_SPARK = " ▁▂▃▄▅▆▇█"


def _sparkline_row(values: list[float | None], width: int) -> str:
    """Unicode sparkline over per-bin values (None = no data)."""
    present = [v for v in values if v is not None]
    if not present:
        return "·" * width
    lo, hi = min(present), max(present)
    span = (hi - lo) or 1.0
    out = []
    for v in values:
        if v is None:
            out.append("·")
        else:
            idx = 1 + int((v - lo) / span * (len(_SPARK) - 2))
            out.append(_SPARK[min(len(_SPARK) - 1, idx)])
    return "".join(out)


def _binned(
    recs: list[dict], t_lo: float, t_hi: float, width: int,
    reduce: str = "last",
) -> list[float | None]:
    """Bin time-ordered samples into ``width`` slots. ``reduce``:
    'last' (gauge semantics), 'sum' (histogram counts), 'max'."""
    bins: list[list[float]] = [[] for _ in range(width)]
    span = (t_hi - t_lo) or 1.0
    for rec in recs:
        t = float(rec.get("t", 0.0))
        if t < t_lo or t > t_hi:
            continue
        i = min(width - 1, int((t - t_lo) / span * width))
        bins[i].append(float(rec.get("value", 0.0)))
    out: list[float | None] = []
    for b in bins:
        if not b:
            out.append(None)
        elif reduce == "sum":
            out.append(sum(b))
        elif reduce == "max":
            out.append(max(b))
        else:
            out.append(b[-1])
    return out


def render_metrics_history(
    samples_by_source: dict, width: int = 48, window_s: float = 3600.0
) -> str:
    """The historical timeline view over a campaign's per-worker
    time-series files (obs/metrics.py): queue depth, completion and
    preemption-latency series rendered as sparklines — "what happened
    over the last hour" without re-running the soak."""
    from ..obs.metrics import series

    all_t = [
        float(r.get("t", 0.0))
        for recs in samples_by_source.values()
        for r in recs
    ]
    if not all_t:
        return "no metrics samples found\n"
    t_hi = max(all_t)
    t_lo = max(min(all_t), t_hi - window_s)
    span = max(1.0, t_hi - t_lo)
    lines = [
        f"metrics history: {len(samples_by_source)} worker(s), "
        f"{len(all_t)} samples over {span:.0f}s"
    ]

    def _row(label: str, values: list, unit: str = "") -> None:
        present = [v for v in values if v is not None]
        if not present:
            return
        lines.append(
            f"  {label:<26} {_sparkline_row(values, width)}  "
            f"min {min(present):g}  max {max(present):g}{unit}"
        )

    for state in ("pending", "running", "done"):
        recs = [
            r
            for r in series(samples_by_source, "queue_depth", "gauge")
            if (r.get("labels") or {}).get("state") == state
        ]
        _row(f"queue depth [{state}]", _binned(recs, t_lo, t_hi, width, "max"))
    _row(
        "jobs done (fleet)",
        _binned(
            series(samples_by_source, "jobs_done_total", "counter"),
            t_lo, t_hi, width, "max",
        ),
    )
    lat = series(
        samples_by_source, "preemption_latency_seconds", "hist"
    )
    _row(
        "preempt latency (s)", _binned(lat, t_lo, t_hi, width, "max"),
    )
    _row(
        "claim wait (s)",
        _binned(
            series(samples_by_source, "claim_wait_seconds", "hist"),
            t_lo, t_hi, width, "max",
        ),
    )
    _row(
        "device mem peak (GB)",
        [
            (v / 1e9 if v is not None else None)
            for v in _binned(
                series(
                    samples_by_source, "device_memory_peak_bytes",
                    "gauge",
                ),
                t_lo, t_hi, width, "max",
            )
        ],
    )
    if len(lines) == 1:
        lines.append("  (no renderable series yet)")
    return "\n".join(lines) + "\n"


def resolve_status_path(path: str) -> str:
    """A directory argument resolves to the campaign rollup inside it
    when one exists (else the single-run status.json)."""
    if os.path.isdir(path):
        camp = os.path.join(path, "campaign_status.json")
        if os.path.exists(camp):
            return camp
        return os.path.join(path, "status.json")
    return path


def _read(path: str) -> dict | None:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None  # not yet written, or mid-replace on exotic fs


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="peasoup-watch",
        description="Tail/render a live status.json heartbeat",
    )
    p.add_argument(
        "status",
        help="path to a run's status.json, a campaign_status.json, or "
        "a campaign directory",
    )
    p.add_argument(
        "--interval", type=float, default=1.0,
        help="poll interval in seconds (default 1)",
    )
    p.add_argument(
        "--once", action="store_true",
        help="render the current snapshot once and exit",
    )
    p.add_argument(
        "--timeout", type=float, default=0.0,
        help="give up after this many seconds without a snapshot "
        "appearing (default: wait forever)",
    )
    p.add_argument(
        "--history", action="store_true",
        help="render the campaign's historical metrics timeline "
        "(queue depth / throughput / preemption latency sparklines "
        "from queue/workers/*.metrics.jsonl) and exit",
    )
    p.add_argument(
        "--window", type=float, default=3600.0,
        help="with --history: how many trailing seconds to render "
        "(default 3600)",
    )
    args = p.parse_args(argv)

    if args.history:
        from ..obs.metrics import fleet_samples

        root = (
            args.status if os.path.isdir(args.status)
            else os.path.dirname(os.path.abspath(args.status))
        )
        samples = fleet_samples(root)
        if not samples:
            sys.stderr.write(
                f"no metrics files under {root}/queue/workers/\n"
            )
            return 1
        sys.stdout.write(
            render_metrics_history(samples, window_s=args.window)
        )
        return 0

    t0 = time.monotonic()
    last_seq = None
    stale_after = max(10.0, 5 * args.interval)
    path = resolve_status_path(args.status)
    while True:
        st = _read(path)
        if st is None:
            # a campaign rollup may appear after the first worker
            # starts — re-resolve directory arguments while waiting
            path = resolve_status_path(args.status)
            if args.once or (
                args.timeout and time.monotonic() - t0 > args.timeout
            ):
                sys.stderr.write(f"no status at {path}\n")
                return 1
            time.sleep(args.interval)
            continue
        campaign = st.get("schema") == "peasoup_tpu.campaign_status"
        # campaign rollups have no seq: key change detection on the
        # writer's timestamp instead
        seq = st.get("updated_unix") if campaign else st.get("seq")
        if seq != last_seq or args.once:
            last_seq = seq
            render = render_campaign_status if campaign else render_status
            sys.stdout.write(render(st, stale_after=stale_after))
            sys.stdout.flush()
        if args.once or st.get("done"):
            return 0
        try:
            time.sleep(args.interval)
        except KeyboardInterrupt:
            return 0


if __name__ == "__main__":
    raise SystemExit(main())
