"""Acc-tie crown stability analysis (PARITY.md, VERDICT r4 item 4).

Context: on tutorial.fil the three accel trials {0, -5, +5} produce
BITWISE-IDENTICAL spectra (shifts < half a sample), so each golden
candidate's acceleration is decided purely by how std::sort's unstable
introsort happens to arrange EXACT S/N ties (distiller.hpp:31) — which
in turn depends on comparator outcomes between UNRELATED rows across
the whole per-DM list. We replay the identical libstdc++ introsort
(native ps_snr_sort_perm_seg) and match the reference's crowned member
on 6/10; the question this module answers quantitatively is whether
the other four are a meaningful target at all.

Method: PEASOUP_TIE_CAPTURE makes the driver dump the raw pre-sort
candidate rows + segment structure (pipeline/search.py
_distill_trials_segmented). :func:`replay` re-runs the full host
distill chain — segmented introsort, harmonic distill, per-DM accel
distill, global DM + harmonic distills — from those rows with an
arbitrary S/N vector, and :func:`mc_crown_stability` Monte-Carlos the
crowns under iid U(-delta, +delta) S/N perturbations at delta = the
combined FFT-rounding bound of the two implementations (ours
<= 4.2e-3 absolute vs the f64 oracle, CUDA's own chain ~1e-4;
PARITY.md "Residual ULP analysis"). A crown whose identity changes
under such perturbations is NOT determined by the physics or the
algorithm — only by sub-rounding comparator noise — so no independent
FFT implementation can be expected to reproduce it.

The distill chain replayed here is the exact production code path
(same native calls, same distiller classes); folding is irrelevant to
crown identity (it reorders final ranks, never the acc of a matched
frequency).
"""

from __future__ import annotations

import numpy as np

COMBINED_FFT_BOUND = 4.3e-3  # ours (<=4.2e-3) + CUDA's (~1e-4), absolute S/N


def load_capture(path: str) -> dict:
    z = np.load(path, allow_pickle=False)
    return {k: z[k] for k in z.files}


def replay(cap: dict, snr: np.ndarray) -> list:
    """Run the full host distill chain on the captured rows with S/N
    vector ``snr`` (same length/order as cap['snr']); returns the final
    candidate list (pre-fold order: S/N descending)."""
    from .. import native
    from ..core.candidates import Candidate
    from ..pipeline.distill import (
        AccelerationDistiller, DMDistiller, HarmonicDistiller,
    )

    freqs = cap["freqs"]
    lvl = cap["lvl"]
    a = cap["a"]
    seg_counts = cap["seg_counts"].astype(np.int64)
    dm_of_seg = cap["dm_of_seg"].astype(np.int64)
    acc_tab = cap["acc_tab"]
    dm_list = cap["dm_list"]
    ndm = len(dm_list)
    snr = np.asarray(snr, np.float64)

    seg_off0 = np.concatenate([np.zeros(1, np.int64), np.cumsum(seg_counts)])
    seg_id = np.repeat(np.arange(seg_counts.size), seg_counts)
    order = native.snr_sort_perm_seg(snr.astype(np.float32), seg_off0)
    if order is None:
        raise RuntimeError("native runtime unavailable: build it first")
    unique = native.harmonic_distill_seg(
        freqs[order], lvl[order], seg_off0,
        float(cap["harm_tol"]), int(cap["harm_max"]),
        bool(cap["harm_frac"]),
    )
    surv = order[unique]
    s_dm = dm_of_seg[seg_id[surv]]
    s_acc = acc_tab[s_dm, a[surv]]
    s_snr = snr[surv]
    s_freq = freqs[surv]
    s_lvl = lvl[surv]

    seg_bounds = np.searchsorted(s_dm, np.arange(ndm + 1))
    order2 = native.snr_sort_perm_seg(
        s_snr.astype(np.float32), seg_bounds.astype(np.int64)
    )
    d_dm, d_a_ = s_dm[order2], s_acc[order2]
    d_lvl, d_snr, d_freq = s_lvl[order2], s_snr[order2], s_freq[order2]
    seg_off2 = np.searchsorted(d_dm, np.arange(ndm + 1))
    unique2, esrc, edst = native.accel_distill_seg(
        d_freq, d_a_, seg_off2, float(cap["acc_tobs_over_c"]),
        float(cap["acc_tol"]),
    )
    row_cands = [
        Candidate(
            dm=float(dm_list[d_dm[r]]), dm_idx=int(d_dm[r]),
            acc=float(d_a_[r]), nh=int(d_lvl[r]), snr=float(d_snr[r]),
            freq=float(d_freq[r]),
        )
        for r in range(len(order2))
    ]
    for s_, t_ in zip(esrc, edst):
        row_cands[s_].append(row_cands[t_])
    per_dm = [
        row_cands[r]
        for dm_idx in range(ndm)
        for r in range(seg_off2[dm_idx], seg_off2[dm_idx + 1])
        if unique2[r]
    ]

    freq_tol = float(cap["freq_tol"])
    max_harm = int(cap["max_harm"])
    dm_still = DMDistiller(freq_tol, keep_related=True)
    harm_still = HarmonicDistiller(
        freq_tol, max_harm, keep_related=True, fractional_harms=False
    )
    return harm_still.distill(dm_still.distill(per_dm))


def crowns_for_golden(cands: list, golden_freqs: np.ndarray) -> list:
    """For each golden frequency (bit-exact f32 match expected), the
    (acc, snr) of our surviving candidate — or None if not recalled."""
    out = []
    for gf in golden_freqs:
        best = None
        for c in cands:
            # golden freqs arrive as 1/period from XML text: equal to
            # our bit-exact f32 freq chain only to print precision
            if abs(c.freq - gf) <= 1e-7 * max(abs(gf), 1.0):
                if best is None or c.snr > best.snr:
                    best = c
        out.append((best.acc, best.snr) if best is not None else None)
    return out


def mc_crown_stability(
    cap: dict,
    golden_freqs: np.ndarray,
    n_draws: int = 200,
    delta: float = COMBINED_FFT_BOUND,
    seed: int = 0,
) -> dict:
    """Monte-Carlo the crowned acc of each golden candidate under iid
    U(-delta, +delta) S/N perturbations. Returns per-candidate crown
    histograms plus the baseline (unperturbed) crowns. A candidate
    whose histogram has more than one key is UNSTABLE at the combined
    FFT-rounding bound: its reference crown is not reproducible by any
    independent FFT implementation."""
    rng = np.random.default_rng(seed)
    base = crowns_for_golden(replay(cap, cap["snr"]), golden_freqs)
    hists: list[dict] = [dict() for _ in golden_freqs]
    snr0 = cap["snr"]
    for _ in range(n_draws):
        pert = snr0 + rng.uniform(-delta, delta, size=snr0.shape)
        crowns = crowns_for_golden(replay(cap, pert), golden_freqs)
        for h, cr in zip(hists, crowns):
            key = None if cr is None else round(cr[0], 6)
            h[key] = h.get(key, 0) + 1
    return {
        "baseline": base,
        "histograms": hists,
        "n_draws": n_draws,
        "delta": delta,
        "unstable": [len(h) > 1 for h in hists],
    }
