"""Render, diff, or merge ``telemetry.json`` run manifests.

The manifest is the machine-readable record a run writes next to
overview.xml (peasoup_tpu/obs/telemetry.py). This tool is the human
end of that pipe:

    python -m peasoup_tpu.tools.report run/telemetry.json
    python -m peasoup_tpu.tools.report before.json after.json   # diff
    python -m peasoup_tpu.tools.report --merge telemetry.proc*.json \\
        -o merged.json                                          # merge

One manifest renders the stage-timer table (the superset of
overview.xml's <execution_times>), counters/gauges (candidate counts
per stage, memory high-water marks), JIT compile stats, the
adaptive-event log, and — when the run was captured with
``--capture-device-trace`` — the per-scope device-time/bytes table
from tools/scope_trace.py. Two manifests render aligned timers and
counters with absolute and relative deltas: the explainability layer
under bench.py's BENCH_*.json wall-clock numbers.

``--merge`` combines the per-host manifest shards a multi-host run
writes (``telemetry.procN.json``, tagged with ``process_index`` /
``hostname``) into ONE merged manifest carrying per-host summaries and
straggler/imbalance statistics: per-stage time spread across hosts
with slowest-host attribution, and wall-clock imbalance. The merged
manifest is itself schema-valid, so it renders and diffs like any
other.

Readers here must tolerate manifests from OLDER schema versions —
every key newer than v1 is accessed with ``.get()`` so a legacy
manifest renders instead of KeyError'ing.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

from ..obs.telemetry import (
    MANIFEST_SCHEMA,
    MANIFEST_VERSION,
    load_manifest,
)


def _fmt_val(v) -> str:
    if isinstance(v, bool):
        return str(v)
    if isinstance(v, float):
        return f"{v:.6g}"
    return str(v)


def _section(title: str) -> list[str]:
    return ["", title, "-" * len(title)]


def render(man: dict, max_events: int = 30) -> str:
    """Pretty-print one manifest (plain, aborted, or merged)."""
    lines = [
        f"telemetry manifest v{man.get('version', '?')}"
        f"  run_id={man.get('run_id', '?')}",
        f"  created: {time.strftime('%Y-%m-%d %H:%M:%SZ', time.gmtime(man.get('created_unix', 0)))}"
        f"  host={man.get('hostname', '?')}  pid={man.get('pid', '?')}",
    ]
    if man.get("process_count", 1) > 1:
        lines.append(
            f"  shard: process {man.get('process_index', 0)}/"
            f"{man.get('process_count', 1)}"
        )
    if man.get("aborted"):
        lines.append(
            f"  ABORTED ({man.get('abort_reason', '?')}) at stage "
            f"{man.get('stage_at_abort', '?')} — partial manifest"
        )
    plat = man.get("platform") or {}
    if plat:
        devs = plat.get("devices") or []
        lines.append(
            f"  platform: jax {plat.get('jax', '?')} "
            f"backend={plat.get('backend', '?')} "
            f"devices={len(devs)} "
            f"process {plat.get('process_index', 0)}/"
            f"{plat.get('process_count', 1)}"
        )
    ctx = man.get("context") or {}
    for k in sorted(ctx):
        lines.append(f"  {k}: {_fmt_val(ctx[k])}")

    if man.get("merged"):
        lines += _render_merged_sections(man)

    timers = man.get("timers") or {}
    if timers:
        lines += _section("stage timers")
        width = max(len(k) for k in timers)
        for k, v in sorted(timers.items(), key=lambda kv: -kv[1]):
            lines.append(f"  {k:<{width}}  {v:10.3f} s")

    for name in ("counters", "gauges"):
        table = man.get(name) or {}
        if table:
            lines += _section(name)
            width = max(len(k) for k in table)
            for k in sorted(table):
                lines.append(f"  {k:<{width}}  {_fmt_val(table[k])}")

    jit = man.get("jit") or {}
    if jit:
        lines += _section("jit compile/lowering")
        width = max(len(k) for k in jit)
        for k in sorted(jit):
            st = jit[k]
            lines.append(
                f"  {k:<{width}}  {st.get('count', 0):5d} x  "
                f"{st.get('seconds', 0.0):8.3f} s"
            )

    events = man.get("events") or []
    if events:
        lines += _section(f"adaptive events ({len(events)})")
        for rec in events[:max_events]:
            extra = " ".join(
                f"{k}={_fmt_val(v)}"
                for k, v in rec.items()
                if k not in ("t", "kind")
            )
            lines.append(
                f"  [{rec.get('t', 0.0):10.3f}s] {rec.get('kind', '?')}"
                f"  {extra}"
            )
        if len(events) > max_events:
            lines.append(f"  ... {len(events) - max_events} more")

    dt = man.get("device_trace")
    if dt:
        lines += _section("device trace (per-scope attribution)")
        lines.append(f"  device busy: {dt.get('device_s', 0.0) * 1e3:.1f} ms")
        phases = dt.get("phases") or {}
        for k in sorted(phases, key=lambda k: -phases[k]):
            lines.append(f"    phase {k:<8} {phases[k] * 1e3:10.1f} ms")
        for row in dt.get("table") or []:
            lines.append(
                f"    {row['seconds'] * 1e3:10.1f} ms  "
                f"{row['gigabytes']:8.2f} GB  {row['scope']}"
            )
    return "\n".join(lines) + "\n"


def _render_merged_sections(man: dict) -> list[str]:
    hosts = man.get("hosts") or []
    lines = _section(f"hosts ({len(hosts)})")
    for h in hosts:
        flags = "  ABORTED" if h.get("aborted") else ""
        lines.append(
            f"  p{h.get('process_index', 0):<3d} "
            f"{h.get('hostname', '?'):<20} "
            f"{h.get('duration_s', 0.0):10.3f} s  "
            f"run_id={h.get('run_id', '?')}{flags}"
        )
    strag = (man.get("straggler") or {}).get("timers") or {}
    if strag:
        lines += _section("per-host stage-time spread (straggler view)")
        width = max(len(k) for k in strag)
        lines.append(
            f"  {'stage':<{width}}  {'min':>9}  {'max':>9}  "
            f"{'spread':>9}  slowest"
        )
        for k, st in sorted(
            strag.items(), key=lambda kv: -kv[1].get("spread", 0.0)
        ):
            lines.append(
                f"  {k:<{width}}  {st.get('min', 0.0):8.3f}s  "
                f"{st.get('max', 0.0):8.3f}s  "
                f"{st.get('spread', 0.0):8.3f}s  "
                f"p{st.get('slowest', {}).get('process_index', '?')}"
                f"@{st.get('slowest', {}).get('hostname', '?')}"
            )
    imb = (man.get("straggler") or {}).get("imbalance")
    if imb:
        lines.append(
            f"  wall-clock imbalance: slowest/mean = "
            f"{imb.get('ratio', 1.0):.3f} "
            f"(slowest p{imb.get('slowest', {}).get('process_index', '?')}"
            f"@{imb.get('slowest', {}).get('hostname', '?')})"
        )
    return lines


def render_timeline(man: dict, width: int = 60) -> str:
    """Historical timeline view of one run: the ``stage`` transition
    events rendered as a gantt over the run's wall clock, with the
    remaining adaptive events as markers — "what was this run doing
    when" from the manifest alone (no live heartbeat needed)."""
    events = man.get("events") or []
    duration = float(man.get("duration_s") or 0.0)
    stages: list[tuple[str, float, float]] = []  # (name, t0, t1)
    cur: tuple[str, float] | None = None
    for rec in events:
        if rec.get("kind") != "stage":
            continue
        t = float(rec.get("t", 0.0))
        if cur is not None:
            stages.append((cur[0], cur[1], t))
        cur = (str(rec.get("name", "?")), t)
    if cur is not None:
        stages.append((cur[0], cur[1], max(duration, cur[1])))
    if not stages:
        return (
            "no stage events in this manifest (older writer?) — "
            "nothing to draw\n"
        )
    total = max(duration, stages[-1][2]) or 1.0
    lines = [
        f"timeline: run {man.get('run_id', '?')}  "
        f"{total:.3f}s wall  ({len(stages)} stage segments)",
        f"  {'stage':<16} 0s{' ' * (width - 6)}{total:8.2f}s",
    ]
    for name, t0, t1 in stages:
        lo = int(t0 / total * width)
        hi = max(lo + 1, int(t1 / total * width))
        bar = " " * lo + "#" * (hi - lo) + " " * (width - hi)
        lines.append(f"  {name:<16} |{bar}|  {t1 - t0:8.3f}s")
    marks = [" "] * width
    other = [r for r in events if r.get("kind") != "stage"]
    for rec in other:
        i = min(width - 1, int(float(rec.get("t", 0.0)) / total * width))
        marks[i] = "*"
    if other:
        lines.append(f"  {'events':<16} |{''.join(marks)}|  ({len(other)})")
    return "\n".join(lines) + "\n"


def diff(a: dict, b: dict, max_events: int = 0) -> str:
    """Aligned comparison of two manifests (timers + counters/gauges):
    the 'why did this BENCH number move' view."""
    lines = [
        f"diff: {a.get('run_id', '?')}  ->  {b.get('run_id', '?')}",
        f"  duration: {a.get('duration_s', 0.0):.3f} s -> "
        f"{b.get('duration_s', 0.0):.3f} s",
    ]
    for name in ("timers", "counters", "gauges"):
        ta, tb = a.get(name) or {}, b.get(name) or {}
        keys = sorted(set(ta) | set(tb))
        if not keys:
            continue
        lines += _section(name)
        width = max(len(k) for k in keys)
        for k in keys:
            va, vb = ta.get(k), tb.get(k)
            if va is None:
                lines.append(f"  {k:<{width}}  (new) -> {_fmt_val(vb)}")
            elif vb is None:
                lines.append(f"  {k:<{width}}  {_fmt_val(va)} -> (gone)")
            else:
                delta = vb - va
                pct = f" ({delta / va * 100.0:+.1f}%)" if va else ""
                lines.append(
                    f"  {k:<{width}}  {_fmt_val(va)} -> {_fmt_val(vb)}"
                    f"  [{delta:+.6g}{pct}]"
                )
    ea, eb = len(a.get("events") or []), len(b.get("events") or [])
    lines += _section("events")
    lines.append(f"  count: {ea} -> {eb}")
    return "\n".join(lines) + "\n"


def merge_manifests(shards: list[dict]) -> dict:
    """Combine per-host manifest shards into one merged manifest with
    straggler/imbalance statistics.

    Merge semantics: ``timers``/``gauges`` take the MAX across hosts
    (a stage is only done when the slowest host is done; gauges are
    high-water marks), ``counters`` SUM (work done), events concatenate
    tagged with their host. The ``straggler`` section carries per-stage
    min/max/mean/spread with slowest-host attribution — the question a
    merged view exists to answer is "which host is dragging the run".
    """
    if not shards:
        raise ValueError("no shards to merge")
    shards = sorted(
        shards,
        key=lambda m: (
            m.get(
                "process_index",
                (m.get("platform") or {}).get("process_index", 0),
            ),
            m.get("hostname", ""),
        ),
    )
    hosts = []
    for man in shards:
        # keep only numeric timers: a malformed shard value must not
        # poison the straggler math or the merged manifest's schema
        timers = {
            k: v
            for k, v in (man.get("timers") or {}).items()
            if isinstance(v, (int, float)) and not isinstance(v, bool)
        }
        host = {
            "process_index": man.get(
                "process_index",
                (man.get("platform") or {}).get("process_index", 0),
            ),
            "hostname": man.get("hostname", "?"),
            "pid": man.get("pid"),
            "run_id": man.get("run_id", "?"),
            "aborted": bool(man.get("aborted", False)),
            "n_events": len(man.get("events") or []),
            "timers": timers,
        }
        # duration is OPTIONAL: an aborted/partial shard without one
        # must not enter the imbalance ranking as a phantom 0.0 s
        # "fastest host"
        if isinstance(man.get("duration_s"), (int, float)):
            host["duration_s"] = float(man["duration_s"])
        hosts.append(host)

    def _host_ref(h: dict) -> dict:
        return {
            "process_index": h["process_index"],
            "hostname": h["hostname"],
        }

    timer_keys = sorted({k for h in hosts for k in h["timers"]})
    straggler_timers: dict[str, dict] = {}
    merged_timers: dict[str, float] = {}
    for k in timer_keys:
        # a shard can be missing a stage entirely (aborted before
        # reaching it, older writer, partial manifest) or carry a
        # non-numeric value: SKIP those hosts rather than KeyError or
        # rank a phantom 0.0 as the fastest host; the entry records who
        # was missing so the straggler view stays honest
        vals = [
            (h["timers"][k], h)
            for h in hosts
            if isinstance(h["timers"].get(k), (int, float))
            and not isinstance(h["timers"].get(k), bool)
        ]
        if not vals:
            continue
        vmin, vmax = (
            min(v for v, _ in vals),
            max(v for v, _ in vals),
        )
        mean = sum(v for v, _ in vals) / len(vals)
        slowest = max(vals, key=lambda vh: vh[0])[1]
        merged_timers[k] = vmax
        if len(vals) > 1:
            straggler_timers[k] = {
                "min": vmin,
                "max": vmax,
                "mean": mean,
                "spread": vmax - vmin,
                "spread_frac": (vmax - vmin) / mean if mean else 0.0,
                "n_hosts": len(vals),
                "slowest": _host_ref(slowest),
            }
            if len(vals) < len(hosts):
                present = {id(h) for _, h in vals}
                straggler_timers[k]["missing"] = [
                    _host_ref(h) for h in hosts if id(h) not in present
                ]

    counters: dict[str, float] = {}
    gauges: dict[str, float] = {}
    for man in shards:
        for k, v in (man.get("counters") or {}).items():
            counters[k] = counters.get(k, 0) + v
        for k, v in (man.get("gauges") or {}).items():
            gauges[k] = max(gauges.get(k, v), v)

    events = []
    for man, h in zip(shards, hosts):
        for rec in man.get("events") or []:
            events.append({**rec, "process_index": h["process_index"]})
    events.sort(key=lambda r: r.get("t", 0.0))

    durations = [
        (h["duration_s"], h) for h in hosts if "duration_s" in h
    ]
    if durations:
        dmax = max(v for v, _ in durations)
        dmean = sum(v for v, _ in durations) / len(durations)
        slowest_host = max(durations, key=lambda vh: vh[0])[1]
    else:  # every shard partial: no imbalance ranking to compute
        dmax = dmean = 0.0
        slowest_host = hosts[0]

    merged = {
        "schema": MANIFEST_SCHEMA,
        "version": MANIFEST_VERSION,
        "run_id": shards[0].get("run_id", "?"),
        "created_unix": min(
            m.get("created_unix", 0.0) for m in shards
        ),
        "duration_s": dmax,
        "merged": True,
        "n_hosts": len(hosts),
        "process_count": max(
            m.get("process_count", len(hosts)) for m in shards
        ),
        "context": shards[0].get("context") or {},
        "hosts": hosts,
        "timers": merged_timers,
        "counters": {k: counters[k] for k in sorted(counters)},
        "gauges": {k: gauges[k] for k in sorted(gauges)},
        "jit": {},
        "events": events,
        "device_trace": None,
        "straggler": {
            "timers": straggler_timers,
            "imbalance": {
                "max_s": dmax,
                "mean_s": dmean,
                "ratio": dmax / dmean if dmean else 1.0,
                "slowest": _host_ref(slowest_host),
            },
        },
    }
    if any(h["aborted"] for h in hosts):
        merged["aborted"] = True
        merged["abort_reason"] = "; ".join(
            f"p{h['process_index']}" for h in hosts if h["aborted"]
        )
    return merged


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="peasoup-report",
        description="Render, diff, or merge telemetry.json run manifests",
    )
    p.add_argument(
        "manifests", nargs="+",
        help="one manifest to render, two to diff (old new), or N "
        "per-host shards with --merge",
    )
    p.add_argument(
        "--events", type=int, default=30,
        help="max adaptive events to render (default 30)",
    )
    p.add_argument(
        "--merge", action="store_true",
        help="combine per-host manifest shards (telemetry.procN.json) "
        "into one merged manifest with straggler statistics",
    )
    p.add_argument(
        "-o", "--output", default=None,
        help="with --merge: write the merged manifest JSON here "
        "(still renders the summary to stdout)",
    )
    p.add_argument(
        "--timeline", action="store_true",
        help="render one manifest's stage transitions as a wall-clock "
        "gantt (the historical what-was-it-doing-when view)",
    )
    args = p.parse_args(argv)
    if args.timeline:
        if len(args.manifests) != 1:
            p.error("--timeline expects exactly one manifest")
        sys.stdout.write(render_timeline(load_manifest(args.manifests[0])))
        return 0
    if args.merge:
        if len(args.manifests) < 2:
            p.error("--merge expects at least two per-host shards")
        merged = merge_manifests(
            [load_manifest(m) for m in args.manifests]
        )
        if args.output:
            with open(args.output, "w") as f:
                json.dump(merged, f, indent=2)
                f.write("\n")
        sys.stdout.write(render(merged, max_events=args.events))
        return 0
    if len(args.manifests) > 2:
        p.error("expected one manifest (render) or two (diff)")
    mans = [load_manifest(m) for m in args.manifests]
    if len(mans) == 1:
        sys.stdout.write(render(mans[0], max_events=args.events))
    else:
        sys.stdout.write(diff(mans[0], mans[1]))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
