"""Render (or diff) ``telemetry.json`` run manifests.

The manifest is the machine-readable record a run writes next to
overview.xml (peasoup_tpu/obs/telemetry.py). This tool is the human
end of that pipe:

    python -m peasoup_tpu.tools.report run/telemetry.json
    python -m peasoup_tpu.tools.report before.json after.json   # diff

One manifest renders the stage-timer table (the superset of
overview.xml's <execution_times>), counters/gauges (candidate counts
per stage, memory high-water marks), JIT compile stats, the
adaptive-event log, and — when the run was captured with
``--capture-device-trace`` — the per-scope device-time/bytes table
from tools/scope_trace.py. Two manifests render aligned timers and
counters with absolute and relative deltas: the explainability layer
under bench.py's BENCH_*.json wall-clock numbers.
"""

from __future__ import annotations

import argparse
import sys
import time

from ..obs.telemetry import load_manifest


def _fmt_val(v) -> str:
    if isinstance(v, bool):
        return str(v)
    if isinstance(v, float):
        return f"{v:.6g}"
    return str(v)


def _section(title: str) -> list[str]:
    return ["", title, "-" * len(title)]


def render(man: dict, max_events: int = 30) -> str:
    """Pretty-print one manifest."""
    lines = [
        f"telemetry manifest v{man['version']}  run_id={man['run_id']}",
        f"  created: {time.strftime('%Y-%m-%d %H:%M:%SZ', time.gmtime(man['created_unix']))}"
        f"  host={man.get('hostname', '?')}  pid={man.get('pid', '?')}",
    ]
    plat = man.get("platform") or {}
    if plat:
        devs = plat.get("devices") or []
        lines.append(
            f"  platform: jax {plat.get('jax', '?')} "
            f"backend={plat.get('backend', '?')} "
            f"devices={len(devs)} "
            f"process {plat.get('process_index', 0)}/"
            f"{plat.get('process_count', 1)}"
        )
    ctx = man.get("context") or {}
    for k in sorted(ctx):
        lines.append(f"  {k}: {_fmt_val(ctx[k])}")

    timers = man.get("timers") or {}
    if timers:
        lines += _section("stage timers")
        width = max(len(k) for k in timers)
        for k, v in sorted(timers.items(), key=lambda kv: -kv[1]):
            lines.append(f"  {k:<{width}}  {v:10.3f} s")

    for name in ("counters", "gauges"):
        table = man.get(name) or {}
        if table:
            lines += _section(name)
            width = max(len(k) for k in table)
            for k in sorted(table):
                lines.append(f"  {k:<{width}}  {_fmt_val(table[k])}")

    jit = man.get("jit") or {}
    if jit:
        lines += _section("jit compile/lowering")
        width = max(len(k) for k in jit)
        for k in sorted(jit):
            st = jit[k]
            lines.append(
                f"  {k:<{width}}  {st['count']:5d} x  "
                f"{st['seconds']:8.3f} s"
            )

    events = man.get("events") or []
    if events:
        lines += _section(f"adaptive events ({len(events)})")
        for rec in events[:max_events]:
            extra = " ".join(
                f"{k}={_fmt_val(v)}"
                for k, v in rec.items()
                if k not in ("t", "kind")
            )
            lines.append(f"  [{rec['t']:10.3f}s] {rec['kind']}  {extra}")
        if len(events) > max_events:
            lines.append(f"  ... {len(events) - max_events} more")

    dt = man.get("device_trace")
    if dt:
        lines += _section("device trace (per-scope attribution)")
        lines.append(f"  device busy: {dt.get('device_s', 0.0) * 1e3:.1f} ms")
        phases = dt.get("phases") or {}
        for k in sorted(phases, key=lambda k: -phases[k]):
            lines.append(f"    phase {k:<8} {phases[k] * 1e3:10.1f} ms")
        for row in dt.get("table") or []:
            lines.append(
                f"    {row['seconds'] * 1e3:10.1f} ms  "
                f"{row['gigabytes']:8.2f} GB  {row['scope']}"
            )
    return "\n".join(lines) + "\n"


def diff(a: dict, b: dict, max_events: int = 0) -> str:
    """Aligned comparison of two manifests (timers + counters/gauges):
    the 'why did this BENCH number move' view."""
    lines = [
        f"diff: {a['run_id']}  ->  {b['run_id']}",
        f"  duration: {a.get('duration_s', 0.0):.3f} s -> "
        f"{b.get('duration_s', 0.0):.3f} s",
    ]
    for name in ("timers", "counters", "gauges"):
        ta, tb = a.get(name) or {}, b.get(name) or {}
        keys = sorted(set(ta) | set(tb))
        if not keys:
            continue
        lines += _section(name)
        width = max(len(k) for k in keys)
        for k in keys:
            va, vb = ta.get(k), tb.get(k)
            if va is None:
                lines.append(f"  {k:<{width}}  (new) -> {_fmt_val(vb)}")
            elif vb is None:
                lines.append(f"  {k:<{width}}  {_fmt_val(va)} -> (gone)")
            else:
                delta = vb - va
                pct = f" ({delta / va * 100.0:+.1f}%)" if va else ""
                lines.append(
                    f"  {k:<{width}}  {_fmt_val(va)} -> {_fmt_val(vb)}"
                    f"  [{delta:+.6g}{pct}]"
                )
    ea, eb = len(a.get("events") or []), len(b.get("events") or [])
    lines += _section("events")
    lines.append(f"  count: {ea} -> {eb}")
    return "\n".join(lines) + "\n"


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="peasoup-report",
        description="Render or diff telemetry.json run manifests",
    )
    p.add_argument(
        "manifests", nargs="+",
        help="one manifest to render, or two to diff (old new)",
    )
    p.add_argument(
        "--events", type=int, default=30,
        help="max adaptive events to render (default 30)",
    )
    args = p.parse_args(argv)
    if len(args.manifests) > 2:
        p.error("expected one manifest (render) or two (diff)")
    mans = [load_manifest(m) for m in args.manifests]
    if len(mans) == 1:
        sys.stdout.write(render(mans[0], max_events=args.events))
    else:
        sys.stdout.write(diff(mans[0], mans[1]))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
