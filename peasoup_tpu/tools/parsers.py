"""Post-processing parsers for peasoup output files.

Reference: tools/peasoup_tools.py — OverviewFile parses overview.xml
into a candidate recarray (with a workaround for invalid bytes in
<username>, peasoup_tools.py:110-118); CandidateFileParser seeks a
candidate's byte_offset in candidates.peasoup and reads the optional
FOLD block plus the detection (hit) list.
"""

from __future__ import annotations

import re
import struct
import xml.etree.ElementTree as ET

import numpy as np

from ..core.candidates import CANDIDATE_POD_DTYPE

CAND_FIELDS = [
    ("period", "f8"),
    ("opt_period", "f8"),
    ("dm", "f4"),
    ("acc", "f4"),
    ("nh", "i4"),
    ("snr", "f4"),
    ("folded_snr", "f4"),
    ("is_adjacent", "i4"),
    ("is_physical", "i4"),
    ("ddm_count_ratio", "f4"),
    ("ddm_snr_ratio", "f4"),
    ("nassoc", "i4"),
    ("byte_offset", "i8"),
    # FDAS extras (io/output.py add_fdas_section); absent elements
    # parse as 0 via vals.get, so plain periodicity overviews are
    # unaffected
    ("fdot", "f4"),
    ("fddot", "f4"),
]


# single-pulse candidate fields (io/output.py SINGLEPULSE_COLUMNS
# minus the time/snr formatting): one row per cluster
SP_CAND_FIELDS = [
    ("dm", "f4"),
    ("snr", "f4"),
    ("time_s", "f8"),
    ("sample", "i8"),
    ("width", "i4"),
    ("width_idx", "i4"),
    ("dm_idx", "i4"),
    ("members", "i4"),
    ("sample_lo", "i8"),
    ("sample_hi", "i8"),
    ("dm_idx_lo", "i4"),
    ("dm_idx_hi", "i4"),
    ("width_lo", "i4"),
    ("width_hi", "i4"),
]


def read_singlepulse(path: str) -> np.ndarray:
    """Parse a ``.singlepulse`` text table (io.output.write_singlepulse)
    into a recarray with SP_CAND_FIELDS. The '#' header row names the
    columns, so extra/reordered columns from newer writers parse by
    NAME (missing fields default to 0)."""
    with open(path, "r", encoding="ascii") as f:
        lines = [ln.strip() for ln in f if ln.strip()]
    names = None
    rows = []
    for ln in lines:
        if ln.startswith("#"):
            if names is None:
                names = ln.lstrip("# ").split()
            continue
        rows.append(ln.split())
    if names is None:
        names = [fname for fname, _ in SP_CAND_FIELDS]
    out = np.zeros(len(rows), dtype=SP_CAND_FIELDS)
    col_of = {n: i for i, n in enumerate(names)}
    for fname, ftype in SP_CAND_FIELDS:
        ci = col_of.get(fname)
        if ci is None:
            continue
        vals = [r[ci] if ci < len(r) else 0 for r in rows]
        out[fname] = np.asarray(vals, dtype=np.dtype(ftype))
    return out


class OverviewFile:
    """Parse overview.xml into header/search dicts + candidate recarray
    (plus, when a <single_pulse_search> section is present, the
    single-pulse width list and candidate recarray)."""

    def __init__(self, path: str):
        with open(path, "rb") as f:
            raw = f.read()
        # strip invalid bytes that the reference writer can emit in
        # <username> (peasoup_tools.py:110-118)
        raw = re.sub(rb"<username>.*?</username>", b"<username></username>", raw,
                     flags=re.S)
        self.root = ET.fromstring(raw.decode("latin-1"))
        self.header = self._section_dict("header_parameters")
        self.search_parameters = self._section_dict("search_parameters")
        self.execution_times = {
            k: float(v) for k, v in self._section_dict("execution_times").items()
        }
        self.dm_list = np.array(
            [float(t.text) for t in self.root.findall("dedispersion_trials/trial")]
        )
        self.acc_list = np.array(
            [float(t.text) for t in self.root.findall("acceleration_trials/trial")]
        )
        self.candidates = self._parse_candidates()
        self.sp_parameters = self._sp_section_dict("search_parameters")
        self.sp_widths = np.array(
            [
                int(t.text)
                for t in self.root.findall(
                    "single_pulse_search/width_trials/trial"
                )
            ],
            dtype=np.int64,
        )
        self.sp_candidates = self._parse_sp_candidates()

    def _sp_section_dict(self, name: str) -> dict:
        node = self.root.find(f"single_pulse_search/{name}")
        if node is None:
            return {}
        return {child.tag: (child.text or "") for child in node}

    def _parse_sp_candidates(self) -> np.ndarray:
        rows = []
        for cand in self.root.findall(
            "single_pulse_search/candidates/candidate"
        ):
            vals = {c.tag: c.text for c in cand}
            rows.append(
                tuple(
                    np.dtype(ftype).type(vals.get(fname, 0) or 0)
                    for fname, ftype in SP_CAND_FIELDS
                )
            )
        return np.array(rows, dtype=SP_CAND_FIELDS)

    def _section_dict(self, name: str) -> dict:
        node = self.root.find(name)
        if node is None:
            return {}
        return {child.tag: (child.text or "") for child in node}

    def _parse_candidates(self) -> np.ndarray:
        rows = []
        for cand in self.root.findall("candidates/candidate"):
            vals = {c.tag: c.text for c in cand}
            rows.append(
                tuple(
                    np.dtype(ftype).type(vals.get(fname, 0) or 0)
                    for fname, ftype in CAND_FIELDS
                )
            )
        return np.array(rows, dtype=CAND_FIELDS)

    def make_predictor(self, idx: int) -> str:
        """TEMPO-style predictor text for one candidate
        (peasoup_tools.py:153-164)."""
        c = self.candidates[idx]
        period = c["opt_period"] if c["opt_period"] else c["period"]
        mjd = float(self.header.get("tstart", 0))
        return (
            "SOURCE: {src}\nPERIOD: {p:.15f}\nDM: {dm:.3f}\nACC: {acc:.3f}\n"
            "PEPOCH: {mjd:.10f}\n".format(
                src=self.header.get("source_name", "unknown"),
                p=float(period),
                dm=float(c["dm"]),
                acc=float(c["acc"]),
                mjd=mjd,
            )
        )


class CandidateFileParser:
    """Read candidates.peasoup records by byte offset
    (tools/peasoup_tools.py:46-80)."""

    def __init__(self, path: str):
        self.path = path
        self.f = open(path, "rb")

    def close(self):
        self.f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def read_candidate(self, byte_offset: int) -> dict:
        self.f.seek(byte_offset)
        magic = self.f.read(4)
        fold = None
        nbins = nints = 0
        if magic == b"FOLD":
            nbins, nints = struct.unpack("<ii", self.f.read(8))
            fold = np.frombuffer(
                self.f.read(4 * nbins * nints), dtype="<f4"
            ).reshape(nints, nbins)
        else:
            self.f.seek(byte_offset)
        (ndets,) = struct.unpack("<i", self.f.read(4))
        hits = np.frombuffer(
            self.f.read(CANDIDATE_POD_DTYPE.itemsize * ndets),
            dtype=CANDIDATE_POD_DTYPE,
        )
        return {"fold": fold, "nbins": nbins, "nints": nints, "hits": hits}
