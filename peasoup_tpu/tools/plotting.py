"""Candidate diagnostic plotting (reference: tools/peasoup_tools.py:167-383
CandidatePlotter). Requires matplotlib; import-guarded so headless
installs work without it."""

from __future__ import annotations

import numpy as np


class CandidatePlotter:
    """Plot profile / subints / DM-acc scatter for one candidate."""

    def __init__(self, overview, cand_file_parser):
        self.overview = overview
        self.parser = cand_file_parser

    def plot(self, idx: int, outfile: str | None = None):
        import matplotlib

        if outfile:
            matplotlib.use("Agg")
        import matplotlib.pyplot as plt

        cand = self.overview.candidates[idx]
        rec = self.parser.read_candidate(int(cand["byte_offset"]))
        fig, axes = plt.subplots(2, 2, figsize=(10, 8))
        fig.suptitle(
            f"cand {idx}: P={cand['period']:.6f}s DM={cand['dm']:.2f} "
            f"acc={cand['acc']:.2f} snr={cand['snr']:.1f}"
        )
        if rec["fold"] is not None:
            prof = rec["fold"].mean(axis=0)
            axes[0, 0].plot(np.r_[prof, prof])
            axes[0, 0].set_title("profile (x2 phase)")
            axes[0, 1].imshow(rec["fold"], aspect="auto", origin="lower")
            axes[0, 1].set_title("subints")
        hits = rec["hits"]
        if len(hits):
            axes[1, 0].scatter(hits["dm"], hits["snr"], s=8)
            axes[1, 0].set_xlabel("DM")
            axes[1, 0].set_ylabel("S/N")
            axes[1, 1].scatter(hits["acc"], hits["snr"], s=8)
            axes[1, 1].set_xlabel("acc")
            axes[1, 1].set_ylabel("S/N")
        if outfile:
            fig.savefig(outfile, dpi=100, bbox_inches="tight")
            plt.close(fig)
            return outfile
        return fig


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(prog="peasoup-plot-cand")
    p.add_argument("overview")
    p.add_argument("candfile")
    p.add_argument("idx", type=int)
    p.add_argument("-o", "--outfile", default="cand.png")
    args = p.parse_args(argv)
    from .parsers import CandidateFileParser, OverviewFile

    ov = OverviewFile(args.overview)
    with CandidateFileParser(args.candfile) as cp:
        CandidatePlotter(ov, cp).plot(args.idx, args.outfile)
    print(args.outfile)
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
