"""Candidate diagnostic plotting.

Full diagnostic-sheet parity with the reference's CandidatePlotter
(reference: tools/peasoup_tools.py:167-383): pulse profile over two
phase turns, folded subintegrations image with a per-subint statistics
side panel, a parameter table, per-harmonic DM-S/N and acc-S/N
scatters, the DM-acceleration plane sized by S/N, and an all-candidate
period-DM overview with a crosshair on the plotted candidate.
Requires matplotlib; import-guarded so headless installs work
without it (tests render with the Agg backend).
"""

from __future__ import annotations

import numpy as np

_HARM_COLORS = ("#1f3d7a", "#7aa6d9", "#2e8b57", "#e08a2e", "#8b1a1a")


def _radec_str(v: float, hours: bool) -> str:
    """Sigproc packed ddmmss.s / hhmmss.s float to a display string."""
    sign = "-" if v < 0 else ""
    v = abs(v)
    d = int(v // 10000)
    m = int((v - d * 10000) // 100)
    s = v - d * 10000 - m * 100
    unit = "h" if hours else "d"
    return f"{sign}{d:02d}{unit}{m:02d}m{s:05.2f}s"


class CandidatePlotter:
    """Render one candidate's full diagnostic sheet from an
    overview.xml + candidates.peasoup pair."""

    def __init__(self, overview, cand_file_parser):
        self.overview = overview
        self.parser = cand_file_parser

    # ---- panel painters -------------------------------------------------

    def _profile(self, ax, fold):
        prof = fold.sum(axis=0)
        ax.plot(np.r_[prof, prof], color="#1f3d7a", lw=1.2)
        ax.axvline(len(prof) - 0.5, color="0.8", lw=0.8)
        ax.set_title("Profile (2 turns)")
        ax.set_xlim(0, 2 * len(prof) - 1)
        ax.tick_params(labelbottom=False, labelleft=False)

    def _subints(self, ax, fold):
        ax.imshow(
            np.r_[fold.T, fold.T].T, aspect="auto", origin="lower",
            interpolation="nearest", cmap="viridis",
        )
        ax.set_xlabel("Phase bin (2 turns)")
        ax.set_ylabel("Subintegration")

    def _subint_stats(self, ax, fold):
        y = np.arange(fold.shape[0])
        mean = fold.mean(axis=1)
        std = fold.std(axis=1)
        ax.fill_betweenx(
            y, mean - 3 * std, mean + 3 * std, alpha=0.4,
            color="#7aa6d9", label="±3σ",
        )
        ax.plot(mean, y, color="#1f3d7a", lw=1.5, label="mean")
        ax.plot(fold.max(axis=1), y, color="#8b1a1a", lw=1.0, label="max")
        ax.invert_xaxis()
        ax.set_ylim(-0.5, fold.shape[0] - 0.5)
        ax.set_title("Subint stats", fontsize=9)
        ax.legend(fontsize=6, loc="upper left")
        ax.tick_params(labelbottom=False)

    def _table(self, ax, cand):
        hdr = self.overview.header
        rows = [
            ("R.A.", _radec_str(float(hdr.get("src_raj", 0) or 0), True)),
            ("Decl.", _radec_str(float(hdr.get("src_dej", 0) or 0), False)),
            ("P0 (s)", f"{cand['period']:.9f}"),
            ("Opt P0 (s)", f"{cand['opt_period']:.9f}"),
            ("DM", f"{cand['dm']:.2f}"),
            ("Acc (m/s²)", f"{cand['acc']:.2f}"),
            ("Harmonic", str(int(cand["nh"]))),
            ("Spec S/N", f"{cand['snr']:.1f}"),
            ("Fold S/N", f"{cand['folded_snr']:.1f}"),
            ("Adjacent?", str(bool(cand["is_adjacent"]))),
            ("Physical?", str(bool(cand["is_physical"]))),
            ("DDM count ratio", f"{cand['ddm_count_ratio']:.3f}"),
            ("DDM S/N ratio", f"{cand['ddm_snr_ratio']:.3f}"),
            ("N assoc", str(int(cand["nassoc"]))),
        ]
        ax.axis("off")
        tab = ax.table(
            cellText=rows, cellLoc="left", loc="center",
            colWidths=[0.62, 0.55],
        )
        tab.auto_set_font_size(False)
        tab.set_fontsize(9)
        tab.scale(1.0, 1.4)
        for cell in tab.get_celld().values():
            cell.set_linewidth(0)

    def _by_harm(self, ax, hits, xfield, yfield, flip=False):
        for i, nh in enumerate(np.unique(hits["nh"])):
            sub = hits[hits["nh"] == nh]
            ax.scatter(
                sub[xfield], sub[yfield], s=10,
                color=_HARM_COLORS[int(nh) % len(_HARM_COLORS)],
                label=f"harm {int(nh)}", edgecolors="none",
            )
        if flip:
            ax.yaxis.tick_right()
            ax.yaxis.set_label_position("right")
        ax.set_xlabel(xfield)
        ax.set_ylabel(yfield)
        ax.legend(fontsize=6)

    def _dm_acc_plane(self, ax, hits):
        snr = hits["snr"].astype(float)
        span = snr.max() - snr.min()
        sizes = 5 + 120 * (snr - snr.min()) / (span if span else 1.0)
        for i, nh in enumerate(np.unique(hits["nh"])):
            m = hits["nh"] == nh
            ax.scatter(
                hits["dm"][m], hits["acc"][m], s=sizes[m],
                color=_HARM_COLORS[int(nh) % len(_HARM_COLORS)],
                alpha=0.7, edgecolors="none",
            )
        ax.set_xlabel("DM (pc cm$^{-3}$)")
        ax.set_ylabel("Acc (m/s²)")
        ax.set_title("DM-acc plane (size ∝ S/N)", fontsize=9)

    def _all_cands(self, ax, cand):
        """Period-DM overview of the WHOLE candidate list with a
        crosshair on the plotted candidate."""
        c = self.overview.candidates
        ax.set_xscale("log")
        ax.scatter(
            c["period"], c["dm"], s=np.clip(c["snr"], 5, 120),
            c=[_HARM_COLORS[int(n) % len(_HARM_COLORS)] for n in c["nh"]],
            alpha=0.7, edgecolors="none",
        )
        ax.axvline(float(cand["period"]), color="0.3", lw=0.8)
        ax.axhline(float(cand["dm"]), color="0.3", lw=0.8)
        ax.set_xlabel("Period (s)")
        ax.set_ylabel("DM (pc cm$^{-3}$)")
        ax.set_title("All candidates (crosshair = this one)", fontsize=9)

    # ---- entry point ----------------------------------------------------

    def plot(self, idx: int, outfile: str | None = None):
        import matplotlib

        if outfile:
            matplotlib.use("Agg")
        import matplotlib.pyplot as plt
        from matplotlib import gridspec

        cand = self.overview.candidates[idx]
        rec = self.parser.read_candidate(int(cand["byte_offset"]))

        fig = plt.figure(figsize=(14, 12))
        gs = gridspec.GridSpec(
            4, 6, figure=fig, hspace=0.55, wspace=0.65,
            height_ratios=[1.0, 1.2, 1.2, 1.6],
        )
        fig.suptitle(
            f"{self.overview.header.get('source_name', 'unknown')} — "
            f"candidate {idx}: P={cand['period']:.6f} s  "
            f"DM={cand['dm']:.2f}  acc={cand['acc']:.2f}  "
            f"S/N={cand['snr']:.1f}",
            fontsize=13,
        )

        ax_prof = fig.add_subplot(gs[0, 1:3])
        ax_fold = fig.add_subplot(gs[1:3, 1:3])
        ax_stats = fig.add_subplot(gs[1:3, 0])
        ax_table = fig.add_subplot(gs[0:3, 3])
        ax_dm = fig.add_subplot(gs[0, 4:6])
        ax_dmacc = fig.add_subplot(gs[1:3, 4])
        ax_acc = fig.add_subplot(gs[1:3, 5])
        ax_all = fig.add_subplot(gs[3, :])

        fold = rec["fold"]
        if fold is not None and fold.size:
            f = fold.astype(float)
            span = f.max() - f.min()
            f = (f - f.min()) / (span if span else 1.0)
            self._profile(ax_prof, f)
            self._subints(ax_fold, f)
            self._subint_stats(ax_stats, f)
        else:
            for ax in (ax_prof, ax_fold, ax_stats):
                ax.text(0.5, 0.5, "no fold", ha="center", va="center")
                ax.axis("off")

        self._table(ax_table, cand)

        hits = rec["hits"]
        if len(hits):
            self._by_harm(ax_dm, hits, "dm", "snr", flip=True)
            self._by_harm(ax_acc, hits, "snr", "acc", flip=True)
            self._dm_acc_plane(ax_dmacc, hits)
        self._all_cands(ax_all, cand)

        if outfile:
            fig.savefig(outfile, dpi=100, bbox_inches="tight")
            plt.close(fig)
            return outfile
        return fig


# --------------------------------------------------------------------------
# DM-time bowtie / waterfall diagnostic (self-contained SVG, no matplotlib)
# --------------------------------------------------------------------------

def render_bowtie_svg(
    times_s,
    dms,
    snrs,
    widths=None,
    title: str = "DM-time bowtie",
    width_px: int = 920,
    height_px: int = 430,
    min_snr: float = 0.0,
) -> str:
    """The classic single-pulse diagnostic: every detection scattered
    in (time, DM) with marker area scaling with S/N. A real dispersed
    pulse traces the bowtie (S/N peaking at the true DM and fading
    symmetrically above/below); RFI stripes the DM axis at constant
    time. Pure-SVG by construction — no matplotlib, so the plot can be
    generated headless and embedded verbatim in the sift HTML report.
    """
    times = np.asarray(times_s, dtype=float)
    dms = np.asarray(dms, dtype=float)
    snrs = np.asarray(snrs, dtype=float)
    keep = snrs >= float(min_snr)
    times, dms, snrs = times[keep], dms[keep], snrs[keep]
    widths_arr = (
        np.asarray(widths)[keep] if widths is not None else None
    )
    ml, mr, mt, mb = 64, 18, 34, 46  # margins
    pw, ph = width_px - ml - mr, height_px - mt - mb
    parts = [
        f'<svg xmlns="http://www.w3.org/2000/svg" width="{width_px}" '
        f'height="{height_px}" viewBox="0 0 {width_px} {height_px}" '
        f'font-family="system-ui, sans-serif">',
        f'<rect width="{width_px}" height="{height_px}" fill="#ffffff"/>',
        f'<rect x="{ml}" y="{mt}" width="{pw}" height="{ph}" '
        f'fill="#f8f9fb" stroke="#c8ccd4"/>',
        f'<text x="{ml}" y="20" font-size="14" fill="#1a1a2e">'
        f"{_esc(title)} — {times.size} events</text>",
    ]
    if times.size == 0:
        parts.append(
            f'<text x="{ml + pw / 2:.0f}" y="{mt + ph / 2:.0f}" '
            f'font-size="13" fill="#666" text-anchor="middle">'
            "no single-pulse events</text></svg>"
        )
        return "".join(parts)
    t0, t1 = float(times.min()), float(times.max())
    d0, d1 = float(dms.min()), float(dms.max())
    tspan = (t1 - t0) or 1.0
    dspan = (d1 - d0) or 1.0
    s0, s1 = float(snrs.min()), float(snrs.max())
    sspan = (s1 - s0) or 1.0

    def _x(t: float) -> float:
        return ml + (t - t0) / tspan * pw

    def _y(d: float) -> float:
        return mt + ph - (d - d0) / dspan * ph

    # axes: 5 ticks each
    for i in range(6):
        tx = t0 + tspan * i / 5.0
        x = _x(tx)
        parts.append(
            f'<line x1="{x:.1f}" y1="{mt + ph}" x2="{x:.1f}" '
            f'y2="{mt + ph + 4}" stroke="#888"/>'
            f'<text x="{x:.1f}" y="{mt + ph + 17}" font-size="10" '
            f'fill="#444" text-anchor="middle">{tx:.3g}</text>'
        )
        dv = d0 + dspan * i / 5.0
        y = _y(dv)
        parts.append(
            f'<line x1="{ml - 4}" y1="{y:.1f}" x2="{ml}" y2="{y:.1f}" '
            f'stroke="#888"/>'
            f'<text x="{ml - 7}" y="{y + 3:.1f}" font-size="10" '
            f'fill="#444" text-anchor="end">{dv:.4g}</text>'
        )
    parts.append(
        f'<text x="{ml + pw / 2:.0f}" y="{height_px - 10}" '
        f'font-size="11" fill="#1a1a2e" text-anchor="middle">'
        "Time (s)</text>"
        f'<text x="14" y="{mt + ph / 2:.0f}" font-size="11" '
        f'fill="#1a1a2e" text-anchor="middle" '
        f'transform="rotate(-90 14 {mt + ph / 2:.0f})">'
        "DM (pc cm&#8315;&#179;)</text>"
    )
    # strongest drawn last (on top); radius grows with S/N
    order = np.argsort(snrs)
    for i in order:
        r = 1.5 + 6.5 * (snrs[i] - s0) / sspan
        extra = (
            f"w={int(widths_arr[i])} " if widths_arr is not None else ""
        )
        parts.append(
            f'<circle cx="{_x(times[i]):.1f}" cy="{_y(dms[i]):.1f}" '
            f'r="{r:.2f}" fill="#2563eb" fill-opacity="0.45" '
            f'stroke="none"><title>t={times[i]:.4f}s DM={dms[i]:.2f} '
            f"S/N={snrs[i]:.1f} {extra}</title></circle>"
        )
    parts.append(
        f'<text x="{width_px - mr}" y="20" font-size="10" fill="#666" '
        f'text-anchor="end">S/N {s0:.1f}&#8211;{s1:.1f} '
        "(area &#8733; S/N)</text></svg>"
    )
    return "".join(parts)


def _esc(s: str) -> str:
    return (
        str(s).replace("&", "&amp;").replace("<", "&lt;")
        .replace(">", "&gt;")
    )


def bowtie_from_singlepulse(path: str, **kw) -> str:
    """Bowtie SVG from a ``.singlepulse`` text table
    (io.output.write_singlepulse / tools.parsers.read_singlepulse)."""
    from .parsers import read_singlepulse

    cands = read_singlepulse(path)
    return render_bowtie_svg(
        cands["time_s"], cands["dm"], cands["snr"],
        widths=cands["width"],
        title=f"DM-time bowtie — {path.split('/')[-1]}",
        **kw,
    )


def bowtie_from_db(
    db_path: str,
    job_id: str | None = None,
    tenant: str | None = None,
    **kw,
) -> str:
    """Bowtie SVG over a campaign database's single-pulse candidates
    (optionally one job's, or one tenant's observations), with
    per-observation time offsets from tstart so a multi-observation
    campaign lays out on one axis."""
    from ..campaign.db import CandidateDB

    with CandidateDB(db_path) as db:
        rows = db.all_candidates(kind="single_pulse")
    if job_id is not None:
        rows = [r for r in rows if r.get("job_id") == job_id]
    if tenant is not None:
        rows = [r for r in rows if (r.get("tenant") or "") == tenant]
    if rows:
        t0_mjd = min(float(r.get("obs_tstart") or 0.0) for r in rows)
    times, dms, snrs, widths = [], [], [], []
    for r in rows:
        day_off = (float(r.get("obs_tstart") or 0.0) - t0_mjd) * 86400.0
        times.append(day_off + float(r.get("time_s") or 0.0))
        dms.append(float(r.get("dm") or 0.0))
        snrs.append(float(r.get("snr") or 0.0))
        widths.append(int(r.get("width") or 0))
    title = (
        "DM-time bowtie — campaign DB"
        + (f" [{job_id}]" if job_id else "")
        + (f" [tenant {tenant}]" if tenant else "")
    )
    return render_bowtie_svg(
        times, dms, snrs, widths=widths, title=title, **kw
    )


def bowtie_main(argv=None) -> int:
    """``peasoup-bowtie`` — render the DM-time bowtie diagnostic from
    a campaign DB (candidates.sqlite) or a .singlepulse table."""
    import argparse

    p = argparse.ArgumentParser(prog="peasoup-bowtie")
    p.add_argument(
        "source",
        help="candidates.sqlite (campaign DB) or a .singlepulse table",
    )
    p.add_argument("-o", "--outfile", default="bowtie.svg")
    p.add_argument("--job", default=None,
                   help="restrict a DB source to one job id")
    p.add_argument("--min-snr", type=float, default=0.0)
    args = p.parse_args(argv)
    if args.source.endswith(".singlepulse"):
        svg = bowtie_from_singlepulse(args.source, min_snr=args.min_snr)
    else:
        svg = bowtie_from_db(
            args.source, job_id=args.job, min_snr=args.min_snr
        )
    with open(args.outfile, "w") as f:
        f.write(svg)
    print(args.outfile)
    return 0


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(prog="peasoup-plot-cand")
    p.add_argument("overview")
    p.add_argument("candfile")
    p.add_argument("idx", type=int)
    p.add_argument("-o", "--outfile", default="cand.png")
    args = p.parse_args(argv)
    from .parsers import CandidateFileParser, OverviewFile

    ov = OverviewFile(args.overview)
    with CandidateFileParser(args.candfile) as cp:
        CandidatePlotter(ov, cp).plot(args.idx, args.outfile)
    print(args.outfile)
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
