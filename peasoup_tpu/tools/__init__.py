from .parsers import OverviewFile, CandidateFileParser
