from .parsers import OverviewFile, CandidateFileParser, read_singlepulse
