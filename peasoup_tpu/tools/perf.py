"""``peasoup-perf`` — AOT warmup, microbenchmarks, regression ratchet.

Subcommands:

* ``warmup`` — AOT-compile every registered program (representative
  shapes), populating the persistent compilation cache so later
  processes cold-start warm. Run it once per machine/toolchain; it is
  also what campaign workers do per bucket automatically.
* ``bench`` — per-program microbenchmarks into a schema-validated
  ``perf.json`` (default ./perf.json).
* ``check`` — compare a perf.json against the checked-in
  ``perf_baseline.json``: structural invariants everywhere (program
  set intact, registry completeness, warm pass 100% cache hits with
  zero recompiles), timing ratchets on real backends. ``--write-
  baseline`` re-pins the baseline from the perf.json.
* ``tune`` — resolve (and, on a cold cache, measure) the auto-tuned
  dedispersion plan for one shape bucket into ``tuning_cache.json``
  (plan/dedisp_plan.py + perf/tuning.py) — the offline form of what
  campaign workers and ``--tune`` pipelines do automatically.

Exit codes (scripts/check.sh relies on these, mirroring peasoup-audit):

* ``0`` — clean
* ``1`` — regression (or missing/broken/unregistered program)
* ``2`` — internal error (bad args, unreadable files, engine crash)
"""

from __future__ import annotations

import argparse
import sys
import traceback


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="peasoup-perf",
        description=(
            "AOT warmup over the program registry, per-program "
            "microbenchmarks, and the perf-regression ratchet"
        ),
    )
    sub = p.add_subparsers(dest="cmd", required=True)

    w = sub.add_parser(
        "warmup",
        help="AOT-compile every registered program into the "
        "persistent compilation cache",
    )
    w.add_argument(
        "--programs", default=None,
        help="comma-separated program names (default: all)",
    )
    w.add_argument(
        "--json", dest="json_path", default=None, metavar="PATH",
        help="also write the warmup report as JSON",
    )

    b = sub.add_parser(
        "bench", help="microbenchmark every registered program"
    )
    b.add_argument(
        "-o", "--output", default="perf.json",
        help="perf.json output path (default ./perf.json)",
    )
    b.add_argument(
        "--reps", type=int, default=5,
        help="timed executions per program (median reported; default 5)",
    )
    b.add_argument(
        "--programs", default=None,
        help="comma-separated program names (default: all)",
    )

    c = sub.add_parser(
        "check", help="ratchet a perf.json against the baseline"
    )
    c.add_argument(
        "--perf", default="perf.json",
        help="perf.json to check (default ./perf.json)",
    )
    c.add_argument(
        "--baseline", default="perf_baseline.json",
        help="checked-in baseline (default ./perf_baseline.json)",
    )
    c.add_argument(
        "--timing", choices=("auto", "on", "off"), default="auto",
        help="timing ratchet: auto = only on matching non-CPU "
        "backends (default), on = always, off = structural only",
    )
    c.add_argument(
        "--no-warm", action="store_true",
        help="skip the warm-registry invariant (zero recompiles / all "
        "persistent-cache hits after a bench in the same cache dir)",
    )
    c.add_argument(
        "--write-baseline", action="store_true",
        help="re-pin --baseline from the perf.json and exit 0",
    )

    t = sub.add_parser(
        "tune",
        help="auto-tune the dedispersion plan for one shape bucket "
        "into the tuning cache (or --list/--prune its entries)",
    )
    t.add_argument(
        "--bucket", default=None,
        help="shape bucket as nchans,nbits,nsamps,tsamp,fch1,foff "
        "(the campaign bucket key fields)",
    )
    t.add_argument(
        "--list", dest="list_entries", action="store_true",
        help="list cached plans with device fingerprint, knobs and "
        "age instead of tuning",
    )
    t.add_argument(
        "--prune", action="store_true",
        help="remove entries under stale device fingerprints (not "
        "this device); with --older-than-days also age-prune "
        "everything else",
    )
    t.add_argument(
        "--older-than-days", type=float, default=None,
        help="with --prune: also remove entries older than this many "
        "days on ANY fingerprint (un-stamped legacy entries count as "
        "infinitely old)",
    )
    t.add_argument(
        "--keep-stale", action="store_true",
        help="with --prune: keep other devices' entries (age-prune "
        "only)",
    )
    t.add_argument(
        "--dry-run", action="store_true",
        help="with --prune: report what would go without rewriting",
    )
    t.add_argument(
        "--pipeline", default="search", choices=("search", "spsearch"),
    )
    t.add_argument(
        "--config", default="{}",
        help="pipeline config overrides as inline JSON "
        "(dm_end, subband_smear, subband_snr_loss, ...)",
    )
    t.add_argument(
        "--cache", default=None,
        help="tuning_cache.json path (default: the per-user cache)",
    )
    t.add_argument(
        "--reps", type=int, default=3,
        help="timed samples per tuner candidate (median; default 3)",
    )
    t.add_argument(
        "--force", action="store_true",
        help="re-measure even when the cache already holds a plan "
        "for this (device, bucket)",
    )
    return p


def _cmd_warmup(args) -> int:
    from peasoup_tpu.perf.warmup import warm_registry

    programs = (
        [s.strip() for s in args.programs.split(",") if s.strip()]
        if args.programs else None
    )
    rep = warm_registry(programs=programs)
    for pw in rep.programs:
        state = (
            "ERROR " + (pw.error or "")
            if pw.error
            else ("cache hit" if pw.cache_hit else "compiled")
        )
        print(f"  {pw.name}: {pw.seconds:.3f}s  {state}")
    print(
        f"peasoup-perf warmup: {len(rep.programs)} programs in "
        f"{rep.seconds:.1f}s ({rep.compiled} compiled, "
        f"{rep.cache_hits} persistent-cache hits"
        + (f", cache {rep.cache_dir}" if rep.cache_dir else ", NO cache")
        + ")"
    )
    if args.json_path:
        import json

        with open(args.json_path, "w") as f:
            json.dump(rep.to_doc(), f, indent=2)
            f.write("\n")
    return 1 if rep.errors else 0


def _cmd_bench(args) -> int:
    from peasoup_tpu.perf.microbench import run_microbench, write_perf

    programs = (
        [s.strip() for s in args.programs.split(",") if s.strip()]
        if args.programs else None
    )
    doc = run_microbench(reps=args.reps, programs=programs)
    write_perf(doc, args.output)
    for name, rec in sorted(doc["programs"].items()):
        if rec["error"]:
            print(f"  {name}: ERROR {rec['error']}")
        else:
            print(
                f"  {name}: compile {rec['compile_s'] * 1e3:8.1f} ms"
                f"{' (cache)' if rec['compile_cache_hit'] else '        '}"
                f"  execute {rec['execute_median_s'] * 1e6:10.1f} us"
            )
    t = doc["totals"]
    print(
        f"peasoup-perf bench: {t['programs']} programs on "
        f"{doc['backend']} ({doc['device_kind']}) in {t['wall_s']:.1f}s "
        f"-> {args.output}"
        + (f"  [{t['errors']} ERRORS]" if t["errors"] else "")
    )
    return 1 if t["errors"] else 0


def _warm_invariant(problems, notices, programs=None) -> None:
    """The zero-recompile contract: with the persistent cache
    populated (a bench/warmup ran in this cache dir), re-lowering the
    benched programs must be pure cache hits — a miss means a
    program's lowering drifted from what was just benched
    (non-deterministic tracing, environment leakage into the jaxpr)
    and campaign workers would silently recompile on every restart."""
    from peasoup_tpu.perf.ratchet import PerfProblem
    from peasoup_tpu.perf.warmup import warm_registry

    rep = warm_registry(programs=programs)
    if rep.cache_dir is None:
        notices.append(
            "warm invariant skipped: persistent compilation cache "
            "unavailable"
        )
        return
    for pw in rep.programs:
        if pw.error:
            problems.append(
                PerfProblem("program_error", pw.name, pw.error)
            )
        elif pw.compiled:
            problems.append(
                PerfProblem(
                    "recompiled_warm", pw.name,
                    "recompiled on warm shapes (persistent-cache miss "
                    "straight after bench): the program's lowering is "
                    "not stable across processes",
                )
            )
    notices.append(
        f"warm invariant: {rep.cache_hits}/{len(rep.programs)} "
        f"persistent-cache hits, {rep.compiled} recompiles"
    )


def _cmd_check(args) -> int:
    import os

    from peasoup_tpu.ops.registry import unregistered_entry_points
    from peasoup_tpu.perf.microbench import load_perf
    from peasoup_tpu.perf.ratchet import (
        PerfProblem,
        baseline_from_perf,
        check_perf,
        load_baseline,
        write_baseline,
    )

    perf_doc = load_perf(args.perf)
    if args.write_baseline:
        write_baseline(baseline_from_perf(perf_doc), args.baseline)
        n = len([
            r for r in perf_doc["programs"].values() if not r["error"]
        ])
        print(
            f"peasoup-perf: baseline written to {args.baseline} "
            f"({n} program(s) pinned on {perf_doc['backend']})"
        )
        return 0
    if not os.path.exists(args.baseline):
        print(
            f"peasoup-perf: baseline {args.baseline} missing "
            "(create one with: peasoup-perf check --write-baseline)",
            file=sys.stderr,
        )
        return 2
    baseline = load_baseline(args.baseline)
    problems, notices = check_perf(
        perf_doc, baseline, timing=args.timing
    )
    for ep in unregistered_entry_points():
        problems.append(
            PerfProblem(
                "unregistered_entry_point", ep,
                "top-level jitted entry point with no registry entry — "
                "it escapes warmup, contracts and benchmarks; register "
                "it next to the op (see ops/registry.py)",
            )
        )
    if not args.no_warm:
        # only the programs this perf.json covers: a subset bench must
        # not flag the rest of the registry as cold
        _warm_invariant(
            problems, notices, programs=sorted(perf_doc["programs"])
        )
    for n in notices:
        print(f"note: {n}")
    for pr in problems:
        print(pr.render())
    if problems:
        print(f"peasoup-perf check: {len(problems)} problem(s)")
        return 1
    print(
        f"peasoup-perf check: OK ({len(baseline['programs'])} baseline "
        f"programs, backend {perf_doc['backend']})"
    )
    return 0


def _fmt_age(age_s) -> str:
    if age_s is None:
        return "age unknown"
    if age_s >= 86400:
        return f"{age_s / 86400:.1f}d old"
    if age_s >= 3600:
        return f"{age_s / 3600:.1f}h old"
    return f"{age_s:.0f}s old"


def _render_entry(row: dict) -> str:
    knobs = f"dedisp_block={row['dedisp_block']}"
    if row.get("subbands"):
        knobs += f" subbands={row['subbands']}"
    return (
        f"  {row['fingerprint']}  {row['key']}  {row['engine']}"
        f"  {knobs}  [{row['source']}, {_fmt_age(row['age_s'])}"
        + (", STALE device]" if row["stale"] else "]")
    )


def _cmd_tune_list(args) -> int:
    from peasoup_tpu.perf.tuning import default_cache_path, list_entries

    rows = list_entries(args.cache)
    for row in rows:
        print(_render_entry(row))
    stale = sum(1 for r in rows if r["stale"])
    print(
        f"peasoup-perf tune --list: {len(rows)} entr"
        f"{'y' if len(rows) == 1 else 'ies'} in "
        f"{args.cache or default_cache_path()}"
        + (f" ({stale} under stale fingerprints)" if stale else "")
    )
    return 0


def _cmd_tune_prune(args) -> int:
    from peasoup_tpu.perf.tuning import default_cache_path, prune_cache

    removed = prune_cache(
        args.cache,
        older_than_s=(
            args.older_than_days * 86400.0
            if args.older_than_days is not None else None
        ),
        keep_stale=args.keep_stale,
        dry_run=args.dry_run,
    )
    for row in removed:
        print(_render_entry(row))
    print(
        f"peasoup-perf tune --prune: "
        f"{'would remove' if args.dry_run else 'removed'} "
        f"{len(removed)} entr{'y' if len(removed) == 1 else 'ies'} "
        f"from {args.cache or default_cache_path()}"
    )
    return 0


def _cmd_tune(args) -> int:
    import json

    from peasoup_tpu.perf.tuning import (
        device_fingerprint,
        measurement_count,
        resolve_plan_for_bucket,
    )

    if sum(map(bool, (args.bucket, args.list_entries, args.prune))) != 1:
        print(
            "peasoup-perf tune: give exactly one of --bucket, --list, "
            "--prune", file=sys.stderr,
        )
        return 2
    if args.list_entries:
        return _cmd_tune_list(args)
    if args.prune:
        return _cmd_tune_prune(args)
    parts = [s.strip() for s in args.bucket.split(",")]
    if len(parts) != 6:
        print(
            "peasoup-perf tune: --bucket wants "
            "nchans,nbits,nsamps,tsamp,fch1,foff", file=sys.stderr,
        )
        return 2
    bucket = (
        int(parts[0]), int(parts[1]), int(parts[2]),
        float(parts[3]), float(parts[4]), float(parts[5]),
    )
    overrides = json.loads(args.config)
    n0 = measurement_count()
    plan = resolve_plan_for_bucket(
        bucket, args.pipeline, overrides, args.cache,
        reps=args.reps, force=args.force,
    )
    measured = measurement_count() - n0
    for k, v in plan.summary().items():
        print(f"  {k}: {v}")
    print(
        f"peasoup-perf tune: {plan.engine} plan for {args.pipeline} "
        f"bucket {args.bucket} on {device_fingerprint()} "
        f"({measured} measurements"
        + (", served from cache)" if plan.source == "cache" else ")")
    )
    return 0


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    try:
        return {
            "warmup": _cmd_warmup,
            "bench": _cmd_bench,
            "check": _cmd_check,
            "tune": _cmd_tune,
        }[args.cmd](args)
    except Exception:
        traceback.print_exc()
        print("peasoup-perf: internal error (exit 2)", file=sys.stderr)
        return 2


if __name__ == "__main__":
    sys.exit(main())
