"""Per-scope device-time/bytes attribution from a jax.profiler trace.

The axon tunnel's wall clock swings 2-3x by the hour, so kernel work is
measured from the profiler's device tracks instead: TPU-pid X events
carry ``args.tf_op`` (the jax named-scope path), ``hlo_category`` and
``raw_bytes_accessed`` — aggregating durations by tf_op prefix gives an
honest (time, bytes) breakdown per pipeline stage (NOTES.md "Roofline
re-measurement").

Library use:
    with scope_trace() as result: run()
    result.table()  # [(scope, seconds, gigabytes), ...]

CLI: ``python -m peasoup_tpu.tools.scope_trace`` runs the dense-grid
tutorial search (the official bench workload) once warm and prints the
table — the source of NOTES.md's per-scope numbers.
"""

from __future__ import annotations

import contextlib
import glob
import gzip
import json
import os
import tempfile


class ScopeResult:
    def __init__(self) -> None:
        self.events: list[tuple[str, float, int]] = []  # (tf_op, us, bytes)

    @property
    def device_s(self) -> float:
        return sum(e[1] for e in self.events) / 1e6

    def table(self, depth: int = 2, top: int = 20):
        """Aggregate by the first ``depth`` components of the tf_op
        scope path; returns [(scope, seconds, gigabytes)] sorted by
        time."""
        agg: dict[str, list[float]] = {}
        for op, us, nbytes in self.events:
            key = "/".join(op.split("/")[:depth]) if op else "<unscoped>"
            a = agg.setdefault(key, [0.0, 0.0])
            a[0] += us / 1e6
            a[1] += nbytes / 1e9
        rows = sorted(agg.items(), key=lambda kv: -kv[1][0])[:top]
        return [(k, v[0], v[1]) for k, v in rows]

    def print_table(self, depth: int = 2, top: int = 20) -> None:
        print(f"device busy: {self.device_s * 1e3:.1f} ms")
        for scope, s, gb in self.table(depth, top):
            print(f"  {s * 1e3:8.1f} ms  {gb:8.2f} GB  {scope}")

    # top-level jit names per pipeline phase (bench.py --survey's
    # device anchor): the driver's phases dispatch distinct jitted
    # programs, so the trace's tf_op head classifies device time even
    # though the phases share one traced run
    PHASES = (
        ("search", ("search_dm_block", "compact_peaks", "pack_chunk",
                    "resample_select", "search_trial")),
        ("dedisp", ("jit(run)", "dedisperse", "subband", "unpack_fil",
                    "_stage1", "_stage2", "tims")),
        ("fold", ("fold", "deredden", "_optimise", "pack_subints")),
    )

    def phase_seconds(self) -> dict:
        """Device-busy seconds per pipeline phase + 'other' for
        anything unclassified (kept visible so mis-attribution can't
        hide)."""
        out = {name: 0.0 for name, _ in self.PHASES}
        out["other"] = 0.0
        for op, us, _ in self.events:
            head = op.split("/")[0] if op else ""
            for name, pats in self.PHASES:
                if any(p in head for p in pats):
                    out[name] += us / 1e6
                    break
            else:
                out["other"] += us / 1e6
        return out

    # finer roofline taxonomy (perf/roofline.py STAGES): matched
    # against the WHOLE tf_op path, so the named scopes the drivers
    # emit inside one jitted program ("Spectrum-Chain", "Resample",
    # "Harmonic summing", "Peaks") split the search program's device
    # time per stage. First match wins; order puts the scoped stages
    # before the top-level jit-name fallbacks.
    STAGE_RULES = (
        ("unpack", ("unpack_fil",)),
        ("spectrum_chain", ("Spectrum-Chain", "whiten", "deredden")),
        ("resample", ("Resample", "resample")),
        ("harmonics", ("Harmonic summing", "harmonic")),
        ("peaks", ("Peaks", "peaks", "compact", "cluster",
                   "single_pulse", "boxcar")),
        ("dedisperse", ("jit(run)", "dedisperse", "subband", "_stage1",
                        "_stage2", "matmul_block", "tims")),
        ("fold", ("fold", "_optimise", "pack_subints")),
    )

    def stage_profile(self) -> dict:
        """{stage: (device seconds, bytes accessed)} over the roofline
        taxonomy, + 'other' for anything unclassified (visible, never
        hidden) — the measured half of perf.roofline.stage_roofline."""
        out: dict = {name: [0.0, 0] for name, _ in self.STAGE_RULES}
        out["other"] = [0.0, 0]
        for op, us, nbytes in self.events:
            path = op or ""
            for name, pats in self.STAGE_RULES:
                if any(p in path for p in pats):
                    out[name][0] += us / 1e6
                    out[name][1] += nbytes
                    break
            else:
                out["other"][0] += us / 1e6
                out["other"][1] += nbytes
        return {k: (v[0], v[1]) for k, v in out.items()}


def parse_trace_events(tr: dict) -> list[tuple[str, float, int]]:
    """(tf_op, duration us, bytes) rows from a loaded trace document's
    TPU device tracks (X events carrying ``hlo_category`` under a
    process whose name mentions TPU)."""
    pids = {
        e["pid"]
        for e in tr["traceEvents"]
        if e.get("ph") == "M"
        and e.get("name") == "process_name"
        and "TPU" in (e.get("args") or {}).get("name", "")
    }
    rows: list[tuple[str, float, int]] = []
    for e in tr["traceEvents"]:
        args = e.get("args") or {}
        if (
            e.get("ph") == "X"
            and e.get("pid") in pids
            and "hlo_category" in args
        ):
            rows.append(
                (
                    args.get("tf_op", ""),
                    float(e.get("dur", 0)),
                    int(args.get("raw_bytes_accessed", 0) or 0),
                )
            )
    return rows


def result_from_trace_file(path: str) -> ScopeResult:
    """Parse one ``*.trace.json.gz`` (as written by jax.profiler) into a
    ScopeResult — no TPU needed, just the file."""
    res = ScopeResult()
    with gzip.open(path, "rt") as f:
        res.events = parse_trace_events(json.load(f))
    return res


@contextlib.contextmanager
def scope_trace():
    """Trace the with-block and populate a ScopeResult from the TPU
    device tracks of the resulting trace.json.gz."""
    import jax

    res = ScopeResult()
    with tempfile.TemporaryDirectory() as tdir:
        with jax.profiler.trace(tdir):
            yield res
        paths = glob.glob(tdir + "/**/*.trace.json.gz", recursive=True)
        if not paths:
            return
        res.events = result_from_trace_file(
            max(paths, key=os.path.getmtime)
        ).events


def main() -> int:
    import sys

    from peasoup_tpu.io import read_filterbank
    from peasoup_tpu.pipeline import PeasoupSearch, SearchConfig

    fil = read_filterbank(
        os.environ.get(
            "PEASOUP_BENCH_FIL", "/root/reference/example_data/tutorial.fil"
        )
    )
    dedupe = "--dedupe" in sys.argv
    search = PeasoupSearch(
        SearchConfig(
            dm_end=250.0, acc_start=-5.0, acc_end=5.0, acc_pulse_width=0.064,
            npdmp=0, limit=1000, dedupe_accel=dedupe,
        )
    )
    search.run(fil)
    search.run(fil)  # second warm-up locks adaptive sizes
    with scope_trace() as res:
        search.run(fil)
    res.print_table(depth=int(os.environ.get("SCOPE_DEPTH", "2")))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
