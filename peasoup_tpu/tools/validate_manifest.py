"""Validate telemetry manifests against the checked-in JSON Schema.

    python -m peasoup_tpu.tools.validate_manifest run/telemetry.json
    python -m peasoup_tpu.tools.validate_manifest --fresh fixtures/*.json

The schema lives at ``peasoup_tpu/obs/manifest.schema.json``; the
validator (``peasoup_tpu/obs/schema.py``) is a dependency-free draft-07
subset. ``--fresh`` additionally generates a brand-new
``RunTelemetry`` manifest in a temp dir and validates it, so
``scripts/check.sh`` catches a drift between what the writer produces
and what the schema promises — in either direction.
"""

from __future__ import annotations

import argparse
import sys


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="peasoup-validate-manifest",
        description="Validate telemetry.json manifests against the "
        "checked-in JSON Schema",
    )
    p.add_argument(
        "manifests", nargs="*", help="manifest files to validate"
    )
    p.add_argument(
        "--fresh", action="store_true",
        help="also generate a fresh RunTelemetry manifest and "
        "validate it (writer/schema drift gate)",
    )
    args = p.parse_args(argv)
    if not args.manifests and not args.fresh:
        p.error("nothing to validate (pass files and/or --fresh)")

    from ..obs.schema import SchemaError, validate_manifest
    from ..obs.telemetry import load_manifest

    n_ok = 0
    failed = False
    for path in args.manifests:
        try:
            validate_manifest(load_manifest(path))
            n_ok += 1
        except (SchemaError, ValueError, OSError) as exc:
            failed = True
            print(f"FAIL {path}: {exc}", file=sys.stderr)

    if args.fresh:
        import os
        import tempfile

        from ..obs.telemetry import RunTelemetry

        tel = RunTelemetry(run_id="schema-gate")
        tel.set_context(command="validate_manifest", fresh=True)
        tel.incr("widgets", 3)
        tel.gauge("level", 1.5)
        with tel.stage("probe"):
            pass
        tel.set_progress(1, 2, unit="steps")
        tel.event("adaptive_thing", old=1, new=2)
        tel.record_jit("/jax/core/compile", 0.1)
        with tempfile.TemporaryDirectory() as d:
            man = tel.write(os.path.join(d, "telemetry.json"))
            aborted = tel.write(
                os.path.join(d, "aborted.json"),
                aborted=True,
                abort_reason="schema-gate",
            )
        for label, doc in (("fresh", man), ("fresh-aborted", aborted)):
            try:
                validate_manifest(doc)
                n_ok += 1
            except SchemaError as exc:
                failed = True
                print(f"FAIL <{label} manifest>: {exc}", file=sys.stderr)

    if failed:
        return 1
    print(f"OK: {n_ok} manifest(s) schema-valid")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
