"""Per-stage divergence harness: NumPy-f64 oracle of the reference chain.

Every function here re-derives one stage of the reference worker
(/root/reference/src/pipeline_multi.cu:144-243) in float64 NumPy,
following the CUDA kernels' exact index math and operation order
(/root/reference/src/kernels.cu).  The harness serves two purposes:

1. locate which stage a candidate's S/N delta enters (compare our TPU
   f32 pipeline stage-by-stage against the oracle);
2. bound the reference run's own f32 error (compare the oracle's final
   S/N against the golden overview.xml values) — the residual that no
   f32 implementation can close.

Run as a module for the report:

    python -m peasoup_tpu.tools.divergence [--dm 239.3756] [--acc 0.0]
"""

from __future__ import annotations

import numpy as np

# dedisp's generate_delay_table constant (the library uses the rounded
# 4.15e3 with a comment noting the more precise 4.148741601e3; peasoup
# links against dedisp, so candidate parity REQUIRES the rounded value).
DEDISP_DELAY_CONSTANT = 4.15e3


def oracle_delay_table(
    f0: float, df: float, nchans: int, dt: float,
    constant: float = DEDISP_DELAY_CONSTANT,
) -> np.ndarray:
    """dedisp generate_delay_table, bit-faithful.

    The library computes ``a = 1.f/(f0+c*df); b = 1.f/f0`` and the
    difference of squares in f32, then scales by the f64 quotient
    ``constant/dt`` and rounds once to the f32 table entry.
    """
    f0 = np.float32(f0)
    df = np.float32(df)
    c = np.arange(nchans, dtype=np.float32)
    a = (np.float32(1.0) / (f0 + c * df)).astype(np.float32)
    b = np.float32(1.0) / f0
    diff2 = (a * a - b * b).astype(np.float32)
    return (
        np.float64(constant) / np.float64(np.float32(dt)) * diff2.astype(np.float64)
    ).astype(np.float32)


def oracle_delay_samples(dm_list: np.ndarray, table: np.ndarray) -> np.ndarray:
    """Whole-sample delays: round-half-even of the F32 product
    ``dm * delay_table[c]`` (the kernel's __float2uint_rn)."""
    prod = (
        np.asarray(dm_list, np.float32)[:, None] * np.abs(table)[None, :]
    ).astype(np.float32)
    return np.rint(prod).astype(np.int32)


def oracle_max_delay(dm_max: float, table: np.ndarray) -> int:
    """dedisp plan max_delay: floor(dm_max * table[-1] + 0.5) with the
    product in f32 (both operands are f32 in the library)."""
    prod = np.float32(np.float32(dm_max) * np.abs(table)[-1])
    return int(np.floor(np.float64(prod) + 0.5))


def oracle_dedisperse(
    data: np.ndarray,  # (nsamps, nchans) unpacked u8
    delays: np.ndarray,  # (nchans,) int
    out_n: int,
    killmask: np.ndarray | None = None,
) -> np.ndarray:
    """Channel sum at integer per-channel delays, f64 (sums of 8-bit
    samples are exact in both f32 and f64)."""
    nsamps, nchans = data.shape
    out = np.zeros(out_n, dtype=np.float64)
    for c in range(nchans):
        if killmask is not None and not killmask[c]:
            continue
        d = int(delays[c])
        out += data[d : d + out_n, c].astype(np.float64)
    return out


# ---- rednoise (Heimdall median cascade, kernels.cu:860-1010) ----------


def oracle_median_scrunch5(x: np.ndarray) -> np.ndarray:
    n = x.shape[0]
    if n == 1:
        return x.copy()
    if n == 2:
        return np.array([0.5 * (x[0] + x[1])])
    if n in (3, 4):
        return np.array([np.median(x)])  # median4 = mean of central two
    m = n // 5
    return np.median(x[: m * 5].reshape(m, 5), axis=1)


def oracle_linear_stretch(x: np.ndarray, out_count: int) -> np.ndarray:
    """linear_stretch_functor: f32 step/position math, values in f64."""
    in_count = x.shape[0]
    step = np.float32(in_count - 1) / np.float32(out_count - 1)
    pos = (np.arange(out_count, dtype=np.float32) * step).astype(np.float32)
    j = pos.astype(np.int32)
    frac = (pos - j.astype(np.float32)).astype(np.float32)
    j1 = np.minimum(j + 1, in_count - 1)
    out = x[j].copy()
    m = frac > np.float32(1e-5)
    out[m] = x[j][m] + frac[m].astype(np.float64) * (x[j1][m] - x[j][m])
    return out


def oracle_running_median(amp: np.ndarray, pos5: int, pos25: int) -> np.ndarray:
    size = amp.shape[0]
    med5 = oracle_median_scrunch5(amp)
    med25 = oracle_median_scrunch5(med5)
    med125 = oracle_median_scrunch5(med25)
    s5 = oracle_linear_stretch(med5, size)
    s25 = oracle_linear_stretch(med25, size)
    s125 = oracle_linear_stretch(med125, size)
    idx = np.arange(size)
    return np.where(idx < pos5, s5, np.where(idx < pos25, s25, s125))


def oracle_whiten(x: np.ndarray, pos5: int, pos25: int) -> np.ndarray:
    """rfft -> |.| -> running median -> divide, bins 0-4 zeroed
    (pipeline_multi.cu:174-186, kernels.cu:1013-1034)."""
    fser = np.fft.rfft(x)
    med = oracle_running_median(np.abs(fser), pos5, pos25)
    out = fser / med
    out[:5] = 0.0
    return out


# ---- spectrum / stats / resample / harmonics --------------------------


def oracle_interbin(fser: np.ndarray) -> np.ndarray:
    """bin_interbin_series_kernel (kernels.cu:231-252)."""
    re = fser.real
    im = fser.imag
    re_l = np.concatenate([[0.0], re[:-1]])
    im_l = np.concatenate([[0.0], im[:-1]])
    ampsq = re * re + im * im
    ampsq_d = 0.5 * ((re - re_l) ** 2 + (im - im_l) ** 2)
    return np.sqrt(np.maximum(ampsq, ampsq_d))


def oracle_stats(s: np.ndarray) -> tuple[float, float, float]:
    mean = float(np.mean(s))
    rms = float(np.sqrt(np.mean(s * s)))
    return mean, rms, float(np.sqrt(rms * rms - mean * mean))


def oracle_resample(xd: np.ndarray, acc: float, tsamp: float) -> np.ndarray:
    """resample_kernelII (kernels.cu:314-346): gather at
    rn(idx + idx*af*(idx-size)), af = a*tsamp/2c in f64."""
    size = xd.shape[0]
    af = (np.float64(np.float32(acc)) * tsamp) / (2 * 299792458.0)
    idx = np.arange(size, dtype=np.float64)
    src = np.rint(idx + idx * af * (idx - size)).astype(np.int64)
    return xd[np.clip(src, 0, size - 1)]


def oracle_harm_levels(sn: np.ndarray, nharms: int = 4) -> list[np.ndarray]:
    """harmonic_sum_kernel (kernels.cu:34-100): cumulative gathers at
    (int)(idx*frac+0.5), level h scaled by rsqrt(2**h)."""
    size = sn.shape[0]
    idx = np.arange(size, dtype=np.float64)
    val = sn.copy()
    out = []
    for h in range(1, nharms + 1):
        denom = 2 << (h - 1)  # 2, 4, 8, 16
        for num in range(1, denom, 2):
            g = (idx * (num / denom) + 0.5).astype(np.int64)  # C trunc
            val = val + sn[g]
        out.append(val * (2.0 ** (-h / 2.0)))
    return out


def oracle_cluster_max(level: np.ndarray, bin_idx: int, gap: int = 31) -> float:
    lo = max(0, bin_idx - gap)
    return float(level[lo : bin_idx + gap + 1].max())


def oracle_search_trial(
    tim: np.ndarray,
    size: int,
    tsamp: float,
    accs: list[float],
    pos5: int,
    pos25: int,
    nharms: int = 4,
) -> dict:
    """The full per-DM-trial oracle; returns every stage for compare."""
    x = tim[:size].astype(np.float64)
    fser = oracle_whiten(x, pos5, pos25)
    s0 = oracle_interbin(fser)
    mean, rms, std = oracle_stats(s0)
    xd = np.fft.irfft(fser, n=size)
    per_acc = {}
    for a in accs:
        xr = oracle_resample(xd, a, tsamp)
        f = np.fft.rfft(xr)
        sn = (oracle_interbin(f) - mean) / std
        levels = [sn] + oracle_harm_levels(sn, nharms)
        per_acc[float(a)] = {"xr": xr, "sn": sn, "levels": levels}
    return {
        "fser": fser,
        "s0": s0,
        "mean": mean,
        "rms": rms,
        "std": std,
        "xd": xd,
        "acc": per_acc,
    }


# ---- report ----------------------------------------------------------


def _relerr(a: np.ndarray, b: np.ndarray, floor: float = 1e-3) -> float:
    """max |a-b| / max(|b|, floor*rms(b)) — per-bin relative error with
    tiny-denominator bins measured against the RMS scale instead."""
    b = np.asarray(b, np.float64)
    a = np.asarray(a, np.float64)
    scale = np.maximum(np.abs(b), floor * np.sqrt(np.mean(b * b)) + 1e-30)
    return float(np.max(np.abs(a - b) / scale))


def compare_trial(fil_path: str, dm: float, accs: list[float] | None = None):
    """Stage-by-stage rel-err of the TPU pipeline vs the f64 oracle for
    one DM trial of ``fil_path`` searched with the golden flags."""
    import jax.numpy as jnp

    from ..io.sigproc import read_filterbank
    from ..ops.rednoise import running_median, whiten_fseries
    from ..ops.resample import accel_factor, resample_accel
    from ..ops.spectrum import form_interpolated, form_power, spectrum_stats
    from ..ops.harmonics import harmonic_sums
    from ..plan.fft_plan import choose_fft_size

    fil = read_filterbank(fil_path)
    h = fil.header
    table = oracle_delay_table(h.fch1, h.foff, h.nchans, h.tsamp)
    max_d = oracle_max_delay(dm, table)  # this trial's span for info
    delays = oracle_delay_samples(np.array([dm]), table)[0]
    out_n = h.nsamples - int(
        oracle_delay_samples(np.array([dm]), table).max()
    )
    tim = oracle_dedisperse(fil.data, delays, out_n)
    size = choose_fft_size(out_n)
    bw = 1.0 / (size * h.tsamp)
    pos5 = int(0.05 / bw)
    pos25 = int(0.5 / bw)
    accs = accs if accs is not None else [0.0]

    oracle = oracle_search_trial(tim, size, h.tsamp, accs, pos5, pos25)

    # ours, stage by stage on device (f32)
    x32 = jnp.asarray(tim[:size], jnp.float32)
    fser = whiten_fseries(x32, pos5=pos5, pos25=pos25)
    med = running_median(form_power(jnp.fft.rfft(x32)), pos5=pos5, pos25=pos25)
    s0 = form_interpolated(fser)
    mean, _, std = spectrum_stats(s0)
    xd = jnp.fft.irfft(fser, n=size)

    o_med = oracle_running_median(
        np.abs(np.fft.rfft(tim[:size].astype(np.float64))), pos5, pos25
    )
    rows = [
        ("median", _relerr(np.asarray(med), o_med)),
        ("whiten.re", _relerr(np.asarray(jnp.real(fser)), oracle["fser"].real)),
        ("interbin0", _relerr(np.asarray(s0), oracle["s0"])),
        ("mean", abs(float(mean) - oracle["mean"]) / abs(oracle["mean"])),
        ("std", abs(float(std) - oracle["std"]) / abs(oracle["std"])),
        ("irfft", _relerr(np.asarray(xd), oracle["xd"])),
    ]
    for a in accs:
        afs = jnp.asarray(accel_factor(np.array([a]), h.tsamp))
        xr = resample_accel(xd, afs)[0]
        f = jnp.fft.rfft(xr)
        sn = (form_interpolated(f) - mean) / std
        levels = [sn] + harmonic_sums(sn, nharms=4)
        oa = oracle["acc"][float(a)]
        rows.append((f"resample[{a}]", _relerr(np.asarray(xr), oa["xr"])))
        for lvl in range(5):
            rows.append(
                (
                    f"snr l{lvl}[{a}]",
                    _relerr(np.asarray(levels[lvl]), oa["levels"][lvl], floor=1.0),
                )
            )
    return rows, oracle, {"size": size, "bw": bw, "max_delay": max_d}


def main(argv=None):
    import argparse

    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--fil", default="/root/reference/example_data/tutorial.fil")
    p.add_argument("--dm", type=float, default=239.3756103515625)
    p.add_argument("--acc", type=float, nargs="*", default=[0.0])
    args = p.parse_args(argv)
    rows, oracle, meta = compare_trial(args.fil, args.dm, args.acc)
    print(f"size={meta['size']} bw={meta['bw']:.6f} max_delay={meta['max_delay']}")
    for name, err in rows:
        print(f"  {name:>16s}  relerr {err:9.3e}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
