"""``peasoup-chaos`` — the chaos soak: real workloads under seeded
fault schedules, judged by end-to-end invariants.

The unit tests prove each recovery path in isolation; this tool proves
they *compose*. It runs a synthetic multi-observation campaign (and a
replay stream) twice — once fault-free for ground truth, once under a
deterministic fault schedule (resilience/faults.py) — and asserts the
invariants that define "survived":

* **exactly-once** — every enqueued job ends done XOR quarantined;
  nothing is lost, nothing double-completes.
* **bitwise-equal results** — for transient-only schedules (flaky
  reads, sqlite contention, worker kills — faults that must not change
  *what* is computed), every job's candidate file is byte-identical to
  the fault-free run, and every replayed stream trigger matches.
* **clean tree** — no leaked claim files, reap tombstones or ``*.tmp``
  atomic-write residue anywhere under the campaign root.
* **valid telemetry** — every done job's manifest validates against
  the checked-in schema; the campaign rollup loads and carries the
  resilience section.
* **bounded + attributed recovery** — retry counts stay within
  policy x injections, and every fault site that fired has a nonzero
  tally on the recovery path that answers it (retries for flaky
  reads/ingest, lease reaping for worker kills, quarantined artifacts
  for corrupted caches).

Runs in seconds on CPU (tiny observations), which is what lets
scripts/check.sh gate every commit on a chaos soak::

    peasoup-chaos --mode both -o /tmp/chaos \\
        --faults 'fil.read:p=0.25:n=4,db.ingest:at=1,worker.kill:at=obs0' \\
        --seed 7

Exit codes: 0 survived (all invariants hold), 1 invariant violated,
2 internal error. A ``chaos_report.json`` with the schedule, the
injection log and the per-invariant outcomes lands in the workdir.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import tempfile
import time

import numpy as np

from ..obs import get_logger

log = get_logger("tools.chaos")

REPORT_SCHEMA = "peasoup_tpu.chaos_report"
# v3: preempt/gang/autoscale in the fleet schedule
# v4: fleet "observability" section — schema-valid metrics series,
#     exposition round-trip, per-job trace connectivity/unclosed spans
# v5: on-demand profile drill over the request protocol, gang barrier
#     flow-id linkage, and the survey-health alerts snapshot
REPORT_VERSION = 5

DEFAULT_CAMPAIGN_FAULTS = (
    "fil.read:p=0.25:n=4,db.ingest:at=1,worker.kill:at=obs0"
)
# at=replay pins the injections to the reader thread's replay loop
# (the cross-thread attribution drill), not the initial batch read
DEFAULT_STREAM_FAULTS = "fil.read:at=replay:n=2"

# sites whose injections must never change results — the schedules this
# tool accepts for the bitwise-equality invariant
TRANSIENT_SITES = frozenset(
    {"fil.read", "queue.claim", "db.ingest", "checkpoint.write",
     "worker.kill", "device.oom", "cache.corrupt", "clock.skew",
     "multihost.barrier", "multihost.merge", "preempt.revoke"}
)

# fault site -> stats tables where its recovery must leave a mark
RECOVERY_TABLES = {
    "fil.read": ("retries", "recoveries", "giveups"),
    "queue.claim": ("retries", "recoveries", "giveups"),
    "db.ingest": ("retries", "recoveries", "giveups"),
    "checkpoint.write": ("retries", "recoveries", "giveups"),
    "device.oom": ("degradations",),
    "cache.corrupt": ("corrupt_artifacts",),
    "multihost.barrier": ("retries", "recoveries", "giveups"),
    "multihost.merge": ("retries", "recoveries", "giveups"),
    # worker.kill recovery is the queue reaper: checked against job
    # attempt counts, not a stats table
    "worker.kill": (),
    "clock.skew": (),
    # preempt.revoke suppresses revoke delivery; its recovery is the
    # grace-deadline reap, checked against attempt counts
    "preempt.revoke": (),
}


# --------------------------------------------------------------------------
# synthetic observations (the check.sh smoke-gate recipe, parameterised)
# --------------------------------------------------------------------------

def make_observations(
    data_dir: str,
    n_obs: int = 3,
    nsamps: int = 1 << 12,
    nchans: int = 8,
) -> list[str]:
    """Write ``n_obs`` small synthetic filterbanks, each with one
    strong dispersed pulse (distinct noise per observation, same
    shape bucket so the campaign exercises warm reuse)."""
    from ..io.sigproc import (
        Filterbank,
        SigprocHeader,
        write_filterbank,
    )
    from ..plan.dm_plan import DMPlan

    os.makedirs(data_dir, exist_ok=True)
    tsamp, fch1, foff = 0.000256, 1400.0, -16.0
    plan = DMPlan.create(
        nsamps=nsamps, nchans=nchans, tsamp=tsamp, fch1=fch1, foff=foff,
        dm_start=0.0, dm_end=20.0, pulse_width=64.0, tol=1.10,
    )
    delays = plan.delay_samples()[plan.ndm // 2]
    paths = []
    for i in range(n_obs):
        rng = np.random.default_rng(100 + i)
        data = rng.normal(32.0, 4.0, size=(nsamps, nchans))
        s0 = 1200 + 400 * i
        for c in range(nchans):
            data[s0 + delays[c] : s0 + 4 + delays[c], c] += 15.0
        hdr = SigprocHeader(
            source_name=f"CHAOS{i}", tsamp=tsamp, tstart=55000.0 + i,
            fch1=fch1, foff=foff, nchans=nchans, nbits=8, nifs=1,
            data_type=1,
        )
        path = os.path.join(data_dir, f"obs{i}.fil")
        write_filterbank(
            path,
            Filterbank(
                header=hdr,
                data=np.clip(np.rint(data), 0, 255).astype(np.uint8),
            ),
        )
        paths.append(path)
    return paths


# --------------------------------------------------------------------------
# campaign soak
# --------------------------------------------------------------------------

def _setup_campaign(
    root: str,
    inputs: list[str],
    config: dict,
    lease_s: float,
    max_attempts: int,
    gang_inputs: dict | None = None,
):
    """Create the campaign directory + config and enqueue the
    observations; returns the JobQueue (shared by the in-process and
    fleet soaks, so both judge identical campaigns). ``gang_inputs``
    maps input paths to an ``nprocs`` gang width (fleet soak only —
    the fault-free reference runs everything single-process, which is
    exactly what makes gang candidates' bitwise equality a proof)."""
    from ..campaign.queue import Job, JobQueue, job_id_for
    from ..campaign.runner import (
        CampaignConfig,
        bucket_for_input,
        save_campaign_config,
    )

    os.makedirs(root, exist_ok=True)
    cfg = CampaignConfig(
        pipeline="spsearch",
        config=config,
        lease_s=lease_s,
        max_attempts=max_attempts,
        backoff_base_s=0.05,
        heartbeat_interval=0.2,
        warmup=False,  # soak speed: compile once via the jit caches
        tune=False,
        preempt_grace_s=max(10.0, 10 * lease_s),
        gang_assemble_s=max(10.0, 10 * lease_s),
        gang_timeout_s=300.0,
    )
    save_campaign_config(root, cfg)
    queue = JobQueue(
        root, lease_s=lease_s, max_attempts=max_attempts,
        backoff_base_s=0.05,
    )
    gang_inputs = gang_inputs or {}
    for p in inputs:
        queue.add_job(
            Job(
                job_id=job_id_for(p), input=p, pipeline="spsearch",
                bucket=bucket_for_input(p),
                nprocs=int(gang_inputs.get(p, 1)),
            )
        )
    return queue


def _run_campaign(
    root: str,
    inputs: list[str],
    config: dict,
    lease_s: float,
    max_attempts: int,
) -> dict:
    """Drain one campaign in-process, surviving injected worker kills
    the way a fleet does: each kill abandons the claim (never released
    — WorkerKilled models SIGKILL), waits out the lease, and a
    replacement worker joins and reaps. The workers enter through
    runner.run_worker — THE production worker entry — so the
    in-process soak and the fleet soak's real subprocesses exercise
    identical code."""
    from ..campaign.rollup import write_status
    from ..campaign.runner import run_worker
    from ..resilience import WorkerKilled

    queue = _setup_campaign(root, inputs, config, lease_s, max_attempts)
    kills = 0
    tally = {"done": 0, "failed": 0, "quarantined": 0}
    worker = 0
    t0 = time.perf_counter()
    while True:
        try:
            t = run_worker(
                root, worker_id=f"chaos-w{worker}", poll_s=0.05
            )
            for k in tally:
                tally[k] += t.get(k, 0)
            break  # drained
        except WorkerKilled as exc:
            kills += 1
            worker += 1
            log.warning(
                "worker chaos-w%d killed (%s); lease will expire and a "
                "replacement joins", worker - 1, exc,
            )
            # a SIGKILLed worker's claim outlives it by the lease
            time.sleep(lease_s + 0.25)
    write_status(root, queue)
    return {
        "tally": tally,
        "workers_killed": kills,
        "workers_used": worker + 1,
        "wall_s": round(time.perf_counter() - t0, 3),
    }


def _job_candidate_bytes(root: str, job_id: str) -> bytes | None:
    path = os.path.join(root, "jobs", job_id, "candidates.singlepulse")
    try:
        with open(path, "rb") as f:
            return f.read()
    except OSError:
        return None


def _tree_residue(root: str) -> list[str]:
    """Leaked atomic-write temps / reap tombstones / claim files /
    preempt requests / retire markers / gang exchange directories /
    fleet-registry entries (a drained campaign must leave an empty
    registry: clean leavers deregister, dead workers get reaped, and
    every revoke/gang artifact is consumed by its protocol)."""
    bad = []
    for pat in ("**/*.tmp", "**/*.reap.*", "**/*.ckpt.tmp"):
        bad.extend(glob.glob(os.path.join(root, pat), recursive=True))
    bad.extend(glob.glob(os.path.join(root, "queue", "claims", "*.json")))
    bad.extend(glob.glob(os.path.join(root, "queue", "claims", "*.preempt")))
    bad.extend(glob.glob(os.path.join(root, "queue", "workers", "*.json")))
    bad.extend(glob.glob(os.path.join(root, "queue", "workers", "*.retire")))
    bad.extend(glob.glob(os.path.join(root, "jobs", "*", "gang-*")))
    return sorted(bad)


def _exactly_once_violations(
    root: str, counts: dict, job_ids: list[str], n_obs: int
) -> list[str]:
    """The exactly-once invariant, shared by the in-process and fleet
    soaks: every job terminal, none lost, none in two states."""
    violations = []
    if counts["total"] != n_obs:
        violations.append(
            f"jobs lost or duplicated: {counts['total']}/{n_obs} records"
        )
    if counts["done"] + counts["quarantined"] != counts["total"]:
        violations.append(f"campaign not drained exactly-once: {counts}")
    for j in job_ids:
        d = os.path.exists(
            os.path.join(root, "queue", "done", f"{j}.json")
        )
        q = os.path.exists(
            os.path.join(root, "queue", "quarantine", f"{j}.json")
        )
        if d == q:  # both (double-terminal) or neither (lost)
            violations.append(
                f"job {j}: done={d} quarantined={q} (must be exactly one)"
            )
    return violations


def run_campaign_soak(
    workdir: str,
    faults_spec: str,
    seed: int,
    n_obs: int = 3,
    nsamps: int = 1 << 12,
    max_attempts: int = 3,
    lease_s: float = 1.0,
    config: dict | None = None,
) -> dict:
    """Reference campaign (fault-free) + chaos campaign (seeded
    schedule) over the same observations; returns the report section
    with a ``violations`` list (empty = survived)."""
    from ..campaign.queue import JobQueue, job_id_for
    from ..campaign.rollup import load_campaign_status
    from ..obs.schema import validate_manifest
    from ..resilience import STATS, faults
    from ..resilience.faults import parse_faults

    plan = parse_faults(faults_spec, seed)
    unknown = set(plan.rules) - TRANSIENT_SITES
    if unknown:
        raise ValueError(f"non-transient fault sites: {sorted(unknown)}")

    config = config or {"dm_end": 20.0, "min_snr": 7.0, "n_widths": 6}
    data_dir = os.path.join(workdir, "data")
    inputs = make_observations(data_dir, n_obs=n_obs, nsamps=nsamps)
    job_ids = [job_id_for(p) for p in inputs]

    # --- reference: the ground truth this soak judges against --------
    faults.configure(None)
    STATS.reset()
    ref_root = os.path.join(workdir, "ref")
    log.info("chaos soak: fault-free reference campaign (%d obs)", n_obs)
    ref = _run_campaign(ref_root, inputs, config, lease_s, max_attempts)
    ref_cands = {j: _job_candidate_bytes(ref_root, j) for j in job_ids}
    if ref["tally"]["done"] != n_obs or any(
        v is None for v in ref_cands.values()
    ):
        raise RuntimeError(
            f"reference campaign did not complete cleanly: {ref}"
        )

    # --- chaos: same inputs, seeded schedule --------------------------
    STATS.reset()
    active = faults.configure(faults_spec, seed)
    chaos_root = os.path.join(workdir, "chaos")
    log.info(
        "chaos soak: campaign under schedule %r (seed %d)",
        faults_spec, seed,
    )
    try:
        chaos = _run_campaign(
            chaos_root, inputs, config, lease_s, max_attempts
        )
    finally:
        faults.configure(None)
    stats = STATS.snapshot()
    injection_log = active.to_doc() if active else {}

    # --- invariants ---------------------------------------------------
    queue = JobQueue(chaos_root)
    counts = queue.counts()

    # exactly-once: every job terminal, none lost, none in two states
    violations: list[str] = _exactly_once_violations(
        chaos_root, counts, job_ids, n_obs
    )

    # transient-only schedule: zero quarantine, bitwise-equal products
    if counts["quarantined"]:
        violations.append(
            f"{counts['quarantined']} job(s) quarantined under a "
            "transient-only schedule"
        )
    for j in job_ids:
        got = _job_candidate_bytes(chaos_root, j)
        if got is None:
            violations.append(f"job {j}: no candidate file after soak")
        elif got != ref_cands[j]:
            violations.append(
                f"job {j}: candidates differ from the fault-free run"
            )

    # clean tree
    residue = _tree_residue(chaos_root)
    if residue:
        violations.append(f"leaked files: {residue[:8]}")

    # valid telemetry + rollup with the resilience section
    for j in job_ids:
        man_path = os.path.join(chaos_root, "jobs", j, "telemetry.json")
        try:
            with open(man_path) as f:
                validate_manifest(json.load(f))
        except Exception as exc:
            violations.append(
                f"job {j}: telemetry manifest invalid: {exc!s:.200}"
            )
    try:
        rollup = load_campaign_status(
            os.path.join(chaos_root, "campaign_status.json")
        )
        if "resilience" not in rollup:
            violations.append("rollup lacks the resilience section")
    except Exception as exc:
        violations.append(f"campaign rollup unreadable: {exc!s:.200}")

    # bounded retries: policy budget x injections per site
    from ..resilience.policy import DB_RETRY, IO_RETRY

    budget = max(IO_RETRY.max_attempts, DB_RETRY.max_attempts)
    for site, n in stats["retries"].items():
        injected = stats["faults_injected"].get(site.split(":")[0], 0)
        if n > budget * max(1, injected):
            violations.append(
                f"unbounded retries at {site}: {n} retries for "
                f"{injected} injection(s) (budget {budget}/each)"
            )

    # attribution: every fired site left a mark on its recovery path
    for site, n in stats["faults_injected"].items():
        tables = RECOVERY_TABLES.get(site, ())
        if tables and not any(
            any(k.startswith(site) or site in k for k in stats[t])
            for t in tables
        ):
            violations.append(
                f"fault {site} fired {n}x but no recovery path "
                f"({'/'.join(tables)}) recorded handling it"
            )
    if "worker.kill" in stats["faults_injected"]:
        # the reaper is worker.kill's recovery: the killed job must
        # have consumed extra attempts yet still completed
        reaped = [
            d for d in queue.done_records()
            if int(d.get("attempts", 1)) > 1
        ]
        if chaos["workers_killed"] and not reaped:
            violations.append(
                "worker.kill fired but no done record shows a reaped "
                "retry (attempts > 1)"
            )

    return {
        "n_obs": n_obs,
        "faults": faults_spec,
        "seed": seed,
        "reference": ref,
        "chaos": chaos,
        "queue": counts,
        "stats": stats,
        "injections": injection_log,
        "violations": violations,
    }


# --------------------------------------------------------------------------
# fleet soak: real worker PROCESSES under kills, churn and skew
# --------------------------------------------------------------------------

# the per-worker fault schedule one (non-victim) worker runs under:
# two deterministic flaky reads, recovered inside the shared IO retry
# budget — so the rollup's resilience section must show the marks
DEFAULT_FLEET_WORKER_FAULTS = "fil.read:n=2"


def _fleet_roles(
    seed: int,
    n_workers: int,
    kills: int = 1,
    leavers: int = 1,
    late_joiners: int = 1,
    skew_s: float = 10.0,
    faults_spec: str = DEFAULT_FLEET_WORKER_FAULTS,
    gangs: int = 0,
) -> list[dict]:
    """Deterministic (seeded) role assignment for the fleet: which
    workers get SIGKILLed mid-job, which leave voluntarily after one
    job, which join late, and which run per-worker fault schedules
    (flaky reads on one drainer; a positive clock skew on a leaver —
    bounded premature reaping, absorbed by the attempt budget). At
    least one plain drainer always remains so the campaign can drain
    whatever the churn does.

    With ``gangs`` > 0 the flaky drainer and the (first) late joiner
    share the process group ``pod0`` — the gang job can only run once
    the late joiner arrives, so gang assembly-over-time is part of the
    drill, and neither group member is ever a kill victim or a leaver
    (a gang that can never assemble would deadlock the job, which the
    assembly timeout turns into a clean release loop instead)."""
    import random

    if n_workers < kills + late_joiners + 1:
        raise ValueError(
            f"fleet of {n_workers} cannot schedule {kills} kill(s) + "
            f"{late_joiners} late join(s) and still keep a drainer"
        )
    rng = random.Random(f"{seed}:fleet-roles")
    order = list(range(n_workers))
    rng.shuffle(order)
    victims = set(order[:kills])
    rest = [i for i in order if i not in victims]
    late = set(rest[-late_joiners:]) if late_joiners else set()
    # leavers drawn from the non-victim, non-late pool (a late joiner
    # that immediately leaves would be churn theatre, not coverage);
    # the FIRST of the pool stays a plain drainer
    pool = [i for i in rest if i not in late]
    leaver_set = set(pool[1 : 1 + leavers])
    faulty = pool[0] if pool else rest[0]
    skewed = next(iter(leaver_set), None)
    gang_members = (
        {faulty, min(late)} if gangs and late else
        set(pool[:2]) if gangs else set()
    )
    roles = []
    for i in range(n_workers):
        env_faults = []
        if i == faulty and faults_spec:
            env_faults.append(faults_spec)
        if i == skewed and skew_s:
            env_faults.append(f"clock.skew:skew={skew_s}")
        roles.append(
            {
                "index": i,
                "worker_id": f"fleet-w{i}",
                "kill": i in victims,
                "max_jobs": 1 if i in leaver_set else None,
                "late": i in late,
                "group": "pod0" if i in gang_members else "",
                "faults": (
                    ",".join(env_faults + [f"seed={seed}"])
                    if env_faults else ""
                ),
            }
        )
    return roles


def run_fleet_soak(
    workdir: str,
    faults_spec: str | None,
    seed: int,
    n_workers: int = 4,
    n_obs: int = 6,
    nsamps: int = 1 << 12,
    lease_s: float = 2.0,
    max_attempts: int = 6,
    kills: int = 1,
    leavers: int = 1,
    late_joiners: int = 1,
    skew_s: float = 10.0,
    timeout_s: float = 900.0,
    config: dict | None = None,
    gangs: int = 1,
    preempts: int = 1,
    autoscale: bool = True,
) -> dict:
    """THE fleet-scale soak: N real ``peasoup-campaign run``
    subprocesses drain one shared campaign directory while the parent
    applies a seeded schedule of real SIGKILLs (delivered the moment a
    victim holds a claim), worker churn (a voluntary single-job
    leaver, a late joiner), a clock-skewed reaper, per-worker
    ``PEASOUP_FAULTS`` — and, new in v3, the scheduling drills:
    ``gangs`` gang-scheduled jobs (nprocs=2 across the ``pod0``
    process group, which only assembles once the late joiner arrives),
    ``preempts`` priority preemptions (an urgent observation enqueued
    mid-soak plus an explicit revoke on a running claim — the victim
    must checkpoint, release with zero attempts, and the job must
    resume bitwise-equal), and — with ``autoscale`` — a REAL
    AutoscaleController spawning at least one extra worker off the
    backlog. Judged by the same invariants as the in-process soak —
    exactly-once, candidates bitwise-equal to a fault-free reference,
    zero leaked claims/preempt-files/retire-markers/gang-dirs/registry
    entries, gang jobs never partially claimed — plus per-site
    recovery and preemption-latency attribution assembled from the
    campaign rollup and the workers' own logs."""
    import signal
    import subprocess
    import sys

    from ..campaign.queue import Job, JobQueue, job_id_for
    from ..campaign.rollup import load_campaign_status, write_status
    from ..campaign.runner import bucket_for_input
    from ..obs.schema import validate_manifest
    from ..resilience import STATS, faults
    from ..resilience.faults import parse_faults

    spec = faults_spec or DEFAULT_FLEET_WORKER_FAULTS
    plan = parse_faults(spec, seed)
    unknown = set(plan.rules) - TRANSIENT_SITES
    if unknown:
        raise ValueError(f"non-transient fault sites: {sorted(unknown)}")
    if n_obs < n_workers:
        raise ValueError(
            f"fleet soak needs >= one job per worker ({n_obs} obs for "
            f"{n_workers} workers): every victim must get a claim to "
            "be killed holding it"
        )

    config = config or {"dm_end": 20.0, "min_snr": 7.0, "n_widths": 6}
    data_dir = os.path.join(workdir, "data")
    # one extra observation per scheduled preemption: the URGENT job,
    # enqueued mid-soak at priority 5 (the reference processes it
    # upfront — priority changes scheduling, never results)
    n_urgent = max(0, int(preempts))
    inputs = make_observations(
        data_dir, n_obs=n_obs + n_urgent, nsamps=nsamps
    )
    base_inputs, urgent_inputs = inputs[:n_obs], inputs[n_obs:]
    job_ids = [job_id_for(p) for p in inputs]
    n_total = len(inputs)
    # the LAST base observation runs as the gang job (any would do;
    # the last keeps the early claims free for the kill schedule)
    gang_inputs = (
        {base_inputs[-1]: 2} if gangs and n_workers >= 2 else {}
    )
    gang_job_ids = {job_id_for(p) for p in gang_inputs}

    # --- fault-free reference (in-process; same code path — the
    # workers enter through runner.run_worker either way) -------------
    faults.configure(None)
    STATS.reset()
    ref_root = os.path.join(workdir, "fleet_ref")
    log.info(
        "fleet soak: fault-free reference campaign (%d obs)", n_total
    )
    ref = _run_campaign(ref_root, inputs, config, lease_s, max_attempts)
    ref_cands = {j: _job_candidate_bytes(ref_root, j) for j in job_ids}
    if ref["tally"]["done"] != n_total or any(
        v is None for v in ref_cands.values()
    ):
        raise RuntimeError(
            f"reference campaign did not complete cleanly: {ref}"
        )

    # --- the fleet ----------------------------------------------------
    root = os.path.join(workdir, "fleet")
    queue = _setup_campaign(
        root, base_inputs, config, lease_s, max_attempts,
        gang_inputs=gang_inputs,
    )
    roles = _fleet_roles(
        seed, n_workers, kills=kills, leavers=leavers,
        late_joiners=late_joiners, skew_s=skew_s, faults_spec=spec,
        gangs=gangs,
    )
    logs_dir = os.path.join(workdir, "fleet_logs")
    os.makedirs(logs_dir, exist_ok=True)
    # one shared persistent compilation cache: the first worker pays
    # the compiles, every later worker (and the late joiner) cold-starts
    # warm — fleet wall time stays minutes, not hours
    cache_dir = os.environ.get("JAX_COMPILATION_CACHE_DIR") or os.path.join(
        workdir, "xla_cache"
    )

    procs: dict[str, dict] = {}

    def spawn(role: dict) -> None:
        env = dict(os.environ)
        env.pop("PEASOUP_FAULTS", None)
        if role["faults"]:
            env["PEASOUP_FAULTS"] = role["faults"]
        env.setdefault("JAX_PLATFORMS", "cpu")
        env["JAX_COMPILATION_CACHE_DIR"] = cache_dir
        cmd = [
            sys.executable, "-m", "peasoup_tpu.cli.campaign", "run",
            "-w", root, "--worker-id", role["worker_id"],
            "--pipeline", "spsearch",
            "--config", json.dumps(config),
            "--lease", str(lease_s),
            "--max-attempts", str(max_attempts),
            "--backoff", "0.05",
            "--no-warmup",
            "--poll", "0.05",
        ]
        if role["max_jobs"]:
            cmd += ["--max-jobs", str(role["max_jobs"])]
        if role.get("group"):
            cmd += ["--group", role["group"]]
        logf = open(
            os.path.join(logs_dir, role["worker_id"] + ".log"), "wb"
        )
        proc = subprocess.Popen(
            cmd, stdout=logf, stderr=subprocess.STDOUT, env=env
        )
        procs[role["worker_id"]] = {
            "proc": proc, "logf": logf,
            "log": logf.name, "role": role, "killed": False,
        }
        log.info(
            "fleet: spawned %s (pid %d)%s%s%s",
            role["worker_id"], proc.pid,
            " [victim]" if role["kill"] else "",
            f" [leaves after {role['max_jobs']}]" if role["max_jobs"]
            else "",
            f" [faults {role['faults']}]" if role["faults"] else "",
        )

    # the real autoscale controller, supervising the same campaign the
    # fleet drains: its spawns go through the soak's own spawn() so the
    # extra worker is settled, logged and attributed like any other
    controller = None
    if autoscale:
        from ..campaign.autoscale import (
            AutoscaleController,
            AutoscalePolicy,
        )

        def _scale_spawn(wid: str):
            role = {
                "worker_id": wid, "kill": False, "max_jobs": None,
                "late": False, "group": "", "faults": "",
            }
            spawn(role)
            return procs[wid]["proc"]

        controller = AutoscaleController(
            root,
            AutoscalePolicy(
                min_workers=1,
                max_workers=n_workers + 1,
                cooldown_s=max(2.0, 2 * lease_s),
                backlog_per_worker=1.0,
            ),
            spawn=_scale_spawn,
            controller_id="scale",
        )

    t0 = time.perf_counter()
    for role in roles:
        if not role["late"]:
            spawn(role)
    late_pending = [r for r in roles if r["late"]]
    pending_victims = {r["worker_id"] for r in roles if r["kill"]}
    gang_workers = {r["worker_id"] for r in roles if r.get("group")}
    kills_done: list[dict] = []
    joins: list[str] = []
    preempts_requested: list[dict] = []
    preempt_targets_tried: set[str] = set()
    urgent_enqueued = False
    last_scale_step = 0.0
    claims_dir = os.path.join(root, "queue", "claims")
    done_dir = os.path.join(root, "queue", "done")
    timed_out = False
    profile_drilled: dict | None = None
    from ..campaign.registry import WorkerRegistry as _Registry

    soak_registry = _Registry(root, lease_s=lease_s)
    while True:
        if time.perf_counter() - t0 > timeout_s:
            timed_out = True
            break
        # preemption drill: once any claim is live, enqueue the urgent
        # observation at priority 5 AND revoke one running claim
        # explicitly (retrying with a new target if a fast job slipped
        # to done before its renewer observed) — never a gang claim,
        # never the kill victim's (those drills must stay orthogonal)
        if n_urgent and os.path.isdir(claims_dir):
            if not urgent_enqueued and any(
                n.endswith(".json") for n in os.listdir(claims_dir)
            ):
                # the fleet is busy: the urgent work arrives NOW, at
                # priority 5 — exactly the displacement scenario
                for up in urgent_inputs:
                    queue.add_job(
                        Job(
                            job_id=job_id_for(up), input=up,
                            pipeline="spsearch",
                            bucket=bucket_for_input(up),
                            priority=5,
                        )
                    )
                urgent_enqueued = True
                log.info(
                    "fleet: enqueued %d urgent obs at priority 5",
                    len(urgent_inputs),
                )
            confirmed = sum(
                1 for jid in preempt_targets_tried
                if (j := queue.get_job(jid)) is not None and j.preemptions
            )
            outstanding = any(
                queue.preempt_request(jid) is not None
                for jid in preempt_targets_tried
            )
            if confirmed < n_urgent and not outstanding:
                for name in sorted(os.listdir(claims_dir)):
                    if not name.endswith(".json"):
                        continue
                    try:
                        with open(os.path.join(claims_dir, name)) as f:
                            doc = json.load(f)
                    except (OSError, json.JSONDecodeError):
                        continue
                    jid = doc.get("job_id")
                    if (
                        not jid
                        or jid in preempt_targets_tried
                        or jid in gang_job_ids
                        or doc.get("gang")
                        or doc.get("worker_id") in pending_victims
                    ):
                        continue
                    # generous grace: the target is usually the FIRST
                    # claim (coldest compile), and the victim can only
                    # answer at a chunk boundary — the grace-deadline
                    # escalation is drilled separately in unit tests
                    if queue.request_preempt(
                        jid, requester="chaos-soak", grace_s=300.0,
                    ):
                        preempt_targets_tried.add(jid)
                        preempts_requested.append(
                            {
                                "job_id": jid,
                                "victim": doc.get("worker_id"),
                            }
                        )
                        log.info(
                            "fleet: preempt requested on %s (held by "
                            "%s)", jid, doc.get("worker_id"),
                        )
                        break
        # autoscale control loop, throttled to ~1 Hz
        if controller is not None and (
            time.perf_counter() - last_scale_step > 1.0
        ):
            last_scale_step = time.perf_counter()
            try:
                controller.step()
            except Exception:
                log.warning("autoscale step failed", exc_info=True)
        # churn: the late joiners arrive once the fleet has made first
        # progress (a done record) — they must claim from the warm
        # bucket tier, not reopen cold ones
        if late_pending and os.listdir(done_dir):
            for role in late_pending:
                spawn(role)
                joins.append(role["worker_id"])
            late_pending = []
        # profile drill: once the fleet has made first progress, ask a
        # live, non-victim worker for an on-demand capture through the
        # real request protocol — on CPU backends the capture is a
        # guarded no-op, but the worker must still observe the marker,
        # clear it and announce the outcome in its metrics stream
        if profile_drilled is None and os.listdir(done_dir):
            for ent in soak_registry.live():
                wid = ent.get("worker_id")
                if not wid or wid in pending_victims:
                    continue
                proc_ent = procs.get(wid)
                if proc_ent is None or proc_ent["proc"].poll() is not None:
                    continue
                if proc_ent["role"].get("max_jobs"):
                    # early leavers may exit before observing the
                    # marker; drill a stayer so the check is sound
                    continue
                soak_registry.request_profile(
                    wid, seconds=0.2, requester="chaos-soak"
                )
                profile_drilled = {"worker_id": wid, "seconds": 0.2}
                log.info("fleet: profile drill requested on %s", wid)
                break
        # kills: a victim dies by REAL SIGKILL the moment it holds a
        # claim (plus a beat so the job is genuinely under way) — the
        # worst case for exactly-once, recovered only by lease reaping
        if pending_victims and os.path.isdir(claims_dir):
            for name in sorted(os.listdir(claims_dir)):
                if not name.endswith(".json"):
                    continue
                try:
                    with open(os.path.join(claims_dir, name)) as f:
                        doc = json.load(f)
                except (OSError, json.JSONDecodeError):
                    continue
                wid = doc.get("worker_id")
                if wid in pending_victims:
                    ent = procs.get(wid)
                    pending_victims.discard(wid)
                    if ent and ent["proc"].poll() is None:
                        time.sleep(0.2)
                        try:
                            os.kill(ent["proc"].pid, signal.SIGKILL)
                        except ProcessLookupError:
                            continue
                        ent["killed"] = True
                        kills_done.append(
                            {
                                "worker_id": wid,
                                "pid": ent["proc"].pid,
                                "job_id": doc.get("job_id"),
                            }
                        )
                        log.warning(
                            "fleet: SIGKILLed %s (pid %d) mid-job %s",
                            wid, ent["proc"].pid, doc.get("job_id"),
                        )
        alive = [e for e in procs.values() if e["proc"].poll() is None]
        if not late_pending and not alive and queue.drained():
            break
        time.sleep(0.05)

    # settle: every spawned process must be gone (drained workers exit
    # on their own; a timeout kills the stragglers and is a violation)
    for ent in procs.values():
        if ent["proc"].poll() is None and timed_out:
            ent["proc"].kill()
        try:
            ent["proc"].wait(timeout=60)
        except subprocess.TimeoutExpired:
            ent["proc"].kill()
            ent["proc"].wait(timeout=10)
        ent["logf"].close()
    wall_s = round(time.perf_counter() - t0, 3)
    from ..campaign.registry import WorkerRegistry

    # sweep what the settled processes can no longer sweep themselves:
    # expired corpses and any retire marker that landed after its
    # worker had already exited (deregistration bugs still surface —
    # a LIVE leftover entry is not reaped here and fails the
    # zero-residue invariant below)
    WorkerRegistry(root, lease_s=lease_s).reap()
    write_status(root, queue)  # final rollup over the settled tree

    # --- invariants ---------------------------------------------------
    counts = queue.counts()
    violations = _exactly_once_violations(root, counts, job_ids, n_total)
    if timed_out:
        violations.append(
            f"fleet did not drain within {timeout_s:.0f}s"
        )
    if pending_victims and not timed_out:
        violations.append(
            f"kill schedule unapplied: {sorted(pending_victims)} never "
            "held a claim"
        )
    if counts["quarantined"]:
        violations.append(
            f"{counts['quarantined']} job(s) quarantined under a "
            "transient-only schedule"
        )
    for j in job_ids:
        got = _job_candidate_bytes(root, j)
        if got is None:
            violations.append(f"job {j}: no candidate file after soak")
        elif got != ref_cands[j]:
            violations.append(
                f"job {j}: candidates differ from the fault-free run"
            )
    residue = _tree_residue(root)
    if residue:
        violations.append(f"leaked files: {residue[:8]}")
    for j in job_ids:
        man_path = os.path.join(root, "jobs", j, "telemetry.json")
        try:
            with open(man_path) as f:
                validate_manifest(json.load(f))
        except Exception as exc:
            violations.append(
                f"job {j}: telemetry manifest invalid: {exc!s:.200}"
            )

    # --- per-site recovery attribution --------------------------------
    # injections counted from the workers' own logs (each subprocess
    # owns its STATS); recoveries from the rollup's resilience section
    # (aggregated per-job deltas) and the queue's attempt accounting
    injected: dict[str, int] = {}
    for ent in procs.values():
        try:
            with open(ent["log"], "rb") as f:
                text = f.read().decode("utf-8", "replace")
        except OSError:
            continue
        for site in SITES_IN_LOGS:
            n = text.count(f"injecting fault at {site}")
            if n:
                injected[site] = injected.get(site, 0) + n
    try:
        rollup = load_campaign_status(
            os.path.join(root, "campaign_status.json")
        )
    except Exception as exc:
        rollup = {}
        violations.append(f"campaign rollup unreadable: {exc!s:.200}")
    res = rollup.get("resilience") or {}
    if "fleet" not in rollup:
        violations.append("rollup lacks the fleet section")
    recovery: dict[str, dict] = {}
    for site, n in injected.items():
        if site in ("clock.skew",):
            recovery[site] = {"injected": n}
            continue
        marks = {
            t: v
            for t in ("retries", "recoveries", "giveups")
            for k, v in (res.get(t) or {}).items()
            if k.startswith(site)
        }
        recovery[site] = {"injected": n, **marks}
        if n and not marks:
            violations.append(
                f"fault {site} fired {n}x across the fleet but the "
                "rollup shows no recovery marks"
            )
    done = queue.done_records()
    if kills_done:
        reaped = [d for d in done if int(d.get("attempts", 1)) > 1]
        recovery["worker.kill"] = {
            "sigkills": len(kills_done),
            "reaped_retries": len(reaped),
        }
        if not reaped:
            violations.append(
                "SIGKILL(s) delivered but no done record shows a "
                "reaped retry (attempts > 1)"
            )

    # --- preemption attribution ---------------------------------------
    preempted_done = [d for d in done if d.get("preemptions")]
    preempt_section = {
        "requested": preempts_requested,
        "jobs_resumed": len(preempted_done),
        "latency_s": sorted(
            float(x)
            for d in preempted_done
            for x in (d.get("preempt_latency_s") or [])
        ),
    }
    if n_urgent:
        if not preempted_done:
            violations.append(
                "preemption scheduled but no done record carries a "
                "preemption tally (revoke never landed or was lost)"
            )
        elif not preempt_section["latency_s"]:
            violations.append(
                "preempted job resumed without preempt_latency_s "
                "attribution in its done record"
            )
        for d in preempted_done:
            if int(d.get("attempts", 1)) == 1:
                continue
            # a revoke must consume ZERO attempts. Attempts > 1 on a
            # preempted job is allowed only when ANOTHER drill also
            # hit it: the SIGKILL victim's reaped claim, or the
            # clock-skewed reaper prematurely reaping a fresh claim
            # (skew >> lease makes every claim look expired to it) —
            # both leave a reap signature in the job record's
            # last_error. The zero-attempt release itself is pinned
            # deterministically by tests/test_fleet.py.
            jid = d.get("job_id")
            job = queue.get_job(jid)
            reap_attributed = jid in {
                k.get("job_id") for k in kills_done
            } or (
                job is not None
                and job.last_error is not None
                and (
                    "lease expired" in job.last_error
                    or "grace deadline" in job.last_error
                )
            )
            if not reap_attributed:
                violations.append(
                    f"preempted job {jid} consumed {d['attempts']} "
                    "attempts (revoke must consume zero) with no reap "
                    "to attribute them to"
                )

    # --- gang attribution ---------------------------------------------
    gang_done = [d for d in done if d.get("gang")]
    gang_section = {
        "scheduled": sorted(gang_job_ids),
        "done": len(gang_done),
        "members": sorted(
            {m for d in gang_done for m in d["gang"].get("members", [])}
        ),
    }
    if gang_inputs:
        if len(gang_done) != len(gang_job_ids):
            violations.append(
                f"{len(gang_job_ids)} gang job(s) scheduled but "
                f"{len(gang_done)} completed with gang provenance"
            )
        for d in gang_done:
            g = d["gang"]
            if len(g.get("members", [])) != int(g.get("nprocs", 0)):
                violations.append(
                    f"gang job {d.get('job_id')} completed PARTIALLY "
                    f"claimed: members {g.get('members')} vs nprocs "
                    f"{g.get('nprocs')}"
                )

    # --- fleet observability: metrics series + connected traces ------
    # (ISSUE 14) the soak is ALSO the proof of the observability layer:
    # every worker's time series must be schema-valid and render a
    # parseable Prometheus exposition with nonzero queue-depth (and,
    # when a preemption was drilled, nonzero preemption-latency)
    # samples covering the soak window; every terminal job's span files
    # must merge into ONE connected trace with zero unclosed spans —
    # the preempted-and-resumed job showing both attempts plus the
    # revoke span, and the gang job showing both members' processes.
    from ..obs import metrics as obs_metrics
    from ..obs.trace import load_spans, trace_paths, trace_summary

    obs_section: dict = {"metrics": {}, "traces": {}}
    try:
        fleet_metrics = obs_metrics.fleet_samples(root, validate=True)
    except Exception as exc:
        fleet_metrics = {}
        violations.append(
            f"metrics series schema-invalid: {exc!s:.200}"
        )
    n_samples = sum(len(v) for v in fleet_metrics.values())
    obs_section["metrics"]["sources"] = sorted(fleet_metrics)
    obs_section["metrics"]["samples"] = n_samples
    if not n_samples:
        violations.append("fleet wrote no metrics samples")
    try:
        expo = obs_metrics.prometheus_exposition(fleet_metrics)
        obs_section["metrics"]["exposition_series"] = len(
            obs_metrics.parse_exposition(expo)
        )
    except Exception as exc:
        violations.append(
            f"Prometheus exposition failed to render/parse: {exc!s:.200}"
        )
    qdepth = obs_metrics.series(fleet_metrics, "queue_depth", "gauge")
    if not qdepth or max(r["value"] for r in qdepth) <= 0:
        violations.append(
            "queue_depth series empty or all-zero over the soak"
        )
    else:
        obs_section["metrics"]["queue_depth_samples"] = len(qdepth)
        obs_section["metrics"]["queue_depth_span_s"] = round(
            qdepth[-1]["t"] - qdepth[0]["t"], 3
        )
        if qdepth[-1]["t"] - qdepth[0]["t"] <= 0:
            violations.append(
                "queue_depth series does not span the soak window"
            )
    plat = obs_metrics.series(
        fleet_metrics, "preemption_latency_seconds", "hist"
    )
    if n_urgent:
        if not plat or max(r["value"] for r in plat) <= 0:
            violations.append(
                "preemption drilled but no nonzero "
                "preemption_latency_seconds metric recorded"
            )
        else:
            obs_section["metrics"]["preemption_latency_max_s"] = round(
                max(r["value"] for r in plat), 4
            )
    # profile drill attribution: the worker must have observed the
    # request (marker cleared) and announced the capture outcome —
    # captured on a device backend, skipped on the CPU guard, either
    # way a profile_captures_total sample with an outcome label
    if profile_drilled is not None:
        pcaps = obs_metrics.series(
            fleet_metrics, "profile_captures_total", "counter"
        )
        outcomes = sorted(
            {
                (r.get("labels") or {}).get("outcome", "")
                for r in pcaps
            }
        )
        obs_section["profile"] = {
            "drilled": profile_drilled,
            "samples": len(pcaps),
            "outcomes": outcomes,
        }
        if not pcaps:
            violations.append(
                "profile drill requested on "
                f"{profile_drilled['worker_id']} but no "
                "profile_captures_total metric was announced"
            )
        wid = profile_drilled["worker_id"]
        if soak_registry.profile_requested(wid) is not None:
            violations.append(
                f"profile drill: request marker for {wid} never "
                "cleared (worker did not observe it)"
            )
    preempted_ids = {
        d.get("job_id") for d in done if d.get("preemptions")
    }
    for j in job_ids:
        spans = load_spans(trace_paths(os.path.join(root, "jobs", j)))
        summ = trace_summary(spans)
        obs_section["traces"][j] = {
            "n_spans": summ["n_spans"],
            "trace_ids": summ["trace_ids"],
            "connected": summ["connected"],
            "workers": summ["workers"],
            "unclosed": summ["unclosed"],
            "n_flows": summ["n_flows"],
            "flows_linked": summ["flows_linked"],
            "attempts": sum(
                1 for s in spans if s.get("name") == "job_attempt"
            ),
        }
        if not spans:
            violations.append(f"job {j}: no trace spans written")
            continue
        if not summ["connected"]:
            violations.append(
                f"job {j}: trace NOT connected (trace_ids "
                f"{summ['trace_ids']})"
            )
        if summ["unclosed"]:
            violations.append(
                f"job {j}: {summ['unclosed']} unclosed span(s)"
            )
        names = set(summ["span_names"])
        if j in preempted_ids:
            n_attempts = obs_section["traces"][j]["attempts"]
            if n_attempts < 2:
                violations.append(
                    f"preempted job {j}: trace shows {n_attempts} "
                    "attempt span(s), expected the original AND the "
                    "resume in one connected trace"
                )
            if "revoke" not in names:
                violations.append(
                    f"preempted job {j}: no revoke-latency span in "
                    "its trace"
                )
        if j in gang_job_ids and len(summ["workers"]) < 2:
            violations.append(
                f"gang job {j}: trace spans from "
                f"{summ['workers']} — expected both members' "
                "processes in one connected trace"
            )
        if (
            j in gang_job_ids
            and len(summ["workers"]) >= 2
            and not summ["flows_linked"]
        ):
            violations.append(
                f"gang job {j}: no flow id links the members' "
                "gang_barrier spans (expected the same deterministic "
                "flow id on every rank of each barrier round)"
            )

    # --- survey-health alerts over the settled tree -------------------
    # the workers evaluated the default SLO/data-quality rules while
    # running; the snapshot must exist and validate (what fired is
    # campaign-dependent — the lifecycle itself is drilled by
    # scripts/check.sh with a controlled clock)
    try:
        from ..obs.alerts import load_alerts, validate_snapshot

        alerts_snap = load_alerts(root)
        validate_snapshot(alerts_snap)
        by_state: dict[str, int] = {}
        for a in alerts_snap.get("alerts", []):
            by_state[a["state"]] = by_state.get(a["state"], 0) + 1
        obs_section["alerts"] = {
            "states": by_state,
            "updated_unix": alerts_snap.get("updated_unix"),
        }
        if not os.path.exists(
            os.path.join(root, "queue", "alerts.json")
        ):
            violations.append(
                "fleet workers never wrote an alerts snapshot "
                "(queue/alerts.json missing after the soak)"
            )
    except Exception as exc:
        violations.append(
            f"alerts snapshot invalid after the soak: {exc!s:.200}"
        )

    # --- autoscale attribution ----------------------------------------
    scale_section = None
    if controller is not None:
        scale_section = {
            "decisions": controller.decisions,
            "ups": sum(
                1 for d in controller.decisions if d["action"] == "up"
            ),
            "downs": sum(
                1 for d in controller.decisions if d["action"] == "down"
            ),
        }
        if not scale_section["ups"]:
            violations.append(
                "autoscale controller never scaled up despite the "
                "backlog (no 'up' decision)"
            )
        if "autoscale" not in (rollup or {}) or not (
            rollup.get("autoscale") or {}
        ).get("decisions"):
            violations.append(
                "rollup lacks the autoscale decision log"
            )

    return {
        "n_obs": n_obs,
        "n_urgent": n_urgent,
        "n_workers": n_workers,
        "faults": spec,
        "seed": seed,
        "roles": [
            {k: v for k, v in r.items() if k != "index"} for r in roles
        ],
        "kills": kills_done,
        "late_joins": joins,
        "reference": ref,
        "wall_s": wall_s,
        "queue": counts,
        "worker_logs": sorted(e["log"] for e in procs.values()),
        "recovery": recovery,
        "preemption": preempt_section,
        "gang": gang_section,
        "autoscale": scale_section,
        "observability": obs_section,
        "violations": violations,
    }


# sites whose injections are counted from worker logs in the fleet
# soak (the log line is faults.py's "injecting fault at <site>")
SITES_IN_LOGS = ("fil.read", "queue.claim", "db.ingest", "clock.skew")


# --------------------------------------------------------------------------
# stream soak
# --------------------------------------------------------------------------

def _run_stream(outdir: str, fil_path: str) -> dict:
    from ..io.sigproc import read_filterbank
    from ..io.stream_source import ReplaySource
    from ..obs.telemetry import RunTelemetry
    from ..stream.driver import StreamConfig, StreamingSearch

    os.makedirs(outdir, exist_ok=True)
    cfg = StreamConfig(
        outdir=outdir, dm_end=20.0, min_snr=7.0, n_widths=6,
        chunk_samples=1024, decimate=8, latency_slo_s=30.0,
        warmup=False,
    )
    tel = RunTelemetry()
    with tel.activate():
        fil = read_filterbank(fil_path)
        result = StreamingSearch(cfg).run(
            ReplaySource(fil, block_samples=512, rate=0.0)
        )
        tel.write(os.path.join(outdir, "telemetry.json"))
    return {
        "triggers": [
            (int(c.dm_idx), int(c.sample), int(c.width), float(c.snr))
            for c in result.candidates
        ],
        "n_chunks": result.n_chunks,
        "drops": result.drops,
        "jit_programs_steady": result.jit_programs_steady,
        "events": tel.events,
    }


def run_stream_soak(
    workdir: str, faults_spec: str, seed: int, nsamps: int = 1 << 12
) -> dict:
    """Replay the same recording fault-free and under the schedule;
    the stream must emit identical triggers with zero drops."""
    from ..resilience import STATS, faults
    from ..resilience.faults import parse_faults

    plan = parse_faults(faults_spec, seed)
    unknown = set(plan.rules) - {"fil.read"}
    if unknown:
        raise ValueError(
            f"stream soak drills fil.read only, got: {sorted(unknown)}"
        )
    [fil_path] = make_observations(
        os.path.join(workdir, "stream_data"), n_obs=1, nsamps=nsamps
    )
    faults.configure(None)
    STATS.reset()
    ref = _run_stream(os.path.join(workdir, "stream_ref"), fil_path)
    STATS.reset()
    active = faults.configure(faults_spec, seed)
    try:
        chaos = _run_stream(
            os.path.join(workdir, "stream_chaos"), fil_path
        )
    finally:
        faults.configure(None)
    stats = STATS.snapshot()

    violations: list[str] = []
    if not ref["triggers"]:
        raise RuntimeError("reference stream produced no triggers")
    if chaos["triggers"] != ref["triggers"]:
        violations.append(
            f"stream triggers differ: {len(chaos['triggers'])} vs "
            f"{len(ref['triggers'])} reference"
        )
    if chaos["drops"].get("blocks") or chaos["drops"].get("gap_samples"):
        violations.append(f"stream dropped data: {chaos['drops']}")
    if chaos["jit_programs_steady"]:
        violations.append(
            f"{chaos['jit_programs_steady']} steady-state recompile(s) "
            "under faults"
        )
    injected = stats["faults_injected"].get("fil.read", 0)
    if injected and not (
        stats["retries"].get("fil.read") or stats["recoveries"].get("fil.read")
    ):
        violations.append(
            "fil.read fired on the stream but no retry/recovery "
            "recorded handling it"
        )
    kinds = {e["kind"] for e in chaos["events"]}
    if injected and "fault_injected" not in kinds:
        violations.append(
            "injections happened without fault_injected telemetry"
        )
    return {
        "faults": faults_spec,
        "seed": seed,
        "reference": {k: ref[k] for k in ("n_chunks", "drops")},
        "chaos": {k: chaos[k] for k in ("n_chunks", "drops")},
        "n_triggers": len(ref["triggers"]),
        "stats": stats,
        "injections": active.to_doc() if active else {},
        "violations": violations,
    }


# --------------------------------------------------------------------------
# CLI
# --------------------------------------------------------------------------

def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="peasoup-chaos",
        description="Chaos soak: run campaign/stream workloads under a "
        "seeded fault schedule and assert the survival invariants "
        "(exactly-once, bitwise-equal candidates, clean tree, valid "
        "telemetry, bounded + attributed recovery).",
    )
    p.add_argument(
        "--mode", choices=("campaign", "stream", "both", "fleet"),
        default="both",
        help="campaign/stream soak in-process workers; fleet spawns N "
        "REAL `peasoup-campaign run` subprocesses and applies a seeded "
        "schedule of SIGKILLs, churn (late join, voluntary leave), "
        "clock skew and per-worker PEASOUP_FAULTS",
    )
    p.add_argument(
        "--faults", default=None,
        help="fault schedule (resilience/faults.py grammar); default: "
        f"campaign {DEFAULT_CAMPAIGN_FAULTS!r}, "
        f"stream {DEFAULT_STREAM_FAULTS!r}",
    )
    p.add_argument("--seed", type=int, default=7)
    p.add_argument(
        "-o", "--workdir", default=None,
        help="soak directory (default: a fresh temp dir)",
    )
    p.add_argument("--n-obs", type=int, default=3)
    p.add_argument(
        "--nsamps", type=int, default=1 << 12,
        help="samples per synthetic observation",
    )
    p.add_argument(
        "--lease", type=float, default=1.0,
        help="campaign claim lease seconds (kill recovery waits it out)",
    )
    p.add_argument(
        "--report", default=None,
        help="chaos_report.json path (default: <workdir>/chaos_report.json)",
    )
    fleet = p.add_argument_group("fleet mode")
    fleet.add_argument(
        "--workers", type=int, default=4,
        help="fleet worker subprocesses (default 4)",
    )
    fleet.add_argument(
        "--kills", type=int, default=1,
        help="workers SIGKILLed mid-job (default 1)",
    )
    fleet.add_argument(
        "--leavers", type=int, default=1,
        help="workers leaving voluntarily after one job (default 1)",
    )
    fleet.add_argument(
        "--late-joiners", type=int, default=1,
        help="workers joining after first progress (default 1)",
    )
    fleet.add_argument(
        "--skew", type=float, default=10.0,
        help="clock skew (s) injected into one leaver's reaper "
        "(default 10)",
    )
    fleet.add_argument(
        "--fleet-timeout", type=float, default=900.0,
        help="seconds before an undrained fleet is a violation "
        "(default 900)",
    )
    fleet.add_argument(
        "--gangs", type=int, default=1,
        help="gang-scheduled jobs (nprocs=2 across the pod0 process "
        "group; default 1, 0 disables)",
    )
    fleet.add_argument(
        "--preempts", type=int, default=1,
        help="priority preemptions: urgent obs enqueued mid-soak + a "
        "revoke on a running claim, asserted checkpointed/zero-attempt/"
        "latency-attributed (default 1, 0 disables)",
    )
    fleet.add_argument(
        "--autoscale", action=argparse.BooleanOptionalAction,
        default=True,
        help="run a real AutoscaleController over the fleet and assert "
        "at least one backlog-driven scale-up (default on)",
    )
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    workdir = args.workdir or tempfile.mkdtemp(prefix="peasoup-chaos-")
    os.makedirs(workdir, exist_ok=True)
    report: dict = {
        "schema": REPORT_SCHEMA,
        "version": REPORT_VERSION,
        "workdir": os.path.abspath(workdir),
        "mode": args.mode,
    }
    try:
        violations: list[str] = []
        if args.mode in ("campaign", "both"):
            sec = run_campaign_soak(
                workdir,
                args.faults or DEFAULT_CAMPAIGN_FAULTS,
                args.seed,
                n_obs=args.n_obs,
                nsamps=args.nsamps,
                lease_s=args.lease,
            )
            report["campaign"] = sec
            violations += [f"campaign: {v}" for v in sec["violations"]]
        if args.mode in ("stream", "both"):
            sec = run_stream_soak(
                workdir,
                args.faults if args.mode == "stream" and args.faults
                else DEFAULT_STREAM_FAULTS,
                args.seed,
                nsamps=args.nsamps,
            )
            report["stream"] = sec
            violations += [f"stream: {v}" for v in sec["violations"]]
        if args.mode == "fleet":
            sec = run_fleet_soak(
                workdir,
                args.faults,
                args.seed,
                n_workers=args.workers,
                n_obs=args.n_obs,
                nsamps=args.nsamps,
                lease_s=args.lease,
                kills=args.kills,
                leavers=args.leavers,
                late_joiners=args.late_joiners,
                skew_s=args.skew,
                timeout_s=args.fleet_timeout,
                gangs=args.gangs,
                preempts=args.preempts,
                autoscale=args.autoscale,
            )
            report["fleet"] = sec
            violations += [f"fleet: {v}" for v in sec["violations"]]
        report["violations"] = violations
        report["ok"] = not violations
    except Exception as exc:
        import traceback

        traceback.print_exc()
        report["ok"] = False
        report["error"] = f"{type(exc).__name__}: {exc!s:.500}"
        _write_report(report, args)
        print("peasoup-chaos: internal error (exit 2)", file=sys.stderr)
        return 2
    _write_report(report, args)
    if report["ok"]:
        print(
            f"peasoup-chaos: SURVIVED ({args.mode}; "
            f"workdir {workdir})"
        )
        return 0
    print("peasoup-chaos: INVARIANT VIOLATIONS:", file=sys.stderr)
    for v in violations:
        print(f"  - {v}", file=sys.stderr)
    return 1


def _write_report(report: dict, args) -> None:
    path = args.report or os.path.join(
        report["workdir"], "chaos_report.json"
    )
    with open(path, "w") as f:
        json.dump(report, f, indent=2, default=str)
        f.write("\n")
    print(f"peasoup-chaos: report -> {path}")


if __name__ == "__main__":
    sys.exit(main())
