"""``peasoup-chaos`` — the chaos soak: real workloads under seeded
fault schedules, judged by end-to-end invariants.

The unit tests prove each recovery path in isolation; this tool proves
they *compose*. It runs a synthetic multi-observation campaign (and a
replay stream) twice — once fault-free for ground truth, once under a
deterministic fault schedule (resilience/faults.py) — and asserts the
invariants that define "survived":

* **exactly-once** — every enqueued job ends done XOR quarantined;
  nothing is lost, nothing double-completes.
* **bitwise-equal results** — for transient-only schedules (flaky
  reads, sqlite contention, worker kills — faults that must not change
  *what* is computed), every job's candidate file is byte-identical to
  the fault-free run, and every replayed stream trigger matches.
* **clean tree** — no leaked claim files, reap tombstones or ``*.tmp``
  atomic-write residue anywhere under the campaign root.
* **valid telemetry** — every done job's manifest validates against
  the checked-in schema; the campaign rollup loads and carries the
  resilience section.
* **bounded + attributed recovery** — retry counts stay within
  policy x injections, and every fault site that fired has a nonzero
  tally on the recovery path that answers it (retries for flaky
  reads/ingest, lease reaping for worker kills, quarantined artifacts
  for corrupted caches).

Runs in seconds on CPU (tiny observations), which is what lets
scripts/check.sh gate every commit on a chaos soak::

    peasoup-chaos --mode both -o /tmp/chaos \\
        --faults 'fil.read:p=0.25:n=4,db.ingest:at=1,worker.kill:at=obs0' \\
        --seed 7

Exit codes: 0 survived (all invariants hold), 1 invariant violated,
2 internal error. A ``chaos_report.json`` with the schedule, the
injection log and the per-invariant outcomes lands in the workdir.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import tempfile
import time

import numpy as np

from ..obs import get_logger

log = get_logger("tools.chaos")

REPORT_SCHEMA = "peasoup_tpu.chaos_report"
REPORT_VERSION = 1

DEFAULT_CAMPAIGN_FAULTS = (
    "fil.read:p=0.25:n=4,db.ingest:at=1,worker.kill:at=obs0"
)
# at=replay pins the injections to the reader thread's replay loop
# (the cross-thread attribution drill), not the initial batch read
DEFAULT_STREAM_FAULTS = "fil.read:at=replay:n=2"

# sites whose injections must never change results — the schedules this
# tool accepts for the bitwise-equality invariant
TRANSIENT_SITES = frozenset(
    {"fil.read", "queue.claim", "db.ingest", "checkpoint.write",
     "worker.kill", "device.oom", "cache.corrupt", "clock.skew"}
)

# fault site -> stats tables where its recovery must leave a mark
RECOVERY_TABLES = {
    "fil.read": ("retries", "recoveries", "giveups"),
    "queue.claim": ("retries", "recoveries", "giveups"),
    "db.ingest": ("retries", "recoveries", "giveups"),
    "checkpoint.write": ("retries", "recoveries", "giveups"),
    "device.oom": ("degradations",),
    "cache.corrupt": ("corrupt_artifacts",),
    # worker.kill recovery is the queue reaper: checked against job
    # attempt counts, not a stats table
    "worker.kill": (),
    "clock.skew": (),
}


# --------------------------------------------------------------------------
# synthetic observations (the check.sh smoke-gate recipe, parameterised)
# --------------------------------------------------------------------------

def make_observations(
    data_dir: str,
    n_obs: int = 3,
    nsamps: int = 1 << 12,
    nchans: int = 8,
) -> list[str]:
    """Write ``n_obs`` small synthetic filterbanks, each with one
    strong dispersed pulse (distinct noise per observation, same
    shape bucket so the campaign exercises warm reuse)."""
    from ..io.sigproc import (
        Filterbank,
        SigprocHeader,
        write_filterbank,
    )
    from ..plan.dm_plan import DMPlan

    os.makedirs(data_dir, exist_ok=True)
    tsamp, fch1, foff = 0.000256, 1400.0, -16.0
    plan = DMPlan.create(
        nsamps=nsamps, nchans=nchans, tsamp=tsamp, fch1=fch1, foff=foff,
        dm_start=0.0, dm_end=20.0, pulse_width=64.0, tol=1.10,
    )
    delays = plan.delay_samples()[plan.ndm // 2]
    paths = []
    for i in range(n_obs):
        rng = np.random.default_rng(100 + i)
        data = rng.normal(32.0, 4.0, size=(nsamps, nchans))
        s0 = 1200 + 400 * i
        for c in range(nchans):
            data[s0 + delays[c] : s0 + 4 + delays[c], c] += 15.0
        hdr = SigprocHeader(
            source_name=f"CHAOS{i}", tsamp=tsamp, tstart=55000.0 + i,
            fch1=fch1, foff=foff, nchans=nchans, nbits=8, nifs=1,
            data_type=1,
        )
        path = os.path.join(data_dir, f"obs{i}.fil")
        write_filterbank(
            path,
            Filterbank(
                header=hdr,
                data=np.clip(np.rint(data), 0, 255).astype(np.uint8),
            ),
        )
        paths.append(path)
    return paths


# --------------------------------------------------------------------------
# campaign soak
# --------------------------------------------------------------------------

def _run_campaign(
    root: str,
    inputs: list[str],
    config: dict,
    lease_s: float,
    max_attempts: int,
) -> dict:
    """Drain one campaign in-process, surviving injected worker kills
    the way a fleet does: each kill abandons the claim (never released
    — WorkerKilled models SIGKILL), waits out the lease, and a
    replacement worker joins and reaps."""
    from ..campaign.queue import Job, JobQueue, job_id_for
    from ..campaign.runner import (
        CampaignConfig,
        CampaignRunner,
        bucket_for_input,
        save_campaign_config,
    )
    from ..campaign.rollup import write_status
    from ..resilience import WorkerKilled

    os.makedirs(root, exist_ok=True)
    cfg = CampaignConfig(
        pipeline="spsearch",
        config=config,
        lease_s=lease_s,
        max_attempts=max_attempts,
        backoff_base_s=0.05,
        heartbeat_interval=0.2,
        warmup=False,  # soak speed: compile once via the jit caches
        tune=False,
    )
    save_campaign_config(root, cfg)
    queue = JobQueue(
        root, lease_s=lease_s, max_attempts=max_attempts,
        backoff_base_s=0.05,
    )
    for p in inputs:
        queue.add_job(
            Job(
                job_id=job_id_for(p), input=p, pipeline="spsearch",
                bucket=bucket_for_input(p),
            )
        )
    kills = 0
    tally = {"done": 0, "failed": 0, "quarantined": 0}
    worker = 0
    t0 = time.perf_counter()
    while True:
        runner = CampaignRunner(root, worker_id=f"chaos-w{worker}")
        try:
            t = runner.run(poll_s=0.05)
            for k in tally:
                tally[k] += t.get(k, 0)
            break  # drained
        except WorkerKilled as exc:
            kills += 1
            worker += 1
            log.warning(
                "worker chaos-w%d killed (%s); lease will expire and a "
                "replacement joins", worker - 1, exc,
            )
            # a SIGKILLed worker's claim outlives it by the lease
            time.sleep(lease_s + 0.25)
    write_status(root, queue)
    return {
        "tally": tally,
        "workers_killed": kills,
        "workers_used": worker + 1,
        "wall_s": round(time.perf_counter() - t0, 3),
    }


def _job_candidate_bytes(root: str, job_id: str) -> bytes | None:
    path = os.path.join(root, "jobs", job_id, "candidates.singlepulse")
    try:
        with open(path, "rb") as f:
            return f.read()
    except OSError:
        return None


def _tree_residue(root: str) -> list[str]:
    """Leaked atomic-write temps / reap tombstones / claim files."""
    bad = []
    for pat in ("**/*.tmp", "**/*.reap.*", "**/*.ckpt.tmp"):
        bad.extend(glob.glob(os.path.join(root, pat), recursive=True))
    bad.extend(glob.glob(os.path.join(root, "queue", "claims", "*.json")))
    return sorted(bad)


def run_campaign_soak(
    workdir: str,
    faults_spec: str,
    seed: int,
    n_obs: int = 3,
    nsamps: int = 1 << 12,
    max_attempts: int = 3,
    lease_s: float = 1.0,
    config: dict | None = None,
) -> dict:
    """Reference campaign (fault-free) + chaos campaign (seeded
    schedule) over the same observations; returns the report section
    with a ``violations`` list (empty = survived)."""
    from ..campaign.queue import JobQueue, job_id_for
    from ..campaign.rollup import load_campaign_status
    from ..obs.schema import validate_manifest
    from ..resilience import STATS, faults
    from ..resilience.faults import parse_faults

    plan = parse_faults(faults_spec, seed)
    unknown = set(plan.rules) - TRANSIENT_SITES
    if unknown:
        raise ValueError(f"non-transient fault sites: {sorted(unknown)}")

    config = config or {"dm_end": 20.0, "min_snr": 7.0, "n_widths": 6}
    data_dir = os.path.join(workdir, "data")
    inputs = make_observations(data_dir, n_obs=n_obs, nsamps=nsamps)
    job_ids = [job_id_for(p) for p in inputs]

    # --- reference: the ground truth this soak judges against --------
    faults.configure(None)
    STATS.reset()
    ref_root = os.path.join(workdir, "ref")
    log.info("chaos soak: fault-free reference campaign (%d obs)", n_obs)
    ref = _run_campaign(ref_root, inputs, config, lease_s, max_attempts)
    ref_cands = {j: _job_candidate_bytes(ref_root, j) for j in job_ids}
    if ref["tally"]["done"] != n_obs or any(
        v is None for v in ref_cands.values()
    ):
        raise RuntimeError(
            f"reference campaign did not complete cleanly: {ref}"
        )

    # --- chaos: same inputs, seeded schedule --------------------------
    STATS.reset()
    active = faults.configure(faults_spec, seed)
    chaos_root = os.path.join(workdir, "chaos")
    log.info(
        "chaos soak: campaign under schedule %r (seed %d)",
        faults_spec, seed,
    )
    try:
        chaos = _run_campaign(
            chaos_root, inputs, config, lease_s, max_attempts
        )
    finally:
        faults.configure(None)
    stats = STATS.snapshot()
    injection_log = active.to_doc() if active else {}

    # --- invariants ---------------------------------------------------
    violations: list[str] = []
    queue = JobQueue(chaos_root)
    counts = queue.counts()

    # exactly-once: every job terminal, none lost, none in two states
    if counts["total"] != n_obs:
        violations.append(
            f"jobs lost or duplicated: {counts['total']}/{n_obs} records"
        )
    if counts["done"] + counts["quarantined"] != counts["total"]:
        violations.append(f"campaign not drained exactly-once: {counts}")
    for j in job_ids:
        d = os.path.exists(
            os.path.join(chaos_root, "queue", "done", f"{j}.json")
        )
        q = os.path.exists(
            os.path.join(chaos_root, "queue", "quarantine", f"{j}.json")
        )
        if d == q:  # both (double-terminal) or neither (lost)
            violations.append(
                f"job {j}: done={d} quarantined={q} (must be exactly one)"
            )

    # transient-only schedule: zero quarantine, bitwise-equal products
    if counts["quarantined"]:
        violations.append(
            f"{counts['quarantined']} job(s) quarantined under a "
            "transient-only schedule"
        )
    for j in job_ids:
        got = _job_candidate_bytes(chaos_root, j)
        if got is None:
            violations.append(f"job {j}: no candidate file after soak")
        elif got != ref_cands[j]:
            violations.append(
                f"job {j}: candidates differ from the fault-free run"
            )

    # clean tree
    residue = _tree_residue(chaos_root)
    if residue:
        violations.append(f"leaked files: {residue[:8]}")

    # valid telemetry + rollup with the resilience section
    for j in job_ids:
        man_path = os.path.join(chaos_root, "jobs", j, "telemetry.json")
        try:
            with open(man_path) as f:
                validate_manifest(json.load(f))
        except Exception as exc:
            violations.append(
                f"job {j}: telemetry manifest invalid: {exc!s:.200}"
            )
    try:
        rollup = load_campaign_status(
            os.path.join(chaos_root, "campaign_status.json")
        )
        if "resilience" not in rollup:
            violations.append("rollup lacks the resilience section")
    except Exception as exc:
        violations.append(f"campaign rollup unreadable: {exc!s:.200}")

    # bounded retries: policy budget x injections per site
    from ..resilience.policy import DB_RETRY, IO_RETRY

    budget = max(IO_RETRY.max_attempts, DB_RETRY.max_attempts)
    for site, n in stats["retries"].items():
        injected = stats["faults_injected"].get(site.split(":")[0], 0)
        if n > budget * max(1, injected):
            violations.append(
                f"unbounded retries at {site}: {n} retries for "
                f"{injected} injection(s) (budget {budget}/each)"
            )

    # attribution: every fired site left a mark on its recovery path
    for site, n in stats["faults_injected"].items():
        tables = RECOVERY_TABLES.get(site, ())
        if tables and not any(
            any(k.startswith(site) or site in k for k in stats[t])
            for t in tables
        ):
            violations.append(
                f"fault {site} fired {n}x but no recovery path "
                f"({'/'.join(tables)}) recorded handling it"
            )
    if "worker.kill" in stats["faults_injected"]:
        # the reaper is worker.kill's recovery: the killed job must
        # have consumed extra attempts yet still completed
        reaped = [
            d for d in queue.done_records()
            if int(d.get("attempts", 1)) > 1
        ]
        if chaos["workers_killed"] and not reaped:
            violations.append(
                "worker.kill fired but no done record shows a reaped "
                "retry (attempts > 1)"
            )

    return {
        "n_obs": n_obs,
        "faults": faults_spec,
        "seed": seed,
        "reference": ref,
        "chaos": chaos,
        "queue": counts,
        "stats": stats,
        "injections": injection_log,
        "violations": violations,
    }


# --------------------------------------------------------------------------
# stream soak
# --------------------------------------------------------------------------

def _run_stream(outdir: str, fil_path: str) -> dict:
    from ..io.sigproc import read_filterbank
    from ..io.stream_source import ReplaySource
    from ..obs.telemetry import RunTelemetry
    from ..stream.driver import StreamConfig, StreamingSearch

    os.makedirs(outdir, exist_ok=True)
    cfg = StreamConfig(
        outdir=outdir, dm_end=20.0, min_snr=7.0, n_widths=6,
        chunk_samples=1024, decimate=8, latency_slo_s=30.0,
        warmup=False,
    )
    tel = RunTelemetry()
    with tel.activate():
        fil = read_filterbank(fil_path)
        result = StreamingSearch(cfg).run(
            ReplaySource(fil, block_samples=512, rate=0.0)
        )
        tel.write(os.path.join(outdir, "telemetry.json"))
    return {
        "triggers": [
            (int(c.dm_idx), int(c.sample), int(c.width), float(c.snr))
            for c in result.candidates
        ],
        "n_chunks": result.n_chunks,
        "drops": result.drops,
        "jit_programs_steady": result.jit_programs_steady,
        "events": tel.events,
    }


def run_stream_soak(
    workdir: str, faults_spec: str, seed: int, nsamps: int = 1 << 12
) -> dict:
    """Replay the same recording fault-free and under the schedule;
    the stream must emit identical triggers with zero drops."""
    from ..resilience import STATS, faults
    from ..resilience.faults import parse_faults

    plan = parse_faults(faults_spec, seed)
    unknown = set(plan.rules) - {"fil.read"}
    if unknown:
        raise ValueError(
            f"stream soak drills fil.read only, got: {sorted(unknown)}"
        )
    [fil_path] = make_observations(
        os.path.join(workdir, "stream_data"), n_obs=1, nsamps=nsamps
    )
    faults.configure(None)
    STATS.reset()
    ref = _run_stream(os.path.join(workdir, "stream_ref"), fil_path)
    STATS.reset()
    active = faults.configure(faults_spec, seed)
    try:
        chaos = _run_stream(
            os.path.join(workdir, "stream_chaos"), fil_path
        )
    finally:
        faults.configure(None)
    stats = STATS.snapshot()

    violations: list[str] = []
    if not ref["triggers"]:
        raise RuntimeError("reference stream produced no triggers")
    if chaos["triggers"] != ref["triggers"]:
        violations.append(
            f"stream triggers differ: {len(chaos['triggers'])} vs "
            f"{len(ref['triggers'])} reference"
        )
    if chaos["drops"].get("blocks") or chaos["drops"].get("gap_samples"):
        violations.append(f"stream dropped data: {chaos['drops']}")
    if chaos["jit_programs_steady"]:
        violations.append(
            f"{chaos['jit_programs_steady']} steady-state recompile(s) "
            "under faults"
        )
    injected = stats["faults_injected"].get("fil.read", 0)
    if injected and not (
        stats["retries"].get("fil.read") or stats["recoveries"].get("fil.read")
    ):
        violations.append(
            "fil.read fired on the stream but no retry/recovery "
            "recorded handling it"
        )
    kinds = {e["kind"] for e in chaos["events"]}
    if injected and "fault_injected" not in kinds:
        violations.append(
            "injections happened without fault_injected telemetry"
        )
    return {
        "faults": faults_spec,
        "seed": seed,
        "reference": {k: ref[k] for k in ("n_chunks", "drops")},
        "chaos": {k: chaos[k] for k in ("n_chunks", "drops")},
        "n_triggers": len(ref["triggers"]),
        "stats": stats,
        "injections": active.to_doc() if active else {},
        "violations": violations,
    }


# --------------------------------------------------------------------------
# CLI
# --------------------------------------------------------------------------

def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="peasoup-chaos",
        description="Chaos soak: run campaign/stream workloads under a "
        "seeded fault schedule and assert the survival invariants "
        "(exactly-once, bitwise-equal candidates, clean tree, valid "
        "telemetry, bounded + attributed recovery).",
    )
    p.add_argument(
        "--mode", choices=("campaign", "stream", "both"), default="both",
    )
    p.add_argument(
        "--faults", default=None,
        help="fault schedule (resilience/faults.py grammar); default: "
        f"campaign {DEFAULT_CAMPAIGN_FAULTS!r}, "
        f"stream {DEFAULT_STREAM_FAULTS!r}",
    )
    p.add_argument("--seed", type=int, default=7)
    p.add_argument(
        "-o", "--workdir", default=None,
        help="soak directory (default: a fresh temp dir)",
    )
    p.add_argument("--n-obs", type=int, default=3)
    p.add_argument(
        "--nsamps", type=int, default=1 << 12,
        help="samples per synthetic observation",
    )
    p.add_argument(
        "--lease", type=float, default=1.0,
        help="campaign claim lease seconds (kill recovery waits it out)",
    )
    p.add_argument(
        "--report", default=None,
        help="chaos_report.json path (default: <workdir>/chaos_report.json)",
    )
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    workdir = args.workdir or tempfile.mkdtemp(prefix="peasoup-chaos-")
    os.makedirs(workdir, exist_ok=True)
    report: dict = {
        "schema": REPORT_SCHEMA,
        "version": REPORT_VERSION,
        "workdir": os.path.abspath(workdir),
        "mode": args.mode,
    }
    try:
        violations: list[str] = []
        if args.mode in ("campaign", "both"):
            sec = run_campaign_soak(
                workdir,
                args.faults or DEFAULT_CAMPAIGN_FAULTS,
                args.seed,
                n_obs=args.n_obs,
                nsamps=args.nsamps,
                lease_s=args.lease,
            )
            report["campaign"] = sec
            violations += [f"campaign: {v}" for v in sec["violations"]]
        if args.mode in ("stream", "both"):
            sec = run_stream_soak(
                workdir,
                args.faults if args.mode == "stream" and args.faults
                else DEFAULT_STREAM_FAULTS,
                args.seed,
                nsamps=args.nsamps,
            )
            report["stream"] = sec
            violations += [f"stream: {v}" for v in sec["violations"]]
        report["violations"] = violations
        report["ok"] = not violations
    except Exception as exc:
        import traceback

        traceback.print_exc()
        report["ok"] = False
        report["error"] = f"{type(exc).__name__}: {exc!s:.500}"
        _write_report(report, args)
        print("peasoup-chaos: internal error (exit 2)", file=sys.stderr)
        return 2
    _write_report(report, args)
    if report["ok"]:
        print(
            f"peasoup-chaos: SURVIVED ({args.mode}; "
            f"workdir {workdir})"
        )
        return 0
    print("peasoup-chaos: INVARIANT VIOLATIONS:", file=sys.stderr)
    for v in violations:
        print(f"  - {v}", file=sys.stderr)
    return 1


def _write_report(report: dict, args) -> None:
    path = args.report or os.path.join(
        report["workdir"], "chaos_report.json"
    )
    with open(path, "w") as f:
        json.dump(report, f, indent=2, default=str)
        f.write("\n")
    print(f"peasoup-chaos: report -> {path}")


if __name__ == "__main__":
    sys.exit(main())
