"""Run-scoped observability: structured logging (obs/log.py), the
telemetry subsystem (obs/telemetry.py) behind the versioned
``telemetry.json`` run manifest, the live ``status.json`` heartbeat +
stall watchdog (obs/heartbeat.py), the crash flight recorder
(obs/flight.py), the manifest schema contract (obs/schema.py +
manifest.schema.json) — and the FLEET layer: per-worker time-series
metrics with Prometheus exposition (obs/metrics.py +
metrics.schema.json), cross-process trace correlation with
Chrome/Perfetto export (obs/trace.py), and on-demand device profiling
of live workers (obs/profiler.py). See README "Observability", "Live
observability" and "Fleet observability"."""

from .flight import FLIGHT_SCHEMA, FlightRecorder, load_flight
from .heartbeat import STATUS_SCHEMA, Heartbeat, load_status
from .log import configure as configure_logging
from .log import get_logger, resolve_level
from .metrics import (
    METRICS_SCHEMA,
    MetricsRecorder,
    fleet_samples,
    load_series,
    parse_exposition,
    prometheus_exposition,
    validate_sample,
)
from .profiler import capture_device_profile
from .schema import SchemaError, validate_manifest
from .telemetry import (
    MANIFEST_SCHEMA,
    MANIFEST_VERSION,
    NOOP,
    RunTelemetry,
    current,
    load_manifest,
)
from .trace import (
    TRACE_SCHEMA,
    Tracer,
    current_tracer,
    export_chrome_trace,
    job_instant,
    job_span,
    load_spans,
    new_trace_id,
    trace_paths,
    trace_summary,
)

__all__ = [
    "configure_logging",
    "get_logger",
    "resolve_level",
    "FLIGHT_SCHEMA",
    "FlightRecorder",
    "load_flight",
    "STATUS_SCHEMA",
    "Heartbeat",
    "load_status",
    "SchemaError",
    "validate_manifest",
    "MANIFEST_SCHEMA",
    "MANIFEST_VERSION",
    "NOOP",
    "RunTelemetry",
    "current",
    "load_manifest",
    "METRICS_SCHEMA",
    "MetricsRecorder",
    "fleet_samples",
    "load_series",
    "parse_exposition",
    "prometheus_exposition",
    "validate_sample",
    "capture_device_profile",
    "TRACE_SCHEMA",
    "Tracer",
    "current_tracer",
    "export_chrome_trace",
    "job_instant",
    "job_span",
    "load_spans",
    "new_trace_id",
    "trace_paths",
    "trace_summary",
]
