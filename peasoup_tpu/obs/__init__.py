"""Run-scoped observability: structured logging (obs/log.py), the
telemetry subsystem (obs/telemetry.py) behind the versioned
``telemetry.json`` run manifest, the live ``status.json`` heartbeat +
stall watchdog (obs/heartbeat.py), the crash flight recorder
(obs/flight.py), and the manifest schema contract (obs/schema.py +
manifest.schema.json). See README "Observability" and "Live
observability"."""

from .flight import FLIGHT_SCHEMA, FlightRecorder, load_flight
from .heartbeat import STATUS_SCHEMA, Heartbeat, load_status
from .log import configure as configure_logging
from .log import get_logger, resolve_level
from .schema import SchemaError, validate_manifest
from .telemetry import (
    MANIFEST_SCHEMA,
    MANIFEST_VERSION,
    NOOP,
    RunTelemetry,
    current,
    load_manifest,
)

__all__ = [
    "configure_logging",
    "get_logger",
    "resolve_level",
    "FLIGHT_SCHEMA",
    "FlightRecorder",
    "load_flight",
    "STATUS_SCHEMA",
    "Heartbeat",
    "load_status",
    "SchemaError",
    "validate_manifest",
    "MANIFEST_SCHEMA",
    "MANIFEST_VERSION",
    "NOOP",
    "RunTelemetry",
    "current",
    "load_manifest",
]
