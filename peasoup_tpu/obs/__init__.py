"""Run-scoped observability: structured logging (obs/log.py) and the
telemetry subsystem (obs/telemetry.py) behind the versioned
``telemetry.json`` run manifest. See README "Observability"."""

from .log import configure as configure_logging
from .log import get_logger, resolve_level
from .telemetry import (
    MANIFEST_SCHEMA,
    MANIFEST_VERSION,
    NOOP,
    RunTelemetry,
    current,
    load_manifest,
)

__all__ = [
    "configure_logging",
    "get_logger",
    "resolve_level",
    "MANIFEST_SCHEMA",
    "MANIFEST_VERSION",
    "NOOP",
    "RunTelemetry",
    "current",
    "load_manifest",
]
