"""Crash flight recorder: forensics for runs that never finish.

The telemetry manifest is written at the *end* of a successful run — a
preempted, OOM-killed or wedged survey job leaves nothing behind.
:class:`FlightRecorder` closes that gap:

- it keeps a **bounded ring buffer** of the most recent telemetry
  events (subscribed via ``RunTelemetry.add_listener``, seeded with the
  tail already recorded), so the dump stays small no matter how long
  the run was;
- it installs **SIGTERM / SIGINT handlers** and a ``sys.excepthook``
  so that a kill, a Ctrl-C or an uncaught fatal exception dumps a
  ``flight.json`` (reason, stage, progress, context, counters/gauges,
  the event ring) *and* a partial telemetry manifest marked
  ``"aborted": true`` — checkpoint-resume tooling can then report what
  was lost, and ``tools/report.py`` renders the partial manifest like
  any other.

After dumping a signal is re-delivered with the previous disposition
restored, so exit codes (``128+signum``) and parent process semantics
are unchanged. The recorder dumps **at most once**; install/close are
idempotent and restore the previous handlers. Signal handlers are only
installed from the main thread (CPython restriction); elsewhere the
recorder still captures events and can be dumped explicitly.
"""

from __future__ import annotations

import json
import os
import signal
import socket
import sys
import threading
import time
import traceback
from collections import deque

from .log import get_logger

FLIGHT_SCHEMA = "peasoup_tpu.flight"
FLIGHT_VERSION = 1

log = get_logger("obs.flight")


def load_flight(path: str) -> dict:
    """Load + validate a flight.json dump."""
    with open(path) as f:
        doc = json.load(f)
    if doc.get("schema") != FLIGHT_SCHEMA:
        raise ValueError(
            f"{path}: not a {FLIGHT_SCHEMA} dump "
            f"(schema={doc.get('schema')!r})"
        )
    return doc


class FlightRecorder:
    """Ring buffer + abort handlers dumping ``flight.json`` and a
    partial (``aborted``) telemetry manifest.

    ``manifest_path`` is where the partial manifest goes on abort —
    usually the same path the run would have written its final
    ``telemetry.json`` to (the abort dump simply pre-empts it)."""

    def __init__(
        self,
        telemetry,
        path: str,
        manifest_path: str | None = None,
        ring: int = 256,
    ) -> None:
        self._tel = telemetry
        self.path = path
        self.manifest_path = manifest_path
        self._ring: deque = deque(telemetry.events[-ring:], maxlen=ring)
        self._dumped = False
        self._installed = False
        self._prev_handlers: dict[int, object] = {}
        self._prev_excepthook = None
        telemetry.add_listener(self._on_event)

    # --- event feed ---------------------------------------------------
    def _on_event(self, rec: dict) -> None:
        self._ring.append(rec)

    # --- install / restore --------------------------------------------
    def install(self) -> "FlightRecorder":
        if self._installed:
            return self
        if threading.current_thread() is threading.main_thread():
            for sig in (signal.SIGTERM, signal.SIGINT):
                try:
                    self._prev_handlers[sig] = signal.signal(
                        sig, self._on_signal
                    )
                except (ValueError, OSError):  # non-main ctx, rare
                    pass
        self._prev_excepthook = sys.excepthook
        sys.excepthook = self._excepthook
        self._installed = True
        log.debug("flight recorder armed: %s", self.path)
        return self

    def close(self) -> None:
        """Restore previous handlers and stop recording (idempotent)."""
        for sig, prev in self._prev_handlers.items():
            try:
                signal.signal(sig, prev)
            except (ValueError, OSError):
                pass
        self._prev_handlers.clear()
        if self._prev_excepthook is not None:
            sys.excepthook = self._prev_excepthook
            self._prev_excepthook = None
        self._tel.remove_listener(self._on_event)
        self._installed = False

    def __enter__(self) -> "FlightRecorder":
        return self.install()

    def __exit__(self, exc_type, exc, tb) -> None:
        # a propagating exception is a dying run: dump before unwinding
        # (deterministic, unlike excepthook which only fires if nothing
        # up-stack catches it)
        if exc is not None and not isinstance(exc, GeneratorExit):
            self.dump(
                f"exception:{exc_type.__name__}",
                exception="".join(
                    traceback.format_exception_only(exc_type, exc)
                ).strip(),
            )
        self.close()

    # --- the dump -----------------------------------------------------
    def dump(
        self,
        reason: str,
        signum: int | None = None,
        exception: str | None = None,
    ) -> dict | None:
        """Write flight.json + the partial manifest (at most once)."""
        if self._dumped:
            return None
        self._dumped = True
        tel = self._tel
        doc = {
            "schema": FLIGHT_SCHEMA,
            "version": FLIGHT_VERSION,
            "run_id": tel.run_id,
            "pid": os.getpid(),
            "hostname": socket.gethostname(),
            "written_unix": time.time(),
            "uptime_s": round(time.perf_counter() - tel._t0, 3),
            "reason": reason,
            "signum": signum,
            "exception": exception,
            "stage": tel.current_stage,
            "progress": dict(tel.progress_state)
            if tel.progress_state
            else None,
            "context": dict(tel.context),
            "counters": dict(tel.counters),
            "gauges": dict(tel.gauges),
            "events": list(self._ring),
        }
        try:
            # live status sections (e.g. the streaming driver's queue/
            # latency/drop state) are abort forensics too
            doc.update(
                {
                    k: v
                    for k, v in tel.snapshot_sections().items()
                    if k not in doc
                }
            )
        except Exception:
            pass  # a section provider must never block the dump
        try:
            d = os.path.dirname(self.path)
            if d:
                os.makedirs(d, exist_ok=True)
            tmp = self.path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(doc, f, indent=2)
                f.write("\n")
            os.replace(tmp, self.path)
            log.error(
                "flight recorder dumped (%s) -> %s", reason, self.path
            )
        except Exception:
            log.exception("flight recorder dump failed")
        if self.manifest_path:
            try:
                tel.write(
                    self.manifest_path, aborted=True, abort_reason=reason
                )
                log.error(
                    "partial telemetry manifest (aborted) -> %s",
                    self.manifest_path,
                )
            except Exception:
                log.exception("partial manifest write failed")
        return doc

    # --- abort paths --------------------------------------------------
    def _on_signal(self, signum, frame) -> None:
        name = signal.Signals(signum).name
        self.dump(f"signal:{name}", signum=signum)
        prev = self._prev_handlers.get(signum)
        if callable(prev):
            # chain (e.g. the default SIGINT handler raising
            # KeyboardInterrupt so the run unwinds normally)
            signal.signal(signum, prev)
            prev(signum, frame)
            return
        # re-deliver with the previous (or default) disposition so the
        # exit status is the conventional 128+signum
        signal.signal(
            signum, prev if prev is not None else signal.SIG_DFL
        )
        os.kill(os.getpid(), signum)

    def _excepthook(self, exc_type, exc, tb) -> None:
        self.dump(
            f"exception:{exc_type.__name__}",
            exception="".join(
                traceback.format_exception_only(exc_type, exc)
            ).strip(),
        )
        hook = self._prev_excepthook or sys.__excepthook__
        hook(exc_type, exc, tb)
