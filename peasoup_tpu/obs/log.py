"""Structured library logging.

The library never prints to stdout: every informational or warning message
goes through a child of the ``peasoup_tpu`` logger (ruff rule T201
enforces this — see pyproject.toml). Importing the package installs a
``NullHandler`` only, so embedded users are silent by default and wire
the logger however their application does; the CLI entry points call
:func:`configure` with the level resolved from ``-v`` / ``--log-level``
(``resolve_level``), which installs a single stderr handler.

Messages always go to **stderr**: stdout is reserved for
machine-readable output (piped candidate lists, report renders), the
same contract as the progress bar (utils/progress.py).
"""

from __future__ import annotations

import logging
import os
import sys

ROOT_LOGGER = "peasoup_tpu"

_LEVELS = {
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warning": logging.WARNING,
    "error": logging.ERROR,
}

# one library-owned handler, reused across configure() calls so repeated
# CLI invocations in one process (tests) never stack duplicate handlers
_handler: logging.StreamHandler | None = None

logging.getLogger(ROOT_LOGGER).addHandler(logging.NullHandler())


def get_logger(name: str | None = None) -> logging.Logger:
    """The library logger, or a dotted child (``get_logger("pipeline")``
    -> ``peasoup_tpu.pipeline``)."""
    return logging.getLogger(
        ROOT_LOGGER if not name else f"{ROOT_LOGGER}.{name}"
    )


def resolve_level(
    log_level: str | int | None, verbose: bool = False
) -> int:
    """Level precedence: explicit ``--log-level`` > ``-v`` (INFO) >
    PEASOUP_LOG_LEVEL env > WARNING."""
    if log_level is None:
        log_level = (
            "info" if verbose else os.environ.get("PEASOUP_LOG_LEVEL")
        )
    if log_level is None:
        return logging.WARNING
    if isinstance(log_level, int):
        return log_level
    try:
        return _LEVELS[str(log_level).strip().lower()]
    except KeyError:
        raise ValueError(
            f"unknown log level {log_level!r}; "
            f"expected one of {sorted(_LEVELS)}"
        ) from None


def configure(
    level: str | int | None = None,
    verbose: bool = False,
    stream=None,
) -> logging.Logger:
    """Install (or retune) the stderr handler on the library logger and
    set its threshold. Idempotent: calling again adjusts the level and
    stream on the existing handler instead of stacking a new one."""
    global _handler
    logger = get_logger()
    resolved = resolve_level(level, verbose)
    if _handler is None:
        _handler = logging.StreamHandler(stream or sys.stderr)
        _handler.setFormatter(
            logging.Formatter("[%(levelname)s] %(name)s: %(message)s")
        )
        logger.addHandler(_handler)
    elif stream is not None:
        _handler.setStream(stream)
    logger.setLevel(resolved)
    return logger
