"""Declarative SLO/alerting engine over the fleet metrics series.

PR 14 gave the fleet raw time series (obs/metrics.py); nothing
*interpreted* them — an operator had to eyeball sparklines to notice a
stalled worker or a failure burst. This module is the interpretation
layer: a small declarative rule engine evaluated over the existing
``MetricsRecorder`` files, with a Prometheus-shaped alert lifecycle.

Rule kinds (each rule is a plain dict — the grammar is data, so the
check gate and tests can inject short windows):

- ``threshold`` — select a scalar from one metric over a trailing
  window (``last``/``sum``/``max``/``min`` over gauges, ``increase``/
  ``rate`` over cumulative counters, ``p50``/``p95``/``p99``/``max``
  over raw histogram observations) and compare against a bound, with
  an optional ``for_s`` pending hold.
- ``absence`` — "no sample of metric M for live worker X within
  ``window_s``" (the heartbeat-stall shape; one alert per worker).
- ``burn_rate`` — multi-window error-budget burn over an SLO
  objective: the bad/total counter ratio must exceed ``factor`` times
  the budget in EVERY window to fire (the fast window catches the
  spike, the slow window suppresses blips).
- ``data_quality`` / ``sentinel`` — finding-driven: the conditions are
  computed by :mod:`peasoup_tpu.obs.health` (median/MAD z-score
  outliers, unrecovered synthetic injections) and passed in; the
  engine owns only the lifecycle.

Lifecycle per (rule, label set): inactive → ``pending`` → ``firing``
→ ``resolved`` (kept ``RESOLVED_RETENTION_S`` then dropped). Every
transition is appended to ``<root>/queue/alerts.jsonl`` (append-only,
like the recorders) and the current state is atomically rewritten to
``<root>/queue/alerts.json`` (tmp + ``os.replace``) — the snapshot the
portal, rollup and ``watch`` read. Concurrent evaluators (several
workers share one campaign) serialise through an ``O_CREAT|O_EXCL``
lock file with stale takeover; a loser skips the round and returns the
current snapshot — alerting is level-based, the next round catches up.

Counters are written as running totals carried across file rotation
(obs/metrics.py), so windowed ``increase`` stays monotone through a
rotation and a resolved alert does not re-fire from replayed deltas; a
process restart (total resets to zero) is treated as a counter reset,
Prometheus-style.

Evaluation must never fail the caller (the runner evaluates beside its
status rollup): :func:`evaluate_campaign` traps everything and returns
the last good snapshot.
"""

from __future__ import annotations

import json
import math
import os
import tempfile
import time
import uuid

from .log import get_logger
from .metrics import _label_str, fleet_samples

log = get_logger("obs.alerts")

ALERTS_SCHEMA = "peasoup_tpu.alerts"
ALERTS_VERSION = 1

# a resolved alert stays visible in the snapshot this long (operators
# want to see what JUST resolved), then drops out
RESOLVED_RETENTION_S = 3600.0

# a crashed evaluator's lock is taken over after this long
LOCK_STALE_S = 60.0

_SCHEMA_PATH = os.path.join(
    os.path.dirname(__file__), "alerts.schema.json"
)

_OPS = {
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
}


def load_alerts_schema() -> dict:
    with open(_SCHEMA_PATH) as f:
        return json.load(f)


def validate_snapshot(doc: dict, schema: dict | None = None) -> None:
    """Validate an alerts snapshot against the checked-in schema
    (raises :class:`~peasoup_tpu.obs.schema.SchemaError`)."""
    from .schema import validate

    validate(doc, schema or load_alerts_schema())


def default_rules(heartbeat_s: float = 2.0) -> list[dict]:
    """The stock survey-health rule set over the metrics the campaign
    and streaming layers already record. ``heartbeat_s`` sizes the
    worker-stall absence window (3x the beat interval, floored so a
    scheduling hiccup is not a page)."""
    return [
        {
            "name": "worker_heartbeat_stalled",
            "kind": "absence",
            "metric": "worker_heartbeat_unix",
            "window_s": max(3.0 * float(heartbeat_s), 5.0),
            "severity": "page",
        },
        {
            # SLO: >= 90% of finished jobs succeed
            "name": "job_failure_burn_rate",
            "kind": "burn_rate",
            "bad": "jobs_failed_total",
            "good": "jobs_done_total",
            "objective": 0.9,
            "windows": [[300.0, 6.0], [1800.0, 3.0]],
            "severity": "page",
        },
        {
            # SLO: >= 95% of streaming chunks inside latency_slo_s
            "name": "chunk_latency_slo_burn",
            "kind": "burn_rate",
            "bad": "chunk_slo_miss_total",
            "total": "chunks_total",
            "objective": 0.95,
            "windows": [[300.0, 6.0], [1800.0, 3.0]],
            "severity": "page",
        },
        {
            "name": "preemption_latency_p95",
            "kind": "threshold",
            "metric": "preemption_latency_seconds",
            "metric_kind": "hist",
            "select": "p95",
            "op": ">",
            "value": 60.0,
            "window_s": 1800.0,
            "severity": "warn",
        },
        {
            # recompile budget: steady-state reuse is the whole point
            # of the bucket ladder; a recompile storm is a regression
            "name": "jit_recompile_budget",
            "kind": "threshold",
            "metric": "jit_programs_compiled_total",
            "metric_kind": "counter",
            "select": "increase",
            "op": ">",
            "value": 50.0,
            "window_s": 3600.0,
            "severity": "warn",
        },
        {"name": "data_quality", "kind": "data_quality",
         "severity": "warn"},
        {"name": "sentinel_unrecovered", "kind": "sentinel",
         "severity": "page"},
        {
            # a tenant parked at its quota ceiling (max_running or
            # device-seconds window) — findings computed by
            # campaign/tenants.throttle_map, routed to the tenant's
            # own journal so THEIR operator sees it without grepping
            # the fleet's
            "name": "tenant_quota_exhausted",
            "kind": "tenant_quota",
            "severity": "warn",
            "route": "tenant",
        },
        {
            # the fleet-wide job_failure_burn_rate above says "the
            # survey is failing"; this one says WHOSE jobs are — the
            # same SLO evaluated per tenant label value
            "name": "tenant_job_failure_burn_rate",
            "kind": "burn_rate",
            "bad": "jobs_failed_total",
            "good": "jobs_done_total",
            "objective": 0.9,
            "windows": [[300.0, 6.0], [1800.0, 3.0]],
            "by": "tenant",
            "severity": "page",
            "route": "tenant",
        },
    ]


def tenant_journal_path(root: str, tenant: str) -> str:
    """The per-tenant alert journal a ``route: "tenant"`` rule's
    transitions are copied to (tenant value sanitised: it becomes a
    file name)."""
    safe = "".join(
        c if c.isalnum() or c in "-_" else "_" for c in str(tenant)
    )[:48] or "_"
    return os.path.join(
        os.path.abspath(root), "queue", f"alerts.{safe}.jsonl"
    )


# --------------------------------------------------------------------------
# selectors over the fleet samples
# --------------------------------------------------------------------------

def counter_increase(
    samples_by_source: dict[str, list[dict]],
    name: str,
    t_lo: float,
    t_hi: float,
) -> float:
    """Windowed increase of a cumulative counter summed across the
    fleet: positive deltas between consecutive samples of one
    (source, labels) series inside ``(t_lo, t_hi]``; a value drop is a
    process-restart reset (the new total IS the increase since it).
    The sample before the window seeds the baseline, so rotation (which
    keeps the newest tail with totals carried in recorder memory)
    never replays old deltas."""
    total = 0.0
    for samples in samples_by_source.values():
        prev: dict[tuple, float] = {}
        for rec in samples:
            if rec.get("name") != name or rec.get("kind") != "counter":
                continue
            t = float(rec.get("t", 0.0))
            v = float(rec.get("value", 0.0))
            key = tuple(sorted((rec.get("labels") or {}).items()))
            if t <= t_lo:
                prev[key] = v
                continue
            if t > t_hi:
                continue
            base = prev.get(key)
            if base is None or v < base:
                total += v  # series born (or reset) inside the window
            else:
                total += v - base
            prev[key] = v
    return total


def _gauge_last(
    samples_by_source: dict, name: str, t_lo: float, t_hi: float
) -> dict[str, float]:
    """Latest in-window gauge value per source."""
    out: dict[str, tuple[float, float]] = {}
    for src, samples in samples_by_source.items():
        for rec in samples:
            if rec.get("name") != name or rec.get("kind") != "gauge":
                continue
            t = float(rec.get("t", 0.0))
            if t <= t_lo or t > t_hi:
                continue
            if src not in out or t >= out[src][0]:
                out[src] = (t, float(rec.get("value", 0.0)))
    return {src: v for src, (_, v) in out.items()}


def _hist_observations(
    samples_by_source: dict, name: str, t_lo: float, t_hi: float
) -> list[float]:
    out = []
    for samples in samples_by_source.values():
        for rec in samples:
            if rec.get("name") != name or rec.get("kind") != "hist":
                continue
            t = float(rec.get("t", 0.0))
            if t_lo < t <= t_hi:
                out.append(float(rec.get("value", 0.0)))
    return out


def _quantile(vals: list[float], q: float) -> float:
    s = sorted(vals)
    return s[min(len(s) - 1, max(0, math.ceil(q * len(s)) - 1))]


# --------------------------------------------------------------------------
# rule evaluation: each evaluator returns the ACTIVE findings
# [(labels, value, message)]; anything previously alerting that is not
# reported active this round resolves
# --------------------------------------------------------------------------

def _eval_threshold(rule: dict, samples: dict, now: float) -> list:
    window = float(rule.get("window_s", 900.0))
    t_lo, t_hi = now - window, now
    sel = rule.get("select", "last")
    kind = rule.get("metric_kind", "gauge")
    metric = rule["metric"]
    value: float | None = None
    if kind == "counter":
        inc = counter_increase(samples, metric, t_lo, t_hi)
        value = inc / window if sel == "rate" else inc
    elif kind == "hist":
        obs = _hist_observations(samples, metric, t_lo, t_hi)
        if obs:
            if sel in ("p50", "p95", "p99"):
                value = _quantile(obs, float(sel[1:]) / 100.0)
            elif sel == "max":
                value = max(obs)
            else:
                value = sum(obs) / len(obs)
    else:
        per_src = _gauge_last(samples, metric, t_lo, t_hi)
        if per_src:
            if sel == "sum":
                value = sum(per_src.values())
            elif sel == "max":
                value = max(per_src.values())
            elif sel == "min":
                value = min(per_src.values())
            else:  # "last": newest value fleet-wide
                value = _gauge_last(
                    {"_": [r for v in samples.values() for r in v]},
                    metric, t_lo, t_hi,
                ).get("_")
    if value is None:
        return []  # no data in window -> no alert
    bound = float(rule["value"])
    if not _OPS[rule.get("op", ">")](value, bound):
        return []
    return [(
        {},
        float(value),
        f"{metric} {sel} {value:.4g} {rule.get('op', '>')} "
        f"{bound:.4g} over {window:.0f}s",
    )]


def _eval_absence(
    rule: dict, samples: dict, now: float,
    live_sources: list[str] | None,
) -> list:
    metric = rule["metric"]
    window = float(rule.get("window_s", 10.0))
    sources = (
        sorted(live_sources) if live_sources is not None
        else sorted(samples)
    )
    out = []
    for src in sources:
        ts = [
            float(r.get("t", 0.0))
            for r in samples.get(src, [])
            if r.get("name") == metric
        ]
        if not ts:
            continue  # never reported: give a fresh worker the benefit
        age = now - max(ts)
        if age > window:
            out.append((
                {"worker": src},
                age,
                f"no {metric} sample from {src} for {age:.1f}s "
                f"(window {window:.1f}s)",
            ))
    return out


def _counter_label_values(
    samples: dict, names: set, label: str
) -> list[str]:
    """Every value the ``label`` takes across the named counters."""
    vals: set[str] = set()
    for ss in samples.values():
        for rec in ss:
            if rec.get("name") in names and rec.get("kind") == "counter":
                v = (rec.get("labels") or {}).get(label)
                if v:
                    vals.add(str(v))
    return sorted(vals)


def _filter_by_label(samples: dict, label: str, value: str) -> dict:
    return {
        src: [
            r for r in ss
            if (r.get("labels") or {}).get(label) == value
        ]
        for src, ss in samples.items()
    }


def _eval_burn_rate(rule: dict, samples: dict, now: float) -> list:
    by = rule.get("by")
    if by:
        # per-label-value grouping: the same SLO evaluated over each
        # slice of the counters (e.g. ``by: "tenant"`` — one alert per
        # burning tenant, labelled so routing can fan it out)
        names = {
            n for n in (
                rule.get("bad"), rule.get("good"), rule.get("total")
            ) if n
        }
        inner = {k: v for k, v in rule.items() if k != "by"}
        out = []
        for val in _counter_label_values(samples, names, by):
            sub = _filter_by_label(samples, by, val)
            for labels, value, msg in _eval_burn_rate(inner, sub, now):
                out.append((
                    {**labels, by: val}, value, f"{msg} [{by}={val}]",
                ))
        return out
    budget = 1.0 - float(rule["objective"])
    first_ratio = None
    for window_s, factor in rule.get("windows", [[300.0, 6.0]]):
        t_lo = now - float(window_s)
        bad = counter_increase(samples, rule["bad"], t_lo, now)
        if rule.get("total"):
            total = counter_increase(samples, rule["total"], t_lo, now)
        else:
            total = bad + counter_increase(
                samples, rule["good"], t_lo, now
            )
        if total <= 0:
            return []  # no traffic in a window -> nothing is burning
        ratio = bad / total
        if ratio <= float(factor) * budget:
            return []  # ALL windows must burn
        if first_ratio is None:
            first_ratio = ratio
    if first_ratio is None:
        return []
    return [(
        {},
        float(first_ratio),
        f"{rule['bad']} error ratio {first_ratio:.3f} burns "
        f">{budget:.3f} budget in every window",
    )]


def _eval_findings(findings: list[dict] | None) -> list:
    out = []
    for f in findings or []:
        labels = {
            str(k): str(v)
            for k, v in (f.get("labels") or {}).items()
        }
        out.append((
            labels,
            float(f.get("value", 1.0)),
            str(f.get("message", "")),
        ))
    return out


# --------------------------------------------------------------------------
# the engine: lifecycle + persistence
# --------------------------------------------------------------------------

def _labels_key(labels: dict) -> tuple:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class AlertEngine:
    """Evaluate the rule set for one campaign and persist the alert
    lifecycle under ``<root>/queue/``. Stateless across instances: the
    previous round's states are restored from the snapshot, so any
    worker (or the CLI) can run a round."""

    def __init__(
        self,
        root: str,
        rules: list[dict] | None = None,
        lock_stale_s: float = LOCK_STALE_S,
    ) -> None:
        self.root = os.path.abspath(root)
        self.rules = (
            [dict(r) for r in rules] if rules is not None
            else default_rules()
        )
        qdir = os.path.join(self.root, "queue")
        self.snapshot_path = os.path.join(qdir, "alerts.json")
        self.log_path = os.path.join(qdir, "alerts.jsonl")
        self.lock_path = os.path.join(qdir, "alerts.lock")
        self.lock_stale_s = float(lock_stale_s)
        self._lock_token: str | None = None

    # --- persistence --------------------------------------------------
    def load_snapshot(self) -> dict:
        doc = _read_json(self.snapshot_path)
        if not isinstance(doc, dict) or doc.get("schema") != ALERTS_SCHEMA:
            return {
                "schema": ALERTS_SCHEMA,
                "version": ALERTS_VERSION,
                "updated_unix": 0.0,
                "alerts": [],
            }
        return doc

    def _acquire_lock(self, now: float) -> bool:
        os.makedirs(os.path.dirname(self.lock_path), exist_ok=True)
        for _ in range(2):
            try:
                fd = os.open(
                    self.lock_path,
                    os.O_CREAT | os.O_EXCL | os.O_WRONLY,
                )
            except FileExistsError:
                doc = _read_json(self.lock_path)
                if doc is not None:
                    held_unix = float(doc.get("t_unix", 0.0))
                    if now - held_unix <= self.lock_stale_s:
                        return False  # live evaluator owns the round
                else:
                    # TORN lock: unreadable is either a holder that
                    # died between the O_CREAT|O_EXCL and the document
                    # publish, or a LIVE acquirer still inside that
                    # window. Age-gate on st_ctime before taking over
                    # — an immediate takeover here stole the round
                    # from a perfectly live evaluator (found by the mc
                    # alerts_lock scenario)
                    try:
                        age = now - os.stat(self.lock_path).st_ctime
                    except OSError:
                        continue  # released in the gap: retry create
                    if age <= self.lock_stale_s:
                        return False
                # stale (or aged-out torn) lock: win the takeover via
                # a rename race, then retry the exclusive create
                reaped = self.lock_path + f".{uuid.uuid4().hex[:8]}.reap"
                try:
                    os.rename(self.lock_path, reaped)
                    os.unlink(reaped)
                except OSError:
                    pass  # another evaluator won the takeover
                continue
            token = uuid.uuid4().hex
            with os.fdopen(fd, "w") as f:
                json.dump(
                    {"pid": os.getpid(), "t_unix": now, "token": token},
                    f,
                )
            self._lock_token = token
            return True
        return False

    def _release_lock(self) -> None:
        """Token-verified release. A blind unlink here deleted a lock
        another evaluator had legitimately taken over after deciding
        ours was stale — mutual exclusion silently lapsed for a round
        (found by the mc alerts_release_race scenario). Rename the
        lock aside, confirm the tombstone still carries OUR token,
        and restore a mismatch via link so a new holder's lock (or
        its own re-acquire in the gap) is never clobbered."""
        token, self._lock_token = self._lock_token, None
        tomb = self.lock_path + f".{uuid.uuid4().hex[:8]}.reap"
        try:
            os.rename(self.lock_path, tomb)
        except OSError:
            return  # taken over and released already — same outcome
        doc = _read_json(tomb)
        if doc is None or doc.get("token") != token:
            try:
                os.link(tomb, self.lock_path)
            except OSError:
                pass  # the new holder re-created it first: they win
        try:
            os.unlink(tomb)
        except OSError:
            pass

    def _append_transitions(self, transitions: list[dict]) -> None:
        if not transitions:
            return
        lines = "".join(
            json.dumps(t, separators=(",", ":")) + "\n"
            for t in transitions
        )
        with open(self.log_path, "a") as f:
            f.write(lines)
        self._route_transitions(transitions)

    def _route_transitions(self, transitions: list[dict]) -> None:
        """Fan transitions of ``route:``-scoped rules out to per-value
        journals: a rule with ``route: "tenant"`` copies each of its
        transitions to ``queue/alerts.<labels[tenant]>.jsonl`` — the
        tenant's own audit trail, beside (never instead of) the
        fleet-wide journal."""
        routes = {
            r["name"]: r["route"]
            for r in self.rules if r.get("route")
        }
        if not routes:
            return
        by_journal: dict[str, list[str]] = {}
        for t in transitions:
            label = routes.get(t.get("rule"))
            if not label:
                continue
            val = (t.get("labels") or {}).get(label)
            if not val:
                continue
            by_journal.setdefault(str(val), []).append(
                json.dumps(t, separators=(",", ":")) + "\n"
            )
        for val, lines in by_journal.items():
            try:
                with open(
                    tenant_journal_path(self.root, val), "a"
                ) as f:
                    f.write("".join(lines))
            except OSError:
                log.debug(
                    "per-tenant alert journal append failed (%s)",
                    val, exc_info=True,
                )

    def _write_snapshot(self, doc: dict) -> None:
        d = os.path.dirname(self.snapshot_path)
        os.makedirs(d, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(doc, f, indent=2)
                f.write("\n")
            os.replace(tmp, self.snapshot_path)
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise

    # --- evaluation ---------------------------------------------------
    def evaluate(
        self,
        samples: dict[str, list[dict]] | None = None,
        now: float | None = None,
        dq_findings: list[dict] | None = None,
        sentinel_findings: list[dict] | None = None,
        live_sources: list[str] | None = None,
        tenant_findings: list[dict] | None = None,
    ) -> dict:
        """Run one evaluation round and return the new snapshot (or
        the current one when another evaluator holds the lock)."""
        now = time.time() if now is None else float(now)
        if samples is None:
            samples = fleet_samples(self.root)
        if not self._acquire_lock(now):
            return self.load_snapshot()
        try:
            return self._evaluate_locked(
                samples, now, dq_findings, sentinel_findings,
                live_sources, tenant_findings,
            )
        finally:
            self._release_lock()

    def _evaluate_locked(
        self, samples, now, dq_findings, sentinel_findings,
        live_sources, tenant_findings=None,
    ) -> dict:
        prev_doc = self.load_snapshot()
        prev = {
            (a.get("rule"), _labels_key(a.get("labels") or {})): a
            for a in prev_doc.get("alerts", [])
        }
        active: dict[tuple, dict] = {}
        for rule in self.rules:
            kind = rule.get("kind", "threshold")
            try:
                if kind == "threshold":
                    found = _eval_threshold(rule, samples, now)
                elif kind == "absence":
                    found = _eval_absence(
                        rule, samples, now, live_sources
                    )
                elif kind == "burn_rate":
                    found = _eval_burn_rate(rule, samples, now)
                elif kind == "data_quality":
                    found = _eval_findings(dq_findings)
                elif kind == "sentinel":
                    found = _eval_findings(sentinel_findings)
                elif kind == "tenant_quota":
                    found = _eval_findings(tenant_findings)
                else:
                    log.warning("unknown alert rule kind: %r", kind)
                    continue
            except Exception:
                # a broken rule must not take the round down
                log.warning(
                    "alert rule %r failed to evaluate",
                    rule.get("name"), exc_info=True,
                )
                continue
            for labels, value, message in found:
                key = (rule["name"], _labels_key(labels))
                ent = {
                    "rule": rule["name"],
                    "labels": {
                        str(k): str(v) for k, v in labels.items()
                    },
                    "severity": str(rule.get("severity", "warn")),
                    "value": float(value),
                    "message": str(message)[:400],
                }
                if "value" in rule and kind != "data_quality":
                    try:
                        ent["threshold"] = float(rule["value"])
                    except (TypeError, ValueError):
                        pass
                active[key] = ent

        transitions: list[dict] = []
        next_alerts: list[dict] = []

        def _log_transition(ent, frm, to):
            transitions.append({
                "t_unix": now,
                "rule": ent["rule"],
                "labels": ent.get("labels") or {},
                "from": frm,
                "to": to,
                "value": ent.get("value"),
                "message": ent.get("message", ""),
            })

        for key, ent in active.items():
            pv = prev.get(key)
            pstate = pv.get("state") if pv else None
            for_s = 0.0
            for rule in self.rules:
                if rule["name"] == key[0]:
                    for_s = float(rule.get("for_s", 0.0))
                    break
            if pstate == "firing":
                ent.update({
                    "state": "firing",
                    "since_unix": pv["since_unix"],
                    "pending_since_unix": pv.get(
                        "pending_since_unix", pv["since_unix"]
                    ),
                    "firing_since_unix": pv.get(
                        "firing_since_unix", pv["since_unix"]
                    ),
                })
            elif pstate == "pending":
                pending_since = pv.get(
                    "pending_since_unix", pv["since_unix"]
                )
                ent.update({
                    "since_unix": pv["since_unix"],
                    "pending_since_unix": pending_since,
                })
                if now - pending_since >= for_s:
                    ent["state"] = "firing"
                    ent["firing_since_unix"] = now
                    _log_transition(ent, "pending", "firing")
                else:
                    ent["state"] = "pending"
            else:
                # inactive (or resolved) -> a fresh pending episode
                ent.update({
                    "state": "pending",
                    "since_unix": now,
                    "pending_since_unix": now,
                })
                _log_transition(ent, pstate or "inactive", "pending")
                if for_s <= 0.0:
                    ent["state"] = "firing"
                    ent["firing_since_unix"] = now
                    _log_transition(ent, "pending", "firing")
            next_alerts.append(ent)

        for key, pv in prev.items():
            if key in active:
                continue
            pstate = pv.get("state")
            if pstate == "pending":
                _log_transition(pv, "pending", "inactive")
            elif pstate == "firing":
                ent = dict(pv)
                ent["state"] = "resolved"
                ent["resolved_unix"] = now
                _log_transition(ent, "firing", "resolved")
                next_alerts.append(ent)
            elif pstate == "resolved":
                if now - float(
                    pv.get("resolved_unix", 0.0)
                ) <= RESOLVED_RETENTION_S:
                    next_alerts.append(pv)

        next_alerts.sort(
            key=lambda a: (a.get("rule", ""), _labels_key(
                a.get("labels") or {}
            ))
        )
        doc = {
            "schema": ALERTS_SCHEMA,
            "version": ALERTS_VERSION,
            "updated_unix": now,
            "alerts": next_alerts,
        }
        self._append_transitions(transitions)
        self._write_snapshot(doc)
        if transitions:
            log.info(
                "alerts: %d transition(s): %s",
                len(transitions),
                ", ".join(
                    f"{t['rule']}:{t['from']}->{t['to']}"
                    for t in transitions[:6]
                ),
            )
        return doc


def _read_json(path: str) -> dict | None:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None  # absent, mid-replace, or torn: treat as absent


def load_alerts(root: str) -> dict:
    """The current alerts snapshot for a campaign (empty when none)."""
    return AlertEngine(root, rules=[]).load_snapshot()


# --------------------------------------------------------------------------
# exposition + one-stop campaign evaluation
# --------------------------------------------------------------------------

def alerts_exposition(snapshot: dict) -> str:
    """Render pending/firing alerts as the Prometheus ``ALERTS``
    convention series (appended to the campaign's /metrics body)."""
    lines: list[str] = []
    for a in snapshot.get("alerts", []):
        if a.get("state") not in ("pending", "firing"):
            continue
        labels = {
            "alertname": a.get("rule", ""),
            "alertstate": a["state"],
            "severity": a.get("severity", "warn"),
            **(a.get("labels") or {}),
        }
        lines.append(f"ALERTS{_label_str(labels)} 1")
    if not lines:
        return ""
    return "# TYPE ALERTS gauge\n" + "\n".join(lines) + "\n"


def evaluate_campaign(
    root: str,
    rules: list[dict] | None = None,
    now: float | None = None,
    queue=None,
    registry=None,
    samples: dict[str, list[dict]] | None = None,
) -> dict:
    """Evaluate the full survey-health round for one campaign: fleet
    metrics + data-quality findings + sentinel recoveries + registry
    liveness. Never raises (the runner calls this beside its status
    rollup): any failure returns the last good snapshot."""
    try:
        from ..campaign.queue import JobQueue
        from ..campaign.registry import WorkerRegistry
        from .health import quality_findings, sentinel_findings

        if queue is None:
            queue = JobQueue(root)
        if registry is None:
            registry = WorkerRegistry(root)
        if samples is None:
            samples = fleet_samples(root)
        heartbeat_s = max(
            1.0, float(getattr(registry, "lease_s", 10.0)) / 3.0
        )
        engine = AlertEngine(
            root,
            rules=rules if rules is not None
            else default_rules(heartbeat_s=heartbeat_s),
        )
        live = sorted(
            e.get("worker_id", "")
            for e in registry.live()
        )
        tenant_findings: list[dict] = []
        try:
            from ..campaign.tenants import throttle_map

            tenant_findings = [
                {
                    "labels": {"tenant": name},
                    "value": 1.0,
                    "message": str(f.get("reason", "over quota")),
                }
                for name, f in sorted(
                    throttle_map(root, now=now).items()
                )
            ]
        except Exception:
            log.warning(
                "tenant quota findings failed", exc_info=True
            )
        return engine.evaluate(
            samples=samples,
            now=now,
            dq_findings=quality_findings(queue.done_records()),
            sentinel_findings=sentinel_findings(root, queue),
            live_sources=[w for w in live if w],
            tenant_findings=tenant_findings,
        )
    except Exception:
        log.warning("alert evaluation failed", exc_info=True)
        return load_alerts(root)
