"""Live run status: the ``status.json`` heartbeat and stall watchdog.

A long survey job is opaque from the outside — the telemetry manifest
only materialises when the run *finishes*. :class:`Heartbeat` is the
live layer: a daemon thread that atomically rewrites a small
``status.json`` snapshot every ``interval`` seconds, driven entirely by
the run's :class:`~peasoup_tpu.obs.telemetry.RunTelemetry` (current
stage, progress counter + rate/ETA, device-memory gauges, event tail).
Operators tail it with ``python -m peasoup_tpu.tools.watch``; schedulers
poll it for liveness (``updated_unix`` going stale means the process is
gone or wedged).

The thread doubles as the **stall watchdog**: when no progress signal
(stage, progress counter, event count, counters) advances for
``stall_timeout`` seconds it emits a structured ``stall`` event into the
telemetry log and a warning log line — so a hung collective or a wedged
device call is visible both live (``"stalled": true`` in status.json)
and post-mortem (the event survives into the manifest / flight dump).

The heartbeat never *fails* a run: every snapshot write is wrapped, and
the thread is a daemon so an aborted run cannot hang on join.
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time

from .log import get_logger

STATUS_SCHEMA = "peasoup_tpu.status"
# v2: optional named status sections from
# RunTelemetry.set_status_section (e.g. the streaming driver's
# "streaming" block with input rate / queue depth / latency-vs-SLO /
# drop tallies). Watchers .get() them; absent for batch runs.
STATUS_VERSION = 2

log = get_logger("obs.heartbeat")


def _atomic_write_json(path: str, doc: dict) -> None:
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=2)
        f.write("\n")
    os.replace(tmp, path)


def load_status(path: str) -> dict:
    """Load + validate a status.json snapshot."""
    with open(path) as f:
        st = json.load(f)
    if st.get("schema") != STATUS_SCHEMA:
        raise ValueError(
            f"{path}: not a {STATUS_SCHEMA} snapshot "
            f"(schema={st.get('schema')!r})"
        )
    return st


class Heartbeat:
    """Daemon thread rewriting ``path`` with a live run snapshot.

    Use as a context manager, or ``start()`` / ``stop()`` explicitly;
    ``stop()`` writes one final snapshot with ``"done": true`` so a
    watcher can distinguish a finished run from a dead one.
    """

    def __init__(
        self,
        telemetry,
        path: str,
        interval: float = 5.0,
        stall_timeout: float = 300.0,
        event_tail: int = 8,
    ) -> None:
        self._tel = telemetry
        self.path = path
        self.interval = max(0.01, float(interval))
        self.stall_timeout = float(stall_timeout)
        self.event_tail = int(event_tail)
        self._seq = 0
        self._thread: threading.Thread | None = None
        self._stop_evt = threading.Event()
        # rate/ETA from successive snapshots of the progress counter
        self._prev_progress: tuple[float, float] | None = None  # (t, done)
        self._rate: float | None = None
        # stall watchdog state
        self._last_token = None
        self._last_change = time.perf_counter()
        self._stalled = False

    # --- lifecycle ----------------------------------------------------
    def start(self) -> "Heartbeat":
        if self._thread is not None:
            return self
        # audit: ignore[PSA009] -- threading.Event is internally locked
        self._stop_evt.clear()
        self._beat()  # immediate first snapshot: liveness from t=0
        self._thread = threading.Thread(
            target=self._run, name="peasoup-heartbeat", daemon=True
        )
        self._thread.start()
        log.debug(
            "heartbeat started: %s every %.3gs (stall watchdog %.3gs)",
            self.path, self.interval, self.stall_timeout,
        )
        return self

    def stop(self) -> None:
        if self._thread is None:
            return
        self._stop_evt.set()
        self._thread.join(timeout=max(1.0, 2 * self.interval))
        self._thread = None
        self._beat(final=True)

    def __enter__(self) -> "Heartbeat":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # --- the beat -----------------------------------------------------
    def _run(self) -> None:
        # crash guard (resilience policy): _beat swallows per-snapshot
        # failures already, but if the loop itself ever dies the run
        # must not lose its liveness signal invisibly — the guard
        # emits a structured thread_crashed event and flips the
        # resilience status section to degraded. Lazy import: obs is
        # below resilience in the import graph.
        from ..resilience import guard_thread

        guard_thread(
            "peasoup-heartbeat", self._beat_loop, telemetry=self._tel
        )

    def _beat_loop(self) -> None:
        while not self._stop_evt.wait(self.interval):
            self._beat()

    def _progress_token(self):
        """Anything whose advance counts as liveness for the watchdog."""
        tel = self._tel
        prog = tel.progress_state
        return (
            tel.current_stage,
            prog.get("done") if prog else None,
            len(tel.events),
            round(sum(tel.counters.values()), 6) if tel.counters else 0.0,
        )

    def _check_stall(self, now: float) -> None:
        token = self._progress_token()
        if token != self._last_token:
            self._last_token = token
            self._last_change = now
            if self._stalled:
                self._stalled = False
                self._tel.event(
                    "stall_recovered", stage=self._tel.current_stage
                )
                log.warning(
                    "run progressing again (stage %s)",
                    self._tel.current_stage,
                )
                self._last_token = self._progress_token()
            return
        if (
            not self._stalled
            and self.stall_timeout > 0
            and now - self._last_change > self.stall_timeout
        ):
            self._stalled = True
            stalled_for = round(now - self._last_change, 3)
            self._tel.event(
                "stall",
                stage=self._tel.current_stage,
                stalled_for_s=stalled_for,
                stall_timeout_s=self.stall_timeout,
            )
            log.warning(
                "no progress for %.1fs (stage %s): run may be stalled",
                stalled_for, self._tel.current_stage,
            )
            # absorb our own event so the watchdog doesn't see it as
            # progress and oscillate stall/recovered every timeout
            self._last_token = self._progress_token()

    def _snapshot(self, final: bool) -> dict:
        tel = self._tel
        now = time.perf_counter()
        prog = dict(tel.progress_state) if tel.progress_state else None
        if prog is not None:
            done, total = prog["done"], prog.get("total")
            if self._prev_progress is not None:
                t_prev, d_prev = self._prev_progress
                if done > d_prev and now > t_prev:
                    self._rate = (done - d_prev) / (now - t_prev)
            self._prev_progress = (now, done)
            prog["rate_per_s"] = (
                round(self._rate, 6) if self._rate else None
            )
            if total:
                prog["frac"] = round(done / total, 6)
                prog["eta_s"] = (
                    round((total - done) / self._rate, 3)
                    if self._rate and done < total
                    else (0.0 if done >= total else None)
                )
        # audit: ignore[PSA009] -- single writer: only the beat thread
        # increments, and stop() joins it before the final beat
        self._seq += 1
        sections = {}
        try:
            sections = tel.snapshot_sections()
        except Exception:
            pass  # a section provider must never fail the beat
        return {
            "schema": STATUS_SCHEMA,
            "version": STATUS_VERSION,
            "run_id": tel.run_id,
            "pid": os.getpid(),
            "hostname": socket.gethostname(),
            "seq": self._seq,
            "updated_unix": time.time(),
            "uptime_s": round(now - tel._t0, 3),
            "done": bool(final),
            "stage": tel.current_stage,
            "progress": prog,
            "stalled": self._stalled,
            "last_progress_age_s": round(now - self._last_change, 3),
            "counters": dict(tel.counters),
            "gauges": dict(tel.gauges),
            "events_tail": list(tel.events[-self.event_tail :]),
        } | {
            k: v for k, v in sections.items()
            # a section can never shadow a core snapshot key
            if k not in (
                "schema", "version", "run_id", "pid", "hostname", "seq",
                "updated_unix", "uptime_s", "done", "stage", "progress",
                "stalled", "last_progress_age_s", "counters", "gauges",
                "events_tail",
            )
        }

    def _beat(self, final: bool = False) -> None:
        try:
            self._tel.capture_device_memory("heartbeat")
            self._check_stall(time.perf_counter())
            _atomic_write_json(self.path, self._snapshot(final))
        except Exception:
            # the heartbeat must never take the run down with it
            log.debug("heartbeat write failed", exc_info=True)
