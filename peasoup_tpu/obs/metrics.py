"""Fleet time-series metrics: the historical layer under the rollup.

``campaign_status.json`` (campaign/rollup.py) answers "what is the
fleet doing NOW"; nothing answered "what was queue depth / throughput /
preemption latency over the last hour" without re-running the soak.
This module is that layer:

- :class:`MetricsRecorder` — a per-worker **append-only** time-series
  file (``queue/workers/<worker>.metrics.jsonl``, one JSON sample per
  line) with bounded size: when the file outgrows ``max_bytes`` it is
  atomically rotated (tmp + ``os.replace``) keeping the newest tail,
  so a week-long campaign never eats the disk and a reader mid-rotate
  sees either the old or the new file, never a torn one. Counters are
  written as **cumulative** values (Prometheus semantics, carried in
  recorder memory across rotations), gauges as point-in-time values,
  and histogram samples as raw observations bucketed at read time.
- the **fleet aggregator** — :func:`fleet_samples` collects every
  worker's series under a campaign root (workers that already left
  the fleet included: their history is the point), and
  :func:`prometheus_exposition` renders the standard text exposition
  format (``# TYPE`` comments, ``{label="..."}`` sets, histogram
  ``_bucket``/``_sum``/``_count`` triplets) for ``peasoup-campaign
  metrics`` and its ``--serve`` stdlib HTTP endpoint.

Every sample line validates against the checked-in
``obs/metrics.schema.json`` through the dependency-free
:mod:`peasoup_tpu.obs.schema` validator — the chaos soak's CI gate
holds the writers to it.

The recorder is single-writer by construction (one worker owns its
file; the worker id IS the filename stem), so appends need no locking
across processes; a thread lock covers the renewer/watcher threads
inside one process.
"""

from __future__ import annotations

import glob as _glob
import json
import math
import os
import threading
import time

from .log import get_logger

log = get_logger("obs.metrics")

METRICS_SCHEMA = "peasoup_tpu.metrics"
METRICS_VERSION = 1

METRICS_SUFFIX = ".metrics.jsonl"

_SCHEMA_PATH = os.path.join(
    os.path.dirname(__file__), "metrics.schema.json"
)

KINDS = ("counter", "gauge", "hist")

# default histogram bucket bounds (seconds-flavoured: latencies are
# the dominant histogram here); the exposition adds the +Inf bucket
DEFAULT_BUCKETS = (
    0.005, 0.02, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
    60.0, 120.0, 300.0,
)


def load_metrics_schema() -> dict:
    with open(_SCHEMA_PATH) as f:
        return json.load(f)


def validate_sample(rec: dict, schema: dict | None = None) -> None:
    """Validate one sample line against the checked-in schema (raises
    :class:`~peasoup_tpu.obs.schema.SchemaError`)."""
    from .schema import validate

    validate(rec, schema or load_metrics_schema())


class MetricsRecorder:
    """Append-only bounded time-series recorder for ONE worker.

    ``enabled=False`` is the campaign's off switch: every method
    becomes a no-op and no file is ever created (mirroring
    :data:`~peasoup_tpu.obs.telemetry.NOOP`).
    """

    def __init__(
        self,
        path: str,
        enabled: bool = True,
        max_bytes: int = 4 << 20,
        keep_bytes: int | None = None,
    ) -> None:
        self.path = path
        self.enabled = bool(enabled)
        self.max_bytes = int(max_bytes)
        self.keep_bytes = int(keep_bytes or max(4096, self.max_bytes // 2))
        self._lock = threading.Lock()
        self._counters: dict[tuple, float] = {}
        self._approx_bytes: int | None = None  # lazily stat()ed

    # --- recording ----------------------------------------------------
    def counter(self, name: str, by: float = 1.0, **labels) -> None:
        """Monotone cumulative counter (the written value is the
        running total, Prometheus-style)."""
        if not self.enabled:
            return
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            total = self._counters.get(key, 0.0) + float(by)
            self._counters[key] = total
            self._append("counter", name, total, labels)

    def gauge(self, name: str, value: float, **labels) -> None:
        """Point-in-time value."""
        if not self.enabled:
            return
        with self._lock:
            self._append("gauge", name, float(value), labels)

    def observe(self, name: str, value: float, **labels) -> None:
        """One histogram observation (bucketed at read time)."""
        if not self.enabled:
            return
        with self._lock:
            self._append("hist", name, float(value), labels)

    # --- the file -----------------------------------------------------
    def _append(self, kind: str, name: str, value: float, labels) -> None:
        now_unix = time.time()  # sample timestamps are epochs, shared
        rec: dict = {
            "t": now_unix,
            "name": str(name),
            "kind": kind,
            "value": value,
        }
        if labels:
            rec["labels"] = {k: str(v) for k, v in sorted(labels.items())}
        line = json.dumps(rec, separators=(",", ":")) + "\n"
        try:
            d = os.path.dirname(self.path)
            if d:
                os.makedirs(d, exist_ok=True)
            with open(self.path, "a") as f:
                f.write(line)
            if self._approx_bytes is None:
                try:
                    self._approx_bytes = os.path.getsize(self.path)
                except OSError:
                    self._approx_bytes = len(line)
            else:
                self._approx_bytes += len(line)
            if self._approx_bytes > self.max_bytes:
                self._rotate()
        except OSError:
            # metrics must never fail the worker (full disk, yanked
            # mount): drop the sample, keep the campaign alive
            log.debug("metrics append failed: %s", self.path, exc_info=True)

    def _rotate(self) -> None:
        """Atomic tail-keeping rewrite: newest samples whose total size
        fits ``keep_bytes`` survive; the counter running totals live in
        recorder memory, so cumulative series stay monotone across the
        rotation."""
        try:
            with open(self.path) as f:
                lines = f.readlines()
        except OSError:
            return
        kept: list[str] = []
        total = 0
        for ln in reversed(lines):
            total += len(ln)
            if total > self.keep_bytes:
                break
            kept.append(ln)
        kept.reverse()
        tmp = self.path + ".tmp"
        try:
            with open(tmp, "w") as f:
                f.writelines(kept)
            os.replace(tmp, self.path)
        except OSError:
            log.debug("metrics rotation failed", exc_info=True)
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return
        self._approx_bytes = sum(len(ln) for ln in kept)
        log.debug(
            "rotated %s: kept %d of %d samples",
            self.path, len(kept), len(lines),
        )


def rotate_journal(
    path: str, max_bytes: int, keep_bytes: int | None = None
) -> bool:
    """The recorder's tail-keeping rotation as a standalone operation
    for any append-only jsonl journal (``queue/alerts.jsonl``,
    ``queue/submissions.jsonl``, the per-tenant alert journals —
    ``peasoup-campaign prune --journals``): when ``path`` exceeds
    ``max_bytes``, atomically rewrite it keeping the newest whole
    lines that fit ``keep_bytes`` (default half of ``max_bytes``).
    Returns True when a rotation happened. Alert-engine state restores
    from the SNAPSHOT (``queue/alerts.json``), never the journal, so
    truncating journal history can never re-fire an alert — the
    restart-no-refire regression test pins that."""
    keep = int(keep_bytes or max(4096, int(max_bytes) // 2))
    try:
        if os.path.getsize(path) <= int(max_bytes):
            return False
        with open(path) as f:
            lines = f.readlines()
    except OSError:
        return False
    kept: list[str] = []
    total = 0
    for ln in reversed(lines):
        # budgets are bytes on disk, so measure encoded length —
        # len(ln) undercounts multibyte UTF-8 journal content
        total += len(ln.encode("utf-8"))
        if total > keep:
            break
        kept.append(ln)
    kept.reverse()
    tmp = path + ".tmp"
    try:
        with open(tmp, "w") as f:
            f.writelines(kept)
        os.replace(tmp, path)
    except OSError:
        log.debug("journal rotation failed: %s", path, exc_info=True)
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False
    log.info(
        "rotated %s: kept %d of %d lines", path, len(kept), len(lines)
    )
    return True


# --------------------------------------------------------------------------
# reading + fleet aggregation
# --------------------------------------------------------------------------

def load_series(path: str, validate: bool = False) -> list[dict]:
    """Samples from one worker's metrics file (torn trailing line —
    the writer mid-append — is skipped, never an error)."""
    out: list[dict] = []
    schema = load_metrics_schema() if validate else None
    try:
        with open(path) as f:
            lines = f.readlines()
    except OSError:
        return out
    for ln in lines:
        ln = ln.strip()
        if not ln:
            continue
        try:
            rec = json.loads(ln)
        except json.JSONDecodeError:
            continue  # torn tail
        if validate:
            validate_sample(rec, schema)
        out.append(rec)
    return out


def metrics_paths(root: str) -> list[str]:
    """Every worker metrics file under a campaign root — departed
    workers' files included (history outlives membership)."""
    return sorted(
        _glob.glob(
            os.path.join(
                os.path.abspath(root), "queue", "workers",
                "*" + METRICS_SUFFIX,
            )
        )
    )


def source_for_path(path: str) -> str:
    base = os.path.basename(path)
    return base[: -len(METRICS_SUFFIX)] if base.endswith(
        METRICS_SUFFIX
    ) else os.path.splitext(base)[0]


def fleet_samples(
    root: str, validate: bool = False
) -> dict[str, list[dict]]:
    """source (worker id) -> its samples, for one campaign root."""
    return {
        source_for_path(p): load_series(p, validate=validate)
        for p in metrics_paths(root)
    }


def series(
    samples_by_source: dict[str, list[dict]],
    name: str,
    kind: str | None = None,
    labels: dict | None = None,
) -> list[dict]:
    """All samples of one metric across the fleet, time-ordered, each
    tagged with its source — the "queue depth over the last hour"
    query shape. ``labels`` filters to samples whose label set
    CONTAINS every given pair (``labels={"tenant": "alice"}`` slices
    one tenant's series out of the fleet's)."""
    out = []
    for src, samples in samples_by_source.items():
        for rec in samples:
            if rec.get("name") != name:
                continue
            if kind is not None and rec.get("kind") != kind:
                continue
            if labels:
                have = rec.get("labels") or {}
                if any(
                    have.get(k) != str(v) for k, v in labels.items()
                ):
                    continue
            out.append({**rec, "source": src})
    out.sort(key=lambda r: r.get("t", 0.0))
    return out


# --------------------------------------------------------------------------
# Prometheus text exposition
# --------------------------------------------------------------------------

def _fmt_value(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _label_str(labels: dict) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{str(v).replace(chr(92), chr(92) * 2).replace(chr(34), chr(92) + chr(34))}"'
        for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


def _metric_name(name: str, prefix: str) -> str:
    safe = "".join(
        c if c.isalnum() or c == "_" else "_" for c in str(name)
    )
    return f"{prefix}_{safe}" if prefix else safe


def prometheus_exposition(
    samples_by_source: dict[str, list[dict]],
    prefix: str = "peasoup",
    buckets: tuple = DEFAULT_BUCKETS,
) -> str:
    """Render the fleet's series in the Prometheus text exposition
    format. Counters and gauges expose their LAST value per
    (source, labels) series; histogram observations are bucketed into
    cumulative ``_bucket`` counts plus ``_sum``/``_count``."""
    last: dict[tuple, tuple[float, float]] = {}  # series -> (t, value)
    kinds: dict[str, str] = {}
    hists: dict[tuple, list[float]] = {}
    for src, samples in sorted(samples_by_source.items()):
        for rec in samples:
            name = rec.get("name")
            kind = rec.get("kind")
            if not name or kind not in KINDS:
                continue
            labels = dict(rec.get("labels") or {})
            labels["worker"] = src
            key = (name, tuple(sorted(labels.items())))
            kinds[name] = kind
            if kind == "hist":
                hists.setdefault(key, []).append(float(rec["value"]))
            else:
                t = float(rec.get("t", 0.0))
                if key not in last or t >= last[key][0]:
                    last[key] = (t, float(rec["value"]))
    lines: list[str] = []
    for name in sorted(kinds):
        kind = kinds[name]
        mname = _metric_name(name, prefix)
        if kind == "hist":
            lines.append(f"# TYPE {mname} histogram")
            for key, obs in sorted(hists.items()):
                if key[0] != name:
                    continue
                labels = dict(key[1])
                cum = 0
                for b in (*buckets, math.inf):
                    cum = sum(1 for v in obs if v <= b)
                    lines.append(
                        f"{mname}_bucket"
                        f"{_label_str({**labels, 'le': _fmt_value(b)})}"
                        f" {cum}"
                    )
                lines.append(
                    f"{mname}_sum{_label_str(labels)} "
                    f"{_fmt_value(sum(obs))}"
                )
                lines.append(
                    f"{mname}_count{_label_str(labels)} {len(obs)}"
                )
        else:
            ptype = "counter" if kind == "counter" else "gauge"
            lines.append(f"# TYPE {mname} {ptype}")
            for key, (_, value) in sorted(last.items()):
                if key[0] != name:
                    continue
                lines.append(
                    f"{mname}{_label_str(dict(key[1]))} "
                    f"{_fmt_value(value)}"
                )
    return "\n".join(lines) + ("\n" if lines else "")


def parse_exposition(text: str) -> list[tuple[str, dict, float]]:
    """Parse exposition text back into (name, labels, value) triples —
    the round-trip check the chaos gate runs. Raises ValueError on a
    malformed line (that IS the gate)."""
    out: list[tuple[str, dict, float]] = []
    for ln in text.splitlines():
        ln = ln.strip()
        if not ln or ln.startswith("#"):
            continue
        head, _, val = ln.rpartition(" ")
        if not head:
            raise ValueError(f"malformed exposition line: {ln!r}")
        labels: dict = {}
        name = head
        if "{" in head:
            if not head.endswith("}"):
                raise ValueError(f"malformed label set: {ln!r}")
            name, _, inner = head.partition("{")
            inner = inner[:-1]
            for part in _split_labels(inner):
                k, _, v = part.partition("=")
                if not (v.startswith('"') and v.endswith('"')):
                    raise ValueError(f"malformed label value: {ln!r}")
                labels[k] = (
                    v[1:-1].replace('\\"', '"').replace("\\\\", "\\")
                )
        if not name.replace("_", "").replace(":", "").isalnum():
            raise ValueError(f"malformed metric name: {ln!r}")
        out.append((name, labels, float(val.replace("+Inf", "inf"))))
    return out


def _split_labels(inner: str) -> list[str]:
    """Split a label set on commas outside quotes."""
    parts, buf, quoted, escaped = [], [], False, False
    for ch in inner:
        if escaped:
            buf.append(ch)
            escaped = False
            continue
        if ch == "\\":
            buf.append(ch)
            escaped = True
            continue
        if ch == '"':
            quoted = not quoted
            buf.append(ch)
            continue
        if ch == "," and not quoted:
            parts.append("".join(buf))
            buf = []
            continue
        buf.append(ch)
    if buf:
        parts.append("".join(buf))
    return [p for p in (s.strip() for s in parts) if p]


# --------------------------------------------------------------------------
# the --serve endpoint (stdlib only)
# --------------------------------------------------------------------------

def serve_metrics(
    root: str,
    port: int = 9099,
    host: str = "127.0.0.1",
    max_requests: int | None = None,
) -> None:
    """Serve ``GET /metrics`` (Prometheus exposition, regenerated per
    request from the campaign's metrics files) on a stdlib HTTP
    server. Blocks; ``max_requests`` bounds it for tests."""
    from http.server import BaseHTTPRequestHandler, HTTPServer

    class _Handler(BaseHTTPRequestHandler):
        def do_GET(self) -> None:  # noqa: N802 (http.server contract)
            if self.path.rstrip("/") not in ("", "/metrics"):
                self.send_error(404)
                return
            try:
                body = prometheus_exposition(
                    fleet_samples(root)
                ).encode()
            except Exception as exc:
                self.send_error(500, f"{type(exc).__name__}: {exc}")
                return
            self.send_response(200)
            self.send_header(
                "Content-Type", "text/plain; version=0.0.4"
            )
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, fmt, *args) -> None:
            log.debug("metrics http: " + fmt, *args)

    server = HTTPServer((host, port), _Handler)
    log.info(
        "serving campaign metrics at http://%s:%d/metrics (root %s)",
        host, server.server_address[1], root,
    )
    try:
        if max_requests is None:
            server.serve_forever()
        else:
            for _ in range(max_requests):
                server.handle_request()
    finally:
        server.server_close()
