"""Cross-process trace correlation: one job, one connected trace.

A campaign job's lifecycle is scattered across processes: claimed by
one worker, preempted and resumed by another, or fanned out across an
N-member gang — and until now the only record was done-record
breadcrumbs on different hosts. This module stitches them back
together:

- a **trace id** is minted when the job is enqueued
  (campaign/queue.py ``Job.trace_id``) and propagated through every
  hand-off artifact: claim documents, preempt-request files, gang
  claim/invitation docs and the ``GangComm`` exchange — so every
  process that ever touches the job tags its spans with the SAME id;
- each process appends **span records** to its own
  ``jobs/<id>/trace-<worker>.jsonl`` (single writer per file, one
  JSON line per finished span — a SIGKILLed process simply stops
  appending, it can never tear the file);
- :func:`export_chrome_trace` merges every span file under a job (or
  a whole campaign) into ONE Chrome trace-event / Perfetto JSON:
  load it at https://ui.perfetto.dev (or chrome://tracing) and the
  preempted-and-resumed job — or the whole gang — renders as one
  connected timeline, one track per worker process.

Span sources: the :class:`Tracer` bridges the run's telemetry
(stage transitions become spans, adaptive events become instants), the
campaign runner adds scheduling spans (claim wait, gang join, revoke
latency), and the pipeline wave loops mark waves and checkpoint saves
through the ambient :func:`job_span` helper — a no-op (one contextvar
read) when no tracer is active, so library users pay nothing.
"""

from __future__ import annotations

import contextlib
import contextvars
import glob as _glob
import json
import os
import threading
import time
import uuid
import zlib

from .log import get_logger

log = get_logger("obs.trace")

TRACE_SCHEMA = "peasoup_tpu.trace"
TRACE_VERSION = 1

_ACTIVE: contextvars.ContextVar["Tracer | None"] = contextvars.ContextVar(
    "peasoup_tpu_tracer", default=None
)

# telemetry event kinds that flip the stage span (emitted by
# RunTelemetry.set_stage); everything else becomes an instant
_STAGE_KIND = "stage"


def new_trace_id() -> str:
    return uuid.uuid4().hex[:16]


def new_span_id() -> str:
    return uuid.uuid4().hex[:12]


def current_tracer() -> "Tracer | None":
    return _ACTIVE.get()


@contextlib.contextmanager
def job_span(name: str, cat: str = "job", flow_id=None, **args):
    """Span on the ambient tracer (no-op when none is active) — how
    deep pipeline code marks waves/checkpoints without threading a
    tracer through every signature."""
    tracer = _ACTIVE.get()
    if tracer is None or not tracer.enabled:
        yield
        return
    with tracer.span(name, cat=cat, flow_id=flow_id, **args):
        yield


def flow_id_for(*parts) -> int:
    """Deterministic Perfetto flow id from shared coordinates — every
    rank of a gang computes the SAME id for the same (gang, context,
    round) without any extra exchange, which is what lets the
    leader's barrier-wait span link to each member's wave span."""
    key = "|".join(str(p) for p in parts).encode()
    return zlib.crc32(key) & 0xFFFFFFFF


def job_instant(name: str, **args) -> None:
    tracer = _ACTIVE.get()
    if tracer is not None and tracer.enabled:
        tracer.instant(name, **args)


class Tracer:
    """Span writer for ONE process's view of one trace.

    Spans are written when they END (one line per complete span), so a
    process killed mid-span leaves no torn record. :meth:`close` ends
    any still-open spans (flagged ``"forced_end": true``) — a graceful
    exit therefore never leaves an unclosed span, which is exactly the
    invariant the chaos gate asserts.
    """

    def __init__(
        self,
        path: str,
        trace_id: str,
        worker: str = "",
        enabled: bool = True,
    ) -> None:
        self.path = path
        self.trace_id = trace_id or new_trace_id()
        self.worker = worker
        self.enabled = bool(enabled)
        self.pid = os.getpid()
        self._lock = threading.Lock()
        self._open: dict[str, dict] = {}  # span_id -> partial record
        self._stage_span: str | None = None  # open stage span id
        self._attached: list[tuple] = []  # (telemetry, listener)
        self._closed = False

    # --- recording ----------------------------------------------------
    def _write(self, rec: dict) -> None:
        line = json.dumps(rec, separators=(",", ":"), default=str) + "\n"
        try:
            d = os.path.dirname(self.path)
            if d:
                os.makedirs(d, exist_ok=True)
            with open(self.path, "a") as f:
                f.write(line)
        except OSError:
            log.debug("trace append failed: %s", self.path, exc_info=True)

    def _base(
        self, name: str, cat: str, args: dict, flow_id=None
    ) -> dict:
        rec: dict = {
            "trace_id": self.trace_id,
            "span_id": new_span_id(),
            "name": str(name),
            "cat": str(cat),
            "worker": self.worker,
            "pid": self.pid,
            "tid": threading.current_thread().name,
        }
        if flow_id is not None:
            # cross-process link: spans sharing a flow id (e.g. a gang
            # barrier round computed identically on every rank) render
            # as connected arrows in Perfetto
            rec["flow_id"] = int(flow_id)
        if args:
            rec["args"] = args
        return rec

    def begin(
        self, name: str, cat: str = "job", flow_id=None, **args
    ) -> str:
        """Open a span; returns its id for :meth:`end`."""
        if not self.enabled:
            return ""
        rec = self._base(name, cat, args, flow_id=flow_id)
        now_unix = time.time()  # span walls are epochs shared across hosts
        rec["ts_unix"] = now_unix
        rec["_t0"] = time.perf_counter()
        with self._lock:
            self._open[rec["span_id"]] = rec
        return rec["span_id"]

    def end(self, span_id: str, **args) -> None:
        if not (self.enabled and span_id):
            return
        with self._lock:
            rec = self._open.pop(span_id, None)
        if rec is None:
            return
        rec["dur_s"] = round(time.perf_counter() - rec.pop("_t0"), 6)
        if args:
            rec["args"] = {**rec.get("args", {}), **args}
        self._write(rec)

    @contextlib.contextmanager
    def span(self, name: str, cat: str = "job", flow_id=None, **args):
        sid = self.begin(name, cat=cat, flow_id=flow_id, **args)
        try:
            yield
        finally:
            self.end(sid)

    def instant(self, name: str, cat: str = "event", **args) -> None:
        if not self.enabled:
            return
        rec = self._base(name, cat, args)
        now_unix = time.time()
        rec["ts_unix"] = now_unix
        rec["dur_s"] = 0.0
        rec["instant"] = True
        self._write(rec)

    def span_at(
        self,
        name: str,
        ts_unix: float,
        dur_s: float,
        cat: str = "sched",
        **args,
    ) -> None:
        """An externally measured span (claim wait, revoke latency):
        the caller supplies the wall-clock start and duration."""
        if not self.enabled:
            return
        rec = self._base(name, cat, args)
        rec["ts_unix"] = float(ts_unix)
        rec["dur_s"] = max(0.0, float(dur_s))
        self._write(rec)

    # --- the telemetry bridge -----------------------------------------
    def attach(self, telemetry) -> None:
        """Subscribe to a RunTelemetry's event stream: ``stage``
        events open/close stage spans, everything else lands as an
        instant — so dedispersion/search/writing spans come for free
        from the stage timers the drivers already maintain."""
        if not self.enabled:
            return
        created_unix = getattr(telemetry, "created_unix", None)
        if created_unix is None:
            created_unix = time.time()

        def _on_event(rec: dict) -> None:
            ts_unix = created_unix + float(rec.get("t", 0.0))
            kind = rec.get("kind", "event")
            args = {
                k: v for k, v in rec.items() if k not in ("t", "kind")
            }
            if kind == _STAGE_KIND:
                with self._lock:
                    prev = self._open.pop(self._stage_span or "", None)
                if prev is not None:
                    prev["dur_s"] = round(
                        time.perf_counter() - prev.pop("_t0"), 6
                    )
                    self._write(prev)
                srec = self._base(
                    f"stage:{args.get('name', '?')}", "stage", {}
                )
                srec["ts_unix"] = ts_unix
                srec["_t0"] = time.perf_counter()
                with self._lock:
                    self._open[srec["span_id"]] = srec
                    self._stage_span = srec["span_id"]
            else:
                irec = self._base(kind, "event", args)
                irec["ts_unix"] = ts_unix
                irec["dur_s"] = 0.0
                irec["instant"] = True
                self._write(irec)

        telemetry.add_listener(_on_event)
        self._attached.append((telemetry, _on_event))

    # --- lifecycle ----------------------------------------------------
    @contextlib.contextmanager
    def activate(self):
        """Make this the ambient tracer (:func:`job_span`)."""
        token = _ACTIVE.set(self if self.enabled else None)
        try:
            yield self
        finally:
            _ACTIVE.reset(token)

    def close(self) -> None:
        """Detach listeners and end any still-open spans (flagged) —
        after close, the file contains no unclosed spans."""
        if self._closed:
            return
        self._closed = True
        for tel, fn in self._attached:
            try:
                tel.remove_listener(fn)
            except Exception:
                pass
        with self._lock:
            open_now = list(self._open.values())
            self._open.clear()
            self._stage_span = None
        for rec in open_now:
            rec["dur_s"] = round(time.perf_counter() - rec.pop("_t0"), 6)
            rec["forced_end"] = True
            self._write(rec)


# --------------------------------------------------------------------------
# reading + export
# --------------------------------------------------------------------------

def trace_paths(job_dir: str) -> list[str]:
    """Every process's span file under one job directory."""
    return sorted(
        _glob.glob(os.path.join(job_dir, "trace-*.jsonl"))
        + _glob.glob(os.path.join(job_dir, "trace.jsonl"))
    )


def load_spans(paths) -> list[dict]:
    """Span records from one or more trace files, time-ordered. Torn
    trailing lines (a writer killed mid-append) are skipped."""
    if isinstance(paths, str):
        paths = [paths]
    out: list[dict] = []
    for path in paths:
        try:
            with open(path) as f:
                lines = f.readlines()
        except OSError:
            continue
        for ln in lines:
            ln = ln.strip()
            if not ln:
                continue
            try:
                rec = json.loads(ln)
            except json.JSONDecodeError:
                continue
            if isinstance(rec, dict) and "trace_id" in rec:
                out.append(rec)
    out.sort(key=lambda r: r.get("ts_unix", 0.0))
    return out


def trace_summary(spans: list[dict]) -> dict:
    """Connectivity + hygiene summary: the chaos gate's questions.
    ``connected`` is True when every span shares one trace id;
    ``unclosed`` counts spans that never recorded a duration (a span
    record without ``dur_s`` can only come from a writer bug — killed
    writers simply don't write — so the gate pins it at zero)."""
    trace_ids = sorted({s.get("trace_id", "") for s in spans})
    workers = sorted({s.get("worker", "") for s in spans if s.get("worker")})
    unclosed = sum(
        1 for s in spans
        if not isinstance(s.get("dur_s"), (int, float))
    )
    # flow linkage: a flow id is "linked" when spans from more than
    # one worker process carry it (the gang-barrier invariant)
    flow_workers: dict[int, set] = {}
    for s in spans:
        fid = s.get("flow_id")
        if isinstance(fid, int):
            flow_workers.setdefault(fid, set()).add(
                s.get("worker") or f"pid{s.get('pid', 0)}"
            )
    return {
        "n_spans": len(spans),
        "trace_ids": trace_ids,
        "connected": len(trace_ids) == 1 and bool(spans),
        "workers": workers,
        "unclosed": unclosed,
        "forced_ends": sum(1 for s in spans if s.get("forced_end")),
        "span_names": sorted({s.get("name", "") for s in spans}),
        "n_flows": len(flow_workers),
        "flows_linked": sum(
            1 for ws in flow_workers.values() if len(ws) > 1
        ),
    }


def export_chrome_trace(
    spans: list[dict], extra_instants: list[dict] | None = None
) -> dict:
    """Merge span records into Chrome trace-event JSON (Perfetto
    loads it directly). One "process" track per worker, named via
    metadata events; timestamps are microseconds relative to the
    earliest span so the viewer opens at t=0. ``extra_instants``
    (e.g. autoscale decisions) are campaign-level events rendered on
    their own track: dicts with name/ts_unix[/args]."""
    extra = list(extra_instants or [])
    all_ts = [
        s["ts_unix"]
        for s in spans + extra
        if isinstance(s.get("ts_unix"), (int, float))
    ]
    t0 = min(all_ts) if all_ts else 0.0
    workers = sorted(
        {s.get("worker") or f"pid{s.get('pid', 0)}" for s in spans}
    )
    pid_of = {w: i + 1 for i, w in enumerate(workers)}
    events: list[dict] = []
    for w in workers:
        events.append(
            {
                "ph": "M", "name": "process_name", "pid": pid_of[w],
                "tid": 0, "args": {"name": w},
            }
        )
    flow_members: dict[int, list[dict]] = {}
    for s in spans:
        w = s.get("worker") or f"pid{s.get('pid', 0)}"
        ts_us = (float(s.get("ts_unix", t0)) - t0) * 1e6
        args = dict(s.get("args") or {})
        args["trace_id"] = s.get("trace_id")
        base = {
            "name": s.get("name", "?"),
            "cat": s.get("cat", "job"),
            "pid": pid_of[w],
            "tid": str(s.get("tid", "main")),
            "ts": round(ts_us, 1),
            "args": args,
        }
        if s.get("instant"):
            events.append({**base, "ph": "i", "s": "t"})
        else:
            events.append(
                {
                    **base,
                    "ph": "X",
                    "dur": round(
                        max(0.0, float(s.get("dur_s") or 0.0)) * 1e6, 1
                    ),
                }
            )
            fid = s.get("flow_id")
            if isinstance(fid, int):
                flow_members.setdefault(fid, []).append(base)
    # flow arrows: one s → t... → f chain per flow id, each event
    # bound to (same pid/tid/ts as) the slice that carries the id
    for fid, members in sorted(flow_members.items()):
        if len(members) < 2:
            continue
        members.sort(key=lambda b: b["ts"])
        for i, b in enumerate(members):
            ph = "s" if i == 0 else ("f" if i == len(members) - 1 else "t")
            fev = {
                "name": b["name"],
                "cat": b["cat"],
                "ph": ph,
                "id": fid,
                "pid": b["pid"],
                "tid": b["tid"],
                "ts": b["ts"],
            }
            if ph == "f":
                fev["bp"] = "e"  # bind to enclosing slice
            events.append(fev)
    if extra:
        apid = len(workers) + 1
        events.append(
            {
                "ph": "M", "name": "process_name", "pid": apid,
                "tid": 0, "args": {"name": "campaign"},
            }
        )
        for e in extra:
            events.append(
                {
                    "name": e.get("name", "?"),
                    "cat": e.get("cat", "campaign"),
                    "ph": "i",
                    "s": "p",
                    "pid": apid,
                    "tid": "autoscale",
                    "ts": round(
                        (float(e.get("ts_unix", t0)) - t0) * 1e6, 1
                    ),
                    "args": dict(e.get("args") or {}),
                }
            )
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "schema": TRACE_SCHEMA,
            "version": TRACE_VERSION,
            "trace_ids": sorted({s.get("trace_id", "") for s in spans}),
            "t0_unix": t0,
        },
    }
