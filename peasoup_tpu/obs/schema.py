"""Manifest schema validation without a jsonschema dependency.

The telemetry manifest contract is pinned by a checked-in JSON Schema
(``obs/manifest.schema.json``); this module implements the small
draft-07 subset that schema actually uses — ``type`` (including union
lists), ``const``, ``enum``, ``minimum``, ``required``, ``properties``,
``additionalProperties`` (bool or schema) and ``items`` — so the
contract is machine-checked in CI (``scripts/check.sh`` validates the
test fixtures and a freshly generated manifest via
``python -m peasoup_tpu.tools.validate_manifest``) with zero third-party
packages. Validation failures raise :class:`SchemaError` with a JSON
path to the offending node.
"""

from __future__ import annotations

import json
import os

SCHEMA_PATH = os.path.join(
    os.path.dirname(__file__), "manifest.schema.json"
)

_TYPES = {
    "object": dict,
    "array": list,
    "string": str,
    "integer": int,
    "number": (int, float),
    "boolean": bool,
    "null": type(None),
}


class SchemaError(ValueError):
    """A manifest violated the checked-in schema."""


def _type_ok(value, name: str) -> bool:
    py = _TYPES.get(name)
    if py is None:
        raise SchemaError(f"schema uses unsupported type {name!r}")
    if isinstance(value, bool) and name in ("integer", "number"):
        return False  # bool is an int subclass; JSON types disagree
    return isinstance(value, py)


def validate(instance, schema: dict, path: str = "$") -> None:
    """Validate ``instance`` against the supported draft-07 subset,
    raising :class:`SchemaError` (with a JSON path) on the first
    violation."""
    if "const" in schema and instance != schema["const"]:
        raise SchemaError(
            f"{path}: expected const {schema['const']!r}, "
            f"got {instance!r}"
        )
    if "enum" in schema and instance not in schema["enum"]:
        raise SchemaError(
            f"{path}: {instance!r} not one of {schema['enum']!r}"
        )
    t = schema.get("type")
    if t is not None:
        names = t if isinstance(t, list) else [t]
        if not any(_type_ok(instance, n) for n in names):
            raise SchemaError(
                f"{path}: expected type {'/'.join(names)}, "
                f"got {type(instance).__name__}"
            )
    if isinstance(instance, (int, float)) and not isinstance(
        instance, bool
    ):
        if "minimum" in schema and instance < schema["minimum"]:
            raise SchemaError(
                f"{path}: {instance!r} < minimum {schema['minimum']!r}"
            )
    if isinstance(instance, dict):
        for key in schema.get("required", ()):
            if key not in instance:
                raise SchemaError(f"{path}: missing required key {key!r}")
        props = schema.get("properties", {})
        for key, sub in props.items():
            if key in instance:
                validate(instance[key], sub, f"{path}.{key}")
        extra = schema.get("additionalProperties")
        if extra is False:
            unknown = set(instance) - set(props)
            if unknown:
                raise SchemaError(
                    f"{path}: unexpected keys {sorted(unknown)!r}"
                )
        elif isinstance(extra, dict):
            for key, val in instance.items():
                if key not in props:
                    validate(val, extra, f"{path}.{key}")
    if isinstance(instance, list):
        items = schema.get("items")
        if isinstance(items, dict):
            for i, val in enumerate(instance):
                validate(val, items, f"{path}[{i}]")


def load_schema() -> dict:
    with open(SCHEMA_PATH) as f:
        return json.load(f)


def validate_manifest(man: dict) -> None:
    """Validate a telemetry manifest dict against the checked-in
    schema (raises :class:`SchemaError` on violation)."""
    validate(man, load_schema())
