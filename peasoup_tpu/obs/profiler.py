"""On-demand device profiling of a LIVE worker.

"Which kernel is this worker stuck in" is a question operators ask
about a process they did not start with profiling enabled. The
fleet-side protocol (campaign/registry.py) is a ``profile.request``
file beside the worker's registry entry — written by
``peasoup-campaign profile``, observed by the worker's lease-renewer
beat (busy worker) or claim loop (idle worker) — and this module is
the worker-side capture: a **bounded** ``jax.profiler`` trace into the
campaign's ``profiles/`` directory, announced in the worker's metrics
stream and telemetry so the capture itself is observable.

The capture is guarded: on the CPU backend the XLA profiler has
nothing useful to say (and the CI soaks run on CPU), so the request is
acknowledged as a structured no-op unless ``allow_cpu`` forces it —
the protocol round-trips everywhere, the device cost is only ever
paid on a real accelerator.
"""

from __future__ import annotations

import os
import time

from .log import get_logger

log = get_logger("obs.profiler")

# hard ceiling on a requested capture: profiling costs device memory
# and wall time, and a fat-fingered request must not profile for hours
MAX_CAPTURE_S = 60.0
DEFAULT_CAPTURE_S = 5.0


def capture_device_profile(
    outdir: str,
    duration_s: float = DEFAULT_CAPTURE_S,
    allow_cpu: bool = False,
    telemetry=None,
) -> dict:
    """Run one bounded ``jax.profiler`` capture into ``outdir``.

    Returns a structured outcome dict (always — failures are reported,
    never raised: a broken profiler must not take the worker down):
    ``{"captured": bool, "skipped": reason|None, "seconds": float,
    "outdir": path|None, "backend": str}``.
    """
    duration_s = max(0.1, min(float(duration_s), MAX_CAPTURE_S))
    t0 = time.perf_counter()
    backend = "unknown"
    outcome: dict = {
        "captured": False,
        "skipped": None,
        "seconds": 0.0,
        "outdir": None,
        "backend": backend,
        "requested_s": duration_s,
    }
    try:
        import jax

        backend = jax.default_backend()
        outcome["backend"] = backend
    except Exception as exc:
        outcome["skipped"] = f"jax unavailable: {exc!s:.120}"
        return _announce(outcome, telemetry)
    if backend == "cpu" and not allow_cpu:
        # guarded no-op: the protocol completes, the cost is not paid
        outcome["skipped"] = "cpu backend (no device profile to take)"
        log.info(
            "profile request acknowledged as a no-op on the CPU backend"
        )
        return _announce(outcome, telemetry)
    try:
        os.makedirs(outdir, exist_ok=True)
        jax.profiler.start_trace(outdir)
        try:
            time.sleep(duration_s)
        finally:
            jax.profiler.stop_trace()
        outcome["captured"] = True
        outcome["outdir"] = os.path.abspath(outdir)
        log.info(
            "device profile captured: %.3gs into %s", duration_s, outdir
        )
    except Exception as exc:
        outcome["skipped"] = f"{type(exc).__name__}: {exc!s:.200}"
        log.warning("device profile capture failed: %s", exc)
    outcome["seconds"] = round(time.perf_counter() - t0, 3)
    return _announce(outcome, telemetry)


def start_profile_capture(
    outdir: str,
    duration_s: float,
    metrics=None,
    telemetry=None,
    allow_cpu: bool = False,
):
    """Run :func:`capture_device_profile` on a daemon helper thread
    (under the resilience crash guard) so the caller's beat/claim loop
    never blocks on the capture; announces the outcome in ``metrics``
    (an obs.metrics.MetricsRecorder) — the capture is itself an
    observable fleet event. Returns the started thread."""
    import threading

    def _capture() -> None:
        outcome = capture_device_profile(
            outdir, duration_s=duration_s, telemetry=telemetry,
            allow_cpu=allow_cpu,
        )
        if metrics is not None:
            metrics.counter(
                "profile_captures_total",
                outcome=(
                    "captured" if outcome.get("captured") else "skipped"
                ),
            )
            metrics.gauge(
                "profile_capture_seconds", outcome.get("seconds", 0.0)
            )

    def _guarded() -> None:
        from ..resilience import guard_thread

        guard_thread("campaign-profile", _capture, telemetry=telemetry)

    thread = threading.Thread(
        target=_guarded, name="campaign-profile", daemon=True
    )
    thread.start()
    return thread


def _announce(outcome: dict, telemetry) -> dict:
    if telemetry is not None:
        try:
            telemetry.event("device_profile", **outcome)
        except Exception:
            pass
    return outcome
