"""Scientific data-quality sentinels for survey campaigns.

Fleet metrics (obs/metrics.py) say whether the MACHINERY is healthy;
nothing said whether the SCIENCE is: an RFI storm that zaps half the
band, a dead receiver polarisation, or a silently broken search all
complete "successfully". This module is the scientific health layer:

- :func:`observation_quality` — cheap per-job gauges computed from the
  filterbank already in memory (a bounded host-side pass, never the
  full observation): dead/RFI channel occupancy via robust per-channel
  statistics, quantisation clip/saturation fraction, and the
  candidate-rate per DM trial that PulsarX-style triage treats as the
  first-class RFI signal.
- per-campaign **baselines** — median/MAD of each gauge across the
  campaign's completed jobs (robust: one storm does not drag the
  baseline), and :func:`quality_findings` flagging jobs whose gauges
  sit beyond a z-score threshold — the ``data_quality`` alert feed.
- the **injection sentinel** — :func:`enqueue_sentinel` writes a
  synthetic observation with one dispersed pulse of KNOWN DM/arrival
  time (the chaos tool's injection recipe), enqueues it at low
  priority (it must never displace real observations), and records the
  ground truth under ``<root>/queue/sentinels/``;
  :func:`sentinel_status` checks each completed sentinel against the
  candidate database — an unrecovered injection means the search
  itself is broken, which no infrastructure metric can see — and
  :func:`sentinel_findings` turns misses into the ``sentinel`` alert
  feed.

Everything here is advisory: quality computation failures degrade to
"no gauges", never to a failed job.
"""

from __future__ import annotations

import json
import math
import os
import tempfile
import time
import uuid

import numpy as np

from .log import get_logger

log = get_logger("obs.health")

# the per-job gauges fed into campaign baselines (and recorded as
# dq_<name> metrics gauges by the runner)
QUALITY_METRICS = ("zap_fraction", "clip_fraction", "candidate_rate")

# MAD floors per metric: a perfectly clean campaign has zero spread,
# and a zero MAD would turn any nonzero gauge into an infinite z-score
_MAD_FLOOR = {
    "zap_fraction": 0.02,
    "clip_fraction": 0.02,
    "candidate_rate": 0.25,
}

# robust z threshold for a data_quality finding, and the minimum
# campaign size before baselines mean anything
DEFAULT_Z = 6.0
DEFAULT_MIN_N = 4

_SENTINELS = "sentinels"  # truth docs live under <root>/queue/sentinels/


# --------------------------------------------------------------------------
# per-observation quality gauges
# --------------------------------------------------------------------------

def observation_quality(
    data: np.ndarray,
    n_candidates: int = 0,
    n_dm_trials: int = 1,
    nbits: int | None = None,
    max_samples: int = 8192,
) -> dict:
    """Quality gauges for one observation's ``(nsamps, nchans)`` block.

    A strided subset of at most ``max_samples`` time samples keeps the
    cost bounded for long observations; the statistics are robust
    (median/MAD across channels), so the injected pulse itself never
    reads as RFI.
    """
    arr = np.asarray(data)
    if arr.ndim != 2 or arr.size == 0:
        return {}
    step = max(1, arr.shape[0] // int(max_samples))
    block = arr[::step].astype(np.float32)
    nchans = block.shape[1]

    ch_mean = block.mean(axis=0)
    ch_std = block.std(axis=0)
    med_std = float(np.median(ch_std))
    dead = ch_std < max(1e-6, 0.05 * med_std)

    # channel-power outliers: robust z of per-channel mean across the
    # band (a persistent narrowband carrier lifts the whole channel)
    med_mean = float(np.median(ch_mean))
    mad_mean = float(np.median(np.abs(ch_mean - med_mean)))
    mad_mean = max(mad_mean, 1e-3 * max(abs(med_mean), 1.0))
    z_power = np.abs(ch_mean - med_mean) / (1.4826 * mad_mean)
    # variance outliers catch impulsive RFI that keeps the mean flat
    mad_std = float(np.median(np.abs(ch_std - med_std)))
    mad_std = max(mad_std, 1e-3 * max(med_std, 1.0))
    z_var = np.abs(ch_std - med_std) / (1.4826 * mad_std)
    rfi = (~dead) & ((z_power > 8.0) | (z_var > 8.0))

    clip = 0.0
    if np.issubdtype(arr.dtype, np.integer):
        info = np.iinfo(arr.dtype)
        hi = (1 << int(nbits)) - 1 if nbits else info.max
        lo = info.min
        clip = float(np.mean((block <= lo) | (block >= hi)))
    elif np.issubdtype(arr.dtype, np.floating):
        clip = float(np.mean(~np.isfinite(block)))

    return {
        "zap_fraction": float((dead.sum() + rfi.sum()) / nchans),
        "dead_channels": float(dead.sum()),
        "rfi_channels": float(rfi.sum()),
        "clip_fraction": clip,
        "candidate_rate": float(n_candidates)
        / float(max(1, n_dm_trials)),
        "nchans": float(nchans),
    }


# --------------------------------------------------------------------------
# campaign baselines + findings
# --------------------------------------------------------------------------

def _quality_records(done_records: list[dict]) -> list[tuple[str, dict]]:
    """(job_id, quality) for real (non-sentinel) completed jobs."""
    out = []
    for rec in done_records or []:
        if rec.get("sentinel"):
            continue  # injections must not drag the science baseline
        q = rec.get("quality")
        if isinstance(q, dict) and q:
            out.append((str(rec.get("job_id", "?")), q))
    return out


def build_baselines(done_records: list[dict]) -> dict:
    """Median/MAD per quality metric across the campaign's completed
    jobs — the robust envelope a single storm cannot shift."""
    recs = _quality_records(done_records)
    out: dict = {}
    for metric in QUALITY_METRICS:
        vals = sorted(
            float(q[metric]) for _, q in recs
            if isinstance(q.get(metric), (int, float))
            and math.isfinite(float(q[metric]))
        )
        if not vals:
            continue
        med = vals[len(vals) // 2]
        mad = sorted(abs(v - med) for v in vals)[len(vals) // 2]
        out[metric] = {
            "median": med,
            "mad": mad,
            "n": len(vals),
        }
    return out


def quality_findings(
    done_records: list[dict],
    baselines: dict | None = None,
    z_threshold: float = DEFAULT_Z,
    min_n: int = DEFAULT_MIN_N,
) -> list[dict]:
    """Jobs whose quality gauges sit beyond ``z_threshold`` robust
    z-scores from the campaign baseline — the ``data_quality`` alert
    feed, in the engine's finding shape."""
    recs = _quality_records(done_records)
    if baselines is None:
        baselines = build_baselines(done_records)
    findings: list[dict] = []
    for metric in QUALITY_METRICS:
        base = baselines.get(metric)
        if not base or int(base.get("n", 0)) < int(min_n):
            continue
        scale = 1.4826 * max(
            float(base["mad"]), _MAD_FLOOR.get(metric, 0.05)
        )
        for job_id, q in recs:
            v = q.get(metric)
            if not isinstance(v, (int, float)) or not math.isfinite(
                float(v)
            ):
                continue
            z = (float(v) - float(base["median"])) / scale
            if abs(z) < float(z_threshold):
                continue
            findings.append({
                "labels": {"metric": metric, "job": job_id},
                "value": round(z, 3),
                "message": (
                    f"{metric}={float(v):.4g} on {job_id} is "
                    f"{z:+.1f} MADs from the campaign median "
                    f"{float(base['median']):.4g} (n={base['n']})"
                ),
            })
    return findings


def data_quality_summary(done_records: list[dict]) -> dict:
    """The rollup's ``data_quality`` section: baselines + outliers."""
    baselines = build_baselines(done_records)
    findings = quality_findings(done_records, baselines=baselines)
    return {
        "jobs": len(_quality_records(done_records)),
        "baselines": baselines,
        "outliers": findings,
    }


# --------------------------------------------------------------------------
# the injection sentinel
# --------------------------------------------------------------------------

def write_sentinel_observation(
    path: str,
    nsamps: int = 1 << 12,
    nchans: int = 8,
    seed: int = 7,
    amplitude: float = 15.0,
) -> dict:
    """Write one synthetic filterbank with a single dispersed pulse of
    known DM and arrival time (the chaos tool's injection recipe) and
    return the ground truth the recovery check needs."""
    from ..io.sigproc import (
        Filterbank,
        SigprocHeader,
        write_filterbank,
    )
    from ..plan.dm_plan import DMPlan

    tsamp, fch1, foff = 0.000256, 1400.0, -16.0
    plan = DMPlan.create(
        nsamps=nsamps, nchans=nchans, tsamp=tsamp, fch1=fch1, foff=foff,
        dm_start=0.0, dm_end=20.0, pulse_width=64.0, tol=1.10,
    )
    dm_idx = plan.ndm // 2
    delays = plan.delay_samples()[dm_idx]
    rng = np.random.default_rng(seed)
    data = rng.normal(32.0, 4.0, size=(nsamps, nchans))
    s0 = nsamps // 3
    for c in range(nchans):
        data[s0 + delays[c] : s0 + 4 + delays[c], c] += amplitude
    hdr = SigprocHeader(
        source_name="SENTINEL", tsamp=tsamp, tstart=55999.0,
        fch1=fch1, foff=foff, nchans=nchans, nbits=8, nifs=1,
        data_type=1,
    )
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    write_filterbank(
        path,
        Filterbank(
            header=hdr,
            data=np.clip(np.rint(data), 0, 255).astype(np.uint8),
        ),
    )
    return {
        "input": os.path.abspath(path),
        "dm": float(plan.dm_list[dm_idx]),
        "time_s": float(s0 * tsamp),
        "nsamps": int(nsamps),
    }


def _sentinel_dir(root: str) -> str:
    return os.path.join(os.path.abspath(root), "queue", _SENTINELS)


def _atomic_write_json(path: str, doc: dict) -> None:
    d = os.path.dirname(path) or "."
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(doc, f, indent=2)
            f.write("\n")
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def _read_json(path: str) -> dict | None:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def enqueue_sentinel(
    root: str,
    queue=None,
    data_dir: str | None = None,
    min_snr: float = 7.0,
    dm_tol: float = 5.0,
    time_tol_s: float = 0.05,
    priority: int = -1,
    nsamps: int = 1 << 12,
    seed: int | None = None,
) -> dict:
    """Inject one sentinel observation into a campaign: write the
    synthetic filterbank, enqueue it at low priority (it must never
    displace survey observations), and persist the ground truth for
    :func:`sentinel_status`. Returns the truth doc."""
    from ..campaign.queue import Job, JobQueue, job_id_for
    from ..campaign.runner import bucket_for_input

    root = os.path.abspath(root)
    if queue is None:
        queue = JobQueue(root)
    data_dir = data_dir or os.path.join(root, "sentinel_data")
    tag = uuid.uuid4().hex[:10]
    path = os.path.join(data_dir, f"sentinel_{tag}.fil")
    truth = write_sentinel_observation(
        path, nsamps=nsamps,
        seed=int(seed) if seed is not None else int(tag[:6], 16),
    )
    job_id = job_id_for(path)
    queue.add_job(Job(
        job_id=job_id,
        input=path,
        pipeline="spsearch",
        bucket=bucket_for_input(path),
        priority=int(priority),
        sentinel=True,
    ))
    doc = {
        **truth,
        "job_id": job_id,
        "min_snr": float(min_snr),
        "dm_tol": float(dm_tol),
        "time_tol_s": float(time_tol_s),
        "enqueued_unix": time.time(),
    }
    _atomic_write_json(
        os.path.join(_sentinel_dir(root), f"{job_id}.json"), doc
    )
    log.info(
        "sentinel enqueued: %s (dm %.2f, t %.3fs, min snr %.1f)",
        job_id, doc["dm"], doc["time_s"], doc["min_snr"],
    )
    return doc


def _sentinel_recovered(root: str, truth: dict) -> tuple[bool, str]:
    """Did the candidate database recover the injected pulse?"""
    from ..campaign.db import DB_FILENAME, CandidateDB

    db_path = os.path.join(root, DB_FILENAME)
    if not os.path.exists(db_path):
        return False, "candidate database missing"
    try:
        with CandidateDB(db_path) as db:
            cands = db.candidates_for(truth["job_id"])
    except Exception as exc:
        return False, f"candidate database unreadable: {exc!s:.120}"
    for c in cands:
        if c.get("kind") != "single_pulse":
            continue
        snr = float(c.get("snr") or 0.0)
        dm = float(c.get("dm") or 0.0)
        t = float(c.get("time_s") or -1e9)
        if (
            snr >= float(truth.get("min_snr", 0.0))
            and abs(dm - float(truth["dm"])) <= float(
                truth.get("dm_tol", 5.0)
            )
            and abs(t - float(truth["time_s"])) <= float(
                truth.get("time_tol_s", 0.05)
            )
        ):
            return True, (
                f"recovered at dm {dm:.2f}, t {t:.3f}s, snr {snr:.1f}"
            )
    return False, (
        f"no candidate within dm±{truth.get('dm_tol', 5.0):.1f} / "
        f"t±{truth.get('time_tol_s', 0.05):.3f}s at snr>="
        f"{truth.get('min_snr', 0.0):.1f} among {len(cands)}"
    )


def sentinel_status(root: str, queue=None) -> list[dict]:
    """Recovery status of every sentinel injection in a campaign:
    ``pending`` (not yet searched), ``recovered``, or ``missed``
    (searched but the known pulse did not come back — the search is
    broken)."""
    root = os.path.abspath(root)
    sdir = _sentinel_dir(root)
    try:
        names = sorted(
            n for n in os.listdir(sdir) if n.endswith(".json")
        )
    except OSError:
        return []
    out = []
    for name in names:
        truth = _read_json(os.path.join(sdir, name))
        if not truth or "job_id" not in truth:
            continue
        jid = truth["job_id"]
        done = _read_json(
            os.path.join(root, "queue", "done", f"{jid}.json")
        )
        ent = {
            "job_id": jid,
            "dm": truth.get("dm"),
            "time_s": truth.get("time_s"),
            "min_snr": truth.get("min_snr"),
            "enqueued_unix": truth.get("enqueued_unix"),
        }
        if done is None:
            quarantined = os.path.exists(
                os.path.join(root, "queue", "quarantine", f"{jid}.json")
            )
            if quarantined:
                ent.update(
                    status="missed",
                    detail="sentinel job quarantined before searching",
                )
            else:
                ent["status"] = "pending"
            out.append(ent)
            continue
        ok, detail = _sentinel_recovered(root, truth)
        ent.update(
            status="recovered" if ok else "missed", detail=detail
        )
        out.append(ent)
    return out


def sentinel_findings(root: str, queue=None) -> list[dict]:
    """Missed sentinels in the alert engine's finding shape."""
    out = []
    for ent in sentinel_status(root, queue=queue):
        if ent.get("status") != "missed":
            continue
        out.append({
            "labels": {"job": str(ent["job_id"])},
            "value": 1.0,
            "message": (
                f"sentinel injection {ent['job_id']} not recovered: "
                f"{ent.get('detail', '')}"
            ),
        })
    return out
