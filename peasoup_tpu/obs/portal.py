"""Per-campaign live status portal (stdlib HTTP, read-only).

One scrape target and one operator URL per campaign: the GSP-style
serving layer the survey-as-a-service direction needs, with zero new
dependencies. The server only ever READS the campaign tree's atomic
artifacts (every one is published via tmp + ``os.replace`` or
append-only JSONL), so it can run beside any number of workers — or on
a different host sharing the campaign filesystem — without joining any
protocol.

Endpoints:

- ``/metrics`` — Prometheus exposition over every worker's time series
  plus the ``ALERTS`` convention series from the alerts snapshot.
- ``/status`` — the campaign rollup JSON (the ``campaign_status.json``
  the workers maintain; rebuilt in-memory when absent).
- ``/alerts`` — the alerts snapshot JSON.
- ``/jobs/<id>`` — one job's queue record, done record, quarantine
  record and trace summary.
- ``/report`` and ``/bowtie.svg`` — the sift HTML report and bowtie
  plot when the campaign has been sifted.
- ``/tenants`` and ``/tenants/<name>`` — the multi-tenant view: per
  tenant queue tallies, quota vs windowed device-seconds, usage
  ledger, firing alerts, per-tenant sift/bowtie links.
- ``/candidates`` (and ``/tenants/<name>/candidates``) — the ranked
  triage table: score-tier tallies + top candidates, read READ-ONLY
  from the sifted candidates.sqlite.
- ``/usage`` — the usage ledger JSON (``queue/usage.json`` content,
  rebuilt in-memory when absent).
- ``/`` — a small HTML index linking the above.

One WRITE endpoint: ``POST /submit`` — the tenant submission front
end. Authenticated by bearer token (``Authorization: Bearer <token>``
or ``X-Peasoup-Token``) against the tenant registry; the JSON body
``{"input": ..., "priority"?, "config"?, "pipeline"?}`` is admitted
through campaign/ingest.submit_observation (quota-checked, journaled
append-only to ``queue/submissions.jsonl``). The ``input`` path is
CONFINED: it must resolve (realpath, so symlinks cannot escape) under
the tenant's own ``watch_dir`` or an operator-configured ``--data-root``
— otherwise 403. A token only authenticates a tenant; it must not let
them enqueue arbitrary server-readable files (another tenant's drops,
host configuration) for the pipeline to open.
"""

from __future__ import annotations

import html
import json
import os

from .log import get_logger

log = get_logger("obs.portal")

_JOB_ID_OK = frozenset(
    "abcdefghijklmnopqrstuvwxyz"
    "ABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789._-"
)


def _read_json(path: str) -> dict | None:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def _metrics_body(root: str) -> bytes:
    from .alerts import alerts_exposition, load_alerts
    from .metrics import fleet_samples, prometheus_exposition

    body = prometheus_exposition(fleet_samples(root))
    body += alerts_exposition(load_alerts(root))
    return body.encode()


def _status_body(root: str) -> bytes:
    doc = _read_json(os.path.join(root, "campaign_status.json"))
    if doc is None:
        from ..campaign.rollup import build_status

        doc = build_status(root)
    return (json.dumps(doc, indent=2) + "\n").encode()


def _alerts_body(root: str) -> bytes:
    from .alerts import load_alerts

    return (json.dumps(load_alerts(root), indent=2) + "\n").encode()


def _job_body(root: str, job_id: str) -> bytes | None:
    if not job_id or any(c not in _JOB_ID_OK for c in job_id):
        return None
    job = _read_json(
        os.path.join(root, "queue", "jobs", f"{job_id}.json")
    )
    if job is None:
        return None
    from .trace import load_spans, trace_paths, trace_summary

    doc = {
        "job": job,
        "done": _read_json(
            os.path.join(root, "queue", "done", f"{job_id}.json")
        ),
        "quarantine": _read_json(
            os.path.join(root, "queue", "quarantine", f"{job_id}.json")
        ),
        "trace": trace_summary(
            load_spans(trace_paths(os.path.join(root, "jobs", job_id)))
        ),
    }
    return (json.dumps(doc, indent=2) + "\n").encode()


def _file_body(path: str) -> bytes | None:
    try:
        with open(path, "rb") as f:
            return f.read()
    except OSError:
        return None


def _input_allowed(input_path: str, roots: list[str]) -> bool:
    """Realpath-prefix confinement for HTTP-submitted inputs: the
    fully-resolved path must sit under one of ``roots`` (each itself
    resolved), so neither ``..`` segments nor symlinks reach outside.
    Empty ``roots`` allows nothing — the HTTP door is deny-by-default."""
    rp = os.path.realpath(input_path)
    for root in roots:
        if not root:
            continue
        rr = os.path.realpath(root)
        if rp == rr or rp.startswith(rr + os.sep):
            return True
    return False


def _tenant_sections(root: str) -> tuple[dict, dict]:
    """(tenants, usage) rollup sections — from the workers' snapshot
    when it carries them, rebuilt in-memory otherwise (pre-tenant
    snapshots lack the keys)."""
    st = _read_json(os.path.join(root, "campaign_status.json"))
    if not st or "tenants" not in st:
        from ..campaign.rollup import build_status

        st = build_status(root)
    return (st.get("tenants") or {}), (st.get("usage") or {})


def _tenant_alerts(root: str, name: str | None = None) -> list[dict]:
    """Active alerts labelled with a tenant (optionally one tenant)."""
    from .alerts import load_alerts

    out = []
    for a in load_alerts(root).get("alerts", []):
        if a.get("state") not in ("pending", "firing"):
            continue
        t = (a.get("labels") or {}).get("tenant")
        if not t or (name is not None and t != name):
            continue
        out.append(a)
    return out


def _usage_body(root: str) -> bytes:
    from ..campaign.usage import build_usage, load_usage

    doc = load_usage(root) or build_usage(root)
    return (json.dumps(doc, indent=2) + "\n").encode()


def _tenants_body(root: str) -> bytes:
    tenants, usage = _tenant_sections(root)
    firing: dict[str, int] = {}
    for a in _tenant_alerts(root):
        t = (a.get("labels") or {}).get("tenant", "")
        firing[t] = firing.get(t, 0) + 1
    rows = []
    for name in sorted(tenants):
        rec = tenants[name] or {}
        u = usage.get(name) or {}
        budget = rec.get("device_s_budget")
        wdev = rec.get("window_device_s")
        budget_cell = (
            f"{wdev:.1f} / {budget:.0f}s"
            if budget and wdev is not None
            else (f"{wdev:.1f}s" if wdev is not None else "-")
        )
        safe = html.escape(str(name))
        rows.append(
            f'<tr><td><a href="/tenants/{safe}">{safe}</a></td>'
            f"<td>{rec.get('queued', 0)}</td>"
            f"<td>{rec.get('running', 0)}</td>"
            f"<td>{rec.get('throttled', 0)}</td>"
            f"<td>{rec.get('done', 0)}</td>"
            f"<td>{html.escape(budget_cell)}</td>"
            f"<td>{u.get('jit_programs_compiled', 0)}</td>"
            f"<td>{firing.get(name, 0)}</td>"
            f"<td>{html.escape(str(rec.get('throttle') or '-'))}</td>"
            "</tr>"
        )
    doc = (
        "<!DOCTYPE html><html><head><title>tenants</title></head>"
        "<body><h1>tenants</h1>"
        "<table border=1><tr><th>tenant</th><th>queued</th>"
        "<th>running</th><th>throttled</th><th>done</th>"
        "<th>device-s (window/budget)</th><th>compiles</th>"
        "<th>alerts</th><th>throttle</th></tr>"
        + "".join(rows)
        + '</table><p><a href="/usage">usage ledger (JSON)</a> · '
        '<a href="/">index</a></p></body></html>'
    )
    return doc.encode()


def _tenant_page_body(root: str, name: str) -> bytes | None:
    from ..campaign.tenants import valid_tenant_name

    if not valid_tenant_name(name):
        return None
    tenants, usage = _tenant_sections(root)
    if name not in tenants and name not in usage:
        return None
    rec = tenants.get(name) or {}
    u = usage.get(name) or {}
    safe = html.escape(name)

    def _table(d: dict) -> str:
        return "<table border=1>" + "".join(
            f"<tr><td>{html.escape(str(k))}</td>"
            f"<td>{html.escape(json.dumps(v))}</td></tr>"
            for k, v in sorted(d.items())
        ) + "</table>"

    alerts = _tenant_alerts(root, name)
    alert_lines = "".join(
        f"<li>{html.escape(a.get('rule', ''))} "
        f"[{html.escape(a.get('state', ''))}] "
        f"{html.escape(a.get('message', ''))}</li>"
        for a in alerts
    ) or "<li>none</li>"
    from ..campaign.ingest import read_submissions

    subs = [
        s for s in read_submissions(root)
        # the journal also carries tenant_admin audit entries (token
        # rotation, quota edits) — not submissions, so not listed here
        if s.get("tenant") == name and s.get("kind") != "tenant_admin"
    ][-20:]
    sub_lines = "".join(
        f"<li>{html.escape(str(s.get('input', '')))} via "
        f"{html.escape(str(s.get('via', '')))}: "
        f"{'accepted' if s.get('accepted') else 'rejected'}"
        f"{' (' + html.escape(str(s['reason'])) + ')' if s.get('reason') else ''}"
        "</li>"
        for s in subs
    ) or "<li>none</li>"
    doc = (
        f"<!DOCTYPE html><html><head><title>tenant {safe}</title>"
        f"</head><body><h1>tenant {safe}</h1>"
        f"<h2>queue</h2>{_table({k: v for k, v in rec.items() if k != 'quota'})}"
        f"<h2>quota</h2>{_table(rec.get('quota') or {})}"
        f"<h2>usage</h2>{_table(u)}"
        f"<h2>alerts</h2><ul>{alert_lines}</ul>"
        f"<h2>recent submissions</h2><ul>{sub_lines}</ul>"
        f'<p><a href="/tenants/{safe}/candidates">candidate '
        "triage</a> · "
        '<a href="/report">sift report</a> · '
        '<a href="/bowtie.svg">bowtie</a> · '
        '<a href="/tenants">all tenants</a></p>'
        "</body></html>"
    )
    return doc.encode()


def _candidates_body(
    root: str, tenant: str | None = None, limit: int = 50
) -> bytes | None:
    """The triage page: score-tier tallies + the top-N sifted
    candidates, read directly (and READ-ONLY — the portal must never
    migrate or write a database it merely renders) from the campaign's
    candidates.sqlite. ``tenant`` narrows to rows touching that
    tenant's observations. Tolerates a pre-ranking (v3) database: the
    score columns simply read as absent."""
    import sqlite3

    if tenant is not None:
        from ..campaign.tenants import valid_tenant_name

        if not valid_tenant_name(tenant):
            return None
    db_path = os.path.join(root, "candidates.sqlite")
    if not os.path.exists(db_path):
        return None
    try:
        conn = sqlite3.connect(f"file:{db_path}?mode=ro", uri=True)
    except sqlite3.Error:
        return None
    try:
        conn.row_factory = sqlite3.Row
        cols = {
            r[1]
            for r in conn.execute(
                "PRAGMA table_info(sift_candidates)"
            )
        }
        if not cols:
            return None  # no sift product in this database yet
        has_scores = "score" in cols
        score_sel = (
            "score, score_tier, model_fp"
            if has_scores
            else "NULL AS score, NULL AS score_tier, "
            "NULL AS model_fp"
        )
        rows = [
            dict(r)
            for r in conn.execute(
                f"SELECT label, tier, {score_sel}, dm, snr, period, "
                "folded_snr, n_obs, job_ids FROM sift_candidates "
                "ORDER BY (score IS NULL), score DESC, snr DESC"
            )
        ]
        keep_jobs = None
        if tenant is not None:
            keep_jobs = {
                r[0]
                for r in conn.execute(
                    "SELECT job_id FROM observations "
                    "WHERE COALESCE(tenant, '') = ?",
                    (tenant,),
                )
            }
    except sqlite3.Error:
        return None
    finally:
        conn.close()
    if keep_jobs is not None:
        rows = [
            r for r in rows
            if any(
                j in keep_jobs
                for j in json.loads(r.get("job_ids") or "[]")
            )
        ]
    tier_counts: dict[str, int] = {}
    model_fp = None
    for r in rows:
        st = r.get("score_tier")
        key = str(st) if st is not None else "unscored"
        tier_counts[key] = tier_counts.get(key, 0) + 1
        model_fp = model_fp or r.get("model_fp")
    tally = ", ".join(
        f"{tier_counts.get(k, 0)} {lbl}"
        for k, lbl in (
            ("1", "tier-1"), ("2", "tier-2"), ("3", "tier-3"),
            ("unscored", "unscored"),
        )
    )
    def _num(v, nd: int) -> str:
        return f"{v:.{nd}f}" if v is not None else "-"

    body_rows = []
    for r in rows[:limit]:
        st = r.get("score_tier")
        body_rows.append(
            "<tr>"
            f"<td>{_num(r.get('score'), 3)}</td>"
            f"<td>{st if st is not None else '-'}</td>"
            f"<td>{html.escape(str(r.get('label') or ''))}</td>"
            f"<td>{r.get('tier')}</td>"
            f"<td>{_num(r.get('period'), 6)}</td>"
            f"<td>{_num(r.get('dm'), 2)}</td>"
            f"<td>{_num(r.get('snr'), 1)}</td>"
            f"<td>{_num(r.get('folded_snr'), 1)}</td>"
            f"<td>{r.get('n_obs')}</td>"
            "</tr>"
        )
    title = "candidate triage" + (
        f" — tenant {html.escape(tenant)}" if tenant else ""
    )
    fp_line = (
        f"<p>ranked by model <code>{html.escape(str(model_fp))}"
        "</code></p>"
        if model_fp else "<p>no ranking scores recorded yet</p>"
    )
    doc = (
        f"<!DOCTYPE html><html><head><title>{title}</title></head>"
        f"<body><h1>{title}</h1>"
        f"<p>score tiers: {tally}</p>{fp_line}"
        "<table border=1><tr><th>score</th><th>s-tier</th>"
        "<th>label</th><th>tier</th><th>P (s)</th><th>DM</th>"
        "<th>S/N</th><th>folded S/N</th><th>obs</th></tr>"
        + "".join(body_rows)
        + '</table><p><a href="/report">sift report</a> · '
        '<a href="/">index</a></p></body></html>'
    )
    return doc.encode()


def _index_body(root: str) -> bytes:
    from .alerts import load_alerts

    snap = load_alerts(root)
    by_state: dict[str, int] = {}
    for a in snap.get("alerts", []):
        by_state[a["state"]] = by_state.get(a["state"], 0) + 1
    st = _read_json(os.path.join(root, "campaign_status.json")) or {}
    queue = st.get("queue") or {}
    rows = "".join(
        f"<tr><td>{html.escape(str(k))}</td>"
        f"<td>{html.escape(str(v))}</td></tr>"
        for k, v in sorted(queue.items())
    )
    alert_line = ", ".join(
        f"{by_state.get(s, 0)} {s}"
        for s in ("firing", "pending", "resolved")
    )
    doc = (
        "<!DOCTYPE html><html><head><title>peasoup campaign</title>"
        "</head><body>"
        f"<h1>campaign {html.escape(os.path.basename(root) or root)}"
        "</h1>"
        f"<p>alerts: {alert_line}</p>"
        f"<table>{rows}</table>"
        '<ul><li><a href="/metrics">/metrics</a></li>'
        '<li><a href="/status">/status</a></li>'
        '<li><a href="/alerts">/alerts</a></li>'
        '<li><a href="/tenants">/tenants</a></li>'
        '<li><a href="/usage">/usage</a></li>'
        '<li><a href="/candidates">candidate triage</a></li>'
        '<li><a href="/report">sift report</a></li>'
        '<li><a href="/bowtie.svg">bowtie</a></li></ul>'
        "</body></html>"
    )
    return doc.encode()


def serve_portal(
    root: str,
    port: int = 9100,
    host: str = "127.0.0.1",
    max_requests: int | None = None,
    data_roots: list[str] | None = None,
) -> None:
    """Serve the campaign portal. Blocks; ``max_requests`` bounds it
    for tests and the check gate. ``data_roots`` are the operator's
    shared staging directories HTTP-submitted inputs may come from (a
    tenant's own ``watch_dir`` is always allowed); with none configured
    and no watch_dir, POST /submit rejects every path with 403."""
    from http.server import BaseHTTPRequestHandler, HTTPServer

    root = os.path.abspath(root)
    data_roots = [d for d in (data_roots or []) if d]

    class _Handler(BaseHTTPRequestHandler):
        def do_GET(self) -> None:  # noqa: N802 (http.server contract)
            try:
                body, ctype = self._route(self.path)
            except Exception as exc:
                self.send_error(500, f"{type(exc).__name__}: {exc}")
                return
            if body is None:
                self.send_error(404)
                return
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _route(self, path: str):
            path = path.split("?", 1)[0].rstrip("/") or "/"
            if path == "/":
                return _index_body(root), "text/html; charset=utf-8"
            if path == "/metrics":
                return _metrics_body(root), "text/plain; version=0.0.4"
            if path == "/status":
                return _status_body(root), "application/json"
            if path == "/alerts":
                return _alerts_body(root), "application/json"
            if path == "/usage":
                return _usage_body(root), "application/json"
            if path == "/candidates":
                return (
                    _candidates_body(root),
                    "text/html; charset=utf-8",
                )
            if path == "/tenants":
                return _tenants_body(root), "text/html; charset=utf-8"
            if path.startswith("/tenants/") and path.endswith(
                "/candidates"
            ):
                name = path[len("/tenants/"):-len("/candidates")]
                return (
                    _candidates_body(root, tenant=name),
                    "text/html; charset=utf-8",
                )
            if path.startswith("/tenants/"):
                return (
                    _tenant_page_body(root, path[len("/tenants/"):]),
                    "text/html; charset=utf-8",
                )
            if path.startswith("/jobs/"):
                return (
                    _job_body(root, path[len("/jobs/"):]),
                    "application/json",
                )
            if path == "/report":
                return (
                    _file_body(
                        os.path.join(root, "sift", "report.html")
                    ),
                    "text/html; charset=utf-8",
                )
            if path == "/bowtie.svg":
                return (
                    _file_body(
                        os.path.join(root, "sift", "bowtie.svg")
                    ),
                    "image/svg+xml",
                )
            return None, ""

        def do_POST(self) -> None:  # noqa: N802 (http.server contract)
            try:
                self._post()
            except Exception as exc:
                self.send_error(500, f"{type(exc).__name__}: {exc}")

        def _post(self) -> None:
            path = self.path.split("?", 1)[0].rstrip("/")
            if path != "/submit":
                self.send_error(404)
                return
            from ..campaign.ingest import submit_observation
            from ..campaign.tenants import TenantRegistry

            token = ""
            auth = self.headers.get("Authorization") or ""
            if auth.lower().startswith("bearer "):
                token = auth[len("bearer "):].strip()
            if not token:
                token = (self.headers.get("X-Peasoup-Token") or "").strip()
            tenant = TenantRegistry(root).by_token(token)
            if tenant is None:
                self._json(401, {"error": "missing or invalid token"})
                return
            try:
                length = int(self.headers.get("Content-Length") or 0)
            except ValueError:
                length = 0
            if length <= 0 or length > 1 << 20:
                self._json(400, {"error": "bad Content-Length"})
                return
            try:
                doc = json.loads(self.rfile.read(length))
            except (ValueError, OSError):
                self._json(400, {"error": "malformed JSON body"})
                return
            if not isinstance(doc, dict) or not isinstance(
                doc.get("input"), str
            ):
                self._json(400, {"error": 'body needs a string "input"'})
                return
            try:
                priority = int(doc.get("priority", 0))
            except (TypeError, ValueError):
                self._json(400, {"error": "priority must be an integer"})
                return
            config = doc.get("config")
            if config is not None and not isinstance(config, dict):
                self._json(400, {"error": "config must be an object"})
                return
            allowed = list(data_roots)
            if tenant.watch_dir:
                allowed.append(tenant.watch_dir)
            if not _input_allowed(doc["input"], allowed):
                import time

                from ..campaign.ingest import append_submission

                now_unix = time.time()
                entry = {
                    "t_unix": round(now_unix, 3),
                    "via": "http",
                    "tenant": tenant.name,
                    "input": doc["input"],
                    "pipeline": str(doc.get("pipeline") or "spsearch"),
                    "priority": priority,
                    "priority_capped": False,
                    "accepted": False,
                    "reason": (
                        "input outside the tenant watch_dir and the "
                        "portal --data-root allowlist"
                    ),
                    "job_id": None,
                }
                append_submission(root, entry)
                self._json(403, entry)
                return
            entry = submit_observation(
                root,
                tenant.name,
                doc["input"],
                priority=priority,
                config=config,
                pipeline=str(doc.get("pipeline") or "spsearch"),
                via="http",
            )
            if entry.get("accepted"):
                code = 200
            else:
                reason = str(entry.get("reason") or "")
                if reason.startswith("duplicate"):
                    code = 409
                elif reason.startswith("max_queued"):
                    code = 429
                else:
                    code = 400
            self._json(code, entry)

        def _json(self, code: int, doc: dict) -> None:
            body = (json.dumps(doc) + "\n").encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, fmt, *args) -> None:
            log.debug("portal http: " + fmt, *args)

    server = HTTPServer((host, port), _Handler)
    log.info(
        "serving campaign portal at http://%s:%d/ (root %s)",
        host, server.server_address[1], root,
    )
    try:
        if max_requests is None:
            server.serve_forever()
        else:
            for _ in range(max_requests):
                server.handle_request()
    finally:
        server.server_close()
