"""Per-campaign live status portal (stdlib HTTP, read-only).

One scrape target and one operator URL per campaign: the GSP-style
serving layer the survey-as-a-service direction needs, with zero new
dependencies. The server only ever READS the campaign tree's atomic
artifacts (every one is published via tmp + ``os.replace`` or
append-only JSONL), so it can run beside any number of workers — or on
a different host sharing the campaign filesystem — without joining any
protocol.

Endpoints:

- ``/metrics`` — Prometheus exposition over every worker's time series
  plus the ``ALERTS`` convention series from the alerts snapshot.
- ``/status`` — the campaign rollup JSON (the ``campaign_status.json``
  the workers maintain; rebuilt in-memory when absent).
- ``/alerts`` — the alerts snapshot JSON.
- ``/jobs/<id>`` — one job's queue record, done record, quarantine
  record and trace summary.
- ``/report`` and ``/bowtie.svg`` — the sift HTML report and bowtie
  plot when the campaign has been sifted.
- ``/`` — a small HTML index linking the above.
"""

from __future__ import annotations

import html
import json
import os

from .log import get_logger

log = get_logger("obs.portal")

_JOB_ID_OK = frozenset(
    "abcdefghijklmnopqrstuvwxyz"
    "ABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789._-"
)


def _read_json(path: str) -> dict | None:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def _metrics_body(root: str) -> bytes:
    from .alerts import alerts_exposition, load_alerts
    from .metrics import fleet_samples, prometheus_exposition

    body = prometheus_exposition(fleet_samples(root))
    body += alerts_exposition(load_alerts(root))
    return body.encode()


def _status_body(root: str) -> bytes:
    doc = _read_json(os.path.join(root, "campaign_status.json"))
    if doc is None:
        from ..campaign.rollup import build_status

        doc = build_status(root)
    return (json.dumps(doc, indent=2) + "\n").encode()


def _alerts_body(root: str) -> bytes:
    from .alerts import load_alerts

    return (json.dumps(load_alerts(root), indent=2) + "\n").encode()


def _job_body(root: str, job_id: str) -> bytes | None:
    if not job_id or any(c not in _JOB_ID_OK for c in job_id):
        return None
    job = _read_json(
        os.path.join(root, "queue", "jobs", f"{job_id}.json")
    )
    if job is None:
        return None
    from .trace import load_spans, trace_paths, trace_summary

    doc = {
        "job": job,
        "done": _read_json(
            os.path.join(root, "queue", "done", f"{job_id}.json")
        ),
        "quarantine": _read_json(
            os.path.join(root, "queue", "quarantine", f"{job_id}.json")
        ),
        "trace": trace_summary(
            load_spans(trace_paths(os.path.join(root, "jobs", job_id)))
        ),
    }
    return (json.dumps(doc, indent=2) + "\n").encode()


def _file_body(path: str) -> bytes | None:
    try:
        with open(path, "rb") as f:
            return f.read()
    except OSError:
        return None


def _index_body(root: str) -> bytes:
    from .alerts import load_alerts

    snap = load_alerts(root)
    by_state: dict[str, int] = {}
    for a in snap.get("alerts", []):
        by_state[a["state"]] = by_state.get(a["state"], 0) + 1
    st = _read_json(os.path.join(root, "campaign_status.json")) or {}
    queue = st.get("queue") or {}
    rows = "".join(
        f"<tr><td>{html.escape(str(k))}</td>"
        f"<td>{html.escape(str(v))}</td></tr>"
        for k, v in sorted(queue.items())
    )
    alert_line = ", ".join(
        f"{by_state.get(s, 0)} {s}"
        for s in ("firing", "pending", "resolved")
    )
    doc = (
        "<!DOCTYPE html><html><head><title>peasoup campaign</title>"
        "</head><body>"
        f"<h1>campaign {html.escape(os.path.basename(root) or root)}"
        "</h1>"
        f"<p>alerts: {alert_line}</p>"
        f"<table>{rows}</table>"
        '<ul><li><a href="/metrics">/metrics</a></li>'
        '<li><a href="/status">/status</a></li>'
        '<li><a href="/alerts">/alerts</a></li>'
        '<li><a href="/report">sift report</a></li>'
        '<li><a href="/bowtie.svg">bowtie</a></li></ul>'
        "</body></html>"
    )
    return doc.encode()


def serve_portal(
    root: str,
    port: int = 9100,
    host: str = "127.0.0.1",
    max_requests: int | None = None,
) -> None:
    """Serve the campaign portal. Blocks; ``max_requests`` bounds it
    for tests and the check gate."""
    from http.server import BaseHTTPRequestHandler, HTTPServer

    root = os.path.abspath(root)

    class _Handler(BaseHTTPRequestHandler):
        def do_GET(self) -> None:  # noqa: N802 (http.server contract)
            try:
                body, ctype = self._route(self.path)
            except Exception as exc:
                self.send_error(500, f"{type(exc).__name__}: {exc}")
                return
            if body is None:
                self.send_error(404)
                return
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _route(self, path: str):
            path = path.split("?", 1)[0].rstrip("/") or "/"
            if path == "/":
                return _index_body(root), "text/html; charset=utf-8"
            if path == "/metrics":
                return _metrics_body(root), "text/plain; version=0.0.4"
            if path == "/status":
                return _status_body(root), "application/json"
            if path == "/alerts":
                return _alerts_body(root), "application/json"
            if path.startswith("/jobs/"):
                return (
                    _job_body(root, path[len("/jobs/"):]),
                    "application/json",
                )
            if path == "/report":
                return (
                    _file_body(
                        os.path.join(root, "sift", "report.html")
                    ),
                    "text/html; charset=utf-8",
                )
            if path == "/bowtie.svg":
                return (
                    _file_body(
                        os.path.join(root, "sift", "bowtie.svg")
                    ),
                    "image/svg+xml",
                )
            return None, ""

        def log_message(self, fmt, *args) -> None:
            log.debug("portal http: " + fmt, *args)

    server = HTTPServer((host, port), _Handler)
    log.info(
        "serving campaign portal at http://%s:%d/ (root %s)",
        host, server.server_address[1], root,
    )
    try:
        if max_requests is None:
            server.serve_forever()
        else:
            for _ in range(max_requests):
                server.handle_request()
    finally:
        server.server_close()
