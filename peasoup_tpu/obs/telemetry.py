"""Run-scoped telemetry: the measurement layer under every BENCH entry.

One :class:`RunTelemetry` object lives for the duration of a pipeline
run (`PeasoupSearch.run`, the FFA search, the coincidencer). It
collects:

- **stage timers** — monotonic (``perf_counter``) per-stage wall time;
  the keys mirror the ``<execution_times>`` table in overview.xml,
- **counters / gauges** — trial counts, candidate counts per stage,
  per-device memory high-water marks (``device.memory_stats()`` where
  the backend reports them),
- **events** — every adaptive decision the driver takes (OOM
  shrink-retry with old/new ``dm_block``, Pallas-disable fallback,
  peak-compaction escalation, wave/chunk geometry, checkpoint resume)
  as structured records with a monotonic offset, replacing bare
  warnings that used to vanish with the terminal scrollback,
- **JIT stats** — compile/lowering counts and durations via
  ``jax.monitoring`` listeners,
- **device trace** (opt-in, ``--capture-device-trace``) — per-scope
  device-time and bytes-accessed attribution folded in from
  ``tools/scope_trace.py``'s profiler parsing.

The result serialises to a versioned ``telemetry.json`` run manifest
(written next to overview.xml by the `peasoup` CLI); render or diff
manifests with ``python -m peasoup_tpu.tools.report``.

Propagation is ambient: the driver calls :func:`current` to get the
run's telemetry (activated by the CLI via ``RunTelemetry.activate``),
so deep pipeline code records events without threading the object
through every signature. When nothing is active, :data:`NOOP` absorbs
every call at near-zero cost — library users who never asked for
telemetry pay nothing and no file is written.
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import os
import socket
import sys
import time

MANIFEST_SCHEMA = "peasoup_tpu.telemetry"
# v2: top-level process_index/process_count (per-host shard tagging for
# tools/report.py --merge) and the optional aborted/abort_reason pair
# written by the crash flight recorder (obs/flight.py). Readers must
# .get() keys newer than a manifest's version — see tools/report.py.
# v3: optional status sections (e.g. the streaming driver's
# ``streaming`` block) snapshotted into the manifest at write time.
MANIFEST_VERSION = 3

_ACTIVE: contextvars.ContextVar["RunTelemetry | None"] = (
    contextvars.ContextVar("peasoup_tpu_telemetry", default=None)
)

# jax.monitoring event-name substrings worth keeping (compile +
# lowering); everything else (tracing cache misses etc.) is noise here.
# "saved" events (e.g. compilation-cache compile_time_saved) are
# SAVINGS estimates, not durations — they can legitimately be negative
# on a slow cache hit and don't belong in a compile-time table.
_JIT_EVENT_KEYS = ("compile", "lower")
_JIT_EVENT_SKIP = ("saved",)
# count events worth keeping as counters: persistent compilation-cache
# traffic. A cache HIT still emits a backend_compile duration event
# (the executable deserialises inside the compile path), so "programs
# really compiled" is backend_compile count minus cache_hits — the
# split campaign done-records and bench.py report.
_JIT_COUNT_EVENT_MARK = "/jax/compilation_cache/"
_jit_listener_installed = False


def current() -> "RunTelemetry":
    """The active run's telemetry, or the module-level no-op sink."""
    return _ACTIVE.get() or NOOP


def _install_jit_listener() -> None:
    """One process-wide jax.monitoring listener forwarding to whatever
    telemetry is active at event time (the registry has no unregister,
    so per-run listeners would accumulate)."""
    global _jit_listener_installed
    if _jit_listener_installed:
        return
    _jit_listener_installed = True
    try:
        from jax import monitoring

        def _on_duration(event: str, duration: float, **kw) -> None:
            tel = _ACTIVE.get()
            if (
                tel is not None
                and duration >= 0  # durations only, not savings deltas
                and any(k in event for k in _JIT_EVENT_KEYS)
                and not any(k in event for k in _JIT_EVENT_SKIP)
            ):
                tel.record_jit(event, float(duration))

        monitoring.register_event_duration_secs_listener(_on_duration)

        def _on_event(event: str, **kw) -> None:
            tel = _ACTIVE.get()
            if tel is not None and _JIT_COUNT_EVENT_MARK in event:
                tel.incr(event.strip("/").replace("/", "."))

        monitoring.register_event_listener(_on_event)
    except Exception:
        pass  # no monitoring API: manifests simply lack jit stats


def persistent_cache_counters(tel: "RunTelemetry") -> tuple[int, int]:
    """(hits, misses) of the persistent XLA compilation cache recorded
    by this telemetry's run — both 0 when the cache is disabled."""
    return (
        int(tel.counters.get("jax.compilation_cache.cache_hits", 0)),
        int(tel.counters.get("jax.compilation_cache.cache_misses", 0)),
    )


class RunTelemetry:
    """Counters, gauges, stage timers and an event log for one run."""

    def __init__(
        self,
        run_id: str | None = None,
        capture_device_trace: bool = False,
        enabled: bool = True,
    ) -> None:
        self.enabled = enabled
        self.run_id = run_id or (
            time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
            + f"-{os.getpid()}"
        )
        self.capture_device_trace = capture_device_trace
        self.created_unix = time.time()
        self._t0 = time.perf_counter()
        self.context: dict = {}
        self.counters: dict[str, float] = {}
        self.gauges: dict[str, float] = {}
        self.timers: dict[str, float] = {}
        self.events: list[dict] = []
        self.jit: dict[str, list] = {}  # event -> [count, total_s]
        self.device_trace: dict | None = None
        # live state read by the heartbeat/flight-recorder layer
        self.current_stage: str | None = None
        self._stage_stack: list[str] = []
        self.progress_state: dict = {}
        self._listeners: list = []
        # named live-status providers (name -> zero-arg callable or
        # plain dict); snapshotted by the status.json heartbeat AND
        # into the manifest — how a long-lived driver (the streaming
        # loop) exposes a structured section without the heartbeat
        # knowing its schema
        self.status_sections: dict = {}
        if enabled:
            _install_jit_listener()
            # every run carries the process's resilience accounting
            # (retries, degradations, injected faults, thread crashes)
            # as a status section in status.json and the manifest.
            # stats.py is dependency-free, so no import cycle.
            from ..resilience.stats import STATS

            self.status_sections["resilience"] = STATS.snapshot

    # --- recording ----------------------------------------------------
    def set_context(self, **fields) -> None:
        """Free-form run context (command, input file, config knobs)."""
        if self.enabled:
            self.context.update(fields)

    def incr(self, name: str, by: float = 1) -> None:
        if self.enabled:
            self.counters[name] = self.counters.get(name, 0) + by

    def gauge(self, name: str, value: float) -> None:
        """Last-write-wins point-in-time value."""
        if self.enabled:
            self.gauges[name] = value

    def gauge_max(self, name: str, value: float) -> None:
        """High-water-mark gauge."""
        if self.enabled:
            self.gauges[name] = max(self.gauges.get(name, value), value)

    def event(self, kind: str, **fields) -> dict | None:
        """Append a structured record to the adaptive-event log. Field
        values must be JSON-serialisable (stringify exceptions)."""
        if not self.enabled:
            return None
        rec = {
            "t": round(time.perf_counter() - self._t0, 6),
            "kind": kind,
            **fields,
        }
        self.events.append(rec)
        for fn in self._listeners:
            try:
                fn(rec)
            except Exception:
                pass  # a broken listener must never fail the run
        return rec

    def set_status_section(self, name: str, provider) -> None:
        """Register a named status section: ``provider`` is a zero-arg
        callable returning a JSON-serialisable dict (or a plain dict).
        Heartbeat snapshots and the manifest embed it top-level under
        ``name`` (pick names the schema knows, e.g. ``streaming``)."""
        if self.enabled:
            self.status_sections[name] = provider

    def snapshot_sections(self) -> dict:
        """Evaluate every registered status section (a failing provider
        yields an ``error`` stub rather than failing the snapshot)."""
        out = {}
        for name, provider in self.status_sections.items():
            try:
                out[name] = provider() if callable(provider) else provider
            except Exception as exc:
                out[name] = {"error": f"{type(exc).__name__}: {exc!s:.200}"}
        return out

    def add_listener(self, fn) -> None:
        """Subscribe ``fn(record)`` to every event as it is recorded
        (the flight recorder's ring-buffer feed)."""
        if fn not in self._listeners:
            self._listeners.append(fn)

    def remove_listener(self, fn) -> None:
        if fn in self._listeners:
            self._listeners.remove(fn)

    def set_stage(self, name: str) -> None:
        """Mark the run's current pipeline stage (drivers that time
        stages manually call this at each phase boundary; drivers using
        :meth:`stage` get it for free). Recorded as a ``stage`` event so
        the flight recorder and manifest keep the transition history."""
        if not self.enabled or name == self.current_stage:
            return
        self.current_stage = name
        self.event("stage", name=name)

    def set_progress(
        self, done: float, total: float | None = None, unit: str = ""
    ) -> None:
        """Update the run's live progress counter (read by the
        status.json heartbeat for rate/ETA and by the stall watchdog)."""
        if not self.enabled:
            return
        self.progress_state = {
            "done": float(done),
            "total": float(total) if total is not None else None,
            "unit": unit,
            "t": round(time.perf_counter() - self._t0, 6),
            "updated_unix": time.time(),
        }

    @contextlib.contextmanager
    def stage(self, name: str):
        """Accumulating monotonic stage timer (same key space as the
        overview.xml ``<execution_times>`` table). Also tracks the
        run's *current* stage for the live status.json heartbeat."""
        t0 = time.perf_counter()
        if self.enabled:
            self._stage_stack.append(name)
            self.set_stage(name)
        try:
            yield
        finally:
            if self.enabled:
                self.timers[name] = self.timers.get(name, 0.0) + (
                    time.perf_counter() - t0
                )
                if self._stage_stack and self._stage_stack[-1] == name:
                    self._stage_stack.pop()
                if self._stage_stack:
                    self.set_stage(self._stage_stack[-1])

    def add_timer(self, name: str, seconds: float) -> None:
        """Merge an externally measured duration into a stage timer."""
        if self.enabled:
            self.timers[name] = self.timers.get(name, 0.0) + seconds

    def merge_timers(self, timers: dict[str, float]) -> None:
        for k, v in timers.items():
            self.add_timer(k, float(v))

    def record_jit(self, event: str, seconds: float) -> None:
        if self.enabled:
            st = self.jit.setdefault(event, [0, 0.0])
            st[0] += 1
            st[1] += seconds

    def capture_device_memory(self, tag: str) -> None:
        """Per-device memory high-water marks where the backend reports
        them (``memory_stats`` is absent on some backends, e.g. CPU)."""
        if not self.enabled:
            return
        try:
            import jax

            devs = jax.local_devices()
        except Exception:
            return
        peak = 0
        for d in devs:
            try:
                stats = d.memory_stats() or {}
            except Exception:
                stats = {}
            peak = max(
                peak,
                int(
                    stats.get("peak_bytes_in_use")
                    or stats.get("bytes_in_use")
                    or 0
                ),
            )
        if peak:
            self.gauge_max(f"memory.{tag}.peak_bytes", peak)
            self.gauge_max("memory.peak_bytes", peak)

    # --- activation ---------------------------------------------------
    @contextlib.contextmanager
    def activate(self):
        """Make this object the run's ambient telemetry (``current()``)
        for the duration of the with-block."""
        token = _ACTIVE.set(self if self.enabled else None)
        try:
            yield self
        finally:
            _ACTIVE.reset(token)

    @contextlib.contextmanager
    def device_capture(self):
        """Opt-in profiler capture: wrap the block in a
        ``jax.profiler.trace`` and fold the parsed per-scope
        device-time/bytes attribution (tools/scope_trace.py) into the
        manifest. No-op unless ``capture_device_trace`` was requested —
        tracing costs memory and wall time."""
        if not (self.enabled and self.capture_device_trace):
            yield
            return
        from ..tools.scope_trace import scope_trace

        with scope_trace() as res:
            yield
        self.device_trace = {
            "device_s": res.device_s,
            "phases": res.phase_seconds(),
            "table": [
                {"scope": k, "seconds": s, "gigabytes": gb}
                for k, s, gb in res.table()
            ],
        }

    # --- serialisation ------------------------------------------------
    def _platform(self) -> dict:
        info: dict = {"python": sys.version.split()[0]}
        try:
            import jax

            info["jax"] = jax.__version__
            info["backend"] = jax.default_backend()
            info["process_index"] = jax.process_index()
            info["process_count"] = jax.process_count()
            info["devices"] = [
                {
                    "id": d.id,
                    "platform": str(d.platform),
                    "kind": str(d.device_kind),
                }
                for d in jax.local_devices()
            ]
        except Exception:
            pass  # platform info must never fail a run
        return info

    def to_manifest(
        self, aborted: bool = False, abort_reason: str | None = None
    ) -> dict:
        """The versioned run manifest. Key order is fixed (schema and
        version lead) so manifests diff cleanly in text tools too.
        ``aborted=True`` marks a partial manifest dumped by the flight
        recorder for a run that did not complete."""
        plat = self._platform()
        man = {
            "schema": MANIFEST_SCHEMA,
            "version": MANIFEST_VERSION,
            "run_id": self.run_id,
            "created_unix": self.created_unix,
            "duration_s": round(time.perf_counter() - self._t0, 6),
            "hostname": socket.gethostname(),
            "pid": os.getpid(),
            # per-host shard tags, duplicated from platform so the
            # --merge reader need not reach into nested dicts
            "process_index": int(plat.get("process_index", 0)),
            "process_count": int(plat.get("process_count", 1)),
            "platform": plat,
            "context": self.context,
            "timers": {k: self.timers[k] for k in sorted(self.timers)},
            "counters": {
                k: self.counters[k] for k in sorted(self.counters)
            },
            "gauges": {k: self.gauges[k] for k in sorted(self.gauges)},
            "jit": {
                k: {"count": v[0], "seconds": v[1]}
                for k, v in sorted(self.jit.items())
            },
            "events": self.events,
            "device_trace": self.device_trace,
        }
        for name, val in self.snapshot_sections().items():
            if name not in man:  # sections can never shadow core keys
                man[name] = val
        if aborted:
            man["aborted"] = True
            man["abort_reason"] = abort_reason
            man["stage_at_abort"] = self.current_stage
            man["progress_at_abort"] = (
                dict(self.progress_state) if self.progress_state else None
            )
        return man

    def write(
        self,
        path: str,
        aborted: bool = False,
        abort_reason: str | None = None,
    ) -> dict:
        """Serialise the manifest to ``path`` (atomic replace) and
        return it."""
        man = self.to_manifest(aborted=aborted, abort_reason=abort_reason)
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(man, f, indent=2)
            f.write("\n")
        os.replace(tmp, path)
        return man


NOOP = RunTelemetry(enabled=False)


def load_manifest(path: str) -> dict:
    """Load + validate a telemetry.json manifest."""
    with open(path) as f:
        man = json.load(f)
    if man.get("schema") != MANIFEST_SCHEMA:
        raise ValueError(
            f"{path}: not a {MANIFEST_SCHEMA} manifest "
            f"(schema={man.get('schema')!r})"
        )
    if int(man.get("version", 0)) > MANIFEST_VERSION:
        raise ValueError(
            f"{path}: manifest version {man.get('version')} is newer "
            f"than this reader (supports <= {MANIFEST_VERSION})"
        )
    return man
