"""ctypes bindings for the native host runtime (libpeasoup_host.so).

Every entry point has a pure-Python/numpy fallback elsewhere in the
package; callers use :func:`available` / the None-returning loaders to
decide. The library builds on demand with the system g++.
"""

from __future__ import annotations

import ctypes
import os
from typing import Optional

import numpy as np

_lib: Optional[ctypes.CDLL] = None
_load_attempted = False

_i8p = np.ctypeslib.ndpointer(dtype=np.uint8, flags="C_CONTIGUOUS")
_i32p = np.ctypeslib.ndpointer(dtype=np.int32, flags="C_CONTIGUOUS")
_i64p = np.ctypeslib.ndpointer(dtype=np.int64, flags="C_CONTIGUOUS")
_f32p = np.ctypeslib.ndpointer(dtype=np.float32, flags="C_CONTIGUOUS")
_f64p = np.ctypeslib.ndpointer(dtype=np.float64, flags="C_CONTIGUOUS")


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _load_attempted
    if _lib is not None or _load_attempted:
        return _lib
    _load_attempted = True
    if os.environ.get("PEASOUP_NO_NATIVE"):
        return None
    from .build import build

    path = build()
    if path is None:
        return None
    lib = ctypes.CDLL(path)

    lib.ps_unpack_bits.argtypes = [_i8p, ctypes.c_int64, ctypes.c_int, _i8p]
    lib.ps_unpack_bits.restype = None

    lib.ps_cluster_peaks.argtypes = [
        _i32p, _f32p, ctypes.c_int64, ctypes.c_int32, _i64p, _f64p,
    ]
    lib.ps_cluster_peaks.restype = ctypes.c_int64

    lib.ps_harmonic_distill.argtypes = [
        _f64p, _i32p, ctypes.c_int64, ctypes.c_double, ctypes.c_int32,
        ctypes.c_int32, ctypes.c_int32, _i8p, _i32p, _i32p, ctypes.c_int64,
    ]
    lib.ps_harmonic_distill.restype = ctypes.c_int64

    lib.ps_harmonic_distill_seg.argtypes = [
        _f64p, _i32p, _i64p, ctypes.c_int64, ctypes.c_double, ctypes.c_int32,
        ctypes.c_int32, _i8p,
    ]
    lib.ps_harmonic_distill_seg.restype = None

    lib.ps_accel_distill.argtypes = [
        _f64p, _f64p, ctypes.c_int64, ctypes.c_double, ctypes.c_double,
        ctypes.c_int32, _i8p, _i32p, _i32p, ctypes.c_int64,
    ]
    lib.ps_accel_distill.restype = ctypes.c_int64

    lib.ps_accel_distill_seg.argtypes = [
        _f64p, _f64p, _i64p, ctypes.c_int64, ctypes.c_double,
        ctypes.c_double, _i8p, _i32p, _i32p, ctypes.c_int64,
    ]
    lib.ps_accel_distill_seg.restype = ctypes.c_int64

    lib.ps_dm_distill.argtypes = [
        _f64p, ctypes.c_int64, ctypes.c_double, ctypes.c_int32, _i8p, _i32p,
        _i32p, ctypes.c_int64,
    ]
    lib.ps_dm_distill.restype = ctypes.c_int64

    lib.ps_snr_sort_perm.argtypes = [_f32p, ctypes.c_int64, _i32p]
    lib.ps_snr_sort_perm.restype = None

    lib.ps_snr_sort_perm_seg.argtypes = [
        _f32p, _i64p, ctypes.c_int64, _i32p,
    ]
    lib.ps_snr_sort_perm_seg.restype = None

    _lib = lib
    return _lib


def available() -> bool:
    return _load() is not None


def unpack_bits(raw: np.ndarray, nbits: int) -> Optional[np.ndarray]:
    lib = _load()
    if lib is None or nbits not in (1, 2, 4, 8):
        return None
    raw = np.ascontiguousarray(raw, dtype=np.uint8)
    out = np.empty(raw.size * 8 // nbits, dtype=np.uint8)
    lib.ps_unpack_bits(raw, raw.size, nbits, out)
    return out


def cluster_peaks(
    idxs: np.ndarray, snrs: np.ndarray, count: int, min_gap: int
) -> Optional[tuple[np.ndarray, np.ndarray]]:
    lib = _load()
    if lib is None:
        return None
    count = int(min(count, len(idxs)))
    idxs = np.ascontiguousarray(idxs[:count], dtype=np.int32)
    snrs = np.ascontiguousarray(snrs[:count], dtype=np.float32)
    out_idx = np.empty(max(count, 1), dtype=np.int64)
    out_snr = np.empty(max(count, 1), dtype=np.float64)
    n = lib.ps_cluster_peaks(idxs, snrs, count, min_gap, out_idx, out_snr)
    return out_idx[:n].copy(), out_snr[:n].copy()


def snr_sort_perm(snrs: np.ndarray) -> Optional[np.ndarray]:
    """The reference's candidate sort as a permutation: libstdc++
    std::sort (unstable introsort) on (snr, index) pairs with the
    ``x.snr > y.snr`` comparator of distiller.hpp:11-13.  Returns None
    when the native library is unavailable (callers fall back to a
    stable sort, losing only exact-tie winner parity)."""
    lib = _load()
    if lib is None:
        return None
    snrs = np.ascontiguousarray(snrs, dtype=np.float32)
    perm = np.empty(len(snrs), dtype=np.int32)
    lib.ps_snr_sort_perm(snrs, len(snrs), perm)
    return perm


def snr_sort_perm_seg(
    snrs: np.ndarray, seg_off: np.ndarray
) -> Optional[np.ndarray]:
    """Per-segment std::sort permutation (global row ids)."""
    lib = _load()
    if lib is None:
        return None
    snrs = np.ascontiguousarray(snrs, dtype=np.float32)
    seg_off = np.ascontiguousarray(seg_off, dtype=np.int64)
    perm = np.empty(len(snrs), dtype=np.int32)
    lib.ps_snr_sort_perm_seg(snrs, seg_off, len(seg_off) - 1, perm)
    return perm


def _run_distill(call, n: int):
    """Run a distill entry point, growing the edge buffer on overflow."""
    cap = max(4 * n, 1024)
    while True:
        src = np.empty(cap, np.int32)
        dst = np.empty(cap, np.int32)
        unique = np.empty(n, np.uint8)
        n_edges = call(unique, src, dst, cap)
        if n_edges <= cap:
            return unique.astype(bool), src[:n_edges], dst[:n_edges]
        cap = int(n_edges)


def harmonic_distill(freqs, nhs, tol, max_harm, fractional, keep_related):
    lib = _load()
    if lib is None:
        return None
    freqs = np.ascontiguousarray(freqs, dtype=np.float64)
    nhs = np.ascontiguousarray(nhs, dtype=np.int32)
    n = len(freqs)
    return _run_distill(
        lambda u, s, d, cap: lib.ps_harmonic_distill(
            freqs, nhs, n, tol, max_harm, int(fractional), int(keep_related),
            u, s, d, cap,
        ),
        n,
    )


def harmonic_distill_seg(
    freqs, nhs, seg_off, tol, max_harm, fractional
) -> Optional[np.ndarray]:
    """Distill every segment (= accel trial) in one native call. Rows
    must be pre-sorted by S/N descending within each segment; returns
    the survivor mask in row order, or None without the library."""
    lib = _load()
    if lib is None:
        return None
    freqs = np.ascontiguousarray(freqs, dtype=np.float64)
    nhs = np.ascontiguousarray(nhs, dtype=np.int32)
    seg_off = np.ascontiguousarray(seg_off, dtype=np.int64)
    unique = np.empty(len(freqs), np.uint8)
    lib.ps_harmonic_distill_seg(
        freqs, nhs, seg_off, len(seg_off) - 1, tol, max_harm,
        int(fractional), unique,
    )
    return unique.astype(bool)


def accel_distill(freqs, accs, tobs_over_c, tol, keep_related):
    lib = _load()
    if lib is None:
        return None
    freqs = np.ascontiguousarray(freqs, dtype=np.float64)
    accs = np.ascontiguousarray(accs, dtype=np.float64)
    n = len(freqs)
    return _run_distill(
        lambda u, s, d, cap: lib.ps_accel_distill(
            freqs, accs, n, tobs_over_c, tol, int(keep_related), u, s, d, cap,
        ),
        n,
    )


def accel_distill_seg(freqs, accs, seg_off, tobs_over_c, tol):
    """Acceleration-distill every DM-trial segment in one native call
    (rows pre-sorted S/N-descending within each segment). Returns
    (survivor mask, edge_src, edge_dst) with GLOBAL row ids, or None
    without the library."""
    lib = _load()
    if lib is None:
        return None
    freqs = np.ascontiguousarray(freqs, dtype=np.float64)
    accs = np.ascontiguousarray(accs, dtype=np.float64)
    seg_off = np.ascontiguousarray(seg_off, dtype=np.int64)
    return _run_distill(
        lambda u, s, d, cap: lib.ps_accel_distill_seg(
            freqs, accs, seg_off, len(seg_off) - 1, tobs_over_c, tol,
            u, s, d, cap,
        ),
        len(freqs),
    )


def dm_distill(freqs, tol, keep_related):
    lib = _load()
    if lib is None:
        return None
    freqs = np.ascontiguousarray(freqs, dtype=np.float64)
    n = len(freqs)
    return _run_distill(
        lambda u, s, d, cap: lib.ps_dm_distill(
            freqs, n, tol, int(keep_related), u, s, d, cap,
        ),
        n,
    )
