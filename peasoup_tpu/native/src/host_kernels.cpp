// Native host runtime for peasoup_tpu.
//
// The reference keeps its host-side hot loops in C++ (candidate
// distilling include/transforms/distiller.hpp, peak clustering
// peakfinder.hpp:27-56, bit handling inside libdedisp); this library is
// the TPU build's equivalent. Exposed as a plain C ABI consumed via
// ctypes — no pybind11 dependency.
//
// Semantics mirror the Python implementations exactly (which in turn
// mirror the reference); the Python versions remain as fallback and as
// the parity oracle in tests.

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <numeric>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// Bit unpacking (LSB-first within each byte, like sigproc/dedisp sub-words)
// ---------------------------------------------------------------------------
void ps_unpack_bits(const uint8_t* in, int64_t nbytes, int nbits, uint8_t* out) {
  switch (nbits) {
    case 8:
      std::memcpy(out, in, static_cast<size_t>(nbytes));
      break;
    case 4:
      for (int64_t i = 0; i < nbytes; ++i) {
        out[2 * i] = in[i] & 0x0F;
        out[2 * i + 1] = in[i] >> 4;
      }
      break;
    case 2:
      for (int64_t i = 0; i < nbytes; ++i) {
        const uint8_t b = in[i];
        out[4 * i] = b & 0x03;
        out[4 * i + 1] = (b >> 2) & 0x03;
        out[4 * i + 2] = (b >> 4) & 0x03;
        out[4 * i + 3] = (b >> 6) & 0x03;
      }
      break;
    case 1:
      for (int64_t i = 0; i < nbytes; ++i) {
        const uint8_t b = in[i];
        for (int k = 0; k < 8; ++k) out[8 * i + k] = (b >> k) & 1;
      }
      break;
    default:
      break;  // unsupported widths are rejected on the Python side
  }
}

// ---------------------------------------------------------------------------
// Peak clustering (exact port of identify_unique_peaks,
// peakfinder.hpp:27-56 — including the lastidx-advances-only-on-new-max
// quirk)
// ---------------------------------------------------------------------------
int64_t ps_cluster_peaks(const int32_t* idxs, const float* snrs, int64_t count,
                         int32_t min_gap, int64_t* out_idx, double* out_snr) {
  int64_t npeaks = 0;
  int64_t ii = 0;
  while (ii < count) {
    float cpeak = snrs[ii];
    int32_t cpeakidx = idxs[ii];
    int32_t lastidx = idxs[ii];
    ++ii;
    while (ii < count && (idxs[ii] - lastidx) < min_gap) {
      if (snrs[ii] > cpeak) {
        cpeak = snrs[ii];
        cpeakidx = idxs[ii];
        lastidx = idxs[ii];
      }
      ++ii;
    }
    out_idx[npeaks] = cpeakidx;
    out_snr[npeaks] = static_cast<double>(cpeak);
    ++npeaks;
  }
  return npeaks;
}

// ---------------------------------------------------------------------------
// Distillers. Inputs are candidate columns ALREADY sorted by S/N
// descending. Outputs: unique mask (1 = survivor) and an edge list
// (fundamental index, absorbed index) with one entry PER MATCHING
// HARMONIC PAIR (multiplicity feeds nassoc / ddm ratios).
// Returns the number of edges written (capped at max_edges; the caller
// retries with a larger buffer if the return value exceeds it).
// ---------------------------------------------------------------------------

struct EdgeSink {
  int32_t* src;
  int32_t* dst;
  int64_t cap;
  int64_t n = 0;
  void add(int64_t s, int64_t d) {
    if (n < cap) {
      src[n] = static_cast<int32_t>(s);
      dst[n] = static_cast<int32_t>(d);
    }
    ++n;
  }
};

// Harmonic-ratio matcher shared by the per-trial and segmented
// distills. Counts matching (jj, kk) pairs; with early_exit it stops
// at the first match (valid only when pair multiplicity is unused,
// i.e. keep_related is false).
static inline int harmonic_hits(double fundi, double freq, int32_t nh,
                                double lo, double hi, int32_t max_harm,
                                int32_t fractional, bool early_exit) {
  const int32_t max_denom = fractional ? (int32_t{1} << nh) : int32_t{1};
  if (early_exit) {
    // Existence check only.  For fixed jj the ratio kk*freq/(jj*fundi)
    // is strictly increasing in kk, so at most a couple of kk values
    // can land inside (lo, hi): locate the window with one divide and
    // verify those candidates with the EXACT original predicate (the
    // located bounds are approximate in double, the decision is not).
    for (int32_t jj = 1; jj <= max_harm; ++jj) {
      const double denom = jj * fundi;
      const double k0 = lo * denom / freq;  // ratio(kk) > lo ~ kk > k0
      int32_t kk = static_cast<int32_t>(k0);  // trunc; candidates k0 +- 1
      if (kk < 1) kk = 1;
      const int32_t kk_end = kk + 2 < max_denom ? kk + 2 : max_denom;
      for (; kk <= kk_end; ++kk) {
        const double ratio = kk * freq / denom;
        if (ratio > lo && ratio < hi) return 1;
        if (ratio >= hi) break;  // increasing in kk: no later hit
      }
    }
    return 0;
  }
  int hits = 0;
  for (int32_t jj = 1; jj <= max_harm; ++jj) {
    for (int32_t kk = 1; kk <= max_denom; ++kk) {
      const double ratio = kk * freq / (jj * fundi);
      if (ratio > lo && ratio < hi) {
        ++hits;
      }
    }
  }
  return hits;
}

int64_t ps_harmonic_distill(const double* freqs, const int32_t* nhs, int64_t n,
                            double tol, int32_t max_harm, int32_t fractional,
                            int32_t keep_related, uint8_t* unique,
                            int32_t* edge_src, int32_t* edge_dst,
                            int64_t max_edges) {
  std::fill(unique, unique + n, uint8_t{1});
  EdgeSink edges{edge_src, edge_dst, max_edges};
  const double lo = 1.0 - tol, hi = 1.0 + tol;
  for (int64_t idx = 0; idx < n; ++idx) {
    if (!unique[idx]) continue;
    const double fundi = freqs[idx];
    for (int64_t jjt = idx + 1; jjt < n; ++jjt) {
      const int hits = harmonic_hits(fundi, freqs[jjt], nhs[jjt], lo, hi,
                                     max_harm, fractional,
                                     /*early_exit=*/!keep_related);
      if (keep_related)
        for (int h = 0; h < hits; ++h) edges.add(idx, jjt);
      if (hits) unique[jjt] = 0;
    }
  }
  return edges.n;
}

// Segmented variant: one call distills EVERY accel trial of a run
// (segment s = rows [seg_off[s], seg_off[s+1])), replacing one
// ctypes round trip per trial. Rows arrive pre-sorted by S/N
// descending within each segment; unique flags are written in that
// same row order. keep_related is always false on this path (the
// per-accel-trial distill discards non-survivors,
// src/pipeline_multi.cu:238).
void ps_harmonic_distill_seg(const double* freqs, const int32_t* nhs,
                             const int64_t* seg_off, int64_t nseg, double tol,
                             int32_t max_harm, int32_t fractional,
                             uint8_t* unique) {
  const double lo = 1.0 - tol, hi = 1.0 + tol;
  for (int64_t s = 0; s < nseg; ++s) {
    const int64_t b = seg_off[s], e = seg_off[s + 1];
    std::fill(unique + b, unique + e, uint8_t{1});
    for (int64_t idx = b; idx < e; ++idx) {
      if (!unique[idx]) continue;
      const double fundi = freqs[idx];
      for (int64_t jjt = idx + 1; jjt < e; ++jjt) {
        if (!unique[jjt]) continue;
        if (harmonic_hits(fundi, freqs[jjt], nhs[jjt], lo, hi, max_harm,
                          fractional, /*early_exit=*/true))
          unique[jjt] = 0;
      }
    }
  }
}

int64_t ps_accel_distill(const double* freqs, const double* accs, int64_t n,
                         double tobs_over_c, double tol, int32_t keep_related,
                         uint8_t* unique, int32_t* edge_src, int32_t* edge_dst,
                         int64_t max_edges) {
  std::fill(unique, unique + n, uint8_t{1});
  EdgeSink edges{edge_src, edge_dst, max_edges};
  for (int64_t idx = 0; idx < n; ++idx) {
    if (!unique[idx]) continue;
    const double fundi_freq = freqs[idx];
    const double fundi_acc = accs[idx];
    const double edge = fundi_freq * tol;
    for (int64_t jj = idx + 1; jj < n; ++jj) {
      const double delta_acc = fundi_acc - accs[jj];
      const double acc_freq =
          fundi_freq + delta_acc * fundi_freq * tobs_over_c;
      bool hit;
      if (acc_freq > fundi_freq) {
        hit = freqs[jj] > fundi_freq - edge && freqs[jj] < acc_freq + edge;
      } else {
        hit = freqs[jj] < fundi_freq + edge && freqs[jj] > acc_freq - edge;
      }
      if (hit) {
        if (keep_related) edges.add(idx, jj);
        unique[jj] = 0;
      }
    }
  }
  return edges.n;
}

// Segmented variant: one call runs the acceleration distill of EVERY
// DM trial (segment s = rows [seg_off[s], seg_off[s+1]), pre-sorted
// S/N-descending within each segment), recording winner->loser edges
// with GLOBAL row ids so the caller can build the assoc tree for the
// survivors only once.  Same pairwise window test as ps_accel_distill
// (reference distiller.hpp:115-164).
int64_t ps_accel_distill_seg(const double* freqs, const double* accs,
                             const int64_t* seg_off, int64_t nseg,
                             double tobs_over_c, double tol, uint8_t* unique,
                             int32_t* edge_src, int32_t* edge_dst,
                             int64_t max_edges) {
  EdgeSink edges{edge_src, edge_dst, max_edges};
  for (int64_t s = 0; s < nseg; ++s) {
    const int64_t b = seg_off[s], e = seg_off[s + 1];
    std::fill(unique + b, unique + e, uint8_t{1});
    for (int64_t idx = b; idx < e; ++idx) {
      if (!unique[idx]) continue;
      const double fundi_freq = freqs[idx];
      const double fundi_acc = accs[idx];
      const double edge = fundi_freq * tol;
      for (int64_t jj = idx + 1; jj < e; ++jj) {
        const double delta_acc = fundi_acc - accs[jj];
        const double acc_freq =
            fundi_freq + delta_acc * fundi_freq * tobs_over_c;
        bool hit;
        if (acc_freq > fundi_freq) {
          hit = freqs[jj] > fundi_freq - edge && freqs[jj] < acc_freq + edge;
        } else {
          hit = freqs[jj] < fundi_freq + edge && freqs[jj] > acc_freq - edge;
        }
        if (hit) {
          edges.add(idx, jj);
          unique[jj] = 0;
        }
      }
    }
  }
  return edges.n;
}

// ---------------------------------------------------------------------------
// The reference's !IMPORTANT S/N sort (distiller.hpp:31) is std::sort —
// an UNSTABLE introsort whose permutation of equal-S/N candidates is
// deterministic but not input-order-preserving.  Real searches contain
// EXACT S/N ties (accel trials whose resample shift never reaches half a
// sample produce bitwise-identical spectra), and the distiller crowns
// whichever tied member the sort leaves first — so matching the golden
// winners requires replaying the same algorithm, not a stable sort.
// Sorting (snr, original-index) pairs with the same comparator yields the
// exact permutation: introsort's compare/move sequence depends only on
// comparator outcomes, never on element payload.
// ---------------------------------------------------------------------------
struct PsSnrTag {
  float snr;
  int32_t idx;
};

void ps_snr_sort_perm(const float* snr, int64_t n, int32_t* perm) {
  std::vector<PsSnrTag> v(static_cast<size_t>(n));
  for (int64_t i = 0; i < n; ++i)
    v[static_cast<size_t>(i)] = {snr[i], static_cast<int32_t>(i)};
  std::sort(v.begin(), v.end(),
            [](const PsSnrTag& x, const PsSnrTag& y) { return x.snr > y.snr; });
  for (int64_t i = 0; i < n; ++i) perm[i] = v[static_cast<size_t>(i)].idx;
}

// Segmented variant: independent std::sort per [seg_off[s], seg_off[s+1])
// slice (the reference sorts each trial's candidate list separately);
// perm entries are GLOBAL row ids.
void ps_snr_sort_perm_seg(const float* snr, const int64_t* seg_off,
                          int64_t nseg, int32_t* perm) {
  std::vector<PsSnrTag> v;
  for (int64_t s = 0; s < nseg; ++s) {
    const int64_t b = seg_off[s], e = seg_off[s + 1];
    v.resize(static_cast<size_t>(e - b));
    for (int64_t i = b; i < e; ++i)
      v[static_cast<size_t>(i - b)] = {snr[i], static_cast<int32_t>(i)};
    std::sort(v.begin(), v.end(), [](const PsSnrTag& x, const PsSnrTag& y) {
      return x.snr > y.snr;
    });
    for (int64_t i = b; i < e; ++i)
      perm[i] = v[static_cast<size_t>(i - b)].idx;
  }
}

int64_t ps_dm_distill(const double* freqs, int64_t n, double tol,
                      int32_t keep_related, uint8_t* unique, int32_t* edge_src,
                      int32_t* edge_dst, int64_t max_edges) {
  std::fill(unique, unique + n, uint8_t{1});
  EdgeSink edges{edge_src, edge_dst, max_edges};
  const double lo = 1.0 - tol, hi = 1.0 + tol;
  for (int64_t idx = 0; idx < n; ++idx) {
    if (!unique[idx]) continue;
    const double fundi = freqs[idx];
    for (int64_t jj = idx + 1; jj < n; ++jj) {
      const double ratio = freqs[jj] / fundi;
      if (ratio > lo && ratio < hi) {
        if (keep_related) edges.add(idx, jj);
        unique[jj] = 0;
      }
    }
  }
  return edges.n;
}

}  // extern "C"
