"""Build libpeasoup_host.so with the system C++ toolchain.

Invoked lazily on first use (or explicitly: python -m
peasoup_tpu.native.build). No pybind11 — plain C ABI via ctypes.
"""

from __future__ import annotations

import os
import subprocess
import sys

_DIR = os.path.dirname(os.path.abspath(__file__))
SRC = os.path.join(_DIR, "src", "host_kernels.cpp")
LIB = os.path.join(_DIR, "libpeasoup_host.so")


def build(force: bool = False) -> str | None:
    """Compile the shared library; returns its path or None on failure.

    Compiles to a temp path and os.replace()s into place so concurrent
    first-use builds (e.g. many sharded-search workers on a cold
    checkout) never dlopen a half-written file.
    """
    if not force and os.path.exists(LIB) and os.path.getmtime(
        LIB
    ) >= os.path.getmtime(SRC):
        return LIB
    import tempfile

    fd, tmp = tempfile.mkstemp(dir=_DIR, suffix=".so.tmp")
    os.close(fd)
    cmd = [
        os.environ.get("CXX", "g++"),
        "-O3",
        "-shared",
        "-fPIC",
        "-std=c++17",
        SRC,
        "-o",
        tmp,
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, text=True)
        os.replace(tmp, LIB)
    except (subprocess.CalledProcessError, FileNotFoundError) as exc:
        import warnings

        if os.path.exists(tmp):
            os.unlink(tmp)
        detail = getattr(exc, "stderr", "") or str(exc)
        warnings.warn(f"native build failed, using Python fallback: {detail}")
        return None
    return LIB


if __name__ == "__main__":
    path = build(force="--force" in sys.argv)
    sys.stdout.write(f"{path or 'BUILD FAILED'}\n")
    sys.exit(0 if path else 1)
