"""File-backed worker registry: live fleet membership for a campaign.

The queue (queue.py) already tolerates workers dying — leases expire
and claims are reaped — but nothing *names* the fleet: operators
watching a campaign cannot see who is working, and a worker joining
mid-campaign cannot tell warm peers from ghosts. This module is the
membership half of elasticity, built on the same idioms as the queue:

- **register** — ``O_CREAT|O_EXCL`` of ``queue/workers/<id>.json``
  carrying pid/hostname and a lease expiry. A stale entry left by a
  previous incarnation of the same worker id (a restart) is taken over
  with an atomic rewrite.
- **beat** — the owner atomically rewrites its entry with a fresh
  expiry plus live stats (jobs done, current job, last bucket); the
  campaign runner beats from the same lease-renewal thread that keeps
  its claim fresh, so a worker alive enough to hold a job is alive in
  the registry too.
- **deregister** — a clean leave unlinks the entry; joins and leaves
  need no coordinator, mirroring claim release.
- **reap** — anyone may unlink an EXPIRED entry (a SIGKILLed worker
  never deregisters). Reaping membership is advisory — job recovery is
  the queue reaper's — so the unlink needs no tombstone dance; a lost
  race is a FileNotFoundError and a shrug.

The rollup (rollup.py) reads the registry read-only into the ``fleet``
status section; ``tools.watch`` renders it.
"""

from __future__ import annotations

import json
import os
import socket
import tempfile
import time

from ..obs import get_logger
from ..resilience import faults

log = get_logger("campaign.registry")

_WORKERS = "workers"


def _atomic_write_json(path: str, doc: dict) -> None:
    d = os.path.dirname(path) or "."
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(doc, f, indent=2)
            f.write("\n")
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def _read_json(path: str) -> dict | None:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None  # gone, mid-replace, or torn: treat as absent


class WorkerRegistry:
    """Heartbeat files under ``<root>/queue/workers/``. ``group``
    names THIS process's gang-scheduling process group: it rides every
    (re-)registration, so an entry recreated by a beat — after a
    clock-skewed peer reaped a perfectly live worker — keeps its group
    membership and the gang pool never silently shrinks."""

    def __init__(
        self, root: str, lease_s: float = 60.0, group: str | None = None
    ) -> None:
        self.root = os.path.abspath(root)
        self.wdir = os.path.join(self.root, "queue", _WORKERS)
        self.lease_s = float(lease_s)
        self.group = group
        os.makedirs(self.wdir, exist_ok=True)

    def _path(self, worker_id: str) -> str:
        safe = "".join(
            c if c.isalnum() or c in "-_." else "_" for c in worker_id
        )
        return os.path.join(self.wdir, f"{safe[:80]}.json")

    def metrics_path(self, worker_id: str) -> str:
        """The worker's time-series file (obs/metrics.py), living
        beside its membership entry so the fleet aggregator finds the
        whole fleet's history in one directory. Deliberately NOT
        removed on deregister/reap: the history of a departed worker
        is the point of having history."""
        return self._path(worker_id)[: -len(".json")] + ".metrics.jsonl"

    # --- lifecycle ----------------------------------------------------
    def register(self, worker_id: str, **info) -> dict:
        """Join the fleet. Idempotent for one incarnation; a stale or
        duplicate entry for the same id is taken over (the newest pid
        wins — worker ids are operator-chosen, and a restart reusing
        one must not be locked out by its own corpse)."""
        now = time.time()
        doc = {
            "worker_id": worker_id,
            "pid": os.getpid(),
            "hostname": socket.gethostname(),
            "registered_unix": now,
            "expires_unix": now + self.lease_s,
            "jobs_done": 0,
            "current_job": None,
            "last_bucket": None,
            "group": self.group,  # process group for gang scheduling
            **info,
        }
        path = self._path(worker_id)
        try:
            fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except FileExistsError:
            prev = _read_json(path) or {}
            if (
                float(prev.get("expires_unix", 0)) >= now
                and prev.get("pid") != doc["pid"]
            ):
                log.warning(
                    "worker id %s already registered live by pid %s; "
                    "taking over (newest registration wins)",
                    worker_id, prev.get("pid"),
                )
            _atomic_write_json(path, doc)
            return doc
        with os.fdopen(fd, "w") as f:
            json.dump(doc, f, indent=2)
            f.write("\n")
        log.info("worker %s joined the fleet", worker_id)
        return doc

    def beat(self, worker_id: str, **updates) -> None:
        """Renew the lease (and fold in live stats). Missing entry —
        reaped from under a stalled worker — is re-created: a worker
        that beats IS alive, whatever the reaper concluded."""
        path = self._path(worker_id)
        doc = _read_json(path)
        if doc is None:
            self.register(worker_id, **updates)
            return
        doc.update(updates)
        now_unix = time.time()
        doc["expires_unix"] = now_unix + self.lease_s
        _atomic_write_json(path, doc)

    def deregister(self, worker_id: str) -> None:
        """Clean leave: remove the membership entry (and any pending
        retire or profile request — the leave answers both)."""
        self.clear_retire(worker_id)
        self.clear_profile(worker_id)
        try:
            os.unlink(self._path(worker_id))
            log.info("worker %s left the fleet", worker_id)
        except FileNotFoundError:
            pass  # reaped already — same outcome

    # --- retirement (autoscale scale-down) ----------------------------
    def _retire_path(self, worker_id: str) -> str:
        # ".retire" (not ".json") so registry scans — which filter on
        # ".json" — never mistake a request for a membership entry
        return self._path(worker_id) + ".retire"

    def request_retire(self, worker_id: str, requester: str = "") -> None:
        """Ask a worker to leave the fleet cleanly: it observes the
        marker between jobs (or mid-job via the revoke token — it then
        checkpoints and releases its claim with zero attempts
        consumed), deregisters, and exits. The autoscale controller's
        scale-down path (campaign/autoscale.py)."""
        _atomic_write_json(
            self._retire_path(worker_id),
            {
                "worker_id": worker_id,
                "requester": requester,
                "requested_unix": time.time(),
            },
        )
        log.info(
            "retire requested for worker %s%s", worker_id,
            f" (by {requester})" if requester else "",
        )

    def retire_requested(self, worker_id: str) -> dict | None:
        return _read_json(self._retire_path(worker_id))

    def clear_retire(self, worker_id: str) -> None:
        try:
            os.unlink(self._retire_path(worker_id))
        except FileNotFoundError:
            pass

    # --- on-demand profiling (obs/profiler.py) ------------------------
    def _profile_path(self, worker_id: str) -> str:
        # ".profile" (not ".json") so registry scans — which filter on
        # ".json" — never mistake a request for a membership entry
        return self._path(worker_id) + ".profile"

    def request_profile(
        self,
        worker_id: str,
        seconds: float = 5.0,
        requester: str = "",
    ) -> None:
        """Ask a live worker for a bounded ``jax.profiler`` capture:
        it observes the marker on its next lease-renewer beat (busy)
        or claim poll (idle), runs the capture on a helper thread
        (guarded no-op on CPU), announces it in its metrics stream,
        and clears the request — ``peasoup-campaign profile``'s write
        half."""
        _atomic_write_json(
            self._profile_path(worker_id),
            {
                "worker_id": worker_id,
                "seconds": float(seconds),
                "requester": requester,
                "requested_unix": time.time(),
            },
        )
        log.info(
            "device profile requested for worker %s (%.3gs)%s",
            worker_id, seconds,
            f" by {requester}" if requester else "",
        )

    def profile_requested(self, worker_id: str) -> dict | None:
        return _read_json(self._profile_path(worker_id))

    def clear_profile(self, worker_id: str) -> None:
        try:
            os.unlink(self._profile_path(worker_id))
        except FileNotFoundError:
            pass

    # --- reading ------------------------------------------------------
    def entries(self) -> list[dict]:
        out = []
        for name in sorted(os.listdir(self.wdir)):
            if name.endswith(".json"):
                doc = _read_json(os.path.join(self.wdir, name))
                if doc:
                    out.append(doc)
        return out

    def live(self, now: float | None = None) -> list[dict]:
        now = time.time() if now is None else now
        return [
            e for e in self.entries()
            if float(e.get("expires_unix", 0)) >= now
        ]

    def live_group(
        self, group: str, now: float | None = None
    ) -> list[str]:
        """Sorted live worker ids of one process group — the gang
        leader is the first entry (queue.claim_next's contract)."""
        return sorted(
            e["worker_id"]
            for e in self.live(now)
            if e.get("group") == group and e.get("worker_id")
        )

    # --- reaping ------------------------------------------------------
    def reap(self, now: float | None = None) -> list[str]:
        """Unlink expired entries (their worker was SIGKILLed or
        wedged past its lease). Advisory membership only — the queue's
        lease reaper owns job recovery — so a lost unlink race is
        harmless. The same clock.skew chaos seam that drills the queue
        reaper shifts this reaper's view too."""
        now = time.time() if now is None else now
        now += faults.clock_skew_s()
        reaped = []
        for name in sorted(os.listdir(self.wdir)):
            if not name.endswith(".json"):
                continue
            path = os.path.join(self.wdir, name)
            doc = _read_json(path)
            if doc is None:
                # TORN entry: the joiner was SIGKILLed between the
                # O_EXCL create and the document publish. It has no
                # expiry so it could never be reaped — it leaked
                # forever, and (worse) a restart reusing the id would
                # take it over and inherit garbage (found by the mc
                # registry_torn_entry scenario). Age-gate on st_ctime
                # so a mid-write joiner gets a full lease to finish
                try:
                    if now - os.stat(path).st_ctime <= self.lease_s:
                        continue
                    os.unlink(path)
                except OSError:
                    continue  # published or reaped in the gap
                reaped.append(os.path.splitext(name)[0])
                log.warning(
                    "reaped torn registry entry %s (joiner died "
                    "mid-publish)", name,
                )
                continue
            if float(doc.get("expires_unix", 0)) >= now:
                continue
            try:
                os.unlink(path)
            except FileNotFoundError:
                continue  # lost the race: already reaped
            reaped.append(doc.get("worker_id", os.path.splitext(name)[0]))
            log.warning(
                "reaped dead worker %s from the fleet registry (lease "
                "expired %.1fs ago)",
                doc.get("worker_id"),
                now - float(doc.get("expires_unix", 0)),
            )
        # orphaned retire/profile markers (the worker died, or left,
        # before observing the request) must not leak — the request is
        # moot either way
        for suffix in (".retire", ".profile"):
            for name in sorted(os.listdir(self.wdir)):
                if not name.endswith(suffix):
                    continue
                if not os.path.exists(
                    os.path.join(self.wdir, name[: -len(suffix)])
                ):
                    try:
                        os.unlink(os.path.join(self.wdir, name))
                    except FileNotFoundError:
                        pass
        return reaped
