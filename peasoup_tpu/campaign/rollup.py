"""Campaign rollup: the atomically rewritten ``campaign_status.json``.

One small JSON snapshot aggregates the whole campaign for operators and
schedulers, the survey-level analogue of a single run's ``status.json``
heartbeat (obs/heartbeat.py): queue depths by derived state, the
running jobs with each one's live stage/progress (read from the per-job
``status.json`` under its job dir), completion throughput and an ETA
extrapolated from the done timestamps, and the failure tallies
(retrying jobs with their last error, quarantined jobs). Workers
rewrite it after every state transition; ``python -m
peasoup_tpu.tools.watch <campaign_dir>`` tails it.
"""

from __future__ import annotations

import glob
import json
import os
import tempfile
import time

from .queue import JobQueue
from .registry import WorkerRegistry

CAMPAIGN_SCHEMA = "peasoup_tpu.campaign_status"
CAMPAIGN_VERSION = 1


def _read_json(path: str) -> dict | None:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def build_status(root: str, queue: JobQueue | None = None) -> dict:
    """Aggregate the campaign directory into one status document."""
    queue = queue or JobQueue(root)
    now = time.time()
    counts = queue.counts()

    running = []
    failures = []
    for jid in queue.job_ids():
        st = queue.state(jid, now)
        job = queue.get_job(jid)
        if st == "running":
            hb = _read_json(os.path.join(root, "jobs", jid, "status.json"))
            claim = _read_json(
                os.path.join(queue.qdir, "claims", f"{jid}.json")
            )
            running.append(
                {
                    "job_id": jid,
                    "worker_id": (claim or {}).get("worker_id"),
                    "stage": (hb or {}).get("stage"),
                    "progress": (hb or {}).get("progress"),
                    "stalled": bool((hb or {}).get("stalled")),
                }
            )
        elif st in ("backoff", "pending") and job and job.attempts:
            failures.append(
                {
                    "job_id": jid,
                    "attempts": job.attempts,
                    "retry_in_s": round(
                        max(0.0, job.next_eligible_unix - now), 3
                    ),
                    "last_error": job.last_error,
                }
            )

    done = queue.done_records()
    throughput = None
    eta_s = None
    if len(done) >= 2:
        ts = sorted(float(d.get("finished_unix", 0)) for d in done)
        span = ts[-1] - ts[0]
        if span > 0:
            throughput = (len(done) - 1) / span  # jobs per second
            remaining = counts["total"] - counts["done"] - counts["quarantined"]
            eta_s = round(remaining / throughput, 3) if remaining else 0.0

    n_candidates = sum(int(d.get("n_candidates", 0) or 0) for d in done)
    warmup_s = sum(float(d.get("warmup_s", 0) or 0) for d in done)
    warmed_jobs = sum(1 for d in done if d.get("warmup_s") is not None)
    tuning_s = sum(float(d.get("tuning_s", 0) or 0) for d in done)
    # per-bucket warmup/tuning tallies: the data warmup-aware claiming
    # (runner._warm_bucket_hint) exploits, surfaced for operators
    warm_buckets: dict[str, dict] = {}
    for d in done:
        b = d.get("bucket")
        if not b:
            continue
        key = ",".join(str(x) for x in b)
        rec = warm_buckets.setdefault(
            key, {"done": 0, "warmup_s": 0.0, "plan": None}
        )
        rec["done"] += 1
        rec["warmup_s"] = round(
            rec["warmup_s"] + float(d.get("warmup_s", 0) or 0), 3
        )
        if d.get("dedisp_plan") is not None:
            rec["plan"] = d["dedisp_plan"]
    # resilience rollup: sum the per-job deltas the runner stores in
    # done records (retries/degradations/faults survived on the way to
    # "done") — campaign-wide recovery accounting without re-reading
    # every job's telemetry manifest
    resilience: dict[str, dict] = {}
    for d in done:
        for table, kv in (d.get("resilience") or {}).items():
            if not isinstance(kv, dict):
                continue
            tgt = resilience.setdefault(table, {})
            for k, v in kv.items():
                tgt[k] = tgt.get(k, 0) + int(v)
    # lost-lease attempts (reaped from under a live run) publish no
    # done record — their survived-fault counters arrive through the
    # queue's per-worker orphaned-resilience spool instead
    # (queue.record_orphaned_resilience), so a recovery the fleet
    # genuinely performed never vanishes from the rollup
    orphaned = queue.orphaned_resilience()
    for rec in orphaned:
        for table, kv in (rec.get("resilience") or {}).items():
            if not isinstance(kv, dict):
                continue
            tgt = resilience.setdefault(table, {})
            for k, v in kv.items():
                tgt[k] = tgt.get(k, 0) + int(v)
    if orphaned:
        resilience["orphaned_attempts"] = {
            "total": len(orphaned),
        }
    quarantined = [
        {
            "job_id": q.get("job_id"),
            "attempts": q.get("attempts"),
            "last_error": q.get("last_error"),
        }
        for q in queue.quarantined()
    ]
    # fleet membership (campaign/registry.py, read-only here) + the
    # per-worker throughput derived from done records — live answers
    # to "who is working" and "who is pulling their weight" for an
    # elastic fleet where workers join and leave mid-campaign
    registry = WorkerRegistry(root)
    live_workers = [
        {
            "worker_id": e.get("worker_id"),
            "hostname": e.get("hostname"),
            "pid": e.get("pid"),
            "jobs_done": e.get("jobs_done", 0),
            "current_job": e.get("current_job"),
            # clamped at zero: a clock-skewed writer can stamp an
            # expiry ahead of this reader's clock, and a NEGATIVE
            # heartbeat age is noise operators learn to distrust
            "last_beat_s": round(
                max(0.0, now - (
                    float(e.get("expires_unix", now)) - registry.lease_s
                )), 3,
            ),
        }
        for e in registry.live(now)
    ]
    live_ids = {w["worker_id"] for w in live_workers}
    per_worker: dict[str, dict] = {}
    for d in done:
        wid = d.get("worker_id") or "?"
        rec = per_worker.setdefault(
            wid, {"done": 0, "first_unix": None, "last_unix": None}
        )
        rec["done"] += 1
        t = float(d.get("finished_unix", 0) or 0)
        if t:
            rec["first_unix"] = min(rec["first_unix"] or t, t)
            rec["last_unix"] = max(rec["last_unix"] or t, t)
    # a departed/reaped worker's rate must AGE OUT: its jobs_per_h was
    # computed over its own active span, so hours later the rollup
    # would still advertise a throughput nobody is delivering. Live
    # workers keep their rate; non-live workers keep it only within a
    # grace window of their last completion.
    rate_decay_s = max(300.0, 10.0 * registry.lease_s)
    for wid, rec in per_worker.items():
        span = (rec["last_unix"] or 0) - (rec["first_unix"] or 0)
        rate = (
            round((rec["done"] - 1) / span * 3600.0, 3)
            if rec["done"] > 1 and span > 0 else None
        )
        rec["live"] = wid in live_ids
        # clamped: under clock skew a done record can be stamped ahead
        # of this reader's clock (negative age = nonsense)
        age = max(0.0, now - (rec["last_unix"] or now))
        rec["last_done_age_s"] = round(age, 3)
        if not rec["live"] and age > rate_decay_s:
            rec["jobs_per_h"] = None
            rec["rate_stale"] = True
        else:
            rec["jobs_per_h"] = rate
    degraded_jobs = sum(1 for d in done if d.get("degraded"))
    # preemption attribution: revoked-and-resumed jobs carry their
    # tally + request->release latency into done records; outstanding
    # requests are revokes still in flight (queue/claims/*.preempt)
    preempted = [d for d in done if d.get("preemptions")]
    latencies = [
        float(x)
        for d in preempted
        for x in (d.get("preempt_latency_s") or [])
    ]
    preemptions = {
        "jobs": len(preempted),
        "total": sum(int(d.get("preemptions", 0)) for d in preempted),
        "outstanding_requests": len(
            glob.glob(
                os.path.join(queue.qdir, "claims", "*.preempt")
            )
        ),
        "latency_s": (
            {
                "mean": round(sum(latencies) / len(latencies), 4),
                "max": round(max(latencies), 4),
            }
            if latencies else None
        ),
    }
    gang_jobs = sum(1 for d in done if d.get("gang"))
    # autoscale decision log (campaign/autoscale.py), embedded so the
    # controller's reasoning rides the same operator surface
    from .autoscale import load_autoscale_log

    autoscale = load_autoscale_log(root)
    if autoscale is not None:
        autoscale = {
            k: autoscale.get(k)
            for k in (
                "controller_id", "last_action_unix", "spawned_total",
                "policy", "decisions",
            )
        }
    # *.corrupt quarantine accumulation (prune with
    # `peasoup-campaign prune --corrupt`)
    corrupt_files = len(
        glob.glob(
            os.path.join(os.path.abspath(root), "**", "*.corrupt"),
            recursive=True,
        )
    )
    # on-demand device-profile captures (obs/profiler.py): capture
    # dirs accumulate under <root>/profiles/ until
    # `peasoup-campaign prune --profiles` reclaims them
    pdir = os.path.join(os.path.abspath(root), "profiles")
    profile_dirs = 0
    profile_bytes = 0
    if os.path.isdir(pdir):
        for name in os.listdir(pdir):
            cap = os.path.join(pdir, name)
            if not os.path.isdir(cap):
                continue
            profile_dirs += 1
            for dp, _, fns in os.walk(cap):
                for fn in fns:
                    try:
                        profile_bytes += os.path.getsize(
                            os.path.join(dp, fn)
                        )
                    except OSError:
                        pass
    # fleet time-series summary (obs/metrics.py): how much history is
    # on disk and where to point `peasoup-campaign metrics`
    from ..obs.metrics import metrics_paths

    mpaths = metrics_paths(root)
    mbytes = 0
    for p in mpaths:
        try:
            mbytes += os.path.getsize(p)
        except OSError:
            pass
    # survey health (obs/alerts.py + obs/health.py): the alerts
    # snapshot (schema-validated; a torn/invalid snapshot is reported,
    # never raised — the rollup must always publish) and the
    # data-quality baselines/outliers over the done records
    from ..obs.alerts import load_alerts, validate_snapshot
    from ..obs.health import data_quality_summary, sentinel_status

    alerts_snapshot = load_alerts(root)
    alerts_section: dict = {"firing": 0, "pending": 0, "resolved": 0}
    try:
        validate_snapshot(alerts_snapshot)
        for a in alerts_snapshot.get("alerts", []):
            st = a.get("state")
            if st in alerts_section:
                alerts_section[st] += 1
        alerts_section["updated_unix"] = alerts_snapshot.get(
            "updated_unix", 0.0
        )
        alerts_section["active"] = [
            {
                "rule": a.get("rule"),
                "state": a.get("state"),
                "severity": a.get("severity"),
                "labels": a.get("labels") or {},
                "value": a.get("value"),
                "message": a.get("message", ""),
                "since_unix": a.get("since_unix"),
            }
            for a in alerts_snapshot.get("alerts", [])
            if a.get("state") in ("pending", "firing")
        ]
    except Exception as exc:
        alerts_section = {"invalid": f"{exc!s:.200}"}
    # multi-tenant view (campaign/tenants.py + usage.py): per-tenant
    # queue-state tallies, quota spec, windowed device-seconds vs
    # budget and the active throttle reason — plus the usage ledger
    # (also written to queue/usage.json by write_status)
    tenants_section: dict = {}
    usage_section: dict = {}
    try:
        from .tenants import TenantRegistry, throttle_map
        from .usage import build_usage

        tenant_entries = TenantRegistry(root).entries()
        if tenant_entries:
            throttles = throttle_map(root, now=now)
            usage_doc = build_usage(root, queue=queue, now=now)
            usage_section = usage_doc.get("tenants", {})
            per_tenant: dict[str, dict] = {
                t.name: {
                    "queued": 0, "running": 0, "throttled": 0,
                    "done": 0, "quarantined": 0,
                }
                for t in tenant_entries
            }
            for jid in queue.job_ids():
                job = queue.get_job(jid)
                if job is None or not job.tenant:
                    continue
                tally = per_tenant.setdefault(job.tenant, {
                    "queued": 0, "running": 0, "throttled": 0,
                    "done": 0, "quarantined": 0,
                })
                st = queue.state(jid, now)
                if st in ("pending", "backoff"):
                    tally["queued"] += 1
                elif st in ("running", "stale"):
                    tally["running"] += 1
                elif st in tally:
                    tally[st] += 1
            quotas = {t.name: t for t in tenant_entries}
            for name, tally in sorted(per_tenant.items()):
                t = quotas.get(name)
                u = usage_section.get(name) or {}
                tenants_section[name] = {
                    **tally,
                    "quota": t.quota_doc() if t else None,
                    "window_device_s": (
                        (u.get("window") or {}).get("device_seconds")
                    ),
                    "device_s_budget": (
                        t.device_seconds if t and t.device_seconds
                        else None
                    ),
                    "throttle": (
                        (throttles.get(name) or {}).get("reason")
                    ),
                }
    except Exception as exc:
        tenants_section = {}
        usage_section = {"invalid": f"{exc!s:.200}"}
    data_quality = data_quality_summary(done)
    sentinels = sentinel_status(root, queue)
    data_quality["sentinels"] = {
        "total": len(sentinels),
        "pending": sum(
            1 for s in sentinels if s.get("status") == "pending"
        ),
        "recovered": sum(
            1 for s in sentinels if s.get("status") == "recovered"
        ),
        "missed": sum(
            1 for s in sentinels if s.get("status") == "missed"
        ),
    }
    return {
        "schema": CAMPAIGN_SCHEMA,
        "version": CAMPAIGN_VERSION,
        "root": os.path.abspath(root),
        "updated_unix": now,
        "queue": counts,
        "done": queue.drained(),
        "running_jobs": running,
        "failures": failures,
        "quarantined": quarantined,
        "throughput_jobs_per_s": throughput,
        "eta_s": eta_s,
        "candidates_total": n_candidates,
        # AOT warmup rollup: seconds spent compiling ahead of data
        # across all workers' first-of-bucket jobs (perf/warmup.py)
        "warmup_total_s": round(warmup_s, 3),
        "warmup_jobs": warmed_jobs,
        # dedispersion auto-tuning rollup (perf/tuning.py): measuring
        # time paid (once per bucket per device) and the per-bucket
        # warm/plan tallies warmup-aware claiming reads
        "tuning_total_s": round(tuning_s, 3),
        "warm_buckets": warm_buckets,
        # what completed jobs survived (resilience/stats.py deltas)
        "resilience": resilience,
        # elastic fleet view: live membership + per-worker throughput
        "fleet": {
            "live": live_workers,
            "workers": per_worker,
        },
        # jobs that completed on a degradation rung (OOM fall-through,
        # crashed helper thread) and quarantined *.corrupt artifacts
        "degraded_jobs": degraded_jobs,
        "corrupt_artifact_files": corrupt_files,
        # per-worker time-series on disk (peasoup-campaign metrics)
        "metrics": {"files": len(mpaths), "bytes": mbytes},
        # device-profile captures on disk (prune with
        # `peasoup-campaign prune --profiles`)
        "profiles": {"captures": profile_dirs, "bytes": profile_bytes},
        # priority preemption: revoked/resumed jobs + revoke latency
        "preemptions": preemptions,
        # gang-scheduled (nprocs > 1) completions
        "gang_jobs": gang_jobs,
        # autoscale controller decision log (None when no controller
        # has acted on this campaign)
        "autoscale": autoscale,
        # survey health: alert lifecycle counts + active alerts
        # (obs/alerts.py snapshot) and the scientific data-quality
        # baselines/outliers/sentinels (obs/health.py)
        "alerts": alerts_section,
        "data_quality": data_quality,
        # multi-tenant view: per-tenant queue tallies + quota/throttle
        # state, and the usage ledger (device-seconds, jobs, bytes,
        # compiles per tenant — campaign/usage.py)
        "tenants": tenants_section,
        "usage": usage_section,
    }


def write_status(root: str, queue: JobQueue | None = None) -> dict:
    """Build + atomically rewrite ``<root>/campaign_status.json``."""
    doc = build_status(root, queue)
    path = os.path.join(root, "campaign_status.json")
    fd, tmp = tempfile.mkstemp(dir=root, suffix=".tmp")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(doc, f, indent=2)
            f.write("\n")
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise
    if doc.get("tenants"):
        # the standalone usage ledger beside the snapshot: portal
        # /usage and external accounting read the file, not the rollup
        try:
            from .usage import write_usage

            write_usage(root, queue=queue)
        except Exception:
            pass  # usage must never fail the status write
    return doc


def load_campaign_status(path: str) -> dict:
    """Load + validate a campaign_status.json snapshot."""
    with open(path) as f:
        doc = json.load(f)
    if doc.get("schema") != CAMPAIGN_SCHEMA:
        raise ValueError(
            f"{path}: not a {CAMPAIGN_SCHEMA} snapshot "
            f"(schema={doc.get('schema')!r})"
        )
    return doc
