"""Fleet autoscaling: grow and shrink the worker pool from the rollup.

The campaign layer already tolerates elastic membership — workers join
and leave at will, leases expire, claims reap — but *someone* has to
decide when the fleet is the wrong size. This controller closes that
loop: it reads the same ``campaign_status.json`` aggregates operators
watch (queue depth by derived state, live membership, per-worker
throughput), applies bounded hysteresis (min/max worker counts, a
cooldown between actions), and acts through the fleet's existing
elasticity verbs:

- **scale up** — spawn a REAL ``peasoup-campaign run`` subprocess
  against the campaign directory (the campaign.json already on disk
  governs its semantics; the shared persistent compilation cache means
  it cold-starts warm);
- **scale down** — write a retire marker beside an idle worker's
  registry entry (campaign/registry.py ``request_retire``): the worker
  observes it between jobs — or mid-job via the revoke token, where it
  checkpoints and releases its claim with ZERO attempts consumed —
  deregisters, and exits. Retirement is elasticity, never failure.

Every decision (including the "no" ones worth explaining) is appended
to ``<root>/autoscale.json``, which the rollup embeds as the
``autoscale`` section of ``campaign_status.json`` — the controller's
reasoning is part of the campaign's operator surface.

Bounds are hard invariants, unit-tested against synthetic rollup
traces: the controller never spawns past ``max_workers``, never
retires below ``min_workers``, and honours ``cooldown_s`` between
actions (restoring the ``min_workers`` floor is the one exemption —
a fleet below its floor is an outage, not an optimisation).
"""

from __future__ import annotations

import dataclasses
import json
import os
import subprocess
import sys
import tempfile
import time

from ..obs import get_logger
from ..obs.metrics import MetricsRecorder
from .registry import WorkerRegistry
from .rollup import build_status

log = get_logger("campaign.autoscale")

AUTOSCALE_FILENAME = "autoscale.json"
AUTOSCALE_SCHEMA = "peasoup_tpu.autoscale"
MAX_LOGGED_DECISIONS = 200


@dataclasses.dataclass
class AutoscalePolicy:
    """The controller's bounds and thresholds."""

    min_workers: int = 1
    max_workers: int = 4
    cooldown_s: float = 60.0
    # scale up when the claimable backlog (pending + backoff + stale)
    # exceeds this many jobs per live worker
    backlog_per_worker: float = 2.0
    # scale down only when the backlog is empty AND at least one live
    # worker is idle (retiring a busy worker would checkpoint-cycle a
    # job for nothing)
    retire_when_idle: bool = True


def _atomic_write_json(path: str, doc: dict) -> None:
    d = os.path.dirname(path) or "."
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(doc, f, indent=2)
            f.write("\n")
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def load_autoscale_log(root: str) -> dict | None:
    try:
        with open(os.path.join(root, AUTOSCALE_FILENAME)) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    return doc if doc.get("schema") == AUTOSCALE_SCHEMA else None


def default_spawn(root: str, worker_id: str, extra_args=None, env=None):
    """Spawn a real campaign worker subprocess (the production scale-up
    action). The campaign.json already persisted in ``root`` governs
    its pipeline/config — first writer wins — so the spawn needs no
    knowledge of the campaign's semantics. Returns the Popen."""
    cmd = [
        sys.executable, "-m", "peasoup_tpu.cli.campaign", "run",
        "-w", root, "--worker-id", worker_id,
    ] + list(extra_args or [])
    proc = subprocess.Popen(
        cmd,
        stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
        env=env,
        start_new_session=True,
    )
    log.info(
        "autoscale: spawned worker %s (pid %d)", worker_id, proc.pid
    )
    return proc


class AutoscaleController:
    """One controller process (or thread) supervising one campaign.

    ``spawn`` / ``retire`` are injectable for tests; the defaults
    spawn real ``peasoup-campaign run`` subprocesses and write retire
    markers through the worker registry.
    """

    def __init__(
        self,
        root: str,
        policy: AutoscalePolicy | None = None,
        spawn=None,
        retire=None,
        extra_args=None,
        env=None,
        controller_id: str = "autoscale",
    ) -> None:
        self.root = os.path.abspath(root)
        self.policy = policy or AutoscalePolicy()
        if self.policy.min_workers > self.policy.max_workers:
            raise ValueError(
                f"autoscale bounds inverted: min "
                f"{self.policy.min_workers} > max "
                f"{self.policy.max_workers}"
            )
        self.registry = WorkerRegistry(self.root)
        self.controller_id = controller_id
        # the controller's own time series rides the same fleet
        # directory as the workers': its decisions are fleet metrics
        self.metrics = MetricsRecorder(
            self.registry.metrics_path(controller_id)
        )
        self._extra_args = list(extra_args or [])
        self._env = env
        self._spawn = spawn or (
            lambda wid: default_spawn(
                self.root, wid, self._extra_args, self._env
            )
        )
        self._retire = retire or (
            lambda wid: self.registry.request_retire(
                wid, requester=self.controller_id
            )
        )
        self._spawned: dict[str, object] = {}  # worker_id -> handle
        self._n_spawned = 0
        self.last_action_unix = 0.0
        self.decisions: list[dict] = []
        prev = load_autoscale_log(self.root)
        if prev:
            # a restarted controller keeps its hysteresis: the
            # cooldown must survive the controller process, or a
            # crash-loop would flap the fleet
            self.last_action_unix = float(prev.get("last_action_unix", 0))
            self._n_spawned = int(prev.get("spawned_total", 0))

    # --- the pure decision (unit-tested on synthetic rollups) ---------
    def decide(self, status: dict, now: float | None = None) -> dict | None:
        """Map one rollup snapshot to an action dict ({"action":
        "up"|"down", "worker_id", "reason"}) or None. Pure in
        ``status`` + controller hysteresis state — no filesystem, no
        subprocesses — so traces of synthetic rollups pin the bounds."""
        now = time.time() if now is None else now
        pol = self.policy
        q = status.get("queue") or {}
        fleet = status.get("fleet") or {}
        live = fleet.get("live") or []
        n_live = len(live)
        backlog = (
            int(q.get("pending", 0))
            + int(q.get("backoff", 0))
            + int(q.get("stale", 0))
        )
        idle = [w for w in live if w.get("current_job") is None]
        throughput = status.get("throughput_jobs_per_s")
        in_cooldown = (
            self.last_action_unix
            and now - self.last_action_unix < pol.cooldown_s
        )
        if status.get("done"):
            return None  # drained: nothing to scale for
        if n_live < pol.min_workers:
            # the floor is an outage, not an optimisation: restoring
            # it is exempt from the cooldown
            return {
                "action": "up",
                "worker_id": self._next_worker_id(),
                "reason": (
                    f"live {n_live} below min_workers "
                    f"{pol.min_workers}"
                ),
            }
        if in_cooldown:
            return None
        if (
            backlog > pol.backlog_per_worker * max(1, n_live)
            and n_live < pol.max_workers
        ):
            return {
                "action": "up",
                "worker_id": self._next_worker_id(),
                "reason": (
                    f"backlog {backlog} > {pol.backlog_per_worker:g}/"
                    f"worker x {n_live} live"
                    + (
                        f" (throughput {throughput * 3600.0:.3g} jobs/h)"
                        if throughput else ""
                    )
                ),
            }
        if (
            backlog == 0
            and int(q.get("running", 0)) < n_live
            and n_live > pol.min_workers
            and (not self.policy.retire_when_idle or idle)
        ):
            victim = self._pick_retiree(idle or live)
            if victim is not None:
                return {
                    "action": "down",
                    "worker_id": victim,
                    "reason": (
                        f"backlog empty, {len(idle)} idle of {n_live} "
                        f"live > min_workers {pol.min_workers}"
                    ),
                }
        return None

    def _next_worker_id(self) -> str:
        self._n_spawned += 1
        return f"{self.controller_id}-{self._n_spawned}"

    def _pick_retiree(self, candidates: list[dict]) -> str | None:
        """Prefer retiring a worker this controller spawned (giving
        back what it took before touching operator-started workers)."""
        ids = [
            w.get("worker_id") for w in candidates if w.get("worker_id")
        ]
        for wid in ids:
            if wid in self._spawned:
                return wid
        return ids[0] if ids else None

    # --- acting + the decision log ------------------------------------
    def step(self, now: float | None = None) -> dict | None:
        """One control iteration: rollup -> decide -> act -> log.
        Returns the applied decision (or None)."""
        now = time.time() if now is None else now
        status = build_status(self.root)
        decision = self.decide(status, now)
        if decision is None:
            return None
        decision["unix"] = now
        decision["live"] = len(
            (status.get("fleet") or {}).get("live") or []
        )
        if decision["action"] == "up":
            handle = self._spawn(decision["worker_id"])
            self._spawned[decision["worker_id"]] = handle
        else:
            self._retire(decision["worker_id"])
        self.last_action_unix = now
        self.decisions.append(decision)
        self._write_log(now)
        try:
            self.metrics.counter(
                "autoscale_decisions_total", action=decision["action"]
            )
            self.metrics.gauge(
                "autoscale_live_workers", decision.get("live", 0)
            )
        except Exception:
            log.debug("autoscale metrics failed", exc_info=True)
        log.info(
            "autoscale %s: %s (%s)", decision["action"],
            decision["worker_id"], decision["reason"],
        )
        return decision

    def _write_log(self, now: float) -> None:
        _atomic_write_json(
            os.path.join(self.root, AUTOSCALE_FILENAME),
            {
                "schema": AUTOSCALE_SCHEMA,
                "controller_id": self.controller_id,
                "updated_unix": now,
                "last_action_unix": self.last_action_unix,
                "spawned_total": self._n_spawned,
                "policy": dataclasses.asdict(self.policy),
                "decisions": self.decisions[-MAX_LOGGED_DECISIONS:],
            },
        )

    def run(
        self,
        poll_s: float = 5.0,
        max_runtime_s: float | None = None,
        stop_when_drained: bool = True,
    ) -> list[dict]:
        """The control loop. Returns the decisions taken."""
        t0 = time.monotonic()
        while True:
            if (
                max_runtime_s is not None
                and time.monotonic() - t0 > max_runtime_s
            ):
                break
            try:
                self.step()
            except Exception:
                log.warning("autoscale step failed", exc_info=True)
            if stop_when_drained:
                try:
                    from .queue import JobQueue

                    if JobQueue(self.root).drained():
                        break
                except Exception:
                    pass
            time.sleep(poll_s)
        self.reap_spawned()
        return self.decisions

    def reap_spawned(self, timeout_s: float = 60.0) -> None:
        """Wait out subprocess handles this controller spawned (drained
        workers exit on their own; anything else is left to the fleet's
        normal lease/registry reaping)."""
        for wid, handle in list(self._spawned.items()):
            wait = getattr(handle, "wait", None)
            if wait is None:
                continue
            try:
                wait(timeout=timeout_s)
            except Exception:
                log.warning(
                    "autoscale-spawned worker %s did not exit within "
                    "%.0fs", wid, timeout_s,
                )
