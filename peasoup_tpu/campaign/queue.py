"""File-backed job queue for multi-worker survey campaigns.

No daemon, no database: the queue IS the filesystem, so any number of
workers on any number of hosts coordinate through a shared campaign
directory (the standard deployment for survey pipelines on cluster
filesystems). Every state transition is an atomic filesystem operation:

- **enqueue** — ``O_CREAT|O_EXCL`` of ``queue/jobs/<id>.json``; two
  workers enqueueing the same manifest collide harmlessly (first wins).
- **claim** — ``O_CREAT|O_EXCL`` of ``queue/claims/<id>.json`` carrying
  the worker identity and a lease expiry. Exactly one claimant can win.
- **renew** — the owner atomically rewrites its claim with a fresh
  expiry (tmp + ``os.replace``); a live worker never loses its lease.
- **reap** — anyone may reap an EXPIRED claim (a SIGKILLed worker never
  releases). The reaper wins an ``os.rename`` race to a private
  tombstone; the loser gets ``FileNotFoundError`` and walks away. A
  reaped job counts as one failed attempt and re-queues with backoff.
- **complete / fail** — the claim holder writes ``queue/done/<id>.json``
  or updates the job record (attempts, exponential-backoff
  ``next_eligible_unix``), then releases the claim. After
  ``max_attempts`` failures the job lands in
  ``queue/quarantine/<id>.json`` and is never claimed again until an
  operator re-queues it (``campaign retry``).

Job records are only ever mutated by the current claim holder (or the
reap winner), so a tmp + ``os.replace`` rewrite needs no further
locking. States are derived, not stored: a job is *pending* when it has
no claim/done/quarantine marker and its backoff has elapsed.
"""

from __future__ import annotations

import hashlib
import json
import os
import socket
import tempfile
import time
import uuid
from dataclasses import dataclass, field

from ..obs import get_logger
from ..resilience import IO_RETRY, faults, is_transient

log = get_logger("campaign.queue")

# terminal + live marker subdirectories under <root>/queue/
_JOBS = "jobs"
_CLAIMS = "claims"
_DONE = "done"
_QUARANTINE = "quarantine"


def _atomic_write_json(path: str, doc: dict) -> None:
    d = os.path.dirname(path) or "."
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(doc, f, indent=2)
            f.write("\n")
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def _read_json(path: str) -> dict | None:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None  # gone, mid-replace, or torn: treat as absent


def job_id_for(input_path: str) -> str:
    """Stable job id for an observation: file stem + a short hash of
    the absolute path, so two workers enqueueing the same manifest
    derive the same id (enqueue is idempotent) and two files with the
    same stem in different directories stay distinct."""
    ap = os.path.abspath(input_path)
    stem = os.path.splitext(os.path.basename(ap))[0]
    safe = "".join(c if c.isalnum() or c in "-_." else "_" for c in stem)
    return f"{safe[:48]}-{hashlib.sha1(ap.encode()).hexdigest()[:8]}"


@dataclass
class Job:
    """One observation to process. ``config`` holds per-job pipeline
    overrides (merged over the campaign's); ``bucket`` is the padded
    shape key the scheduler groups on (None when the header could not
    be read at enqueue time — the job will fail at run time and walk
    the normal retry/quarantine path)."""

    job_id: str
    input: str
    pipeline: str = "spsearch"
    config: dict = field(default_factory=dict)
    bucket: tuple | None = None
    priority: int = 0  # higher claims sooner; outranks bucket affinity
    attempts: int = 0
    next_eligible_unix: float = 0.0
    last_error: str | None = None
    created_unix: float = 0.0

    def to_doc(self) -> dict:
        return {
            "job_id": self.job_id,
            "input": self.input,
            "pipeline": self.pipeline,
            "config": self.config,
            "bucket": list(self.bucket) if self.bucket else None,
            "priority": self.priority,
            "attempts": self.attempts,
            "next_eligible_unix": self.next_eligible_unix,
            "last_error": self.last_error,
            "created_unix": self.created_unix,
        }

    @classmethod
    def from_doc(cls, doc: dict) -> "Job":
        b = doc.get("bucket")
        return cls(
            job_id=doc["job_id"],
            input=doc.get("input", ""),
            pipeline=doc.get("pipeline", "spsearch"),
            config=doc.get("config") or {},
            bucket=tuple(b) if b else None,
            priority=int(doc.get("priority", 0)),
            attempts=int(doc.get("attempts", 0)),
            next_eligible_unix=float(doc.get("next_eligible_unix", 0.0)),
            last_error=doc.get("last_error"),
            created_unix=float(doc.get("created_unix", 0.0)),
        )


@dataclass
class Claim:
    """A held lease on one job. Only its holder may complete/fail the
    job or rewrite the job record."""

    job: Job
    worker_id: str
    expires_unix: float
    path: str


class JobQueue:
    """The file-backed queue rooted at ``<root>/queue/``."""

    def __init__(
        self,
        root: str,
        lease_s: float = 60.0,
        max_attempts: int = 3,
        backoff_base_s: float = 2.0,
    ) -> None:
        self.root = os.path.abspath(root)
        self.qdir = os.path.join(self.root, "queue")
        self.lease_s = float(lease_s)
        self.max_attempts = int(max_attempts)
        self.backoff_base_s = float(backoff_base_s)
        for sub in (_JOBS, _CLAIMS, _DONE, _QUARANTINE):
            os.makedirs(os.path.join(self.qdir, sub), exist_ok=True)

    # --- paths --------------------------------------------------------
    def _p(self, sub: str, job_id: str) -> str:
        return os.path.join(self.qdir, sub, f"{job_id}.json")

    # --- enqueue ------------------------------------------------------
    def add_job(self, job: Job) -> bool:
        """Idempotent enqueue: True when this call created the record,
        False when the job already exists (any state)."""
        job.created_unix = job.created_unix or time.time()
        path = self._p(_JOBS, job.job_id)
        try:
            fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except FileExistsError:
            return False
        with os.fdopen(fd, "w") as f:
            json.dump(job.to_doc(), f, indent=2)
            f.write("\n")
        log.debug("enqueued %s (%s)", job.job_id, job.input)
        return True

    # --- inspection ---------------------------------------------------
    def job_ids(self) -> list[str]:
        return sorted(
            os.path.splitext(n)[0]
            for n in os.listdir(os.path.join(self.qdir, _JOBS))
            if n.endswith(".json")
        )

    def get_job(self, job_id: str) -> Job | None:
        doc = _read_json(self._p(_JOBS, job_id))
        return Job.from_doc(doc) if doc else None

    def state(self, job_id: str, now: float | None = None) -> str:
        """Derived state: done | quarantined | running | stale |
        backoff | pending | unknown."""
        now = time.time() if now is None else now
        if os.path.exists(self._p(_DONE, job_id)):
            return "done"
        if os.path.exists(self._p(_QUARANTINE, job_id)):
            return "quarantined"
        claim = _read_json(self._p(_CLAIMS, job_id))
        if claim is not None:
            return (
                "running"
                if float(claim.get("expires_unix", 0)) >= now
                else "stale"
            )
        job = self.get_job(job_id)
        if job is None:
            return "unknown"
        return "backoff" if job.next_eligible_unix > now else "pending"

    def counts(self) -> dict[str, int]:
        out = {
            "total": 0, "pending": 0, "backoff": 0, "running": 0,
            "stale": 0, "done": 0, "quarantined": 0,
        }
        now = time.time()
        for jid in self.job_ids():
            out["total"] += 1
            st = self.state(jid, now)
            if st in out:
                out[st] += 1
        return out

    def drained(self) -> bool:
        """True when every job is terminal (done or quarantined)."""
        c = self.counts()
        return c["total"] > 0 and c["done"] + c["quarantined"] == c["total"]

    # --- claim / renew / release -------------------------------------
    @staticmethod
    def default_worker_id() -> str:
        return f"{socket.gethostname()}-{os.getpid()}"

    def try_claim(
        self, job_id: str, worker_id: str, now: float | None = None
    ) -> Claim | None:
        now = time.time() if now is None else now
        if os.path.exists(self._p(_DONE, job_id)) or os.path.exists(
            self._p(_QUARANTINE, job_id)
        ):
            return None
        job = self.get_job(job_id)
        if job is None or job.next_eligible_unix > now:
            return None
        path = self._p(_CLAIMS, job_id)

        def _create_claim():
            faults.fire("queue.claim", context=job_id)
            return os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)

        try:
            # transient I/O (flaky mount, injected queue.claim fault)
            # retries under the shared policy; losing the O_EXCL race
            # (FileExistsError) is a protocol outcome, not an error
            fd = IO_RETRY.call(
                _create_claim, site="queue.claim", context=job_id
            )
        except FileExistsError:
            return None
        except OSError as exc:
            if is_transient(exc):
                # retry budget spent: walk away; the job stays pending
                # and any worker (including us, next poll) claims it
                log.warning(
                    "claim of %s abandoned after transient I/O "
                    "failures: %.200s", job_id, exc,
                )
                return None
            raise
        if os.path.exists(self._p(_DONE, job_id)) or os.path.exists(
            self._p(_QUARANTINE, job_id)
        ):
            # lost the completion race: between our eligibility check
            # and the O_EXCL create, the previous owner finished and
            # released — without this re-check a second worker would
            # re-run a terminal job (exactly-once violation seen as a
            # duplicate under load in the two-worker race test)
            os.close(fd)
            try:
                os.unlink(path)
            except FileNotFoundError:
                pass
            return None
        expires = now + self.lease_s
        with os.fdopen(fd, "w") as f:
            json.dump(
                {
                    "job_id": job_id,
                    "worker_id": worker_id,
                    "pid": os.getpid(),
                    "hostname": socket.gethostname(),
                    "claimed_unix": now,
                    "expires_unix": expires,
                },
                f, indent=2,
            )
            f.write("\n")
        return Claim(
            job=job, worker_id=worker_id, expires_unix=expires, path=path
        )

    def claim_next(
        self,
        worker_id: str,
        prefer_bucket: tuple | None = None,
        warm_buckets: "set[tuple] | frozenset[tuple] | None" = None,
    ) -> Claim | None:
        """Claim the next eligible job, ranked priority class first
        (higher ``Job.priority`` always claims sooner — an urgent
        re-observation must not wait behind a warm-bucket streak),
        then jobs sharing ``prefer_bucket`` (the worker's previous
        shape bucket), then jobs whose bucket is in ``warm_buckets``
        (buckets already warmed/tuned — this worker's own plus any
        recorded in the campaign's done records, see runner.py), then
        the remainder — each tier grouped BY bucket — so a fleet of
        workers naturally partitions into shape-coherent streaks,
        consecutive jobs hit the compiled-program caches, and
        already-paid warmup/tuning work is exploited before any new
        bucket is opened."""
        self.reap_stale()
        now = time.time()
        warm = {tuple(b) for b in warm_buckets} if warm_buckets else set()
        eligible: list[tuple[tuple, str]] = []
        for jid in self.job_ids():
            if self.state(jid, now) != "pending":
                continue
            job = self.get_job(jid)
            if job is None:
                continue
            bucket = job.bucket or ()
            if prefer_bucket and bucket == tuple(prefer_bucket):
                tier = 0
            elif bucket and tuple(bucket) in warm:
                tier = 1
            else:
                tier = 2
            rank = (
                -job.priority,
                tier,
                tuple(str(x) for x in bucket),
                jid,
            )
            eligible.append((rank, jid))
        for _, jid in sorted(eligible):
            claim = self.try_claim(jid, worker_id, now)
            if claim is not None:
                return claim
        return None

    def renew(self, claim: Claim) -> None:
        """Extend the holder's lease (atomic rewrite of the claim)."""
        claim.expires_unix = time.time() + self.lease_s
        doc = _read_json(claim.path) or {}
        doc.update(
            {
                "job_id": claim.job.job_id,
                "worker_id": claim.worker_id,
                "pid": os.getpid(),
                "hostname": socket.gethostname(),
                "expires_unix": claim.expires_unix,
            }
        )
        _atomic_write_json(claim.path, doc)

    # --- terminal transitions ----------------------------------------
    def complete(self, claim: Claim, **info) -> None:
        """Success: write the done record, release the claim."""
        _atomic_write_json(
            self._p(_DONE, claim.job.job_id),
            {
                "job_id": claim.job.job_id,
                "input": claim.job.input,
                "worker_id": claim.worker_id,
                "finished_unix": time.time(),
                "attempts": claim.job.attempts + 1,
                **info,
            },
        )
        self._release(claim)

    def fail(self, claim: Claim, error: str) -> str:
        """Failure by the claim holder: one attempt consumed. Returns
        the resulting state: 'backoff' (will retry) or 'quarantined'."""
        state = self._record_failure(claim.job.job_id, error)
        self._release(claim)
        return state

    def release(self, claim: Claim) -> None:
        """Voluntary release by the claim holder — a worker leaving the
        fleet cleanly hands its unstarted job back with ZERO attempts
        consumed (a clean leave is elasticity, not a failure; the job
        is immediately claimable by anyone)."""
        self._release(claim)
        log.info(
            "claim on %s released cleanly by %s (no attempt consumed)",
            claim.job.job_id, claim.worker_id,
        )

    def _release(self, claim: Claim) -> None:
        try:
            os.unlink(claim.path)
        except FileNotFoundError:
            pass  # reaped from under us (lease must have expired)

    def _record_failure(self, job_id: str, error: str) -> str:
        """Consume one attempt: exponential backoff, or quarantine when
        the budget is spent. Caller must hold the claim (or have won
        the reap race) — job records have a single writer at a time."""
        job = self.get_job(job_id)
        if job is None:
            return "unknown"
        job.attempts += 1
        job.last_error = f"{error}"[:2000]
        if job.attempts >= self.max_attempts:
            _atomic_write_json(
                self._p(_QUARANTINE, job_id),
                {
                    "job_id": job_id,
                    "input": job.input,
                    "attempts": job.attempts,
                    "last_error": job.last_error,
                    "quarantined_unix": time.time(),
                },
            )
            _atomic_write_json(self._p(_JOBS, job_id), job.to_doc())
            log.warning(
                "job %s quarantined after %d attempts: %s",
                job_id, job.attempts, job.last_error,
            )
            return "quarantined"
        backoff = self.backoff_base_s * (2 ** (job.attempts - 1))
        job.next_eligible_unix = time.time() + backoff
        _atomic_write_json(self._p(_JOBS, job_id), job.to_doc())
        log.warning(
            "job %s failed (attempt %d/%d, retry in %.3gs): %s",
            job_id, job.attempts, self.max_attempts, backoff,
            job.last_error,
        )
        return "backoff"

    # --- stale-claim reaping -----------------------------------------
    def reap_stale(self, now: float | None = None) -> list[str]:
        """Re-queue jobs whose claim lease expired (their worker was
        SIGKILLed or wedged past its lease). Exactly one reaper wins
        per claim: the claim is renamed to a private tombstone first,
        and only the winner of that rename records the failure.

        A renewal racing the reap is detected by re-reading the
        tombstone: if the lease is no longer expired the rename
        caught a freshly renewed claim, and it is put back."""
        now = time.time() if now is None else now
        # chaos seam: a scheduled clock.skew fault shifts THIS
        # reaper's view of lease expiry (drills premature reaping —
        # the renew-race putback below must absorb it)
        now += faults.clock_skew_s()
        reaped = []
        cdir = os.path.join(self.qdir, _CLAIMS)
        for name in sorted(os.listdir(cdir)):
            if not name.endswith(".json"):
                continue
            path = os.path.join(cdir, name)
            doc = _read_json(path)
            if doc is None or float(doc.get("expires_unix", 0)) >= now:
                continue
            tomb = f"{path}.reap.{uuid.uuid4().hex[:8]}"
            try:
                os.rename(path, tomb)
            except OSError:
                continue  # lost the reap race
            fresh = _read_json(tomb)
            if fresh and float(fresh.get("expires_unix", 0)) >= now:
                # the owner renewed between our read and the rename:
                # restore its claim (if a third party claimed in the
                # gap the owner has genuinely lost the lease)
                try:
                    os.rename(tomb, path)
                except OSError:
                    os.unlink(tomb)
                continue
            job_id = os.path.splitext(name)[0]
            worker = (fresh or {}).get("worker_id", "?")
            self._record_failure(
                job_id,
                f"lease expired (worker {worker} presumed dead)",
            )
            os.unlink(tomb)
            reaped.append(job_id)
            log.warning(
                "reaped stale claim on %s (worker %s)", job_id, worker
            )
        return reaped

    # --- operator controls -------------------------------------------
    def quarantined(self) -> list[dict]:
        qdir = os.path.join(self.qdir, _QUARANTINE)
        out = []
        for name in sorted(os.listdir(qdir)):
            if name.endswith(".json"):
                doc = _read_json(os.path.join(qdir, name))
                if doc:
                    out.append(doc)
        return out

    def retry(self, job_id: str) -> bool:
        """Re-queue a quarantined job: reset its attempt budget and
        remove the quarantine marker. Returns False when the job is
        not quarantined."""
        qpath = self._p(_QUARANTINE, job_id)
        if not os.path.exists(qpath):
            return False
        job = self.get_job(job_id)
        if job is None:
            return False
        job.attempts = 0
        job.next_eligible_unix = 0.0
        _atomic_write_json(self._p(_JOBS, job_id), job.to_doc())
        # marker removed LAST: a crash mid-retry leaves the job
        # quarantined (safe), never half-requeued
        os.unlink(qpath)
        log.info("job %s re-queued from quarantine", job_id)
        return True

    def done_records(self) -> list[dict]:
        ddir = os.path.join(self.qdir, _DONE)
        out = []
        for name in sorted(os.listdir(ddir)):
            if name.endswith(".json"):
                doc = _read_json(os.path.join(ddir, name))
                if doc:
                    out.append(doc)
        return out
