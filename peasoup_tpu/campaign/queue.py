"""File-backed job queue for multi-worker survey campaigns.

No daemon, no database: the queue IS the filesystem, so any number of
workers on any number of hosts coordinate through a shared campaign
directory (the standard deployment for survey pipelines on cluster
filesystems). Every state transition is an atomic filesystem operation:

- **enqueue** — ``O_CREAT|O_EXCL`` of ``queue/jobs/<id>.json``; two
  workers enqueueing the same manifest collide harmlessly (first wins).
- **claim** — ``O_CREAT|O_EXCL`` of ``queue/claims/<id>.json`` carrying
  the worker identity and a lease expiry. Exactly one claimant can win.
- **renew** — the owner republishes its claim with a fresh expiry via
  the ownership dance (take-verify-recreate, below); a *deposed*
  owner (reaped, job re-claimed) learns it lost the lease instead of
  stomping the new owner's claim.
- **reap** — anyone may reap an EXPIRED claim (a SIGKILLed worker never
  releases). The reaper wins an ``os.rename`` race to a private
  tombstone; the loser gets ``FileNotFoundError`` and walks away. A
  reaped job counts as one failed attempt and re-queues with backoff.
- **complete / fail** — the claim holder writes ``queue/done/<id>.json``
  or updates the job record (attempts, exponential-backoff
  ``next_eligible_unix``), then releases the claim. After
  ``max_attempts`` failures the job lands in
  ``queue/quarantine/<id>.json`` and is never claimed again until an
  operator re-queues it (``campaign retry``).

Job records are only ever mutated by the current claim holder (or the
reap winner), so a tmp + ``os.replace`` rewrite needs no further
locking. States are derived, not stored: a job is *pending* when it has
no claim/done/quarantine marker and its backoff has elapsed.

**The ownership dance.** Every holder-side transition (renew,
complete, fail, release, preempted release, carried-resilience
rewrite) must first prove it still holds the lease — a worker that
was reaped while wedged is a *zombie*, and a zombie acting on its
stale :class:`Claim` used to delete the new owner's claim, overwrite
its renewed lease, double-charge attempts or double-publish done
records (all found by the protocol model checker,
``analysis/mc/``). :meth:`JobQueue._take_claim` serializes this
against the reaper with the same primitive the reaper uses: rename
the claim to a private tombstone, re-read, and verify the document
still names us; on mismatch the rename is undone and the caller
learns the lease is lost. Done records publish via tmp +
``os.link`` — all-or-nothing, and a duplicate publication surfaces
as ``FileExistsError`` instead of a silent overwrite.
"""

from __future__ import annotations

import hashlib
import json
import os
import socket
import tempfile
import time
import uuid
from dataclasses import dataclass, field

from ..obs import get_logger
from ..resilience import IO_RETRY, faults, is_transient

log = get_logger("campaign.queue")

# terminal + live marker subdirectories under <root>/queue/
_JOBS = "jobs"
_CLAIMS = "claims"
_DONE = "done"
_QUARANTINE = "quarantine"
# per-worker append-only spools for LOST attempts' resilience marks
_RESILIENCE = "resilience"


def _atomic_write_json(path: str, doc: dict) -> None:
    d = os.path.dirname(path) or "."
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(doc, f, indent=2)
            f.write("\n")
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def _read_json(path: str) -> dict | None:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None  # gone, mid-replace, or torn: treat as absent


def _discard(path: str) -> None:
    """Consume a dance artifact (tombstone/tmp) that may already be
    gone: the orphan sweep ages tombstones out by st_ctime, so a
    holder stalled long enough mid-dance finds its tombstone swept by
    a peer — the unlink's outcome is the same either way."""
    try:
        os.unlink(path)
    except FileNotFoundError:
        pass


def job_id_for(input_path: str) -> str:
    """Stable job id for an observation: file stem + a short hash of
    the absolute path, so two workers enqueueing the same manifest
    derive the same id (enqueue is idempotent) and two files with the
    same stem in different directories stay distinct."""
    ap = os.path.abspath(input_path)
    stem = os.path.splitext(os.path.basename(ap))[0]
    safe = "".join(c if c.isalnum() or c in "-_." else "_" for c in stem)
    return f"{safe[:48]}-{hashlib.sha1(ap.encode()).hexdigest()[:8]}"


@dataclass
class Job:
    """One observation to process. ``config`` holds per-job pipeline
    overrides (merged over the campaign's); ``bucket`` is the padded
    shape key the scheduler groups on (None when the header could not
    be read at enqueue time — the job will fail at run time and walk
    the normal retry/quarantine path)."""

    job_id: str
    input: str
    pipeline: str = "spsearch"
    config: dict = field(default_factory=dict)
    bucket: tuple | None = None
    priority: int = 0  # higher claims sooner; outranks bucket affinity
    nprocs: int = 1  # >1: gang-scheduled across a named process group
    # multi-tenant stamp (campaign/tenants.py): which tenant submitted
    # this observation; empty = operator-owned (quota-exempt). Rides
    # into done records, metrics labels and the usage ledger
    tenant: str = ""
    # trace correlation (obs/trace.py): minted at enqueue, propagated
    # through claim docs / preempt requests / gang invitations, so a
    # preempted-and-resumed or gang-scheduled job renders as ONE
    # connected trace across every worker process that touched it
    trace_id: str = ""
    attempts: int = 0
    next_eligible_unix: float = 0.0
    last_error: str | None = None
    created_unix: float = 0.0
    # preemption provenance: how many times a revoke handed this job
    # back (zero attempts consumed) and each revoke's request->release
    # latency — carried into the resumed run's done record
    preemptions: int = 0
    preempt_latency_s: list = field(default_factory=list)
    # resilience counters a RELEASED attempt survived (retries,
    # degradations, injected faults): a revoke consumes zero attempts
    # and writes no done record, so without this carry the marks would
    # vanish and the campaign rollup could no longer attribute every
    # injected fault to its recovery path — the chaos soak's invariant
    carried_resilience: dict = field(default_factory=dict)
    # synthetic injection sentinel (obs/health.py): excluded from the
    # campaign's data-quality baselines and flagged in the rollup
    sentinel: bool = False

    def to_doc(self) -> dict:
        return {
            "job_id": self.job_id,
            "input": self.input,
            "pipeline": self.pipeline,
            "config": self.config,
            "bucket": list(self.bucket) if self.bucket else None,
            "priority": self.priority,
            "nprocs": self.nprocs,
            "tenant": self.tenant,
            "trace_id": self.trace_id,
            "attempts": self.attempts,
            "next_eligible_unix": self.next_eligible_unix,
            "last_error": self.last_error,
            "created_unix": self.created_unix,
            "preemptions": self.preemptions,
            "preempt_latency_s": self.preempt_latency_s,
            "carried_resilience": self.carried_resilience,
            "sentinel": self.sentinel,
        }

    @classmethod
    def from_doc(cls, doc: dict) -> "Job":
        b = doc.get("bucket")
        return cls(
            job_id=doc["job_id"],
            input=doc.get("input", ""),
            pipeline=doc.get("pipeline", "spsearch"),
            config=doc.get("config") or {},
            bucket=tuple(b) if b else None,
            priority=int(doc.get("priority", 0)),
            nprocs=int(doc.get("nprocs", 1)),
            tenant=str(doc.get("tenant") or ""),
            trace_id=str(doc.get("trace_id") or ""),
            attempts=int(doc.get("attempts", 0)),
            next_eligible_unix=float(doc.get("next_eligible_unix", 0.0)),
            last_error=doc.get("last_error"),
            created_unix=float(doc.get("created_unix", 0.0)),
            preemptions=int(doc.get("preemptions", 0)),
            preempt_latency_s=[
                float(x) for x in (doc.get("preempt_latency_s") or [])
            ],
            carried_resilience=doc.get("carried_resilience") or {},
            sentinel=bool(doc.get("sentinel", False)),
        )


@dataclass
class Claim:
    """A held lease on one job. Only its holder may complete/fail the
    job or rewrite the job record. ``gang`` (gang-scheduled jobs only)
    names the process group and the exact member set the leader
    assembled — {"group", "members", "nprocs", "epoch"}."""

    job: Job
    worker_id: str
    expires_unix: float
    path: str
    gang: dict | None = None


class JobQueue:
    """The file-backed queue rooted at ``<root>/queue/``."""

    def __init__(
        self,
        root: str,
        lease_s: float = 60.0,
        max_attempts: int = 3,
        backoff_base_s: float = 2.0,
    ) -> None:
        self.root = os.path.abspath(root)
        self.qdir = os.path.join(self.root, "queue")
        self.lease_s = float(lease_s)
        self.max_attempts = int(max_attempts)
        self.backoff_base_s = float(backoff_base_s)
        for sub in (_JOBS, _CLAIMS, _DONE, _QUARANTINE, _RESILIENCE):
            os.makedirs(os.path.join(self.qdir, sub), exist_ok=True)
        # tenant throttle-map cache: (valid_until_unix, map). The map
        # is an O(jobs + claims + done) artifact scan; state() asks per
        # job, so without the short TTL counts()/claim_next would go
        # quadratic. Claim-time revalidation bypasses it (fresh=True)
        self._throttle_cache: tuple[float, dict] = (0.0, {})

    # --- paths --------------------------------------------------------
    def _p(self, sub: str, job_id: str) -> str:
        return os.path.join(self.qdir, sub, f"{job_id}.json")

    # --- enqueue ------------------------------------------------------
    def add_job(self, job: Job) -> bool:
        """Idempotent enqueue: True when this call created the record,
        False when the job already exists (any state)."""
        job.created_unix = job.created_unix or time.time()
        if not job.trace_id:
            # the trace id is born here: enqueue is the first event of
            # the job's life, and everything downstream inherits it
            from ..obs.trace import new_trace_id

            job.trace_id = new_trace_id()
        path = self._p(_JOBS, job.job_id)
        try:
            fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except FileExistsError:
            return False
        with os.fdopen(fd, "w") as f:
            json.dump(job.to_doc(), f, indent=2)
            f.write("\n")
        log.debug("enqueued %s (%s)", job.job_id, job.input)
        return True

    # --- inspection ---------------------------------------------------
    def job_ids(self) -> list[str]:
        return sorted(
            os.path.splitext(n)[0]
            for n in os.listdir(os.path.join(self.qdir, _JOBS))
            if n.endswith(".json")
        )

    def get_job(self, job_id: str) -> Job | None:
        doc = _read_json(self._p(_JOBS, job_id))
        return Job.from_doc(doc) if doc else None

    def tenant_throttles(
        self, now: float | None = None, fresh: bool = False
    ) -> dict[str, dict]:
        """Currently over-quota tenants (tenants.throttle_map), cached
        for ~0.5s so per-job state() queries stay linear. ``fresh``
        bypasses and refills the cache — the claim-time revalidation
        path, where a stale admission would over-run a quota."""
        now = time.time() if now is None else now
        until, cached = self._throttle_cache
        if not fresh and now < until:
            return cached
        # lazy import: tenants.py is pure stdlib, but keeping the
        # dependency one-way (tenants never imports queue) needs the
        # import at call time, mirroring add_job's obs.trace import
        from .tenants import throttle_map

        m = throttle_map(self.root, now=now)
        self._throttle_cache = (now + 0.5, m)
        return m

    def state(self, job_id: str, now: float | None = None) -> str:
        """Derived state: done | quarantined | running | stale |
        throttled | backoff | pending | unknown."""
        now = time.time() if now is None else now
        if os.path.exists(self._p(_DONE, job_id)):
            return "done"
        if os.path.exists(self._p(_QUARANTINE, job_id)):
            return "quarantined"
        claim = _read_json(self._p(_CLAIMS, job_id))
        if claim is not None:
            return (
                "running"
                if float(claim.get("expires_unix", 0)) >= now
                else "stale"
            )
        job = self.get_job(job_id)
        if job is None:
            return "unknown"
        if job.tenant and job.tenant in self.tenant_throttles(now):
            # over-quota tenants' jobs PARK (visible in counts, the
            # rollup and watch) rather than claim — and rather than
            # being dropped; the state clears when the quota releases
            return "throttled"
        return "backoff" if job.next_eligible_unix > now else "pending"

    def counts(self) -> dict[str, int]:
        out = {
            "total": 0, "pending": 0, "backoff": 0, "running": 0,
            "stale": 0, "done": 0, "quarantined": 0, "throttled": 0,
        }
        now = time.time()
        for jid in self.job_ids():
            out["total"] += 1
            st = self.state(jid, now)
            if st in out:
                out[st] += 1
        return out

    def drained(self) -> bool:
        """True when every job is terminal (done or quarantined)."""
        c = self.counts()
        return c["total"] > 0 and c["done"] + c["quarantined"] == c["total"]

    # --- claim / renew / release -------------------------------------
    @staticmethod
    def default_worker_id() -> str:
        return f"{socket.gethostname()}-{os.getpid()}"

    def try_claim(
        self,
        job_id: str,
        worker_id: str,
        now: float | None = None,
        gang: dict | None = None,
    ) -> Claim | None:
        now = time.time() if now is None else now
        if os.path.exists(self._p(_DONE, job_id)) or os.path.exists(
            self._p(_QUARANTINE, job_id)
        ):
            return None
        job = self.get_job(job_id)
        if job is None or job.next_eligible_unix > now:
            return None
        if job.tenant and job.tenant in self.tenant_throttles(now):
            return None  # tenant over quota: the job parks as throttled
        path = self._p(_CLAIMS, job_id)

        def _create_claim():
            faults.fire("queue.claim", context=job_id)
            return os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)

        try:
            # transient I/O (flaky mount, injected queue.claim fault)
            # retries under the shared policy; losing the O_EXCL race
            # (FileExistsError) is a protocol outcome, not an error
            fd = IO_RETRY.call(
                _create_claim, site="queue.claim", context=job_id
            )
        except FileExistsError:
            return None
        except OSError as exc:
            if is_transient(exc):
                # retry budget spent: walk away; the job stays pending
                # and any worker (including us, next poll) claims it
                log.warning(
                    "claim of %s abandoned after transient I/O "
                    "failures: %.200s", job_id, exc,
                )
                return None
            raise
        if os.path.exists(self._p(_DONE, job_id)) or os.path.exists(
            self._p(_QUARANTINE, job_id)
        ):
            # lost the completion race: between our eligibility check
            # and the O_EXCL create, the previous owner finished and
            # released — without this re-check a second worker would
            # re-run a terminal job (exactly-once violation seen as a
            # duplicate under load in the two-worker race test)
            os.close(fd)
            try:
                os.unlink(path)
            except FileNotFoundError:
                pass
            return None
        if job.tenant and job.tenant in self.tenant_throttles(
            now, fresh=True
        ):
            # claim-time quota REVALIDATION: between the cached
            # pre-check and winning the O_EXCL race another worker may
            # have filled the tenant's last max_running slot. Our own
            # claim file exists but its document is still unwritten, so
            # the fresh scan (which skips unparsable claims) naturally
            # excludes us — only OTHER holders count against the quota
            os.close(fd)
            try:
                os.unlink(path)
            except FileNotFoundError:
                pass
            return None
        expires = now + self.lease_s
        doc = {
            "job_id": job_id,
            "worker_id": worker_id,
            "pid": os.getpid(),
            "hostname": socket.gethostname(),
            "claimed_unix": now,
            "expires_unix": expires,
            # trace propagation: the claim is the hand-off artifact a
            # gang member (or a watcher) reads, so the trace id rides it
            "trace_id": job.trace_id,
        }
        if gang:
            doc["gang"] = gang
        with os.fdopen(fd, "w") as f:
            json.dump(doc, f, indent=2)
            f.write("\n")
        return Claim(
            job=job, worker_id=worker_id, expires_unix=expires, path=path,
            gang=gang,
        )

    def claim_next(
        self,
        worker_id: str,
        prefer_bucket: tuple | None = None,
        warm_buckets: "set[tuple] | frozenset[tuple] | None" = None,
        group: str | None = None,
        group_members: "list[str] | None" = None,
    ) -> Claim | None:
        """Claim the next eligible job, ranked priority class first
        (higher ``Job.priority`` always claims sooner — an urgent
        re-observation must not wait behind a warm-bucket streak),
        then jobs sharing ``prefer_bucket`` (the worker's previous
        shape bucket), then jobs whose bucket is in ``warm_buckets``
        (buckets already warmed/tuned — this worker's own plus any
        recorded in the campaign's done records, see runner.py), then
        the remainder — each tier grouped BY bucket, then by ARRIVAL
        (``created_unix``): a released job (preempted, or handed back
        by a retiring worker) keeps its original queue position
        instead of sorting as fresh — so a fleet of workers naturally
        partitions into shape-coherent streaks, consecutive jobs hit
        the compiled-program caches, and already-paid warmup/tuning
        work is exploited before any new bucket is opened.

        Gang jobs (``Job.nprocs > 1``): claimable only by the LEADER
        of a process group (the lexicographically-first entry of
        ``group_members``, the caller's live group membership) and
        only when the group musters ``nprocs`` live members — the
        claim then carries the assembled member set (all-or-nothing:
        non-leaders never initiate, an unassemblable gang job is
        simply skipped so it cannot head-of-line-block ordinary
        work)."""
        self.reap_stale()
        now = time.time()
        warm = {tuple(b) for b in warm_buckets} if warm_buckets else set()
        members = sorted(group_members) if group_members else []
        eligible: list[tuple[tuple, str, dict | None]] = []
        for jid in self.job_ids():
            if self.state(jid, now) != "pending":
                continue
            job = self.get_job(jid)
            if job is None:
                continue
            gang = None
            if job.nprocs > 1:
                if (
                    not group
                    or len(members) < job.nprocs
                    or worker_id != members[0]
                ):
                    continue  # not this worker's gang to lead (or none)
                gang = {
                    "group": group,
                    "members": members[: job.nprocs],
                    "nprocs": int(job.nprocs),
                    "epoch": uuid.uuid4().hex[:12],
                }
            bucket = job.bucket or ()
            if prefer_bucket and bucket == tuple(prefer_bucket):
                tier = 0
            elif bucket and tuple(bucket) in warm:
                tier = 1
            else:
                tier = 2
            rank = (
                -job.priority,
                tier,
                tuple(str(x) for x in bucket),
                job.created_unix,
                jid,
            )
            eligible.append((rank, jid, gang))
        for _, jid, gang in sorted(eligible, key=lambda e: e[0]):
            claim = self.try_claim(jid, worker_id, now, gang=gang)
            if claim is not None:
                return claim
        return None

    def _take_claim(self, claim: Claim) -> str | None:
        """Atomically take our claim file off the namespace iff we
        still hold the lease. Returns the private tombstone path
        (caller must consume or restore it), or None when the lease
        has been lost — the claim was reaped (and possibly re-claimed
        by a new owner, whose claim must not be touched).

        The verify step re-reads the TOMBSTONE, not the original
        path: the rename is the serialization point, so whatever
        document the tombstone holds is exactly what we took. Between
        the rename and the caller's follow-up the claim path is
        briefly absent; a racing claimant may win the job in that
        window (renew's O_EXCL republish then fails and the caller
        reports the lease lost — safety over liveness)."""
        doc = _read_json(claim.path)
        if doc is None or doc.get("worker_id") != claim.worker_id:
            return None
        tomb = f"{claim.path}.release.{uuid.uuid4().hex[:8]}"
        try:
            os.rename(claim.path, tomb)
        except OSError:
            return None  # reaped from under us mid-check
        fresh = _read_json(tomb)
        if fresh is None or fresh.get("worker_id") != claim.worker_id:
            # the document changed between read and rename: a reaper
            # took the lease and a new owner re-claimed — undo
            try:
                os.rename(tomb, claim.path)
            except OSError:
                try:
                    os.unlink(tomb)
                except FileNotFoundError:
                    pass
            return None
        return tomb

    def renew(self, claim: Claim) -> bool:
        """Extend the holder's lease. The rewrite is an ownership
        dance, not a blind replace: take our claim (verified rename
        to a tombstone), then republish with the fresh expiry via
        ``O_CREAT|O_EXCL``. Returns False when the lease has been
        lost — the caller must stop working on the job (a blind
        ``os.replace`` here used to let a reaped-and-replaced zombie
        stomp the new owner's claim)."""
        tomb = self._take_claim(claim)
        if tomb is None:
            return False
        claim.expires_unix = time.time() + self.lease_s
        doc = _read_json(tomb) or {}
        doc.update(
            {
                "job_id": claim.job.job_id,
                "worker_id": claim.worker_id,
                "pid": os.getpid(),
                "hostname": socket.gethostname(),
                "expires_unix": claim.expires_unix,
            }
        )
        try:
            fd = os.open(
                claim.path, os.O_CREAT | os.O_EXCL | os.O_WRONLY
            )
        except FileExistsError:
            # a claimant won the job during the absence window: it
            # owns the lease now; our tombstone is all that is ours
            _discard(tomb)
            return False
        with os.fdopen(fd, "w") as f:
            json.dump(doc, f, indent=2)
            f.write("\n")
        _discard(tomb)
        return True

    # --- terminal transitions ----------------------------------------
    def complete(self, claim: Claim, **info) -> bool:
        """Success: publish the done record exactly once, release the
        claim. Only the LIVE holder may publish — a zombie completer
        (reaped while wedged, job re-claimed) gets False and must not
        account the job as done. The record publishes via tmp +
        ``os.link``: all-or-nothing, never torn, and a duplicate
        publication surfaces as ``FileExistsError`` (swallowed — the
        record is there) instead of silently overwriting the first
        winner's document."""
        tomb = self._take_claim(claim)
        if tomb is None:
            log.warning(
                "complete of %s by %s ignored: lease lost (reaped)",
                claim.job.job_id, claim.worker_id,
            )
            return False
        done = self._p(_DONE, claim.job.job_id)
        fd, tmp = tempfile.mkstemp(
            dir=os.path.dirname(done), suffix=".tmp"
        )
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(
                    {
                        "job_id": claim.job.job_id,
                        "input": claim.job.input,
                        "worker_id": claim.worker_id,
                        "finished_unix": time.time(),
                        "attempts": claim.job.attempts + 1,
                        **info,
                    },
                    f,
                    indent=2,
                )
                f.write("\n")
            try:
                os.link(tmp, done)
            except FileExistsError:
                pass  # already published — exactly-once holds
        finally:
            try:
                os.unlink(tmp)
            except FileNotFoundError:
                pass
        # a revoke answered by completion is answered
        self.clear_preempt(claim.job.job_id)
        _discard(tomb)
        return True

    def fail(self, claim: Claim, error: str) -> str:
        """Failure by the claim holder: one attempt consumed. Returns
        the resulting state: 'backoff' (will retry), 'quarantined',
        or 'lost' — the lease was reaped from under us, the reaper
        already charged the attempt, and charging a second one here
        (the old behaviour) double-counted the failure."""
        tomb = self._take_claim(claim)
        if tomb is None:
            return "lost"
        state = self._record_failure(claim.job.job_id, error)
        self.clear_preempt(claim.job.job_id)
        _discard(tomb)
        return state

    def release(self, claim: Claim) -> None:
        """Voluntary release by the claim holder — a worker leaving the
        fleet cleanly hands its unstarted job back with ZERO attempts
        consumed (a clean leave is elasticity, not a failure; the job
        is immediately claimable by anyone). Idempotent, and a no-op
        for a lost lease: a deposed holder must not unlink the new
        owner's claim or clear its preempt marker (the old blind
        unlink did both)."""
        tomb = self._take_claim(claim)
        if tomb is None:
            return
        self.clear_preempt(claim.job.job_id)
        _discard(tomb)
        log.info(
            "claim on %s released cleanly by %s (no attempt consumed)",
            claim.job.job_id, claim.worker_id,
        )

    # --- priority preemption -----------------------------------------
    def _preempt_path(self, job_id: str) -> str:
        # ".preempt" (not ".json") so claim-directory scans — which
        # filter on ".json" — never mistake a request for a claim
        return self._p(_CLAIMS, job_id) + ".preempt"

    def request_preempt(
        self,
        job_id: str,
        requester: str = "",
        grace_s: float = 60.0,
    ) -> bool:
        """Ask the holder of ``job_id``'s claim to checkpoint and hand
        the job back: a preempt-request file lands beside the claim,
        the victim's lease-renewer beat observes it
        (campaign/runner.py), and the driver stops at the next
        DM-block boundary with its checkpoint freshly saved. A victim
        unresponsive past ``grace_s`` is escalated to the reap path
        by :meth:`reap_stale`. Returns False when the job holds no
        live claim (nothing to revoke)."""
        claim_doc = _read_json(self._p(_CLAIMS, job_id))
        if claim_doc is None:
            return False
        now = time.time()
        _atomic_write_json(
            self._preempt_path(job_id),
            {
                "job_id": job_id,
                "requester": requester,
                "victim_worker": claim_doc.get("worker_id"),
                "requested_unix": now,
                "deadline_unix": now + float(grace_s),
                # trace propagation: the revoke is part of the job's
                # one connected trace (the revoke-latency span)
                "trace_id": claim_doc.get("trace_id"),
            },
        )
        from ..resilience import STATS

        STATS.preemption("requested")
        log.info(
            "preempt requested on %s (held by %s%s; grace %.3gs)",
            job_id, claim_doc.get("worker_id"),
            f" for {requester}" if requester else "", grace_s,
        )
        return True

    def preempt_request(self, job_id: str) -> dict | None:
        """The pending preempt request on ``job_id``, if any."""
        return _read_json(self._preempt_path(job_id))

    def clear_preempt(self, job_id: str) -> None:
        try:
            os.unlink(self._preempt_path(job_id))
        except FileNotFoundError:
            pass

    def release_preempted(
        self, claim: Claim, observed_unix: float | None = None
    ) -> float:
        """The revoke's happy path: the victim checkpointed and hands
        the claim back with ZERO attempts consumed (preemption is
        scheduling, not failure). The job record gains a preemption
        tally + the request->release latency (flows into the resumed
        run's done record and the rollup) and keeps its
        ``created_unix`` so :meth:`claim_next` re-claims it at its
        ORIGINAL queue position. Returns the recorded latency, or 0.0
        when the lease was already lost (the grace-deadline reaper
        beat us to the hand-back and owns the accounting)."""
        now = time.time()
        tomb = self._take_claim(claim)
        if tomb is None:
            return 0.0
        req = self.preempt_request(claim.job.job_id) or {}
        requested = float(
            req.get("requested_unix") or observed_unix or now
        )
        latency = max(0.0, now - requested)
        job = self.get_job(claim.job.job_id)
        if job is not None:
            job.preemptions += 1
            job.preempt_latency_s.append(round(latency, 4))
            _atomic_write_json(self._p(_JOBS, job.job_id), job.to_doc())
            claim.job = job  # the caller sees the updated tallies
        self.clear_preempt(claim.job.job_id)
        _discard(tomb)
        from ..resilience import STATS

        STATS.preemption("released")
        log.info(
            "claim on %s preempted away from %s after %.3fs "
            "(checkpointed; zero attempts consumed)",
            claim.job.job_id, claim.worker_id, latency,
        )
        return latency

    def record_carried_resilience(
        self, claim: Claim, delta: dict
    ) -> bool:
        """Fold a to-be-released attempt's resilience counter deltas
        (resilience/stats.py ``delta_since`` shape: table -> key ->
        count) into the job record, so the resumed run's done record
        still accounts for every fault this attempt survived. Call
        BEFORE :meth:`release` / :meth:`release_preempted`. The claim
        is taken for the duration of the rewrite (and restored after)
        so the fold cannot race the reaper's own job-record write —
        the lost-update that used to drop carried counters when a
        grace-deadline reap overlapped the hand-back. Returns True
        when the fold landed on the record, False when the lease was
        lost (the reaper charged the attempt and owns the record)."""
        if not delta:
            return True
        tomb = self._take_claim(claim)
        if tomb is None:
            log.warning(
                "carried-resilience fold for %s dropped: lease lost "
                "(the reaper owns the job record now)",
                claim.job.job_id,
            )
            return False
        try:
            job = self.get_job(claim.job.job_id)
            if job is not None:
                for table, kv in delta.items():
                    if not isinstance(kv, dict):
                        continue
                    tgt = job.carried_resilience.setdefault(table, {})
                    for k, v in kv.items():
                        tgt[k] = tgt.get(k, 0) + int(v)
                _atomic_write_json(
                    self._p(_JOBS, job.job_id), job.to_doc()
                )
                claim.job = job  # the caller sees the carried tallies
        finally:
            # restore our claim: the dance only serialized the rewrite.
            # link (not rename) so a claimant that won the job during
            # the absence window is never overwritten — they keep the
            # lease and our next holder-side call reports it lost.
            # OSError also covers the tombstone itself aging out under
            # a peer's orphan sweep: the lease is simply lost
            try:
                os.link(tomb, claim.path)
            except OSError:
                pass
            _discard(tomb)
        return True

    def record_orphaned_resilience(
        self, worker_id: str, job_id: str, delta: dict
    ) -> None:
        """Spool a LOST attempt's survived-fault counters. A lease
        reaped from under a live run publishes no done record, and the
        deposed holder may not touch the job record either (the reaper
        or a new claimant owns it) — so without this spool every
        retry/recovery that attempt performed would vanish from the
        campaign rollup. Each worker appends to its OWN
        ``queue/resilience/<worker_id>.jsonl`` (single writer, append
        mode — no shared-state race to lose), and the rollup folds the
        spooled deltas in beside the done-record ones."""
        if not delta:
            return
        path = os.path.join(
            self.qdir, _RESILIENCE, f"{worker_id}.jsonl"
        )
        rec = {
            "job_id": job_id,
            "worker_id": worker_id,
            "recorded_unix": time.time(),
            "resilience": delta,
        }
        with open(path, "a") as f:
            f.write(json.dumps(rec) + "\n")

    def orphaned_resilience(self) -> list[dict]:
        """Every spooled lost-attempt record (see
        :meth:`record_orphaned_resilience`), campaign-wide. A torn
        tail line — a worker killed mid-append — is skipped, not
        fatal."""
        rdir = os.path.join(self.qdir, _RESILIENCE)
        out: list[dict] = []
        try:
            names = sorted(os.listdir(rdir))
        except FileNotFoundError:
            return out
        for name in names:
            if not name.endswith(".jsonl"):
                continue
            try:
                with open(os.path.join(rdir, name)) as f:
                    lines = f.readlines()
            except OSError:
                continue
            for line in lines:
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict):
                    out.append(rec)
        return out

    def preemption_wanted(
        self, claim: Claim, now: float | None = None
    ) -> dict | None:
        """Does a PENDING job outrank this claim's priority class? The
        decentralised preemption trigger: a busy worker's
        lease-renewer asks this each beat, and — when it also holds
        the lowest-priority running claim
        (:meth:`is_lowest_priority_running`) — revokes itself so the
        urgent job gets a worker without any coordinator. Gang jobs
        are excluded (they wait for their group, not for a victim)."""
        now = time.time() if now is None else now
        best: dict | None = None
        for jid in self.job_ids():
            if self.state(jid, now) != "pending":
                continue
            job = self.get_job(jid)
            if job is None or job.nprocs > 1:
                continue
            if job.priority > claim.job.priority and (
                best is None or job.priority > best["priority"]
            ):
                best = {"job_id": jid, "priority": job.priority}
        return best

    def is_lowest_priority_running(
        self, claim: Claim, now: float | None = None
    ) -> bool:
        """Deterministic victim selection: among live (unexpired,
        non-gang) claims, the one with the smallest (priority, job_id)
        is THE victim — so when every busy worker evaluates the same
        pending urgent job, exactly one self-revokes."""
        now = time.time() if now is None else now
        lowest: tuple | None = None
        cdir = os.path.join(self.qdir, _CLAIMS)
        for name in sorted(os.listdir(cdir)):
            if not name.endswith(".json"):
                continue
            doc = _read_json(os.path.join(cdir, name))
            if doc is None or float(doc.get("expires_unix", 0)) < now:
                continue
            if doc.get("gang"):
                continue
            jid = doc.get("job_id") or os.path.splitext(name)[0]
            job = self.get_job(jid)
            if job is None:
                continue
            key = (job.priority, jid)
            if lowest is None or key < lowest:
                lowest = key
        return lowest is not None and lowest[1] == claim.job.job_id

    # --- gang membership ----------------------------------------------
    def gang_invitation(self, worker_id: str) -> dict | None:
        """A live gang claim naming ``worker_id`` as a (non-leader)
        member: the member-side entry into a gang job. Returns the
        claim document (carrying the gang member set, epoch and
        job_id) or None."""
        now = time.time()
        cdir = os.path.join(self.qdir, _CLAIMS)
        for name in sorted(os.listdir(cdir)):
            if not name.endswith(".json"):
                continue
            doc = _read_json(os.path.join(cdir, name))
            if doc is None or float(doc.get("expires_unix", 0)) < now:
                continue
            gang = doc.get("gang")
            if (
                gang
                and worker_id in gang.get("members", [])
                and worker_id != doc.get("worker_id")
            ):
                return doc
        return None

    def _record_failure(self, job_id: str, error: str) -> str:
        """Consume one attempt: exponential backoff, or quarantine when
        the budget is spent. Caller must hold the claim (or have won
        the reap race) — job records have a single writer at a time."""
        job = self.get_job(job_id)
        if job is None:
            return "unknown"
        job.attempts += 1
        job.last_error = f"{error}"[:2000]
        if job.attempts >= self.max_attempts:
            _atomic_write_json(
                self._p(_QUARANTINE, job_id),
                {
                    "job_id": job_id,
                    "input": job.input,
                    "attempts": job.attempts,
                    "last_error": job.last_error,
                    "quarantined_unix": time.time(),
                },
            )
            _atomic_write_json(self._p(_JOBS, job_id), job.to_doc())
            log.warning(
                "job %s quarantined after %d attempts: %s",
                job_id, job.attempts, job.last_error,
            )
            return "quarantined"
        backoff = self.backoff_base_s * (2 ** (job.attempts - 1))
        job.next_eligible_unix = time.time() + backoff
        _atomic_write_json(self._p(_JOBS, job_id), job.to_doc())
        log.warning(
            "job %s failed (attempt %d/%d, retry in %.3gs): %s",
            job_id, job.attempts, self.max_attempts, backoff,
            job.last_error,
        )
        return "backoff"

    # --- stale-claim reaping -----------------------------------------
    def reap_stale(self, now: float | None = None) -> list[str]:
        """Re-queue jobs whose claim lease expired (their worker was
        SIGKILLed or wedged past its lease) — and jobs whose holder
        blew a preempt request's grace deadline (alive enough to renew
        its lease yet unresponsive to the revoke: wedged in device
        code, or the revoke delivery itself is failing — the
        ``preempt.revoke`` chaos seam). Exactly one reaper wins per
        claim: the claim is renamed to a private tombstone first, and
        only the winner of that rename records the failure.

        A renewal racing the reap is detected by re-reading the
        tombstone: if the lease is no longer expired the rename
        caught a freshly renewed claim, and it is put back. (A
        grace-deadline reap deliberately skips the putback — renewing
        the lease is exactly what an unresponsive victim does.)"""
        now = time.time() if now is None else now
        # chaos seam: a scheduled clock.skew fault shifts THIS
        # reaper's view of lease expiry (drills premature reaping —
        # the renew-race putback below must absorb it)
        now += faults.clock_skew_s()
        reaped = []
        cdir = os.path.join(self.qdir, _CLAIMS)
        for name in sorted(os.listdir(cdir)):
            if not name.endswith(".json"):
                continue
            path = os.path.join(cdir, name)
            doc = _read_json(path)
            job_id = os.path.splitext(name)[0]
            if doc is None:
                # TORN claim: its creator was SIGKILLed between the
                # O_EXCL create and the document publish. It carries
                # no expiry, so it can never go stale — yet it blocks
                # every future O_EXCL claim: the job was stuck
                # forever (found by the mc claim_crash_reap
                # scenario). Age-gate on st_ctime (rename-proof, and
                # bumped by the publish) so a mid-write claimant gets
                # a full lease to finish, then reap with ZERO
                # attempts charged — the job never ran
                try:
                    age = now - os.stat(path).st_ctime
                except OSError:
                    continue  # vanished (publish or release race)
                if age <= self.lease_s:
                    continue
                tomb = f"{path}.reap.{uuid.uuid4().hex[:8]}"
                try:
                    os.rename(path, tomb)
                except OSError:
                    continue  # lost the reap race
                if _read_json(tomb) is not None:
                    # published after all (very slow writer): put the
                    # live claim back, re-judge next sweep
                    try:
                        os.rename(tomb, path)
                    except OSError:
                        _discard(tomb)
                    continue
                _discard(tomb)
                self.clear_preempt(job_id)
                reaped.append(job_id)
                log.warning(
                    "reaped torn claim on %s (creator died mid-"
                    "publish; zero attempts charged)", job_id,
                )
                continue
            expired = float(doc.get("expires_unix", 0)) < now
            req = self.preempt_request(job_id)
            overdue = req is not None and (
                float(req.get("deadline_unix", 0)) < now
            )
            if not expired and not overdue:
                continue
            tomb = f"{path}.reap.{uuid.uuid4().hex[:8]}"
            try:
                os.rename(path, tomb)
            except OSError:
                continue  # lost the reap race
            fresh = _read_json(tomb)
            if fresh is None or (
                not overdue
                and float(fresh.get("expires_unix", 0)) >= now
            ):
                # our rename caught a renewal, not the expired claim
                # we read: either the republished document (fresh
                # lease) or the renewer's O_EXCL file still awaiting
                # its publish — torn, which is why an unreadable
                # tombstone here means a LIVE owner, never the dead
                # one we diagnosed (found by the mc renew_vs_reap
                # scenario: charging this torn file re-queued a job
                # whose renewer kept running it). Put the claim back
                # via link so a claimant that won the job in the gap
                # is never clobbered, then drop the tombstone name
                try:
                    os.link(tomb, path)
                except OSError:
                    pass  # a new claimant owns the job: they win
                _discard(tomb)
                continue
            worker = fresh.get("worker_id", "?")
            if overdue and not expired:
                self._record_failure(
                    job_id,
                    f"preempt grace deadline expired (worker {worker} "
                    "unresponsive to revoke)",
                )
                from ..resilience import STATS

                STATS.preemption("reaped")
            else:
                self._record_failure(
                    job_id,
                    f"lease expired (worker {worker} presumed dead)",
                )
            self.clear_preempt(job_id)
            os.unlink(tomb)
            reaped.append(job_id)
            log.warning(
                "reaped %s claim on %s (worker %s)",
                "revoke-unresponsive" if overdue and not expired
                else "stale",
                job_id, worker,
            )
        # orphan sweep: artifacts of dances whose worker died mid-step.
        # Tombstones (".reap."/".release.") age out by st_ctime — a
        # LIVE dance is at most a few ops long, so a full lease of age
        # means its owner is gone. Orphaned preempt requests (their
        # claim is gone) wait out deadline + lease before removal: the
        # ownership dance makes a live claim briefly absent, and a
        # revoke must survive that window
        for name in sorted(os.listdir(cdir)):
            p = os.path.join(cdir, name)
            if ".reap." in name or ".release." in name:
                try:
                    if now - os.stat(p).st_ctime > self.lease_s:
                        os.unlink(p)
                except OSError:
                    pass  # consumed by its dance, or swept by a peer
            elif name.endswith(".preempt"):
                if os.path.exists(p[: -len(".preempt")]):
                    continue  # claim lives: the request is active
                req = _read_json(p)
                deadline = float((req or {}).get("deadline_unix", 0.0))
                if now > deadline + self.lease_s:
                    try:
                        os.unlink(p)
                    except FileNotFoundError:
                        pass
        return reaped

    # --- operator controls -------------------------------------------
    def quarantined(self) -> list[dict]:
        qdir = os.path.join(self.qdir, _QUARANTINE)
        out = []
        for name in sorted(os.listdir(qdir)):
            if name.endswith(".json"):
                doc = _read_json(os.path.join(qdir, name))
                if doc:
                    out.append(doc)
        return out

    def retry(self, job_id: str) -> bool:
        """Re-queue a quarantined job: reset its attempt budget and
        remove the quarantine marker. Returns False when the job is
        not quarantined."""
        qpath = self._p(_QUARANTINE, job_id)
        if not os.path.exists(qpath):
            return False
        job = self.get_job(job_id)
        if job is None:
            return False
        job.attempts = 0
        job.next_eligible_unix = 0.0
        _atomic_write_json(self._p(_JOBS, job_id), job.to_doc())
        # marker removed LAST: a crash mid-retry leaves the job
        # quarantined (safe), never half-requeued
        os.unlink(qpath)
        log.info("job %s re-queued from quarantine", job_id)
        return True

    def done_records(self) -> list[dict]:
        ddir = os.path.join(self.qdir, _DONE)
        out = []
        for name in sorted(os.listdir(ddir)):
            if name.endswith(".json"):
                doc = _read_json(os.path.join(ddir, name))
                if doc:
                    out.append(doc)
        return out
