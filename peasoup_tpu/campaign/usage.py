"""Per-tenant usage accounting over a campaign's queue artifacts.

Who consumed what: device-seconds, jobs done/failed/quarantined,
bytes read, XLA programs compiled, candidates found — rolled up from
tenant-stamped done records (campaign/queue.py writes them, the
runner stamps ``tenant``/``bytes_read``/``jit_programs_compiled``)
plus job/quarantine records for the failure tally. The ledger is
written atomically to ``queue/usage.json`` by the rollup
(campaign/rollup.py calls :func:`write_usage` beside the status
snapshot) and rendered at the portal's ``/tenants`` pages and by
tools/watch.py.

The ledger is DERIVED, never incremented: recomputing from the
artifacts on every rollup means a crashed writer can never leave the
accounting out of sync with the done records — the same
states-are-derived principle the queue itself follows.
"""

from __future__ import annotations

import os
import time

from .queue import JobQueue, _atomic_write_json, _read_json
from .tenants import TenantRegistry

SCHEMA = "peasoup_tpu.usage"
VERSION = 1


def usage_path(root: str) -> str:
    return os.path.join(os.path.abspath(root), "queue", "usage.json")


def _blank() -> dict:
    return {
        "jobs_done": 0,
        "jobs_failed": 0,
        "jobs_quarantined": 0,
        "device_seconds": 0.0,
        "bytes_read": 0,
        "jit_programs_compiled": 0,
        "candidates": 0,
    }


def build_usage(
    root: str, queue: JobQueue | None = None, now: float | None = None
) -> dict:
    """The full ledger document. Tenants with a registry record appear
    even at zero usage; done records stamped with an UNREGISTERED
    tenant (record deleted after jobs ran) still account under their
    stamp — usage is historical truth, not a join against the present
    registry."""
    now = time.time() if now is None else now
    root = os.path.abspath(root)
    queue = queue or JobQueue(root)
    reg = TenantRegistry(root)
    tenants: dict[str, dict] = {t.name: _blank() for t in reg.entries()}
    quotas = {t.name: t for t in reg.entries()}

    records = queue.done_records()
    for rec in records:
        name = rec.get("tenant")
        if not name:
            continue
        u = tenants.setdefault(name, _blank())
        u["jobs_done"] += 1
        u["device_seconds"] += float(rec.get("duration_s") or 0.0)
        u["bytes_read"] += int(rec.get("bytes_read") or 0)
        u["jit_programs_compiled"] += int(
            rec.get("jit_programs_compiled") or 0
        )
        u["candidates"] += int(rec.get("n_candidates") or 0)
        # a done record's ``attempts`` counts every attempt including
        # the successful one; the excess were failures
        u["jobs_failed"] += max(0, int(rec.get("attempts") or 1) - 1)

    qdir = os.path.join(root, "queue")
    for jid in queue.job_ids():
        if os.path.exists(os.path.join(qdir, "done", f"{jid}.json")):
            continue  # already tallied above
        doc = _read_json(os.path.join(qdir, "jobs", f"{jid}.json"))
        if not doc or not doc.get("tenant"):
            continue
        u = tenants.setdefault(str(doc["tenant"]), _blank())
        u["jobs_failed"] += int(doc.get("attempts") or 0)
        if os.path.exists(
            os.path.join(qdir, "quarantine", f"{jid}.json")
        ):
            u["jobs_quarantined"] += 1

    for name, u in tenants.items():
        u["device_seconds"] = round(u["device_seconds"], 3)
        t = quotas.get(name)
        if t is not None:
            lo = now - t.window_s
            in_window = sum(
                float(rec.get("duration_s") or 0.0)
                for rec in records
                if rec.get("tenant") == name
                and float(rec.get("finished_unix") or 0.0) >= lo
            )
            u["window"] = {
                "window_s": t.window_s,
                "device_seconds": round(in_window, 3),
                "budget": t.device_seconds or None,
            }
    return {
        "schema": SCHEMA,
        "version": VERSION,
        "generated_unix": round(now, 3),
        "tenants": tenants,
    }


def write_usage(
    root: str, queue: JobQueue | None = None, now: float | None = None
) -> str:
    """Atomically (re)write ``queue/usage.json``; returns its path."""
    path = usage_path(root)
    _atomic_write_json(path, build_usage(root, queue=queue, now=now))
    return path


def load_usage(root: str) -> dict | None:
    return _read_json(usage_path(root))
