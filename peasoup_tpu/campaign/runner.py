"""The campaign worker: a long-lived scheduler/executor loop.

One invocation of ``campaign run`` is one worker. Workers share nothing
but the campaign directory (queue.py); N workers on M hosts need no
coordinator. What makes the loop worth having over ``for f in *.fil:
peasoup -i $f`` is **compiled-program reuse**: a fresh process pays the
full XLA compile per observation (minutes at survey sizes — NOTES.md),
while a long-lived worker that feeds same-shaped observations through
one process hits the in-process jit caches (every op-building function
is ``lru_cache``'d on its shape signature) and compiles *zero* new
programs after the first observation of a shape.

Observations rarely share exact shapes, so the runner buckets them:
``nsamps`` is padded up to a coarse geometric ladder (powers of two and
3·2^(k-1) — two rungs per octave) with per-channel median samples, and
the queue hands a worker jobs from its previous bucket first
(queue.claim_next prefer_bucket). The bucket key includes everything
shape-determining (nchans, nbits, padded nsamps, tsamp, fch1, foff) so
two jobs in one bucket provably trace identical programs. Reuse is
asserted, not assumed: each job's telemetry JIT stats yield a
``jit_programs_compiled`` count recorded in its done record, and a
same-bucket successor that compiled anything raises a structured
``jit_cache_miss`` event.

Each job runs with the full live-observability stack under its own job
dir (``<root>/jobs/<id>/``): status.json heartbeat, crash flight
recorder, telemetry.json manifest — ``tools.watch`` and
``tools.report`` work on campaign jobs unchanged. A lease-renewal
thread keeps the claim fresh while the job computes; if the worker is
SIGKILLed the lease expires and any other worker reaps + re-queues the
job (queue.py).
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time

import numpy as np

from ..obs import get_logger
from ..obs.flight import FlightRecorder
from ..obs.heartbeat import Heartbeat
from ..obs.metrics import MetricsRecorder
from ..obs.telemetry import RunTelemetry
from ..obs.trace import Tracer, new_trace_id
from .db import DB_FILENAME, CandidateDB
from .queue import Claim, Job, JobQueue, job_id_for
from .registry import WorkerRegistry
from .rollup import write_status

log = get_logger("campaign.runner")

CAMPAIGN_CONFIG = "campaign.json"
CAMPAIGN_CONFIG_SCHEMA = "peasoup_tpu.campaign"

PIPELINES = ("search", "spsearch", "ffa", "fdas")


def _safe_name(s: str) -> str:
    """Filesystem-safe worker id (same sanitisation as the registry's
    entry filenames, so per-worker artifacts line up by stem)."""
    return "".join(
        c if c.isalnum() or c in "-_." else "_" for c in s
    )[:80]


# --------------------------------------------------------------------------
# campaign config
# --------------------------------------------------------------------------

@dataclasses.dataclass
class CampaignConfig:
    """Campaign-wide settings, persisted as ``<root>/campaign.json`` so
    every worker (and every later ``status``/``retry`` invocation) runs
    with identical semantics. First writer wins; later writers attach."""

    pipeline: str = "spsearch"
    config: dict = dataclasses.field(default_factory=dict)
    lease_s: float = 60.0
    max_attempts: int = 3
    backoff_base_s: float = 2.0
    heartbeat_interval: float = 2.0
    bucket_nsamps: list | None = None  # explicit ladder override
    # AOT warmup: compile a new bucket's programs on a background
    # thread (overlapping the first observation's filterbank read)
    # before the pipeline touches data — the first job of a warmed
    # bucket then reports jit_programs_compiled == 0 like its
    # successors. "dryrun" runs the real pipeline once over a
    # synthetic bucket-shaped observation (exact: every driver-side
    # shape traces); "aot" only lower().compile()s the registry
    # through its ShapeCtx hooks (cheaper: no data execution, but
    # driver-internal shapes are approximated). See perf/warmup.py.
    warmup: bool = True
    warmup_mode: str = "dryrun"  # "dryrun" | "aot"
    # auto-tuned dedispersion plans (perf/tuning.py): each new bucket
    # resolves exact-vs-subband + per-device shape knobs on the warmup
    # thread (overlapping the first observation's read) and persists
    # the winner in the campaign-shared tuning cache, so every other
    # worker/job of the bucket loads the plan with zero re-measurement
    tune: bool = False
    tuning_cache: str = ""  # "" = <campaign root>/tuning_cache.json
    # priority preemption: a worker holding the lowest-priority
    # running claim revokes ITSELF when a pending job outranks it and
    # no idle worker is live (the decentralised trigger; operators and
    # schedulers can also `peasoup-campaign preempt` explicitly). The
    # victim checkpoints at the next DM-block boundary and releases
    # with zero attempts consumed; one unresponsive past the grace
    # deadline is escalated to the reap path.
    preempt: bool = True
    preempt_grace_s: float = 60.0
    # gang-scheduled jobs (Job.nprocs > 1): how long the leader waits
    # for the full group at the join barrier before releasing the
    # claim cleanly (no partial-gang deadlock), and how long any
    # member waits at a mid-run barrier before the gang fails
    # transient (a dead member must consume exactly one attempt)
    gang_assemble_s: float = 30.0
    gang_timeout_s: float = 600.0
    # fleet observability (obs/metrics.py, obs/trace.py): per-worker
    # time-series metrics under queue/workers/ and per-job trace span
    # files under jobs/<id>/ — both on by default (append-only JSON
    # lines, negligible next to device work); `peasoup-campaign
    # metrics` / `trace` consume them
    metrics: bool = True
    trace: bool = True

    def tuning_cache_path(self, root: str) -> str:
        return self.tuning_cache or os.path.join(root, "tuning_cache.json")

    def to_doc(self) -> dict:
        return {
            "schema": CAMPAIGN_CONFIG_SCHEMA,
            **dataclasses.asdict(self),
        }


def save_campaign_config(root: str, cfg: CampaignConfig) -> CampaignConfig:
    """Persist the campaign config; if one already exists it WINS (a
    second worker attaching with different flags must not fork the
    campaign's semantics mid-flight)."""
    os.makedirs(root, exist_ok=True)
    path = os.path.join(root, CAMPAIGN_CONFIG)
    try:
        fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
    except FileExistsError:
        existing = load_campaign_config(root)
        if existing.to_doc() != cfg.to_doc():
            log.warning(
                "campaign %s already configured; using its existing "
                "campaign.json (pipeline=%s) over this invocation's flags",
                root, existing.pipeline,
            )
        return existing
    with os.fdopen(fd, "w") as f:
        json.dump(cfg.to_doc(), f, indent=2)
        f.write("\n")
    return cfg


def load_campaign_config(root: str) -> CampaignConfig:
    path = os.path.join(root, CAMPAIGN_CONFIG)
    with open(path) as f:
        doc = json.load(f)
    if doc.get("schema") != CAMPAIGN_CONFIG_SCHEMA:
        raise ValueError(f"{path}: not a {CAMPAIGN_CONFIG_SCHEMA} file")
    doc.pop("schema", None)
    return CampaignConfig(**doc)


# --------------------------------------------------------------------------
# shape buckets
# --------------------------------------------------------------------------

def bucket_nsamps(n: int, ladder: list[int] | None = None) -> int:
    """Pad target for ``n`` samples: the smallest rung >= n of the
    geometric ladder {2^k, 3*2^(k-1)} — two rungs per octave, so
    padding stays under 50% (and under 10% for the common
    just-short-of-a-power-of-two observation lengths) while the whole
    survey shares only ~2 compiled program sets per octave of
    observation length. An explicit campaign ladder overrides."""
    if ladder:
        above = [int(x) for x in ladder if int(x) >= n]
        if above:
            return min(above)
        # beyond the explicit ladder: fall through to the default rungs
    p = 1 << max(0, (int(n) - 1).bit_length())
    if 3 * p // 4 >= n:
        return 3 * p // 4
    return p


def bucket_for_header(hdr, ladder: list[int] | None = None) -> tuple:
    """The shape-bucket key: everything that determines traced program
    shapes for a fixed campaign config. nsamps enters padded; the plan
    scalars (tsamp/fch1/foff) enter because they set the DM trial count
    and therefore every wave geometry downstream."""
    return (
        int(hdr.nchans),
        int(hdr.nbits),
        bucket_nsamps(int(hdr.nsamples), ladder),
        round(float(hdr.tsamp), 12),
        round(float(hdr.fch1), 6),
        round(float(hdr.foff), 6),
    )


def bucket_for_input(path: str, ladder: list[int] | None = None) -> tuple | None:
    """Bucket key from just the file header (cheap at enqueue time);
    None when the header is unreadable — the job still enqueues and
    fails into quarantine through the normal retry path at run time."""
    from ..io.sigproc import read_sigproc_header

    try:
        with open(path, "rb") as f:
            hdr = read_sigproc_header(f)
        if hdr.nsamples <= 0 or hdr.nchans <= 0:
            return None
        return bucket_for_header(hdr, ladder)
    except Exception:
        return None


def pad_to_nsamps(fil, target: int):
    """Pad a filterbank's time axis up to ``target`` samples with each
    channel's median level (flat baseline: the normalisers see a few
    percent more pure-baseline samples, no fake transient edges).
    Returns (padded_fil, original_nsamps)."""
    orig = fil.nsamps
    if target <= orig:
        return fil, orig
    data = fil.data
    fill = np.median(data, axis=0)
    if np.issubdtype(data.dtype, np.integer):
        fill = np.rint(fill)
    pad = np.broadcast_to(
        fill.astype(data.dtype), (target - orig, data.shape[1])
    )
    from ..io.sigproc import Filterbank

    hdr = dataclasses.replace(fil.header, nsamples=target)
    return Filterbank(
        header=hdr, data=np.concatenate([data, pad], axis=0)
    ), orig


# --------------------------------------------------------------------------
# manifest -> jobs
# --------------------------------------------------------------------------

def parse_manifest(path: str) -> list[dict]:
    """One observation per line: either a bare filterbank path or a
    JSON object ``{"input": ..., "config": {...}}`` with per-job
    pipeline overrides. ``#`` comments and blank lines are skipped;
    relative paths resolve against the manifest's directory."""
    base = os.path.dirname(os.path.abspath(path))
    entries = []
    with open(path) as f:
        for ln in f:
            ln = ln.strip()
            if not ln or ln.startswith("#"):
                continue
            if ln.startswith("{"):
                doc = json.loads(ln)
                if "input" not in doc:
                    raise ValueError(
                        f"{path}: manifest JSON line lacks 'input': {ln}"
                    )
            else:
                doc = {"input": ln}
            if not os.path.isabs(doc["input"]):
                doc["input"] = os.path.join(base, doc["input"])
            entries.append(doc)
    return entries


def enqueue_entries(
    queue: JobQueue,
    entries: list[dict],
    pipeline: str,
    ladder: list[int] | None = None,
    priority: int = 0,
    nprocs: int = 1,
    tenant: str = "",
) -> int:
    """Idempotently enqueue manifest entries; returns how many were
    new. ``priority`` is the default priority class; a per-entry
    ``"priority"`` in a manifest JSON line overrides it (higher claims
    sooner — queue.claim_next ranks priority above bucket affinity).
    ``nprocs`` (default / per-entry ``"nprocs"``) > 1 gang-schedules
    the job across a worker process group via the multi-host drivers —
    supported for the search and spsearch pipelines. ``tenant``
    (default / per-entry ``"tenant"``) stamps jobs for the
    multi-tenant quota + usage accounting (campaign/tenants.py) —
    quota-validated submissions should instead go through
    campaign/ingest.submit_observation, which journals the decision."""
    added = 0
    for e in entries:
        inp = e["input"]
        job = Job(
            job_id=job_id_for(inp),
            input=inp,
            pipeline=e.get("pipeline", pipeline),
            config=e.get("config") or {},
            bucket=bucket_for_input(inp, ladder),
            priority=int(e.get("priority", priority)),
            nprocs=int(e.get("nprocs", nprocs)),
            tenant=str(e.get("tenant", tenant) or ""),
        )
        if job.pipeline not in PIPELINES:
            raise ValueError(
                f"unknown pipeline {job.pipeline!r} for {inp} "
                f"(expected one of {PIPELINES})"
            )
        if job.nprocs > 1 and job.pipeline not in (
            "search", "spsearch", "fdas"
        ):
            raise ValueError(
                f"gang scheduling (nprocs={job.nprocs}) is supported "
                f"for the search/spsearch/fdas pipelines only, not "
                f"{job.pipeline!r} ({inp})"
            )
        added += bool(queue.add_job(job))
    return added


# --------------------------------------------------------------------------
# per-job execution
# --------------------------------------------------------------------------

def _build_config(cls, overrides: dict, **fixed):
    """Instantiate a pipeline config dataclass from campaign + job
    overrides, rejecting unknown keys loudly (a typo'd knob must fail
    the job visibly, not silently run with defaults)."""
    names = {f.name for f in dataclasses.fields(cls)}
    unknown = set(overrides) - names
    if unknown:
        raise ValueError(
            f"unknown {cls.__name__} keys in campaign config: "
            f"{sorted(unknown)}"
        )
    merged = dict(overrides)
    merged.update(fixed)
    return cls(**merged)


def jit_programs_compiled(tel: RunTelemetry) -> int:
    """Backend programs REALLY compiled during this telemetry's run:
    the jax.monitoring backend_compile counter minus persistent-cache
    hits (a cache hit still emits a backend_compile duration event
    while it deserialises the stored executable, but no XLA compile
    ran). Zero on a job whose every program came out of the in-process
    jit caches or the warmed persistent cache."""
    from ..obs.telemetry import persistent_cache_counters

    compiled = int(
        sum(v[0] for k, v in tel.jit.items() if "backend_compile" in k)
    )
    hits, _ = persistent_cache_counters(tel)
    return max(0, compiled - hits)


def tuned_overrides(
    overrides: dict, plan_doc: dict, pipeline: str
) -> dict:
    """Merge a resolved dedispersion plan's shape knobs into the job's
    pipeline overrides. Operator-set knobs always win (an explicit
    ``subbands``/``dedisp_block`` in the campaign or job config is a
    decision, not a default), and in-driver re-resolution is disabled
    — the campaign already resolved the plan for this bucket."""
    out = dict(overrides)
    if pipeline == "search" and not overrides.get("subbands"):
        if plan_doc.get("engine") == "subband":
            out["subbands"] = int(plan_doc["subbands"])
            out["subband_smear"] = float(plan_doc.get("subband_smear", 1.0))
            if plan_doc.get("subband_matmul"):
                out["subband_matmul"] = True
        elif plan_doc.get("engine") == "matmul" and not overrides.get(
            "dedisp_engine"
        ):
            out["dedisp_engine"] = "matmul"
    if "dedisp_block" not in overrides and plan_doc.get("dedisp_block"):
        out["dedisp_block"] = int(plan_doc["dedisp_block"])
    if "dm_block" not in overrides and plan_doc.get("dm_block"):
        out["dm_block"] = int(plan_doc["dm_block"])
    if "accel_bucket" not in overrides and plan_doc.get("accel_bucket"):
        out["accel_bucket"] = int(plan_doc["accel_bucket"])
    out["tune"] = False
    return out


def run_observation(
    job: Job, overrides: dict, job_dir: str, tel: RunTelemetry,
    bucket_ladder: list[int] | None = None,
    warmer: "_BucketWarmer | None" = None,
    tuning_cache: str | None = None,
    comm=None,
    write_outputs: bool = True,
) -> dict:
    """Execute one observation end-to-end inside this process and write
    its outputs (overview.xml + pipeline-specific candidate files)
    under ``job_dir``. Returns the done-record info dict. ``warmer``
    is an in-flight bucket warmup joined after the filterbank read —
    I/O and compile overlap — whose stats land in the telemetry and
    done record. ``comm`` (a parallel.multihost.GangComm) routes a
    gang-scheduled job through the multi-host drivers: this process
    computes its rank's DM slice and the gang's file-backed exchange
    merges, so the leader writes outputs identical to a single-process
    run."""
    from ..io.output import (
        CandidateFileWriter,
        OutputFileWriter,
        write_ffa_candidates,
        write_singlepulse,
    )
    from ..io.sigproc import read_filterbank

    t0 = time.perf_counter()
    tel.set_stage("reading")
    fil = read_filterbank(job.input)
    if fil.nsamps <= 0 or fil.nchans <= 0:
        raise ValueError(f"{job.input}: empty filterbank")
    reading = time.perf_counter() - t0

    target = (
        job.bucket[2]
        if job.bucket
        else bucket_nsamps(fil.nsamps, bucket_ladder)
    )
    fil, orig_nsamps = pad_to_nsamps(fil, target)
    if fil.nsamps != orig_nsamps:
        tel.event(
            "campaign_pad", orig_nsamps=orig_nsamps,
            padded_nsamps=int(fil.nsamps),
        )

    warmup_stats = None
    if warmer is not None:
        tel.set_stage("warmup")
        warmup_stats = warmer.result()
        tel.event("warmup", **warmup_stats)
        tel.add_timer("warmup", float(warmup_stats["seconds"]))
        tel.gauge("warmup.seconds", float(warmup_stats["seconds"]))
        tel.gauge(
            "warmup.programs_compiled",
            int(warmup_stats["programs_compiled"]),
        )

    plan_doc = None
    # the dedispersion planner knows the search/spsearch drivers only;
    # FFA/FDAS jobs keep their manual knobs
    if tuning_cache and job.bucket and job.pipeline not in ("ffa", "fdas"):
        # resolve AFTER the warmer join: the warmer tuned a cold bucket
        # on its thread and persisted the plan, so this is a pure cache
        # hit (zero measurements) for it and for every later job
        try:
            from ..perf.tuning import resolve_plan_for_bucket

            plan_doc = resolve_plan_for_bucket(
                tuple(job.bucket), job.pipeline, overrides, tuning_cache
            ).summary()
        except Exception as exc:
            log.warning(
                "tuned-plan resolution failed for %s: %.200s",
                job.job_id, exc,
            )
        if plan_doc is not None:
            overrides = tuned_overrides(overrides, plan_doc, job.pipeline)
            tel.event("dedisp_plan", **plan_doc)
            tel.set_context(dedisp_plan=plan_doc)

    outdir = job_dir.rstrip("/")
    if job.pipeline == "spsearch":
        from ..pipeline.single_pulse import (
            SinglePulseConfig,
            SinglePulseSearch,
        )

        cfg = _build_config(
            SinglePulseConfig, overrides, outdir=outdir,
            checkpoint_file=os.path.join(outdir, "search.ckpt.npz"),
        )
        if comm is not None:
            from ..parallel.multihost import run_single_pulse_search

            result = run_single_pulse_search(fil, cfg, comm=comm)
        else:
            result = SinglePulseSearch(cfg).run(fil)
        # detections whose peak lies in the padding are artefacts of
        # the bucket, not the sky
        cands = [c for c in result.candidates if c.sample < orig_nsamps]
        result.timers["reading"] = reading
        tel.merge_timers(result.timers)
        if write_outputs:
            tel.set_stage("writing")
            write_singlepulse(
                os.path.join(outdir, "candidates.singlepulse"), cands
            )
            stats = OutputFileWriter()
            stats.add_misc_info()
            stats.add_header(fil.header)
            stats.add_dm_list(result.dm_list)
            stats.add_device_info()
            stats.add_single_pulse_section(
                cfg, job.input, result.widths, cands
            )
            stats.add_timing_info(result.timers)
            stats.to_file(os.path.join(outdir, "overview.xml"))
        n_cands = len(cands)
    elif job.pipeline == "ffa":
        from ..pipeline.ffa import FFAConfig, FFASearch

        cfg = _build_config(FFAConfig, overrides, outdir=outdir)
        result = FFASearch(cfg).run(fil)
        result.timers["reading"] = reading
        tel.merge_timers(result.timers)
        if write_outputs:
            tel.set_stage("writing")
            write_ffa_candidates(
                os.path.join(outdir, "candidates.ffa"), result.candidates
            )
            stats = OutputFileWriter()
            stats.add_misc_info()
            stats.add_header(fil.header)
            stats.add_dm_list(result.dm_list)
            stats.add_device_info()
            stats.add_ffa_section(cfg, job.input, result.candidates)
            stats.add_timing_info(result.timers)
            stats.to_file(os.path.join(outdir, "overview.xml"))
        n_cands = len(result.candidates)
    elif job.pipeline == "fdas":
        from ..io.output import write_fdas_candidates
        from ..pipeline.fdas import FdasConfig, FdasSearch

        cfg = _build_config(
            FdasConfig, overrides, outdir=outdir,
            checkpoint_file=os.path.join(outdir, "search.ckpt.npz"),
        )
        if comm is not None:
            from ..parallel.multihost import run_fdas_search

            result = run_fdas_search(fil, cfg, comm=comm)
        else:
            result = FdasSearch(cfg).run(fil)
        result.timers["reading"] = reading
        tel.merge_timers(result.timers)
        if write_outputs:
            tel.set_stage("writing")
            writer = CandidateFileWriter(outdir)
            writer.write_binary(result.candidates, "candidates.peasoup")
            write_fdas_candidates(
                os.path.join(outdir, "candidates.fdas"), result.candidates
            )
            stats = OutputFileWriter()
            stats.add_misc_info()
            stats.add_header(fil.header)
            stats.add_fdas_section(cfg, result.zs, result.ws)
            stats.add_dm_list(result.dm_list)
            stats.add_device_info()
            stats.add_candidates_fdas(
                result.candidates, writer.byte_mapping
            )
            stats.add_timing_info(result.timers)
            stats.to_file(os.path.join(outdir, "overview.xml"))
        n_cands = len(result.candidates)
    else:  # "search" (validated at enqueue)
        from ..pipeline.search import PeasoupSearch, SearchConfig

        cfg = _build_config(
            SearchConfig, overrides, outdir=outdir,
            checkpoint_file=os.path.join(outdir, "search.ckpt.npz"),
        )
        if comm is not None:
            from ..parallel.multihost import run_search

            result = run_search(fil, cfg, comm=comm)
        else:
            result = PeasoupSearch(cfg).run(fil)
        result.timers["reading"] = reading
        tel.merge_timers(result.timers)
        if write_outputs:
            tel.set_stage("writing")
            writer = CandidateFileWriter(outdir)
            writer.write_binary(result.candidates, "candidates.peasoup")
            stats = OutputFileWriter()
            stats.add_misc_info()
            stats.add_header(fil.header)
            stats.add_search_parameters(cfg, job.input)
            stats.add_dm_list(result.dm_list)
            stats.add_acc_list(result.acc_list_dm0)
            stats.add_device_info()
            stats.add_candidates(result.candidates, writer.byte_mapping)
            stats.add_timing_info(result.timers)
            stats.to_file(os.path.join(outdir, "overview.xml"))
        n_cands = len(result.candidates)

    tel.gauge("candidates.written", n_cands)
    # scientific data-quality gauges (obs/health.py) over the block
    # already in memory: advisory — a failure degrades to "no gauges",
    # never to a failed job
    quality: dict = {}
    try:
        from ..obs.health import observation_quality

        quality = observation_quality(
            fil.data[:orig_nsamps],
            n_candidates=n_cands,
            n_dm_trials=len(result.dm_list),
            nbits=fil.nbits,
        )
        for qk, qv in quality.items():
            tel.gauge(f"dq.{qk}", qv)
    except Exception:
        log.warning(
            "quality gauges failed for %s", job.job_id, exc_info=True
        )
    info = {
        "n_candidates": n_cands,
        "pipeline": job.pipeline,
        "bucket": list(job.bucket) if job.bucket else None,
        "duration_s": round(time.perf_counter() - t0, 3),
        "padded_from": orig_nsamps if fil.nsamps != orig_nsamps else None,
    }
    if job.tenant:
        # tenant provenance rides the done record into the usage
        # ledger (campaign/usage.py), quota windows and metric labels
        info["tenant"] = job.tenant
        try:
            info["bytes_read"] = os.path.getsize(job.input)
        except OSError:
            pass
    if quality:
        info["quality"] = quality
    if job.sentinel:
        info["sentinel"] = True
    if warmup_stats is not None:
        info["warmup_s"] = float(warmup_stats["seconds"])
        info["warmup"] = warmup_stats
        if warmup_stats.get("tuning") is not None:
            # the warmer thread did the actual measuring for this
            # bucket; attribute the tuning wall to ITS job only (later
            # jobs are cache hits and must not re-count it)
            info["tuning_s"] = float(
                warmup_stats["tuning"].get("tuning_s", 0.0)
            )
    if plan_doc is not None:
        info["dedisp_plan"] = plan_doc
    return info


class _BucketWarmer(threading.Thread):
    """Background AOT warmup (and, with ``tuning_cache``, dedispersion
    auto-tuning) for one shape bucket, started when a worker claims the
    first job of a bucket it has not warmed yet. It overlaps the job's
    filterbank read: the driver joins (``result``) after reading,
    before the pipeline dispatches. Tuning runs FIRST, so the warmup
    compiles the tuned shapes and the plan is already persisted in the
    campaign's tuning cache when the job (and every other worker)
    resolves it — pure cache hits from then on. Runs on its own thread
    context, so its compiles never count against the job's telemetry
    JIT stats — by the time the pipeline runs, every program is in the
    in-process jit caches (dryrun) or the persistent compilation cache
    (aot).

    The body runs under the resilience crash guard: an escaping
    exception emits a structured ``thread_crashed`` event on the job's
    telemetry (instead of dying invisibly, as it used to), flips the
    ``resilience`` status section to degraded, and the job proceeds
    unwarmed — warmup is an optimisation, never a dependency."""

    def __init__(
        self, bucket: tuple, pipeline: str, overrides: dict,
        scratch_dir: str, mode: str, tuning_cache: str | None = None,
        telemetry=None,
    ) -> None:
        super().__init__(name="campaign-warmup", daemon=True)
        self._args = (bucket, pipeline, overrides, scratch_dir, mode)
        self._tuning_cache = tuning_cache
        self._telemetry = telemetry
        self._stats: dict | None = None
        self._error: Exception | None = None

    def run(self) -> None:
        from ..resilience import guard_thread

        self._error = guard_thread(
            "campaign-warmup", self._warm, telemetry=self._telemetry
        )

    def _warm(self) -> None:
        from ..perf.warmup import warm_bucket

        bucket, pipeline, overrides, scratch_dir, mode = self._args
        tuning = None
        if self._tuning_cache and pipeline not in ("ffa", "fdas"):
            try:
                from ..perf.tuning import resolve_plan_for_bucket

                plan = resolve_plan_for_bucket(
                    bucket, pipeline, overrides, self._tuning_cache
                )
                tuning = plan.summary()
                overrides = tuned_overrides(
                    overrides, tuning, pipeline
                )
            except Exception as exc:
                log.warning(
                    "bucket tuning failed for %s: %.200s", bucket, exc
                )
        self._stats = warm_bucket(
            bucket, pipeline, overrides, scratch_dir, mode
        )
        self._stats["tuning"] = tuning

    def result(self, timeout: float | None = None) -> dict:
        self.join(timeout=timeout)
        if self._stats is None:  # thread died before warm_bucket ran
            bucket, _, _, _, mode = self._args
            return {
                "bucket": list(bucket), "mode": mode, "seconds": 0.0,
                "programs_compiled": 0, "cache_hits": 0,
                "error": (
                    f"warmup thread crashed: {self._error!s:.200}"
                    if self._error is not None
                    else "warmup thread produced no result"
                ),
                "tuning": None,
            }
        return self._stats


class _LeaseRenewer(threading.Thread):
    """Daemon renewing the worker's claim (and its fleet-registry
    heartbeat) at a third of the lease, so only a dead (or
    wedged-past-lease) worker ever loses a job or drops out of the
    fleet view. The loop body already tolerates per-renewal failures;
    the crash guard covers everything else (a bug here silently
    forfeiting leases is exactly the invisible-thread-death failure
    mode).

    The beat is also the fleet's revoke channel: it observes a
    preempt-request file beside the claim (or a retire marker beside
    the registry entry) and flips the job's
    :class:`~peasoup_tpu.resilience.revoke.RevokeToken`, which the
    driver answers at its next checkpoint boundary. With
    ``self_preempt`` it additionally runs the decentralised victim
    selection: when a pending job outranks this claim, no live idle
    worker exists, and this is THE lowest-priority running claim, it
    writes the preempt request on its own claim — priority preemption
    with no coordinator."""

    def __init__(
        self, queue: JobQueue, claim: Claim, telemetry=None,
        registry: "WorkerRegistry | None" = None,
        token=None,
        self_preempt: bool = False,
        grace_s: float = 60.0,
        on_beat=None,
    ) -> None:
        super().__init__(name="campaign-lease", daemon=True)
        self._queue = queue
        self._claim = claim
        self._telemetry = telemetry
        self._registry = registry
        self._token = token
        self._self_preempt = bool(self_preempt)
        self._grace_s = float(grace_s)
        # per-beat hook: how a BUSY worker observes fleet requests that
        # are not revokes (the on-demand profile.request watcher)
        self._on_beat = on_beat
        # NB: not "_stop" — Thread uses that name internally
        self._halt = threading.Event()

    def run(self) -> None:
        from ..resilience import guard_thread

        guard_thread(
            "campaign-lease", self._renew_loop, telemetry=self._telemetry
        )

    def _renew_loop(self) -> None:
        period = max(0.05, self._queue.lease_s / 3.0)
        while not self._halt.wait(period):
            try:
                ok = self._queue.renew(self._claim)
                if (
                    ok is False
                    and self._token is not None
                    and not self._token.is_set()
                ):
                    # the lease is GONE — reaped, or a racing claimant
                    # won the renewal window. This worker is a zombie
                    # on the job: revoke so the driver stops at its
                    # next checkpoint boundary. It must then touch
                    # NOTHING in the queue (the new owner's state is
                    # authoritative)
                    self._token.revoke(
                        kind="lost",
                        reason="claim lease lost (reaped or "
                        "re-claimed by a peer)",
                    )
                if self._registry is not None:
                    self._registry.beat(
                        self._claim.worker_id,
                        current_job=self._claim.job.job_id,
                    )
            except Exception:
                log.debug("lease renewal failed", exc_info=True)
            try:
                self._observe_revoke()
            except Exception:
                log.debug("revoke observation failed", exc_info=True)
            if self._on_beat is not None:
                try:
                    self._on_beat()
                except Exception:
                    log.debug("beat hook failed", exc_info=True)

    def _observe_revoke(self) -> None:
        token = self._token
        if token is None or token.is_set():
            return
        job_id = self._claim.job.job_id
        req = self._queue.preempt_request(job_id)
        if req is None and self._self_preempt and not self._claim.gang:
            wanted = self._queue.preemption_wanted(self._claim)
            if wanted is not None and not self._idle_worker_live():
                if self._queue.is_lowest_priority_running(self._claim):
                    self._queue.request_preempt(
                        job_id,
                        requester=(
                            f"priority:{wanted['job_id']}"
                            f"(p{wanted['priority']})"
                        ),
                        grace_s=self._grace_s,
                    )
                    req = self._queue.preempt_request(job_id)
        if req is not None:
            from ..resilience import TransientIOError, faults

            try:
                # the revoke-delivery seam: an injected fault makes
                # THIS beat miss the request (an unresponsive victim —
                # the grace deadline escalates to the reaper)
                faults.fire("preempt.revoke", context=job_id)
            except TransientIOError:
                return
            token.revoke(
                kind="preempt",
                reason=req.get("requester") or "preempt request",
                requested_unix=req.get("requested_unix"),
            )
            if self._telemetry is not None:
                self._telemetry.event(
                    "preempt_observed", job_id=job_id,
                    requester=req.get("requester"),
                    requested_unix=req.get("requested_unix"),
                )
            return
        if self._registry is not None:
            ret = self._registry.retire_requested(self._claim.worker_id)
            if ret is not None:
                token.revoke(
                    kind="retire",
                    reason=ret.get("requester") or "retire request",
                    requested_unix=ret.get("requested_unix"),
                )
                if self._telemetry is not None:
                    self._telemetry.event(
                        "retire_observed",
                        worker_id=self._claim.worker_id,
                        requester=ret.get("requester"),
                    )

    def _idle_worker_live(self) -> bool:
        if self._registry is None:
            return False
        return any(
            e.get("current_job") is None
            and e.get("worker_id") != self._claim.worker_id
            for e in self._registry.live()
        )

    def stop(self) -> None:
        self._halt.set()
        self.join(timeout=5.0)


# --------------------------------------------------------------------------
# the worker loop
# --------------------------------------------------------------------------

class CampaignRunner:
    """One worker process draining a campaign directory. ``group``
    names the process group this worker belongs to for gang-scheduled
    jobs (Job.nprocs > 1): the group's lexicographically-first live
    member leads gang claims; the rest join as ranked members."""

    def __init__(
        self,
        root: str,
        worker_id: str | None = None,
        group: str | None = None,
    ) -> None:
        self.root = os.path.abspath(root)
        self.campaign = load_campaign_config(self.root)
        self.queue = JobQueue(
            self.root,
            lease_s=self.campaign.lease_s,
            max_attempts=self.campaign.max_attempts,
            backoff_base_s=self.campaign.backoff_base_s,
        )
        self.worker_id = worker_id or JobQueue.default_worker_id()
        self.group = group
        # fleet membership: workers join and leave at will; the
        # registry's heartbeat files are what rollup/watch render and
        # what the fleet soak audits for leaks (campaign/registry.py)
        self.registry = WorkerRegistry(
            self.root, lease_s=self.campaign.lease_s, group=group
        )
        self._jobs_done = 0
        self._last_bucket: tuple | None = None
        self._warmed_buckets: set[tuple] = set()
        self._retiring = False
        # gang epochs this worker already served as a member (the
        # invitation outlives the member's run until the leader
        # completes — never join the same epoch twice)
        self._gang_epochs_joined: set[str] = set()
        self._tuning_cache = (
            self.campaign.tuning_cache_path(self.root)
            if self.campaign.tune else None
        )
        # fleet observability: this worker's append-only time series
        # (queue depth, throughput, preemption latency...) next to its
        # registry entry, and the single-flight on-demand profiler
        self.metrics = MetricsRecorder(
            self.registry.metrics_path(self.worker_id),
            enabled=self.campaign.metrics,
        )
        self._profile_thread: threading.Thread | None = None
        self._last_queue_sample = 0.0
        self._last_alert_eval = 0.0
        # the persistent XLA cache backs the in-process caches across
        # worker restarts (utils/cache.py)
        from ..utils.cache import enable_compilation_cache

        enable_compilation_cache()

    # --- one job ------------------------------------------------------
    def process_claim(
        self, claim: Claim, claim_wait_s: float | None = None
    ) -> str:
        """Run one claimed job under its own observability stack.
        Returns the job's resulting state (done|backoff|quarantined),
        "released" when a revoke (preempt/retire) handed the job back
        mid-run with zero attempts consumed, or "lost" when the claim
        lease was reaped from under a live run (the reaper charged
        the attempt; this worker mutates no further queue state). ``claim_wait_s`` is
        how long this worker idled before winning the claim (a
        scheduling span in the job's trace and a fleet latency
        histogram)."""
        from ..resilience import RevokeToken, activate_token

        job = claim.job
        job_dir = os.path.join(self.root, "jobs", job.job_id)
        os.makedirs(job_dir, exist_ok=True)
        manifest_path = os.path.join(job_dir, "telemetry.json")
        tel = RunTelemetry()
        tel.set_context(
            command="campaign-job",
            job_id=job.job_id,
            worker_id=self.worker_id,
            pipeline=job.pipeline,
            inputfile=job.input,
            outdir=job_dir,
            attempt=job.attempts + 1,
            bucket=list(job.bucket) if job.bucket else None,
            gang=claim.gang,
            trace_id=job.trace_id or None,
        )
        # the job's trace: this process's span file under the job dir,
        # keyed by the trace id minted at enqueue — a resumed or
        # gang-scheduled run appends to the SAME trace from another
        # process/worker, and the export stitches them into one
        tracer = Tracer(
            os.path.join(
                job_dir, f"trace-{_safe_name(self.worker_id)}.jsonl"
            ),
            job.trace_id or new_trace_id(),
            worker=self.worker_id,
            enabled=self.campaign.trace,
        )
        tracer.attach(tel)
        now_unix = time.time()
        if claim_wait_s is not None:
            tracer.span_at(
                "claim_wait", now_unix - claim_wait_s, claim_wait_s,
                job_id=job.job_id,
            )
            self.metrics.observe("claim_wait_seconds", claim_wait_s)
        from ..resilience import STATS as _RES_STATS

        res_base = _RES_STATS.snapshot()
        token = RevokeToken()
        renewer = _LeaseRenewer(
            self.queue, claim, telemetry=tel, registry=self.registry,
            token=token,
            self_preempt=self.campaign.preempt,
            grace_s=self.campaign.preempt_grace_s,
            on_beat=self._observe_profile,
        )
        renewer.start()
        comm = None
        if claim.gang:
            # gang leader: assemble the group at the join barrier (the
            # file-backed exchange's round 0), then route through the
            # multi-host driver. An unassembled gang is a clean release
            # — zero attempts, no partial-gang deadlock.
            comm = self._gang_comm(claim.gang, job_dir, rank=0)
            try:
                with tracer.span(
                    "gang_join", cat="sched", rank=0,
                    nprocs=claim.gang.get("nprocs"),
                ):
                    comm.allgather(
                        self.worker_id.encode(),
                        context=f"gang-join:{job.job_id}",
                        timeout_s=self.campaign.gang_assemble_s,
                    )
            except Exception as exc:
                renewer.stop()
                self._gang_cleanup(comm)
                tel.event(
                    "gang_unassembled", job_id=job.job_id,
                    gang=claim.gang, error=f"{exc!s:.200}",
                )
                tracer.close()
                self.queue.release(claim)
                log.warning(
                    "gang for %s did not assemble (%s); claim released "
                    "cleanly", job.job_id, exc,
                )
                return "released"
            tel.event(
                "gang_assembled", job_id=job.job_id, gang=claim.gang
            )
        warmer = None
        if (
            self.campaign.warmup
            and job.bucket
            and tuple(job.bucket) not in self._warmed_buckets
        ):
            # first job of a bucket this worker has not warmed: compile
            # its programs on a background thread while the filterbank
            # reads (run_observation joins before dispatching)
            warmer = _BucketWarmer(
                tuple(job.bucket), job.pipeline,
                {**self.campaign.config, **job.config},
                os.path.join(self.root, "warmup", job.job_id),
                self.campaign.warmup_mode,
                tuning_cache=self._tuning_cache,
                telemetry=tel,
            )
            warmer.start()
            self._warmed_buckets.add(tuple(job.bucket))
        recorder = FlightRecorder(
            tel,
            os.path.join(job_dir, "flight.json"),
            manifest_path=manifest_path,
        ).install()
        heartbeat = Heartbeat(
            tel,
            os.path.join(job_dir, "status.json"),
            interval=self.campaign.heartbeat_interval,
        ).start()
        overrides = {**self.campaign.config, **job.config}
        from ..resilience import SearchPreempted

        try:
            with tel.activate(), activate_token(token), \
                    tracer.activate(), tracer.span(
                        "job_attempt",
                        job_id=job.job_id,
                        pipeline=job.pipeline,
                        attempt=job.attempts + 1,
                        priority=job.priority,
                    ):
                try:
                    # chaos seam: a scheduled worker.kill raises
                    # WorkerKilled (BaseException) here — it skips the
                    # except below exactly like a real SIGKILL skips
                    # the failure path, the claim is never released,
                    # and the lease reaper is the only recovery
                    from ..resilience import faults

                    faults.fire("worker.kill", context=job.job_id)
                    info = run_observation(
                        job, overrides, job_dir, tel,
                        bucket_ladder=self.campaign.bucket_nsamps,
                        warmer=warmer,
                        tuning_cache=self._tuning_cache,
                        comm=comm,
                    )
                    compiled = jit_programs_compiled(tel)
                    info["jit_programs_compiled"] = compiled
                    tel.gauge("jit.programs_compiled", compiled)
                    if (
                        compiled
                        and job.bucket
                        and job.bucket == self._last_bucket
                    ):
                        # same bucket yet new programs: the reuse
                        # contract broke — surface it, don't fail
                        tel.event(
                            "jit_cache_miss", bucket=list(job.bucket),
                            programs_compiled=compiled,
                        )
                        log.warning(
                            "job %s recompiled %d programs despite "
                            "matching the previous bucket %s",
                            job.job_id, compiled, job.bucket,
                        )
                    tel.set_stage("ingest")
                    with CandidateDB(
                        os.path.join(self.root, DB_FILENAME)
                    ) as db:
                        info["ingested"] = db.ingest_job(
                            job.job_id, job_dir, job.input,
                            tenant=job.tenant,
                        )
                    # per-job resilience accounting: what THIS job
                    # survived (retries, degradations, injected
                    # faults), for the done record + campaign rollup
                    res_delta = _RES_STATS.delta_since(res_base)
                    # a previously RELEASED attempt's survived faults
                    # ride the job record (queue.record_carried_
                    # resilience) — fold them in so the done record
                    # accounts for the job's WHOLE history
                    for table, kv in (
                        claim.job.carried_resilience or {}
                    ).items():
                        if not isinstance(kv, dict):
                            continue
                        tgt = res_delta.setdefault(table, {})
                        for k, v in kv.items():
                            tgt[k] = tgt.get(k, 0) + int(v)
                    if res_delta:
                        info["resilience"] = res_delta
                    # a job that descended a degradation ladder (OOM
                    # fall-through, thread crash) completed DEGRADED:
                    # correct results, reduced machinery — surfaced in
                    # the done record so operators can audit the tail
                    info["degraded"] = bool(
                        res_delta.get("degradations")
                        or res_delta.get("thread_crashes")
                    )
                    # preemption provenance: a job that was revoked and
                    # resumed carries its tally + request->release
                    # latency into the done record (claim.job is the
                    # record as re-read at claim time)
                    if job.preemptions:
                        info["preemptions"] = int(job.preemptions)
                        info["preempt_latency_s"] = list(
                            job.preempt_latency_s
                        )
                    if claim.gang:
                        info["gang"] = dict(claim.gang)
                    tel.set_stage("done")
                    tel.write(manifest_path)
                except SearchPreempted as exc:
                    # the revoke's cooperative stop: the checkpoint on
                    # disk is consistent (check_revoke's contract), so
                    # the claim is RELEASED — zero attempts consumed —
                    # and the job resumes from the checkpoint later,
                    # bitwise-equal to an uninterrupted run
                    tel.event(
                        "preempted", job_id=job.job_id,
                        revoke_kind=exc.kind, reason=exc.reason,
                    )
                    tel.write(
                        manifest_path, aborted=True,
                        abort_reason=f"revoked ({exc.kind}): "
                        f"{exc.reason:.200}",
                    )
                    if comm is not None:
                        comm.abort(f"leader revoked ({exc.kind})")
                    if exc.kind == "lost":
                        # the lease was reaped (or re-claimed) from
                        # under a live run: the reaper already charged
                        # the attempt and a new owner may hold the
                        # claim — this zombie must not mutate ANY
                        # shared queue state (no release, no carried
                        # fold, no preempt accounting). The checkpoint
                        # on disk still serves the re-run
                        from ..resilience import STATS

                        STATS.preemption("lost")
                        self.metrics.counter(
                            "preemptions_total", event=exc.kind
                        )
                        log.warning(
                            "job %s lease lost mid-run; abandoning "
                            "attempt without queue mutations",
                            job.job_id,
                        )
                        # ...except the worker's OWN spool: the faults
                        # this attempt survived must still reach the
                        # campaign rollup, and the append-only sidecar
                        # races nobody (the job record is off-limits —
                        # we hold no lease)
                        lost_delta = _RES_STATS.delta_since(res_base)
                        if lost_delta:
                            self.queue.record_orphaned_resilience(
                                self.worker_id, job.job_id, lost_delta
                            )
                        return "lost"
                    # whatever this attempt survived must not vanish
                    # with the zero-attempt release: carry it on the
                    # job record into the resumed run's done record
                    rel_delta = _RES_STATS.delta_since(res_base)
                    if rel_delta:
                        self.queue.record_carried_resilience(
                            claim, rel_delta
                        )
                    if exc.kind == "retire":
                        self.queue.release(claim)
                        self._retiring = True
                        from ..resilience import STATS

                        STATS.preemption("retire")
                        log.info(
                            "worker %s retiring: job %s released "
                            "cleanly at a checkpoint boundary",
                            self.worker_id, job.job_id,
                        )
                    else:
                        latency = self.queue.release_preempted(
                            claim, observed_unix=token.observed_unix
                        )
                        tel.event(
                            "preempt_released", job_id=job.job_id,
                            latency_s=round(latency, 4),
                        )
                        # the revoke-latency span: request -> release,
                        # in the job's one connected trace
                        release_unix = time.time()
                        tracer.span_at(
                            "revoke", release_unix - latency, latency,
                            kind=exc.kind, job_id=job.job_id,
                        )
                        self.metrics.observe(
                            "preemption_latency_seconds", latency
                        )
                    self.metrics.counter(
                        "preemptions_total", event=exc.kind
                    )
                    return "released"
                except Exception as exc:
                    tel.event(
                        "campaign_job_failed",
                        error=f"{type(exc).__name__}: {exc!s:.500}",
                    )
                    tel.write(
                        manifest_path, aborted=True,
                        abort_reason=f"{type(exc).__name__}: {exc!s:.200}",
                    )
                    if comm is not None:
                        # any gang failure fails the gang as ONE unit:
                        # peers abort fast at their next barrier, and
                        # the job requeues as a single consumed attempt
                        comm.abort(
                            f"leader failed: {type(exc).__name__}"
                        )
                    state = self.queue.fail(
                        claim, f"{type(exc).__name__}: {exc}"
                    )
                    fail_labels = {"state": state}
                    if job.tenant:
                        fail_labels["tenant"] = job.tenant
                    self.metrics.counter(
                        "jobs_failed_total", **fail_labels
                    )
                    log.warning(
                        "job %s failed -> %s: %s", job.job_id, state, exc
                    )
                    return state
        finally:
            heartbeat.stop()
            recorder.close()
            renewer.stop()
            tracer.close()
            if comm is not None:
                self._gang_cleanup(comm)
        # second chaos seam: dying AFTER the work but BEFORE the done
        # record is the worst case for exactly-once — the reaped job
        # re-runs in full and must complete idempotently
        from ..resilience import faults as _faults

        _faults.fire("worker.kill", context=f"{job.job_id}:pre-complete")
        if not self.queue.complete(
            claim, worker_id=self.worker_id, **info
        ):
            # the lease was lost between the last renewal and this
            # publish: the reaper charged the attempt and the done
            # record is the next owner's to write — claiming "done"
            # here would double-count the job
            log.warning(
                "job %s finished but its lease was lost; done record "
                "not published (the job will re-run)", job.job_id,
            )
            # the attempt's survived faults still count: spool the
            # delta (NOT info["resilience"] — that folds in carried
            # marks, which stay on the job record for the re-run's
            # done record; spooling them too would double-count)
            lost_delta = _RES_STATS.delta_since(res_base)
            if lost_delta:
                self.queue.record_orphaned_resilience(
                    self.worker_id, job.job_id, lost_delta
                )
            return "lost"
        self._record_job_metrics(tel, info)
        if job.bucket:
            self._last_bucket = job.bucket
        log.info(
            "job %s done: %d candidates, %d programs compiled",
            job.job_id, info["n_candidates"], info["jit_programs_compiled"],
        )
        return "done"

    # --- gang-scheduled jobs ------------------------------------------
    def _gang_comm(self, gang: dict, job_dir: str, rank: int):
        """The file-backed exchange for one gang epoch. The leader
        (rank 0) sweeps stale epoch directories first — a SIGKILLed
        previous attempt must not leak its blobs."""
        import shutil

        from ..parallel.multihost import GangComm

        if rank == 0:
            for name in list(os.listdir(job_dir)) if os.path.isdir(
                job_dir
            ) else []:
                # stale epochs only: a racing member may already have
                # created (and written its join blob into) THIS epoch
                if name.startswith("gang-") and name != (
                    f"gang-{gang['epoch']}"
                ):
                    shutil.rmtree(
                        os.path.join(job_dir, name), ignore_errors=True
                    )
        return GangComm(
            os.path.join(job_dir, f"gang-{gang['epoch']}"),
            nprocs=int(gang["nprocs"]),
            rank=rank,
            timeout_s=self.campaign.gang_timeout_s,
            heartbeat=lambda: self.registry.beat(self.worker_id),
        )

    def _gang_cleanup(self, comm) -> None:
        import shutil

        shutil.rmtree(comm.gang_dir, ignore_errors=True)

    def _gang_member(self, claim_doc: dict) -> None:
        """The member side of a gang job: compute this rank's DM slice
        through the same multi-host driver the leader runs, feeding
        the file-backed exchange. Members hold no claim and consume no
        attempts — a dying leader (claim reaped, exchange aborted or
        timed out) just sends the member back to the queue loop; a
        dying member surfaces at the LEADER's next barrier and fails
        the gang transiently as one unit."""
        gang = claim_doc["gang"]
        job_id = claim_doc["job_id"]
        epoch = gang.get("epoch", "")
        self._gang_epochs_joined.add(epoch)
        job = self.queue.get_job(job_id)
        if job is None:
            return
        rank = gang["members"].index(self.worker_id)
        job_dir = os.path.join(self.root, "jobs", job_id)
        os.makedirs(job_dir, exist_ok=True)
        tel = RunTelemetry()
        tel.set_context(
            command="campaign-gang-member",
            job_id=job_id,
            worker_id=self.worker_id,
            pipeline=job.pipeline,
            inputfile=job.input,
            outdir=job_dir,
            gang=gang,
            process_index=rank,
            process_count=int(gang["nprocs"]),
            trace_id=claim_doc.get("trace_id") or job.trace_id or None,
        )
        # the member's spans join the job's ONE trace: the id rides the
        # gang claim document the invitation handed us
        tracer = Tracer(
            os.path.join(
                job_dir, f"trace-{_safe_name(self.worker_id)}.jsonl"
            ),
            claim_doc.get("trace_id") or job.trace_id or new_trace_id(),
            worker=self.worker_id,
            enabled=self.campaign.trace,
        )
        tracer.attach(tel)
        self.registry.beat(self.worker_id, current_job=job_id)
        comm = self._gang_comm(gang, job_dir, rank=rank)
        log.info(
            "joining gang for %s as rank %d/%d (epoch %s)",
            job_id, rank, gang["nprocs"], epoch,
        )
        try:
            with tel.activate(), tracer.activate(), tracer.span(
                "gang_member", job_id=job_id, rank=rank,
                nprocs=int(gang["nprocs"]),
            ):
                with tracer.span(
                    "gang_join", cat="sched", rank=rank,
                    nprocs=gang.get("nprocs"),
                ):
                    comm.allgather(
                        self.worker_id.encode(),
                        context=f"gang-join:{job_id}",
                        timeout_s=self.campaign.gang_assemble_s,
                    )
                tel.event("gang_assembled", job_id=job_id, gang=gang)
                run_observation(
                    job,
                    {**self.campaign.config, **job.config},
                    job_dir, tel,
                    bucket_ladder=self.campaign.bucket_nsamps,
                    tuning_cache=self._tuning_cache,
                    comm=comm,
                    write_outputs=False,  # the leader owns the outputs
                )
                tel.write(
                    os.path.join(job_dir, f"telemetry.proc{rank}.json")
                )
        except Exception as exc:
            comm.abort(f"member rank {rank} failed: {type(exc).__name__}")
            log.warning(
                "gang member rank %d of %s stopped: %.300s",
                rank, job_id, exc,
            )
            tel.event(
                "gang_member_failed", job_id=job_id, rank=rank,
                error=f"{exc!s:.200}",
            )
        finally:
            tracer.close()
            self.registry.beat(self.worker_id, current_job=None)

    # --- warmup-aware claiming ----------------------------------------
    def _warm_bucket_hint(self) -> set[tuple]:
        """Buckets whose warmup/tuning has already been paid for: this
        worker's own warmed set unioned with every bucket a done
        record carries warmup tallies for (the same data the rollup's
        warm-bucket summary aggregates) — so a worker joining a
        running campaign prefers already-warm buckets over opening a
        cold one, maximising bucket streaks."""
        warm = set(self._warmed_buckets)
        try:
            for doc in self.queue.done_records():
                b = doc.get("bucket")
                if b and (
                    doc.get("warmup_s") is not None
                    or doc.get("dedisp_plan") is not None
                ):
                    warm.add(tuple(b))
        except Exception:  # a torn done record must not stall claiming
            log.debug("warm-bucket hint scan failed", exc_info=True)
        return warm

    # --- fleet observability ------------------------------------------
    def _record_job_metrics(self, tel: RunTelemetry, info: dict) -> None:
        """One completed job's contribution to this worker's time
        series: completion/duration, per-stage seconds + throughput,
        device-memory high water, warmup/tuning wall, compiles."""
        m = self.metrics
        if not m.enabled:
            return
        try:
            # tenant label on the per-job series: Prometheus exposition
            # and series(labels=...) queries slice usage by tenant
            tlab = (
                {"tenant": info["tenant"]} if info.get("tenant") else {}
            )
            m.counter(
                "jobs_done_total", pipeline=info.get("pipeline", ""),
                **tlab,
            )
            dur = float(info.get("duration_s") or 0.0)
            if dur:
                m.observe("job_duration_seconds", dur, **tlab)
            if tlab and dur:
                m.counter("tenant_device_seconds_total", dur, **tlab)
            for stage, secs in sorted(tel.timers.items()):
                m.counter("stage_seconds_total", float(secs), stage=stage)
            trials = float(tel.counters.get("search.dm_trials_done", 0))
            searching = float(tel.timers.get("searching", 0.0))
            if trials and searching > 0:
                m.gauge(
                    "stage_throughput_per_s", trials / searching,
                    stage="searching", unit="dm_trials",
                )
            peak = tel.gauges.get("memory.peak_bytes")
            if peak:
                m.gauge("device_memory_peak_bytes", float(peak))
            if info.get("warmup_s") is not None:
                m.counter("warmup_seconds_total", float(info["warmup_s"]))
            if info.get("tuning_s") is not None:
                m.counter("tuning_seconds_total", float(info["tuning_s"]))
            m.counter(
                "jit_programs_compiled_total",
                int(info.get("jit_programs_compiled", 0)),
                **tlab,
            )
            if info.get("gang"):
                m.counter("gang_jobs_total")
            if info.get("degraded"):
                m.counter("degraded_jobs_total")
            # scientific data-quality gauges (obs/health.py): the last
            # job's values as worker-level series for the sparklines;
            # campaign baselines read the done records, not these
            for qk, qv in sorted((info.get("quality") or {}).items()):
                m.gauge(f"dq_{qk}", float(qv))
        except Exception:  # metrics must never fail a completed job
            log.debug("job metrics recording failed", exc_info=True)

    def _sample_queue_metrics(self, min_interval_s: float = 1.0) -> None:
        """Throttled queue-depth gauges (one sample per derived state)
        — the "what was queue depth over the last hour" series."""
        if not self.metrics.enabled:
            return
        now_mono = time.monotonic()
        if now_mono - self._last_queue_sample < min_interval_s:
            return
        self._last_queue_sample = now_mono
        try:
            counts = self.queue.counts()
            for state in (
                "pending", "running", "backoff", "stale", "done",
                "quarantined", "throttled",
            ):
                self.metrics.gauge(
                    "queue_depth", counts.get(state, 0), state=state
                )
            self.metrics.gauge("queue_jobs_total", counts.get("total", 0))
            # liveness series for the heartbeat-absence alert rule
            now_unix = time.time()
            self.metrics.gauge("worker_heartbeat_unix", now_unix)
        except Exception:
            log.debug("queue metrics sampling failed", exc_info=True)

    def _evaluate_alerts(self, min_interval_s: float = 5.0) -> None:
        """Throttled survey-health round (obs/alerts.py) beside the
        status rollup. Any worker may run it; concurrent evaluators
        serialise on the engine's lock file. Never fails the worker."""
        now_mono = time.monotonic()
        if now_mono - self._last_alert_eval < min_interval_s:
            return
        self._last_alert_eval = now_mono
        try:
            from ..obs.alerts import default_rules, evaluate_campaign

            evaluate_campaign(
                self.root,
                rules=default_rules(
                    heartbeat_s=max(
                        float(self.campaign.heartbeat_interval), 0.1
                    )
                ),
                queue=self.queue,
                registry=self.registry,
            )
        except Exception:
            log.debug("alert evaluation failed", exc_info=True)

    def _observe_profile(self) -> None:
        """The worker side of on-demand profiling: observe a
        ``profile.request`` beside our registry entry (written by
        ``peasoup-campaign profile``), clear it (single-flight), and
        run the bounded capture on a helper thread so neither the
        renewer beat nor the claim loop blocks on it."""
        if self._profile_thread is not None and (
            self._profile_thread.is_alive()
        ):
            return
        req = self.registry.profile_requested(self.worker_id)
        if req is None:
            return
        self.registry.clear_profile(self.worker_id)
        seconds = float(req.get("seconds") or 5.0)
        now_unix = time.time()
        outdir = os.path.join(
            self.root, "profiles",
            f"{_safe_name(self.worker_id)}-{int(now_unix)}",
        )
        from ..obs.profiler import start_profile_capture

        # the capture announces itself in this worker's metrics stream
        self._profile_thread = start_profile_capture(
            outdir, seconds, metrics=self.metrics
        )
        log.info(
            "device profile capture started for %s (%.3gs, requested "
            "by %s)", self.worker_id, seconds, req.get("requester") or "?",
        )

    # --- the loop -----------------------------------------------------
    def run(
        self,
        max_jobs: int | None = None,
        drain: bool = True,
        poll_s: float = 1.0,
    ) -> dict:
        """Claim and process jobs until the campaign drains (every job
        terminal), ``max_jobs`` are processed, a retire request lands
        (autoscale scale-down: the worker finishes — or checkpoints
        and releases — its current job, deregisters and exits), or —
        with ``drain=False`` — the queue has nothing immediately
        claimable. Registers in the fleet registry for the duration
        (heartbeat renewed alongside the claim lease; clean
        deregistration on any exit path — only a SIGKILL leaves an
        entry, which peers reap). Returns this worker's tally."""
        from ..resilience import WorkerKilled

        tally = {
            "done": 0, "failed": 0, "quarantined": 0, "released": 0,
            "lost": 0,
        }
        processed = 0
        self.registry.register(self.worker_id, group=self.group)
        wait_t0 = time.perf_counter()  # claim-wait latency base
        try:
            while True:
                if max_jobs is not None and processed >= max_jobs:
                    break
                if self._retiring or self.registry.retire_requested(
                    self.worker_id
                ):
                    log.info(
                        "worker %s retiring (requested): leaving the "
                        "fleet cleanly", self.worker_id,
                    )
                    break
                self.registry.beat(
                    self.worker_id, jobs_done=self._jobs_done,
                    current_job=None,
                )
                # fleet observability: queue-depth time series and the
                # idle-side profile.request watcher (the busy side is
                # the lease renewer's beat hook)
                self._sample_queue_metrics()
                self._observe_profile()
                if self.group:
                    # a gang claim naming this worker outranks new
                    # work: the leader is holding the claim for the
                    # whole group
                    inv = self.queue.gang_invitation(self.worker_id)
                    if inv is not None and (
                        inv["gang"].get("epoch")
                        not in self._gang_epochs_joined
                    ):
                        self._gang_member(inv)
                        continue
                claim = self.queue.claim_next(
                    self.worker_id, prefer_bucket=self._last_bucket,
                    warm_buckets=self._warm_bucket_hint(),
                    group=self.group,
                    group_members=(
                        self.registry.live_group(self.group)
                        if self.group else None
                    ),
                )
                if claim is None:
                    self.registry.reap()
                    write_status(self.root, self.queue)
                    self._evaluate_alerts()
                    if self.queue.drained() or not drain:
                        break
                    counts = self.queue.counts()
                    if counts["total"] == 0:
                        break
                    # others are running, or retries back off: wait
                    time.sleep(poll_s)
                    continue
                state = self.process_claim(
                    claim,
                    claim_wait_s=round(
                        time.perf_counter() - wait_t0, 6
                    ),
                )
                wait_t0 = time.perf_counter()
                if state == "released":
                    # a revoke (preempt/retire) or an unassembled gang
                    # handed the job back: nothing was consumed and
                    # nothing was processed
                    tally["released"] += 1
                    continue
                if state == "lost":
                    # the lease was reaped from under a live run: the
                    # reaper charged the attempt and a peer owns the
                    # job now — this worker has nothing to account for
                    tally["lost"] += 1
                    continue
                processed += 1
                if state == "done":
                    tally["done"] += 1
                    self._jobs_done += 1
                elif state == "quarantined":
                    tally["quarantined"] += 1
                else:
                    tally["failed"] += 1
                self.registry.beat(
                    self.worker_id, jobs_done=self._jobs_done,
                    current_job=None,
                    last_bucket=(
                        list(self._last_bucket)
                        if self._last_bucket else None
                    ),
                )
                write_status(self.root, self.queue)
                self._evaluate_alerts()
            # dead peers' membership entries expire within one lease;
            # reap them on the way out so a drained campaign leaves a
            # clean registry (the fleet soak's zero-leak invariant)
            self.registry.reap()
            write_status(self.root, self.queue)
            self._evaluate_alerts(min_interval_s=0.0)
        except WorkerKilled:
            # the simulated SIGKILL: a real kill runs no cleanup, so
            # the membership entry must stay behind for peers to reap
            raise
        except BaseException:
            self.registry.deregister(self.worker_id)
            raise
        self.registry.deregister(self.worker_id)
        return tally


def run_worker(
    root: str,
    worker_id: str | None = None,
    max_jobs: int | None = None,
    drain: bool = True,
    poll_s: float = 1.0,
    group: str | None = None,
) -> dict:
    """THE worker entry point: one call makes this process a campaign
    worker (fleet registration, warmup-aware claiming, per-job
    observability, rollup writes) until it leaves. The CLI
    (``peasoup-campaign run``), the in-process chaos soak, the
    autoscale controller's spawns, and the fleet soak's real
    subprocesses all enter through here, so every soak exercises
    exactly the code a production worker runs. ``group`` opts the
    worker into a gang-scheduling process group."""
    return CampaignRunner(root, worker_id=worker_id, group=group).run(
        max_jobs=max_jobs, drain=drain, poll_s=poll_s
    )
