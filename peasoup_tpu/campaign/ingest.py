"""Tenant submission front end: admission control + audit journal.

Every path an observation can enter a multi-tenant campaign by —
portal POST /submit (obs/portal.py), the watch-folder ingester below,
`peasoup-campaign submit` — funnels through :func:`submit_observation`
so admission policy lives in exactly one place:

1. the tenant must exist (campaign/tenants.py registry);
2. the input file must exist;
3. a duplicate job id (same observation already enqueued, any state)
   is rejected — enqueue is idempotent, and a resubmission must not
   reset another tenant's (or an earlier) job;
4. priority above the tenant's ``priority_max`` ceiling is CLAMPED,
   never rejected (the job still runs, at the class the tenant is
   entitled to), and flagged ``priority_capped`` in the journal;
5. a tenant at its ``max_queued`` ceiling is rejected outright —
   queue-depth pressure is an admission problem, unlike the runtime
   quotas (max_running / device-seconds) which park jobs as
   ``throttled`` at claim time.

Every decision — accepted or rejected, with reason — is journaled
append-only to ``queue/submissions.jsonl`` (who, what, when, via which
door), so operator audit is a log read, not archaeology. The journal
is size-capped by ``peasoup-campaign prune --journals`` via the shared
rotation idiom (obs/metrics.rotate_journal).
"""

from __future__ import annotations

import json
import os
import time

from ..obs import get_logger
from .queue import Job, JobQueue, job_id_for
from .tenants import TenantRegistry, queued_counts

log = get_logger("campaign.ingest")

SUBMISSIONS = "submissions.jsonl"

_SUBMIT_EXTS = (".fil", ".fbk")  # watch-folder drop extensions


def submissions_path(root: str) -> str:
    return os.path.join(os.path.abspath(root), "queue", SUBMISSIONS)


def append_submission(root: str, entry: dict) -> None:
    """Append-only journal write. A single ``write`` of one
    newline-terminated line is atomic at the sizes we emit, matching
    the alerts-journal idiom; readers tolerate a torn tail."""
    path = submissions_path(root)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "a") as f:
        f.write(json.dumps(entry, sort_keys=True) + "\n")


def read_submissions(root: str) -> list[dict]:
    """Every parseable journal entry, in append order (a torn final
    line — writer killed mid-append — is skipped, not fatal)."""
    out: list[dict] = []
    try:
        with open(submissions_path(root)) as f:
            for ln in f:
                ln = ln.strip()
                if not ln:
                    continue
                try:
                    out.append(json.loads(ln))
                except json.JSONDecodeError:
                    continue
    except FileNotFoundError:
        pass
    return out


def submit_observation(
    root: str,
    tenant_name: str,
    input_path: str,
    *,
    priority: int = 0,
    config: dict | None = None,
    pipeline: str = "spsearch",
    via: str = "cli",
    queue: JobQueue | None = None,
    now: float | None = None,
) -> dict:
    """Admit (or reject) one observation for ``tenant_name`` and
    journal the decision. Returns the journal entry, whose
    ``accepted`` / ``reason`` / ``job_id`` fields the callers (portal,
    CLI, watch-folder) render directly. The caller authenticates the
    tenant (the portal by bearer token, the CLI by being the
    operator); this function enforces quota + policy."""
    now = time.time() if now is None else now
    queue = queue or JobQueue(root)
    entry: dict = {
        "t_unix": round(now, 3),
        "via": via,
        "tenant": tenant_name,
        "input": input_path,
        "pipeline": pipeline,
        "priority": int(priority),
        "priority_capped": False,
        "accepted": False,
        "reason": None,
        "job_id": None,
    }

    def _reject(reason: str) -> dict:
        entry["reason"] = reason
        append_submission(root, entry)
        log.warning(
            "submission rejected (%s, via %s): %s — %s",
            tenant_name, via, input_path, reason,
        )
        return entry

    tenant = TenantRegistry(root).get(tenant_name)
    if tenant is None:
        return _reject(f"unknown tenant {tenant_name!r}")
    if not input_path or not os.path.isfile(input_path):
        return _reject(f"input not found: {input_path}")
    job_id = job_id_for(input_path)
    entry["job_id"] = job_id
    if queue.get_job(job_id) is not None:
        return _reject(f"duplicate submission (job {job_id} exists)")
    if tenant.priority_max is not None and priority > tenant.priority_max:
        entry["priority"] = int(tenant.priority_max)
        entry["priority_capped"] = True
    if tenant.max_queued > 0:
        # Check-then-act across processes (CLI, watch ingester and
        # portal each run their own submit_observation): concurrent
        # submissions for one tenant can land between this count and
        # add_job below, over-admitting by at most the number of
        # simultaneous racers. Matching the running_counts contract,
        # that transient is accepted rather than locked away — the
        # very next submission counts every admitted job and the
        # ceiling re-asserts; retracting an already-visible job here
        # would race the workers' claim path instead.
        queued = queued_counts(root).get(tenant_name, 0)
        if queued >= tenant.max_queued:
            return _reject(
                f"max_queued reached ({queued}/{tenant.max_queued})"
            )
    # bucket derivation imports the sigproc reader lazily inside
    # runner.bucket_for_input, keeping this module (and the portal
    # handler that calls it) import-light
    from .runner import PIPELINES, bucket_for_input

    if pipeline not in PIPELINES:
        return _reject(f"unknown pipeline {pipeline!r}")
    job = Job(
        job_id=job_id,
        input=os.path.abspath(input_path),
        pipeline=pipeline,
        config=dict(config or {}),
        bucket=bucket_for_input(input_path),
        priority=int(entry["priority"]),
        tenant=tenant_name,
    )
    if not queue.add_job(job):
        return _reject(f"duplicate submission (job {job_id} exists)")
    entry["accepted"] = True
    append_submission(root, entry)
    log.info(
        "submission accepted (%s, via %s): %s -> job %s prio %d%s",
        tenant_name, via, input_path, job_id, entry["priority"],
        " (priority capped)" if entry["priority_capped"] else "",
    )
    return entry


def ingest_watch_folders(
    root: str,
    queue: JobQueue | None = None,
    pipeline: str = "spsearch",
) -> list[dict]:
    """One poll of every tenant's ``watch_dir``: new filterbank drops
    submit through the same admission path as HTTP (journaled with
    ``via="watch"``). Files whose job id is already enqueued are
    skipped SILENTLY — polling is repetitive by nature and must not
    spam the journal with duplicate rejections. Returns the journal
    entries for this poll's fresh submissions."""
    queue = queue or JobQueue(root)
    out: list[dict] = []
    for tenant in TenantRegistry(root).entries():
        wdir = tenant.watch_dir
        if not wdir or not os.path.isdir(wdir):
            continue
        try:
            names = sorted(os.listdir(wdir))
        except OSError:
            continue
        for name in names:
            if not name.lower().endswith(_SUBMIT_EXTS):
                continue
            path = os.path.join(wdir, name)
            if not os.path.isfile(path):
                continue
            if queue.get_job(job_id_for(path)) is not None:
                continue  # seen on an earlier poll: not a fresh drop
            out.append(
                submit_observation(
                    root, tenant.name, path,
                    pipeline=pipeline, via="watch", queue=queue,
                )
            )
    return out
