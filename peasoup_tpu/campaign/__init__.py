"""Campaign orchestration: run the pipelines over MANY observations.

The reference processes one filterbank per invocation; a survey runs
thousands. This package is the orchestration + aggregation layer that
survey pipelines (the FAST drift-scan PRESTO pipeline, arXiv:1912.12807;
the GSP single-pulse pipeline with its candidate database,
arXiv:2110.12749) show is where throughput and operability are won:

- :mod:`.queue` — a file-backed job queue, safe for many workers on a
  shared filesystem: atomic claim files, lease expiry + stale-claim
  reaping (a SIGKILLed worker's job is re-queued), per-job retry with
  exponential backoff, quarantine after the retry budget.
- :mod:`.runner` — the long-lived worker loop: orders jobs into shape
  buckets so consecutive observations hit the in-process jit caches and
  the persistent XLA compilation cache, runs each job with its own
  live-observability stack (heartbeat, flight recorder, telemetry
  manifest under the job dir), and records per-job compile counts so
  cache reuse is asserted, not assumed.
- :mod:`.db` — the survey-level candidate database (stdlib sqlite):
  every completed job's overview.xml / .singlepulse outputs ingested
  into queryable tables with per-observation provenance.
- :mod:`.rollup` — the atomically rewritten ``campaign_status.json``
  aggregating queue depth, running-job heartbeats, throughput/ETA and
  failure tallies; ``python -m peasoup_tpu.tools.watch`` renders it.

Entry point: ``python -m peasoup_tpu.cli.campaign``.
"""

from .db import CandidateDB
from .queue import Claim, Job, JobQueue
from .rollup import CAMPAIGN_SCHEMA, build_status, write_status
from .runner import CampaignRunner, load_campaign_config

__all__ = [
    "CAMPAIGN_SCHEMA",
    "CandidateDB",
    "CampaignRunner",
    "Claim",
    "Job",
    "JobQueue",
    "build_status",
    "load_campaign_config",
    "write_status",
]
