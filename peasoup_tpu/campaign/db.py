"""Survey-level candidate database (stdlib sqlite).

Per-observation outputs (overview.xml, candidates.singlepulse) are
files a human reads one at a time; a survey needs the union queryable
— "every candidate above S/N 9 across all beams at DM 56±1", "which
observations produced nothing" (the GSP pipeline's candidate database,
arXiv:2110.12749, is the model). One sqlite file per campaign holds:

- ``observations`` — one row per ingested job: input path, header
  provenance (source, tstart, tsamp, nchans, nsamps), ingest time.
- ``candidates`` — one row per candidate with ``kind`` in
  ``('periodicity', 'single_pulse')``; periodicity rows carry
  period/acc/harmonic columns, single-pulse rows carry
  time/width/members columns, both share dm/snr — so survey-wide
  queries (top-N by S/N, DM histograms) need no UNION.

Ingest is idempotent per job (delete + reinsert under one
transaction), so re-running ``campaign ingest`` after adding jobs or
re-processing is safe. Writes from concurrent workers serialise on
sqlite's own locking (WAL where the filesystem supports it, plus a
generous busy timeout).
"""

from __future__ import annotations

import os
import sqlite3
import time

from ..obs import get_logger
from ..resilience import DB_RETRY, faults

log = get_logger("campaign.db")

DB_FILENAME = "candidates.sqlite"

_SCHEMA = """
CREATE TABLE IF NOT EXISTS observations (
    job_id       TEXT PRIMARY KEY,
    input        TEXT,
    source_name  TEXT,
    tstart       REAL,
    tsamp        REAL,
    nchans       INTEGER,
    nsamps       INTEGER,
    ingested_unix REAL
);
CREATE TABLE IF NOT EXISTS candidates (
    id        INTEGER PRIMARY KEY,
    job_id    TEXT NOT NULL REFERENCES observations(job_id),
    kind      TEXT NOT NULL CHECK (kind IN ('periodicity', 'single_pulse')),
    dm        REAL,
    snr       REAL,
    -- periodicity columns
    period    REAL,
    opt_period REAL,
    acc       REAL,
    nh        INTEGER,
    folded_snr REAL,
    -- single-pulse columns
    time_s    REAL,
    sample    INTEGER,
    width     INTEGER,
    members   INTEGER
);
CREATE INDEX IF NOT EXISTS idx_cand_snr ON candidates (kind, snr DESC);
CREATE INDEX IF NOT EXISTS idx_cand_job ON candidates (job_id);
CREATE INDEX IF NOT EXISTS idx_cand_dm ON candidates (dm);
"""


class CandidateDB:
    """The campaign's sqlite candidate store."""

    def __init__(self, path: str, busy_timeout_ms: int = 30000) -> None:
        self.path = path
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        self._conn = sqlite3.connect(
            path, timeout=max(0.001, busy_timeout_ms / 1000.0)
        )
        self._conn.row_factory = sqlite3.Row
        try:
            self._conn.execute("PRAGMA journal_mode=WAL")
        except sqlite3.OperationalError:
            pass  # WAL unsupported on some shared filesystems
        # first line of defence against concurrent writers; the
        # resilience DB_RETRY wrapped around every transaction is the
        # second (sqlite can still surface `database is locked` when a
        # writer starves the handle past this timeout). Tests shrink it
        # to force real two-process contention through the retry path.
        self._conn.execute(f"PRAGMA busy_timeout={int(busy_timeout_ms)}")
        self._conn.executescript(_SCHEMA)
        self._conn.commit()

    def close(self) -> None:
        self._conn.close()

    def __enter__(self) -> "CandidateDB":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # --- ingest -------------------------------------------------------
    def ingest_job(self, job_id: str, job_dir: str, input_path: str = "") -> dict:
        """Ingest one completed job's outputs (idempotent: any prior
        rows for ``job_id`` are replaced in the same transaction).
        Returns counts of ingested rows per kind."""
        from ..tools.parsers import OverviewFile

        xml_path = os.path.join(job_dir, "overview.xml")
        ov = OverviewFile(xml_path)
        hdr = ov.header
        counts = {"periodicity": 0, "single_pulse": 0}
        rows: list[tuple] = []
        for c in ov.candidates:
            rows.append(
                (
                    job_id, "periodicity", float(c["dm"]), float(c["snr"]),
                    float(c["period"]), float(c["opt_period"]),
                    float(c["acc"]), int(c["nh"]), float(c["folded_snr"]),
                    None, None, None, None,
                )
            )
            counts["periodicity"] += 1
        for c in ov.sp_candidates:
            rows.append(
                (
                    job_id, "single_pulse", float(c["dm"]), float(c["snr"]),
                    None, None, None, None, None,
                    float(c["time_s"]), int(c["sample"]), int(c["width"]),
                    int(c["members"]),
                )
            )
            counts["single_pulse"] += 1
        ingested_unix = time.time()

        def _ingest_txn():
            faults.fire("db.ingest", context=job_id)
            with self._conn:  # one transaction: delete + reinsert
                self._conn.execute(
                    "DELETE FROM candidates WHERE job_id = ?", (job_id,)
                )
                self._conn.execute(
                    "INSERT OR REPLACE INTO observations VALUES "
                    "(?,?,?,?,?,?,?,?)",
                    (
                        job_id,
                        input_path or hdr.get("rawdatafile", ""),
                        hdr.get("source_name", ""),
                        float(hdr.get("tstart", 0) or 0),
                        float(hdr.get("tsamp", 0) or 0),
                        int(float(hdr.get("nchans", 0) or 0)),
                        int(float(hdr.get("nsamples", 0) or 0)),
                        ingested_unix,
                    ),
                )
                self._conn.executemany(
                    "INSERT INTO candidates (job_id, kind, dm, snr, "
                    "period, opt_period, acc, nh, folded_snr, time_s, "
                    "sample, width, members) VALUES "
                    "(?,?,?,?,?,?,?,?,?,?,?,?,?)",
                    rows,
                )

        # WAL + busy_timeout serialise most contention, but two racing
        # ingesters can still surface `database is locked` (e.g. a
        # checkpoint starving the write lock past the timeout); the
        # transaction is idempotent, so the shared bounded-backoff
        # policy retries it whole
        DB_RETRY.call(_ingest_txn, site="db.ingest", context=job_id)
        log.info(
            "ingested %s: %d periodicity + %d single-pulse candidates",
            job_id, counts["periodicity"], counts["single_pulse"],
        )
        return counts

    # --- queries ------------------------------------------------------
    def _query(self, q: str, args=()) -> list[dict]:
        """Read path under the same busy/locked retry as ingest (a
        reader can see SQLITE_BUSY during a WAL checkpoint)."""
        return DB_RETRY.call(
            lambda: [dict(r) for r in self._conn.execute(q, args)],
            site="db.query",
        )

    def top_candidates(
        self, kind: str | None = None, limit: int = 20
    ) -> list[dict]:
        q = "SELECT c.*, o.source_name FROM candidates c JOIN observations o ON o.job_id = c.job_id"
        args: list = []
        if kind:
            q += " WHERE c.kind = ?"
            args.append(kind)
        q += " ORDER BY c.snr DESC LIMIT ?"
        args.append(int(limit))
        return self._query(q, args)

    def counts(self) -> dict:
        obs = self._query("SELECT COUNT(*) AS n FROM observations")
        by_kind = {
            r["kind"]: r["n"]
            for r in self._query(
                "SELECT kind, COUNT(*) AS n FROM candidates GROUP BY kind"
            )
        }
        return {"observations": obs[0]["n"], "candidates": by_kind}

    def candidates_for(self, job_id: str) -> list[dict]:
        return self._query(
            "SELECT * FROM candidates WHERE job_id = ? ORDER BY snr DESC",
            (job_id,),
        )
