"""Survey-level candidate database (stdlib sqlite).

Per-observation outputs (overview.xml, candidates.singlepulse) are
files a human reads one at a time; a survey needs the union queryable
— "every candidate above S/N 9 across all beams at DM 56±1", "which
observations produced nothing" (the GSP pipeline's candidate database,
arXiv:2110.12749, is the model). One sqlite file per campaign holds:

- ``observations`` — one row per ingested job: input path, header
  provenance (source, tstart, tsamp, nchans, nsamps, beam, sky
  position), ingest time.
- ``candidates`` — one row per candidate with ``kind`` in
  ``('periodicity', 'single_pulse')``; periodicity rows carry
  period/acc/harmonic columns, single-pulse rows carry
  time/width/members columns, both share dm/snr — so survey-wide
  queries (top-N by S/N, DM histograms) need no UNION.
- the ``sift_*`` tables — the sifted survey product written by
  ``peasoup-sift`` (peasoup_tpu/sift/): the deduplicated catalogue,
  known-pulsar cross-matches, and repeat single-pulse (RRAT) sources.

**Schema versioning**: the file carries ``PRAGMA user_version``
(:data:`SCHEMA_VERSION`). Opening an older database migrates it in
place through :data:`MIGRATIONS` (campaign DBs written before
versioning existed read as version 1); opening a *newer* database than
this code understands raises :class:`SchemaVersionError` loudly —
never silently misread a future schema.

Ingest is idempotent per job (delete + reinsert under one
transaction), so re-running ``campaign ingest`` after adding jobs or
re-processing is safe; the sift ingest replaces the whole sifted
product the same way (latest run wins). Writes from concurrent workers
serialise on sqlite's own locking (WAL where the filesystem supports
it, plus a generous busy timeout).
"""

from __future__ import annotations

import json
import os
import sqlite3
import time

from ..obs import get_logger
from ..resilience import DB_RETRY, faults

log = get_logger("campaign.db")

DB_FILENAME = "candidates.sqlite"

#: Current on-disk schema version (PRAGMA user_version).
#: 1 — the PR 4 campaign schema (observations + candidates), written
#:     before explicit versioning; detected by table presence.
#: 2 — observations gain beam/src_raj/src_dej provenance and the
#:     ``sift_*`` tables arrive (the peasoup-sift product).
#: 3 — observations gain the ``tenant`` stamp (multi-tenant usage
#:     accounting + per-tenant sift slices).
#: 4 — sift_candidates gain ``score``/``score_tier``/``model_fp``
#:     (the peasoup-rank calibrated scorer's output + provenance).
SCHEMA_VERSION = 4


class SchemaVersionError(RuntimeError):
    """The database was written by a newer peasoup_tpu than this one."""


# version-1 base tables (unchanged since PR 4; legacy DBs have exactly
# these and migrate forward from here)
_SCHEMA_V1 = """
CREATE TABLE IF NOT EXISTS observations (
    job_id       TEXT PRIMARY KEY,
    input        TEXT,
    source_name  TEXT,
    tstart       REAL,
    tsamp        REAL,
    nchans       INTEGER,
    nsamps       INTEGER,
    ingested_unix REAL
);
CREATE TABLE IF NOT EXISTS candidates (
    id        INTEGER PRIMARY KEY,
    job_id    TEXT NOT NULL REFERENCES observations(job_id),
    kind      TEXT NOT NULL CHECK (kind IN ('periodicity', 'single_pulse')),
    dm        REAL,
    snr       REAL,
    -- periodicity columns
    period    REAL,
    opt_period REAL,
    acc       REAL,
    nh        INTEGER,
    folded_snr REAL,
    -- single-pulse columns
    time_s    REAL,
    sample    INTEGER,
    width     INTEGER,
    members   INTEGER
);
CREATE INDEX IF NOT EXISTS idx_cand_snr ON candidates (kind, snr DESC);
CREATE INDEX IF NOT EXISTS idx_cand_job ON candidates (job_id);
CREATE INDEX IF NOT EXISTS idx_cand_dm ON candidates (dm);
"""

# columns added to observations in version 2 (multi-beam coincidence
# and sky-position association need beam + pointing provenance)
_OBS_V2_COLUMNS = (
    ("beam", "INTEGER"),
    ("src_raj", "REAL"),
    ("src_dej", "REAL"),
)

# version-2 sift tables: the peasoup-sift product. One sifted run at a
# time (latest wins — the sift ingest replaces these wholesale), so
# downstream readers never see a half-old half-new catalogue.
_SCHEMA_SIFT = """
CREATE TABLE IF NOT EXISTS sift_runs (
    run_id        TEXT PRIMARY KEY,
    created_unix  REAL,
    config        TEXT,
    n_folded      INTEGER,
    n_catalogue   INTEGER,
    n_known       INTEGER,
    n_rfi         INTEGER,
    n_sp_sources  INTEGER
);
CREATE TABLE IF NOT EXISTS sift_candidates (
    id          INTEGER PRIMARY KEY,
    run_id      TEXT NOT NULL REFERENCES sift_runs(run_id),
    kind        TEXT NOT NULL CHECK (kind IN ('periodicity', 'single_pulse')),
    label       TEXT NOT NULL CHECK (label IN ('candidate', 'known', 'rfi')),
    tier        INTEGER NOT NULL,
    dm          REAL,
    snr         REAL,
    period      REAL,
    folded_snr  REAL,
    opt_period  REAL,
    known_source TEXT,
    harmonic    TEXT,
    n_obs       INTEGER,
    members     INTEGER,
    job_ids     TEXT,
    fold_json   TEXT
);
CREATE INDEX IF NOT EXISTS idx_sift_cand ON sift_candidates (label, tier, snr DESC);
CREATE TABLE IF NOT EXISTS sift_known_matches (
    id             INTEGER PRIMARY KEY,
    run_id         TEXT NOT NULL REFERENCES sift_runs(run_id),
    candidate_id   INTEGER REFERENCES candidates(id),
    job_id         TEXT,
    psr            TEXT,
    psr_period     REAL,
    psr_dm         REAL,
    harmonic       TEXT,
    period_frac_err REAL,
    dm_err         REAL
);
CREATE TABLE IF NOT EXISTS sift_sp_sources (
    id                INTEGER PRIMARY KEY,
    run_id            TEXT NOT NULL REFERENCES sift_runs(run_id),
    dm                REAL,
    n_obs             INTEGER,
    n_pulses          INTEGER,
    best_snr          REAL,
    period_s          REAL,
    period_frac_resid REAL,
    job_ids           TEXT,
    toas_s            TEXT
);
"""

_SIFT_TABLES = (
    "sift_candidates", "sift_known_matches", "sift_sp_sources",
    "sift_runs",
)


def _exec_script(conn: sqlite3.Connection, script: str) -> None:
    """Run a multi-statement DDL script with plain ``execute`` calls:
    ``executescript`` would implicitly COMMIT the caller's migration
    transaction (sqlite3 legacy transaction control), and these scripts
    carry no embedded semicolons."""
    for stmt in script.split(";"):
        if stmt.strip():
            conn.execute(stmt)


def _migrate_1_to_2(conn: sqlite3.Connection) -> None:
    """v1 -> v2: beam/sky provenance columns + the sift tables."""
    existing = {
        r[1] for r in conn.execute("PRAGMA table_info(observations)")
    }
    for col, typ in _OBS_V2_COLUMNS:
        if col not in existing:
            conn.execute(
                f"ALTER TABLE observations ADD COLUMN {col} {typ}"
            )
    _exec_script(conn, _SCHEMA_SIFT)


# column added to observations in version 3: the tenant stamp
_OBS_V3_COLUMNS = (("tenant", "TEXT"),)


def _migrate_2_to_3(conn: sqlite3.Connection) -> None:
    """v2 -> v3: the observations.tenant stamp."""
    existing = {
        r[1] for r in conn.execute("PRAGMA table_info(observations)")
    }
    for col, typ in _OBS_V3_COLUMNS:
        if col not in existing:
            conn.execute(
                f"ALTER TABLE observations ADD COLUMN {col} {typ}"
            )


# columns added to sift_candidates in version 4: the rank scorer's
# calibrated probability, triage tier, and the fingerprint of the
# model artifact that produced them
_SIFT_V4_COLUMNS = (
    ("score", "REAL"),
    ("score_tier", "INTEGER"),
    ("model_fp", "TEXT"),
)


def _migrate_3_to_4(conn: sqlite3.Connection) -> None:
    """v3 -> v4: ranking columns on sift_candidates."""
    existing = {
        r[1] for r in conn.execute("PRAGMA table_info(sift_candidates)")
    }
    for col, typ in _SIFT_V4_COLUMNS:
        if col not in existing:
            conn.execute(
                f"ALTER TABLE sift_candidates ADD COLUMN {col} {typ}"
            )


#: in-place upgrades, keyed by FROM-version; applied in sequence until
#: the file reads :data:`SCHEMA_VERSION`
MIGRATIONS = {1: _migrate_1_to_2, 2: _migrate_2_to_3, 3: _migrate_3_to_4}


def _fnum(v, cast=float, default=None):
    """Header values arrive as strings from overview.xml; coerce with a
    default rather than failing ingest on a missing/blank field."""
    try:
        return cast(float(v))
    except (TypeError, ValueError):
        return default


class CandidateDB:
    """The campaign's sqlite candidate store."""

    def __init__(self, path: str, busy_timeout_ms: int = 30000) -> None:
        self.path = path
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        self._conn = sqlite3.connect(
            path, timeout=max(0.001, busy_timeout_ms / 1000.0)
        )
        self._conn.row_factory = sqlite3.Row
        try:
            self._conn.execute("PRAGMA journal_mode=WAL")
        except sqlite3.OperationalError:
            pass  # WAL unsupported on some shared filesystems
        # first line of defence against concurrent writers; the
        # resilience DB_RETRY wrapped around every transaction is the
        # second (sqlite can still surface `database is locked` when a
        # writer starves the handle past this timeout). Tests shrink it
        # to force real two-process contention through the retry path.
        self._conn.execute(f"PRAGMA busy_timeout={int(busy_timeout_ms)}")
        # open = migrate: racing workers serialise on BEGIN IMMEDIATE
        # and the loser finds the work already done
        DB_RETRY.call(self._migrate, site="db.migrate", context=path)

    # --- schema versioning -------------------------------------------
    def schema_version(self) -> int:
        v = int(self._conn.execute("PRAGMA user_version").fetchone()[0])
        if v == 0:
            has_tables = self._conn.execute(
                "SELECT name FROM sqlite_master WHERE type='table' "
                "AND name='candidates'"
            ).fetchone()
            if has_tables:
                return 1  # pre-versioning campaign DB (PR 4 era)
        return v

    def _migrate(self) -> None:
        v = self.schema_version()
        if v > SCHEMA_VERSION:
            raise SchemaVersionError(
                f"{self.path}: database schema version {v} is newer "
                f"than this peasoup_tpu (supports <= {SCHEMA_VERSION}); "
                "upgrade the software, do not let it touch this file"
            )
        if v == SCHEMA_VERSION:
            return
        # one writer migrates; BEGIN IMMEDIATE takes the write lock up
        # front so a racing opener blocks (busy timeout) instead of
        # both running the ALTERs
        self._conn.execute("BEGIN IMMEDIATE")
        try:
            v = self.schema_version()  # re-check under the lock
            if v > SCHEMA_VERSION:
                raise SchemaVersionError(
                    f"{self.path}: schema version {v} from the future"
                )
            if v == 0:
                _exec_script(self._conn, _SCHEMA_V1)
                _migrate_1_to_2(self._conn)
                _migrate_2_to_3(self._conn)
                _migrate_3_to_4(self._conn)
            else:
                for step in range(v, SCHEMA_VERSION):
                    MIGRATIONS[step](self._conn)
                    log.info(
                        "migrated %s: schema v%d -> v%d",
                        self.path, step, step + 1,
                    )
            self._conn.execute(f"PRAGMA user_version = {SCHEMA_VERSION}")
        except BaseException:
            self._conn.execute("ROLLBACK")
            raise
        else:
            self._conn.execute("COMMIT")

    def close(self) -> None:
        self._conn.close()

    def __enter__(self) -> "CandidateDB":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # --- ingest -------------------------------------------------------
    def ingest_job(
        self,
        job_id: str,
        job_dir: str,
        input_path: str = "",
        tenant: str = "",
    ) -> dict:
        """Ingest one completed job's outputs (idempotent: any prior
        rows for ``job_id`` are replaced in the same transaction).
        Returns counts of ingested rows per kind."""
        from ..tools.parsers import OverviewFile

        xml_path = os.path.join(job_dir, "overview.xml")
        ov = OverviewFile(xml_path)
        hdr = ov.header
        counts = {"periodicity": 0, "single_pulse": 0}
        rows: list[tuple] = []
        for c in ov.candidates:
            rows.append(
                (
                    job_id, "periodicity", float(c["dm"]), float(c["snr"]),
                    float(c["period"]), float(c["opt_period"]),
                    float(c["acc"]), int(c["nh"]), float(c["folded_snr"]),
                    None, None, None, None,
                )
            )
            counts["periodicity"] += 1
        for c in ov.sp_candidates:
            rows.append(
                (
                    job_id, "single_pulse", float(c["dm"]), float(c["snr"]),
                    None, None, None, None, None,
                    float(c["time_s"]), int(c["sample"]), int(c["width"]),
                    int(c["members"]),
                )
            )
            counts["single_pulse"] += 1
        ingested_unix = time.time()

        def _ingest_txn():
            faults.fire("db.ingest", context=job_id)
            with self._conn:  # one transaction: delete + reinsert
                self._conn.execute(
                    "DELETE FROM candidates WHERE job_id = ?", (job_id,)
                )
                self._conn.execute(
                    "INSERT OR REPLACE INTO observations (job_id, "
                    "input, source_name, tstart, tsamp, nchans, nsamps, "
                    "ingested_unix, beam, src_raj, src_dej, tenant) "
                    "VALUES (?,?,?,?,?,?,?,?,?,?,?,?)",
                    (
                        job_id,
                        input_path or hdr.get("rawdatafile", ""),
                        hdr.get("source_name", ""),
                        float(hdr.get("tstart", 0) or 0),
                        float(hdr.get("tsamp", 0) or 0),
                        int(float(hdr.get("nchans", 0) or 0)),
                        int(float(hdr.get("nsamples", 0) or 0)),
                        ingested_unix,
                        _fnum(hdr.get("ibeam"), int, 0),
                        _fnum(hdr.get("src_raj"), float, 0.0),
                        _fnum(hdr.get("src_dej"), float, 0.0),
                        tenant or "",
                    ),
                )
                self._conn.executemany(
                    "INSERT INTO candidates (job_id, kind, dm, snr, "
                    "period, opt_period, acc, nh, folded_snr, time_s, "
                    "sample, width, members) VALUES "
                    "(?,?,?,?,?,?,?,?,?,?,?,?,?)",
                    rows,
                )

        # WAL + busy_timeout serialise most contention, but two racing
        # ingesters can still surface `database is locked` (e.g. a
        # checkpoint starving the write lock past the timeout); the
        # transaction is idempotent, so the shared bounded-backoff
        # policy retries it whole
        DB_RETRY.call(_ingest_txn, site="db.ingest", context=job_id)
        log.info(
            "ingested %s: %d periodicity + %d single-pulse candidates",
            job_id, counts["periodicity"], counts["single_pulse"],
        )
        return counts

    # --- queries ------------------------------------------------------
    def _query(self, q: str, args=()) -> list[dict]:
        """Read path under the same busy/locked retry as ingest (a
        reader can see SQLITE_BUSY during a WAL checkpoint)."""
        return DB_RETRY.call(
            lambda: [dict(r) for r in self._conn.execute(q, args)],
            site="db.query",
        )

    def top_candidates(
        self, kind: str | None = None, limit: int = 20
    ) -> list[dict]:
        q = "SELECT c.*, o.source_name FROM candidates c JOIN observations o ON o.job_id = c.job_id"
        args: list = []
        if kind:
            q += " WHERE c.kind = ?"
            args.append(kind)
        q += " ORDER BY c.snr DESC LIMIT ?"
        args.append(int(limit))
        return self._query(q, args)

    def counts(self) -> dict:
        obs = self._query("SELECT COUNT(*) AS n FROM observations")
        by_kind = {
            r["kind"]: r["n"]
            for r in self._query(
                "SELECT kind, COUNT(*) AS n FROM candidates GROUP BY kind"
            )
        }
        return {"observations": obs[0]["n"], "candidates": by_kind}

    def candidates_for(self, job_id: str) -> list[dict]:
        return self._query(
            "SELECT * FROM candidates WHERE job_id = ? ORDER BY snr DESC",
            (job_id,),
        )

    def observations(self) -> list[dict]:
        return self._query(
            "SELECT * FROM observations ORDER BY tstart, job_id"
        )

    def max_observation_rowid(self) -> int:
        """High-water mark over ingested observations — the
        incremental-sift watermark (``peasoup-sift run --incremental``
        re-sifts only when this moved past the last run's recorded
        value). A re-ingested job bumps its rowid (INSERT OR REPLACE),
        which correctly reads as new data."""
        rows = self._query(
            "SELECT COALESCE(MAX(rowid), 0) AS hi FROM observations"
        )
        return int(rows[0]["hi"]) if rows else 0

    def all_candidates(self, kind: str | None = None) -> list[dict]:
        """Every candidate joined with its observation's provenance —
        the sift passes consume this (cross-observation association
        needs tstart/beam/position next to each detection)."""
        q = (
            "SELECT c.*, o.source_name, o.tstart AS obs_tstart, "
            "o.tsamp AS obs_tsamp, o.input AS obs_input, o.beam, "
            "o.src_raj, o.src_dej, o.nsamps AS obs_nsamps, o.tenant "
            "FROM candidates c JOIN observations o "
            "ON o.job_id = c.job_id"
        )
        args: list = []
        if kind:
            q += " WHERE c.kind = ?"
            args.append(kind)
        q += " ORDER BY c.snr DESC, c.id"
        return self._query(q, args)

    # --- the sifted product ------------------------------------------
    def ingest_sift_run(
        self,
        run_id: str,
        config: dict,
        catalogue: list[dict],
        known_matches: list[dict],
        sp_sources: list[dict],
    ) -> dict:
        """Replace the sifted survey product with one run's output in a
        single transaction (idempotent: latest run wins wholesale, so a
        reader never joins half-old tables). Returns the tally row."""
        tally = {
            "n_folded": int(config.get("n_folded", 0)),
            "n_catalogue": len(catalogue),
            "n_known": sum(1 for c in catalogue if c["label"] == "known"),
            "n_rfi": sum(1 for c in catalogue if c["label"] == "rfi"),
            "n_sp_sources": len(sp_sources),
        }

        created_unix = time.time()

        def _txn():
            faults.fire("db.ingest", context=f"sift:{run_id}")
            with self._conn:
                for t in _SIFT_TABLES:
                    self._conn.execute(f"DELETE FROM {t}")
                self._conn.execute(
                    "INSERT INTO sift_runs (run_id, created_unix, "
                    "config, n_folded, n_catalogue, n_known, n_rfi, "
                    "n_sp_sources) VALUES (?,?,?,?,?,?,?,?)",
                    (
                        run_id, created_unix,
                        json.dumps(config, sort_keys=True),
                        tally["n_folded"], tally["n_catalogue"],
                        tally["n_known"], tally["n_rfi"],
                        tally["n_sp_sources"],
                    ),
                )
                self._conn.executemany(
                    "INSERT INTO sift_candidates (run_id, kind, label, "
                    "tier, dm, snr, period, folded_snr, opt_period, "
                    "known_source, harmonic, n_obs, members, job_ids, "
                    "fold_json, score, score_tier, model_fp) VALUES "
                    "(?,?,?,?,?,?,?,?,?,?,?,?,?,?,?,?,?,?)",
                    [
                        (
                            run_id, c["kind"], c["label"], int(c["tier"]),
                            c.get("dm"), c.get("snr"), c.get("period"),
                            c.get("folded_snr"), c.get("opt_period"),
                            c.get("known_source"), c.get("harmonic"),
                            int(c.get("n_obs", 1)),
                            int(c.get("members", 1)),
                            json.dumps(c.get("job_ids", [])),
                            json.dumps(c["fold"])
                            if c.get("fold") is not None else None,
                            c.get("score"),
                            int(c["score_tier"])
                            if c.get("score_tier") is not None else None,
                            c.get("model_fp"),
                        )
                        for c in catalogue
                    ],
                )
                self._conn.executemany(
                    "INSERT INTO sift_known_matches (run_id, "
                    "candidate_id, job_id, psr, psr_period, psr_dm, "
                    "harmonic, period_frac_err, dm_err) VALUES "
                    "(?,?,?,?,?,?,?,?,?)",
                    [
                        (
                            run_id, m.get("candidate_id"), m.get("job_id"),
                            m["psr"], m["psr_period"], m["psr_dm"],
                            m["harmonic"], m["period_frac_err"],
                            m["dm_err"],
                        )
                        for m in known_matches
                    ],
                )
                self._conn.executemany(
                    "INSERT INTO sift_sp_sources (run_id, dm, n_obs, "
                    "n_pulses, best_snr, period_s, period_frac_resid, "
                    "job_ids, toas_s) VALUES (?,?,?,?,?,?,?,?,?)",
                    [
                        (
                            run_id, s["dm"], int(s["n_obs"]),
                            int(s["n_pulses"]), s.get("best_snr"),
                            s.get("period_s"), s.get("period_frac_resid"),
                            json.dumps(s.get("job_ids", [])),
                            json.dumps(s.get("toas_s", [])),
                        )
                        for s in sp_sources
                    ],
                )

        DB_RETRY.call(_txn, site="db.ingest", context=f"sift:{run_id}")
        log.info(
            "sift run %s ingested: %d catalogue rows (%d known, %d "
            "rfi), %d single-pulse sources",
            run_id, tally["n_catalogue"], tally["n_known"],
            tally["n_rfi"], tally["n_sp_sources"],
        )
        return tally

    def latest_sift_run(self) -> dict | None:
        rows = self._query(
            "SELECT * FROM sift_runs ORDER BY created_unix DESC LIMIT 1"
        )
        return rows[0] if rows else None

    def sift_catalogue(
        self, label: str | None = None, limit: int | None = None
    ) -> list[dict]:
        q = "SELECT * FROM sift_candidates"
        args: list = []
        if label:
            q += " WHERE label = ?"
            args.append(label)
        q += " ORDER BY tier, snr DESC"
        if limit:
            q += " LIMIT ?"
            args.append(int(limit))
        return self._query(q, args)

    def update_sift_scores(self, scored: list[dict]) -> int:
        """Write a re-scoring pass back onto existing sift rows (the
        ``peasoup-rank score`` path; the sift service ingests scores
        inline). Rows need ``id``, ``score``, ``score_tier``,
        ``model_fp``."""

        def _txn():
            with self._conn:
                self._conn.executemany(
                    "UPDATE sift_candidates SET score = ?, "
                    "score_tier = ?, model_fp = ? WHERE id = ?",
                    [
                        (
                            s.get("score"), s.get("score_tier"),
                            s.get("model_fp"), s["id"],
                        )
                        for s in scored
                    ],
                )

        DB_RETRY.call(_txn, site="db.ingest", context="rank.score")
        return len(scored)

    def sift_known_matches(self) -> list[dict]:
        return self._query(
            "SELECT * FROM sift_known_matches ORDER BY psr, job_id"
        )

    def sift_sp_sources(self) -> list[dict]:
        return self._query(
            "SELECT * FROM sift_sp_sources ORDER BY n_pulses DESC, dm"
        )
