"""File-backed tenant registry + quota throttling for shared fleets.

A survey instrument is shared infrastructure: more than one programme
submits observations to the same campaign directory, and the fleet
must account for — and bound — what each consumes. Tenants are plain
JSON records under ``queue/tenants/<name>.json`` following the same
filesystem protocol as everything else in campaign/: creation is
``O_CREAT|O_EXCL`` (two operators racing to create the same tenant
collide harmlessly, first wins), updates are tmp + ``os.replace``
rewrites, and torn/mid-replace reads parse as absent.

A tenant's quota spec:

- ``max_queued`` — ceiling on non-terminal jobs (pending, backing
  off, throttled, running) the tenant may have in the queue at once;
  enforced at ADMISSION (campaign/ingest.py rejects, journaled).
- ``max_running`` — ceiling on simultaneously held claims; enforced
  at CLAIM time (over-quota jobs park in the derived ``throttled``
  state, rendered by the rollup/watch — never silently dropped).
- ``device_seconds`` / ``window_s`` — device-seconds budget per
  rolling window, measured from done records' ``duration_s``; an
  exhausted budget throttles like ``max_running`` and releases as
  the window slides.
- ``priority_max`` — priority-class ceiling: submissions above it
  are CLAMPED (and flagged in the submissions journal), so a tenant
  cannot out-rank the operator's urgent work by asking nicely.

Zero (or ``None`` for ``priority_max``) means unlimited. Enforcement
lives in :func:`throttle_map` — a pure scan over raw queue artifacts
(job docs, live claim docs, done records) so the queue can call it
without recursing into its own derived-state machinery.
"""

from __future__ import annotations

import hmac
import json
import os
import tempfile
import time
import uuid
from dataclasses import dataclass, field

from ..obs import get_logger

log = get_logger("campaign.tenants")

_TENANTS = "tenants"


def _atomic_write_json(path: str, doc: dict) -> None:
    d = os.path.dirname(path) or "."
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(doc, f, indent=2)
            f.write("\n")
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def _read_json(path: str) -> dict | None:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None  # gone, mid-replace, or torn: treat as absent


def valid_tenant_name(name: str) -> bool:
    """Tenant names become file names and journal suffixes
    (``queue/alerts.<tenant>.jsonl``), so the charset is alnum plus
    ``-`` and ``_`` only, non-empty, bounded. Dots are deliberately
    excluded (unlike worker ids): a name must parse back unambiguously
    out of the dotted journal filename, and can never be a hidden
    file or a path dodge. The portal's ``/tenants/<name>`` route uses
    this same predicate — one validator for every door."""
    return (
        0 < len(name) <= 48
        and all(c.isalnum() or c in "-_" for c in name)
    )


@dataclass
class Tenant:
    """One tenant record. ``token`` is the bearer secret the portal's
    POST /submit authenticates against (compare via
    :meth:`TenantRegistry.by_token`, which is constant-time); the
    watch-folder ingester maps ``watch_dir`` drops to this tenant."""

    name: str
    token: str = ""
    max_queued: int = 0  # 0 = unlimited
    max_running: int = 0  # 0 = unlimited
    device_seconds: float = 0.0  # budget per window; 0 = unlimited
    window_s: float = 3600.0  # rolling budget window
    priority_max: int | None = None  # None = no ceiling
    watch_dir: str = ""
    created_unix: float = 0.0
    meta: dict = field(default_factory=dict)

    def to_doc(self) -> dict:
        return {
            "name": self.name,
            "token": self.token,
            "max_queued": int(self.max_queued),
            "max_running": int(self.max_running),
            "device_seconds": float(self.device_seconds),
            "window_s": float(self.window_s),
            "priority_max": (
                None if self.priority_max is None else int(self.priority_max)
            ),
            "watch_dir": self.watch_dir,
            "created_unix": self.created_unix,
            "meta": self.meta,
        }

    @classmethod
    def from_doc(cls, doc: dict) -> "Tenant":
        pm = doc.get("priority_max")
        return cls(
            name=doc["name"],
            token=str(doc.get("token") or ""),
            max_queued=int(doc.get("max_queued", 0)),
            max_running=int(doc.get("max_running", 0)),
            device_seconds=float(doc.get("device_seconds", 0.0)),
            window_s=float(doc.get("window_s", 3600.0)),
            priority_max=None if pm is None else int(pm),
            watch_dir=str(doc.get("watch_dir") or ""),
            created_unix=float(doc.get("created_unix", 0.0)),
            meta=doc.get("meta") or {},
        )

    def quota_doc(self) -> dict:
        """The quota spec alone (rollup/portal rendering)."""
        return {
            "max_queued": int(self.max_queued),
            "max_running": int(self.max_running),
            "device_seconds": float(self.device_seconds),
            "window_s": float(self.window_s),
            "priority_max": (
                None if self.priority_max is None else int(self.priority_max)
            ),
        }


class TenantRegistry:
    """The tenant records rooted at ``<root>/queue/tenants/``."""

    def __init__(self, root: str) -> None:
        self.root = os.path.abspath(root)
        self.dir = os.path.join(self.root, "queue", _TENANTS)

    def _path(self, name: str) -> str:
        if not valid_tenant_name(name):
            raise ValueError(f"invalid tenant name {name!r}")
        return os.path.join(self.dir, f"{name}.json")

    def create(self, tenant: Tenant) -> Tenant:
        """O_EXCL create: raises FileExistsError when the tenant
        already exists (first creator wins; update() to change it).
        Mints a bearer token when the record carries none."""
        path = self._path(tenant.name)
        os.makedirs(self.dir, exist_ok=True)
        tenant.created_unix = tenant.created_unix or time.time()
        if not tenant.token:
            tenant.token = uuid.uuid4().hex
        fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        with os.fdopen(fd, "w") as f:
            json.dump(tenant.to_doc(), f, indent=2)
            f.write("\n")
        log.info("tenant %s registered", tenant.name)
        return tenant

    def update(self, tenant: Tenant) -> None:
        """Atomic rewrite of an existing record (quota changes)."""
        _atomic_write_json(self._path(tenant.name), tenant.to_doc())

    def get(self, name: str) -> Tenant | None:
        if not valid_tenant_name(name):
            return None
        doc = _read_json(os.path.join(self.dir, f"{name}.json"))
        return Tenant.from_doc(doc) if doc and doc.get("name") else None

    def entries(self) -> list[Tenant]:
        try:
            names = sorted(os.listdir(self.dir))
        except FileNotFoundError:
            return []
        out = []
        for n in names:
            if not n.endswith(".json"):
                continue
            doc = _read_json(os.path.join(self.dir, n))
            if doc and doc.get("name"):
                out.append(Tenant.from_doc(doc))
        return out

    def by_token(self, token: str) -> Tenant | None:
        """Authenticate a bearer token. Constant-time comparison per
        candidate so the portal does not leak token prefixes through
        response timing."""
        if not token:
            return None
        for t in self.entries():
            if t.token and hmac.compare_digest(t.token, token):
                return t
        return None

    def remove(self, name: str) -> bool:
        try:
            os.unlink(self._path(name))
            return True
        except FileNotFoundError:
            return False


# --------------------------------------------------------------------------
# quota evaluation over raw queue artifacts
# --------------------------------------------------------------------------

def _scan_job_tenants(qdir: str) -> dict[str, str]:
    """job_id -> tenant for every job record carrying one."""
    jobs_dir = os.path.join(qdir, "jobs")
    out: dict[str, str] = {}
    try:
        names = os.listdir(jobs_dir)
    except FileNotFoundError:
        return out
    for n in names:
        if not n.endswith(".json"):
            continue
        doc = _read_json(os.path.join(jobs_dir, n))
        if doc and doc.get("tenant"):
            out[os.path.splitext(n)[0]] = str(doc["tenant"])
    return out


def running_counts(
    qdir: str, job_tenant: dict[str, str], now: float
) -> dict[str, int]:
    """Live (unexpired) claims per tenant. A claim file whose document
    is still unwritten (a claimant mid-``try_claim``) parses as absent
    and is skipped — which is exactly what claim-time revalidation
    needs: the claimant's OWN in-flight claim never counts against it.
    Two simultaneous unwritten racers can transiently over-admit by
    one; the steady state converges on the next claim attempt."""
    counts: dict[str, int] = {}
    cdir = os.path.join(qdir, "claims")
    try:
        names = os.listdir(cdir)
    except FileNotFoundError:
        return counts
    for n in names:
        if not n.endswith(".json"):
            continue
        doc = _read_json(os.path.join(cdir, n))
        if doc is None or float(doc.get("expires_unix", 0)) < now:
            continue
        tid = job_tenant.get(os.path.splitext(n)[0])
        if tid:
            counts[tid] = counts.get(tid, 0) + 1
    return counts


def window_device_seconds(qdir: str) -> list[tuple[str, float, float]]:
    """(tenant, finished_unix, duration_s) per tenant-stamped done
    record — the caller filters per tenant window (windows differ)."""
    ddir = os.path.join(qdir, "done")
    out: list[tuple[str, float, float]] = []
    try:
        names = os.listdir(ddir)
    except FileNotFoundError:
        return out
    for n in names:
        if not n.endswith(".json"):
            continue
        doc = _read_json(os.path.join(ddir, n))
        if not doc or not doc.get("tenant"):
            continue
        out.append((
            str(doc["tenant"]),
            float(doc.get("finished_unix") or 0.0),
            float(doc.get("duration_s") or 0.0),
        ))
    return out


def throttle_map(root: str, now: float | None = None) -> dict[str, dict]:
    """tenant -> throttle finding for every currently over-quota
    tenant: ``{"reason", "quota", "running"| "spent_device_s", ...}``.
    Pure scan of raw queue artifacts (never queue.state(), which
    derives ``throttled`` FROM this map). Empty when no tenant is
    registered or none is over quota."""
    now = time.time() if now is None else now
    reg = TenantRegistry(root)
    tenants = reg.entries()
    if not tenants:
        return {}
    qdir = os.path.join(os.path.abspath(root), "queue")
    job_tenant = _scan_job_tenants(qdir)
    running = running_counts(qdir, job_tenant, now)
    spent_raw = window_device_seconds(qdir)
    out: dict[str, dict] = {}
    for t in tenants:
        if t.max_running and running.get(t.name, 0) >= t.max_running:
            out[t.name] = {
                "reason": (
                    f"max_running reached "
                    f"({running.get(t.name, 0)}/{t.max_running})"
                ),
                "quota": "max_running",
                "running": running.get(t.name, 0),
                "limit": t.max_running,
            }
            continue
        if t.device_seconds > 0:
            lo = now - t.window_s
            spent = sum(
                dur for name, fin, dur in spent_raw
                if name == t.name and fin >= lo
            )
            if spent >= t.device_seconds:
                out[t.name] = {
                    "reason": (
                        f"device-seconds budget exhausted "
                        f"({spent:.1f}/{t.device_seconds:.0f}s in "
                        f"{t.window_s:.0f}s window)"
                    ),
                    "quota": "device_seconds",
                    "spent_device_s": round(spent, 3),
                    "limit": t.device_seconds,
                }
    return out


def queued_counts(root: str, queue=None) -> dict[str, int]:
    """Non-terminal jobs per tenant (admission-time ``max_queued``
    accounting): every tenant-stamped job record without a done or
    quarantine marker."""
    qdir = os.path.join(os.path.abspath(root), "queue")
    job_tenant = _scan_job_tenants(qdir)
    counts: dict[str, int] = {}
    for jid, tid in job_tenant.items():
        if os.path.exists(os.path.join(qdir, "done", f"{jid}.json")):
            continue
        if os.path.exists(os.path.join(qdir, "quarantine", f"{jid}.json")):
            continue
        counts[tid] = counts.get(tid, 0) + 1
    return counts
