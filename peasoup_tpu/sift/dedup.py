"""Campaign-level dedup + multi-beam coincidence vetoing.

Two sifting passes over the joined candidate set:

- **harmonic/DM dedup across observations** — the per-observation
  distillers already folded harmonics *within* one observation; a
  campaign re-detects the same source in many observations (and at
  different harmonics when the S/N ladder differs). Greedy
  association, strongest candidate first: anything harmonically
  related within a DM gate joins the leader's catalogue row, so the
  survey catalogue carries one row per sky source with its detection
  history.

- **multi-beam coincidence veto** — terrestrial RFI enters many beams
  at once, a real pulsar enters one (or a neighbouring few). The veto
  re-uses the framework's coincidence machinery
  (:func:`peasoup_tpu.ops.coincidence.coincidence_mask`, the op behind
  :func:`peasoup_tpu.parallel.coincidence.sharded_coincidence`) over a
  (beam, period-DM cell) S/N matrix built from the database: cells
  where ``beam_thresh`` or more distinct beams exceed the threshold
  are flagged RFI.
"""

from __future__ import annotations

import math

import numpy as np

from ..obs import get_logger
from .crossmatch import harmonic_identify

log = get_logger("sift.dedup")


def packed_position_deg(
    raj: float, dej: float
) -> tuple[float, float]:
    """Sigproc packed ``HHMMSS.s`` / ``DDMMSS.s`` header position ->
    ``(ra_deg, dec_deg)``."""
    sign = -1.0 if dej < 0 else 1.0
    a = abs(float(raj))
    hh = int(a // 10000)
    mm = int((a - hh * 10000) // 100)
    ss = a - hh * 10000 - mm * 100
    d = abs(float(dej))
    dd = int(d // 10000)
    dmm = int((d - dd * 10000) // 100)
    dss = d - dd * 10000 - dmm * 100
    return (
        (hh + mm / 60.0 + ss / 3600.0) * 15.0,
        sign * (dd + dmm / 60.0 + dss / 3600.0),
    )


def sky_separation_deg(
    ra1: float, dec1: float, ra2: float, dec2: float
) -> float:
    """Great-circle angular separation (haversine) in degrees."""
    r1, d1, r2, d2 = (
        math.radians(v) for v in (ra1, dec1, ra2, dec2)
    )
    s = (
        math.sin((d2 - d1) / 2.0) ** 2
        + math.cos(d1) * math.cos(d2)
        * math.sin((r2 - r1) / 2.0) ** 2
    )
    return math.degrees(2.0 * math.asin(min(1.0, math.sqrt(s))))


def _row_position_deg(c: dict) -> tuple[float, float] | None:
    """A row's sky position in degrees, or None when the observation
    recorded none (rows without positions are never position-gated)."""
    raj, dej = c.get("src_raj"), c.get("src_dej")
    if raj is None or dej is None:
        return None
    return packed_position_deg(float(raj), float(dej))


def position_gate_ok(a: dict, b: dict, pos_tol_deg: float) -> bool:
    """Whether two rows may associate under the sky-position gate: a
    disabled gate (``pos_tol_deg <= 0``) or a missing position on
    either side always passes; otherwise the great-circle separation
    must stay within tolerance — a harmonic coincidence between
    antipodal pointings is not one pulsar."""
    if pos_tol_deg <= 0:
        return True
    pa, pb = _row_position_deg(a), _row_position_deg(b)
    if pa is None or pb is None:
        return True
    return sky_separation_deg(*pa, *pb) <= pos_tol_deg


def dedup_candidates(
    cands: list[dict],
    *,
    max_harm: int = 8,
    period_tol: float = 2e-3,
    dm_tol: float = 2.0,
    pos_tol_deg: float = 0.0,
) -> list[dict]:
    """Associate harmonically-related candidates across observations.

    ``cands`` rows need ``id``, ``job_id``, ``period`` (the effective
    one — opt_period when folded), ``dm``, ``snr``, and optionally
    ``src_raj``/``src_dej`` (sigproc packed) for the sky-position gate
    (``pos_tol_deg > 0``: members beyond that separation from the
    leader never merge; rows without positions always pass). Returns
    one group dict per distinct source: ``leader`` (the highest-S/N
    member),
    ``members`` (every absorbed row, leader included), ``n_obs``
    (distinct observations), ``job_ids`` and, when the leader absorbed
    a non-fundamental detection, the member's ladder identity.
    """
    order = sorted(
        cands, key=lambda c: (-float(c.get("snr") or 0.0), c["id"])
    )
    claimed: set = set()
    groups: list[dict] = []
    for lead in order:
        if lead["id"] in claimed:
            continue
        claimed.add(lead["id"])
        members = [dict(lead, harmonic="1/1")]
        for other in order:
            if other["id"] in claimed:
                continue
            if abs(float(other["dm"]) - float(lead["dm"])) > dm_tol:
                continue
            if not position_gate_ok(lead, other, pos_tol_deg):
                continue
            rung = harmonic_identify(
                float(other["period"]), float(lead["period"]),
                max_harm=max_harm, tol=period_tol,
            )
            if rung is None:
                continue
            num, den, _ = rung
            claimed.add(other["id"])
            members.append(dict(other, harmonic=f"{num}/{den}"))
        job_ids = sorted({m["job_id"] for m in members})
        groups.append(
            {
                "leader": lead,
                "members": members,
                "n_obs": len(job_ids),
                "job_ids": job_ids,
            }
        )
    return groups


def _cell_key(
    period: float, dm: float, period_tol: float, dm_cell: float
) -> tuple[int, int]:
    """Quantise (period, DM) into a coincidence cell: log-period bins
    of width ~2*period_tol (two detections of one signal land within a
    bin or its neighbour; the veto is statistical, not exact), linear
    DM bins of dm_cell."""
    return (
        int(round(math.log(max(period, 1e-9)) / (2.0 * period_tol))),
        int(round(dm / max(dm_cell, 1e-6))),
    )


def multibeam_veto(
    cands: list[dict],
    *,
    snr_thresh: float = 6.0,
    beam_thresh: int = 4,
    period_tol: float = 2e-3,
    dm_cell: float = 2.0,
) -> set:
    """Candidate ids vetoed as multi-beam RFI.

    ``cands`` rows need ``id``, ``period``, ``dm``, ``snr`` and
    ``beam`` (observation provenance; rows with no beam recorded are
    never vetoed). Builds the (beam, cell) best-S/N matrix and keeps
    cells where :func:`coincidence_mask` says fewer than
    ``beam_thresh`` beams fired."""
    import jax.numpy as jnp

    from ..ops.coincidence import coincidence_mask

    beams = sorted(
        {int(c["beam"]) for c in cands if c.get("beam")}
    )
    if len(beams) < max(2, int(beam_thresh)):
        return set()  # veto needs enough distinct beams to vote
    beam_row = {b: i for i, b in enumerate(beams)}
    cells: dict[tuple[int, int], list[dict]] = {}
    for c in cands:
        if not c.get("beam"):
            continue
        key = _cell_key(
            float(c["period"]), float(c["dm"]), period_tol, dm_cell
        )
        cells.setdefault(key, []).append(c)
    if not cells:
        return set()
    keys = sorted(cells)
    mat = np.zeros((len(beams), len(keys)), dtype=np.float32)
    for j, key in enumerate(keys):
        for c in cells[key]:
            i = beam_row[int(c["beam"])]
            mat[i, j] = max(mat[i, j], float(c.get("snr") or 0.0))
    keep = np.asarray(
        coincidence_mask(
            jnp.asarray(mat),
            jnp.float32(snr_thresh),
            jnp.int32(beam_thresh),
        )
    )
    vetoed: set = set()
    for j, key in enumerate(keys):
        if keep[j] < 0.5:
            vetoed.update(c["id"] for c in cells[key])
    if vetoed:
        log.info(
            "multi-beam veto: %d candidates in %d cells flagged RFI "
            "(>= %d of %d beams above S/N %.1f)",
            len(vetoed), int((keep < 0.5).sum()), beam_thresh,
            len(beams), snr_thresh,
        )
    return vetoed
