"""The survey report: one JSON document, one self-contained HTML page.

``peasoup-sift report`` renders the sifted product (the ``sift_*``
tables) together with the campaign rollup into:

- a schema-validated JSON report (``sift/report.schema.json`` through
  the dependency-free :mod:`peasoup_tpu.obs.schema` validator) — the
  machine-readable artefact downstream tooling and the tests consume;
- a **self-contained** HTML page: zero external assets, the full
  report JSON inlined in a ``<script type="application/json">`` block
  (so the page IS the data product), tables rendered server-side and
  fold postage stamps drawn as inline SVG profiles.
"""

from __future__ import annotations

import html
import json
import os
import time

from ..campaign.db import CandidateDB

REPORT_SCHEMA = "peasoup_tpu.sift_report"
REPORT_VERSION = 1

_SCHEMA_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "report.schema.json"
)


def validate_report(doc: dict) -> None:
    """Validate a report document against the checked-in JSON Schema;
    raises ``obs.schema.SchemaError`` on drift."""
    from ..obs.schema import validate

    with open(_SCHEMA_PATH) as f:
        schema = json.load(f)
    validate(doc, schema)


def _tenant_jobs(db: CandidateDB, tenant: str) -> set:
    """Job ids of observations stamped with this tenant."""
    return {
        o["job_id"]
        for o in db.observations()
        if (o.get("tenant") or "") == tenant
    }


def build_report(
    db: CandidateDB,
    campaign_status: dict | None = None,
    *,
    limit: int = 50,
    tenant: str | None = None,
) -> dict:
    """Aggregate DB + rollup into the report document. With ``tenant``
    the catalogue/known/SP sections keep only rows touching that
    tenant's observations (the sifted product itself is campaign-wide;
    this is a view)."""
    run = db.latest_sift_run()
    if run is None:
        raise RuntimeError(
            "no sift run in the database — run `peasoup-sift run` first"
        )
    keep_jobs = _tenant_jobs(db, tenant) if tenant else None
    full = db.sift_catalogue()
    for row in full:
        row["job_ids"] = json.loads(row.get("job_ids") or "[]")
        fold = row.pop("fold_json", None)
        row["fold"] = json.loads(fold) if fold else None
    if keep_jobs is not None:
        full = [
            row for row in full
            if any(j in keep_jobs for j in row["job_ids"])
        ]
    catalogue = full[:limit] if limit else full
    known = db.sift_known_matches()
    if keep_jobs is not None:
        known = [m for m in known if m.get("job_id") in keep_jobs]
    by_psr: dict[str, dict] = {}
    for m in known:
        rec = by_psr.setdefault(
            m["psr"],
            {
                "psr": m["psr"], "psr_period": m["psr_period"],
                "psr_dm": m["psr_dm"], "n_matches": 0,
                "harmonics": [], "job_ids": [],
            },
        )
        rec["n_matches"] += 1
        if m["harmonic"] not in rec["harmonics"]:
            rec["harmonics"].append(m["harmonic"])
        if m["job_id"] not in rec["job_ids"]:
            rec["job_ids"].append(m["job_id"])
    sp_sources = db.sift_sp_sources()
    for s in sp_sources:
        s["job_ids"] = json.loads(s.get("job_ids") or "[]")
        s["toas_s"] = json.loads(s.get("toas_s") or "[]")
    if keep_jobs is not None:
        sp_sources = [
            s for s in sp_sources
            if any(j in keep_jobs for j in s["job_ids"])
        ]
    tiers: dict[str, int] = {}
    labels: dict[str, int] = {}
    score_tiers: dict[str, int] = {}
    model_fp = None
    for row in full:
        tiers[str(row["tier"])] = tiers.get(str(row["tier"]), 0) + 1
        labels[row["label"]] = labels.get(row["label"], 0) + 1
        st = row.get("score_tier")
        if st is not None:
            score_tiers[str(st)] = score_tiers.get(str(st), 0) + 1
            model_fp = model_fp or row.get("model_fp")
    counts = db.counts()
    n_observations = (
        len(keep_jobs)
        if keep_jobs is not None else counts["observations"]
    )
    return {
        "schema": REPORT_SCHEMA,
        "version": REPORT_VERSION,
        "generated_unix": time.time(),
        "run": {
            "run_id": run["run_id"],
            "created_unix": run["created_unix"],
            "config": json.loads(run.get("config") or "{}"),
            "n_folded": run["n_folded"],
            "n_catalogue": run["n_catalogue"],
            "n_known": run["n_known"],
            "n_rfi": run["n_rfi"],
            "n_sp_sources": run["n_sp_sources"],
        },
        "observations": n_observations,
        "candidates": counts["candidates"],
        "tiers": tiers,
        "labels": labels,
        "score_tiers": score_tiers,
        "model_fp": model_fp,
        "tenant": tenant or None,
        "known_sources": sorted(
            by_psr.values(), key=lambda r: -r["n_matches"]
        ),
        "catalogue": catalogue,
        "sp_sources": sp_sources,
        "campaign": campaign_status,
    }


# --------------------------------------------------------------------------
# HTML rendering (self-contained: no external assets)
# --------------------------------------------------------------------------

_CSS = """
body { font: 14px/1.45 system-ui, sans-serif; margin: 2em auto;
       max-width: 70em; color: #1a1a2e; }
h1, h2 { font-weight: 600; }
table { border-collapse: collapse; width: 100%; margin: 0.8em 0; }
th, td { text-align: left; padding: 0.3em 0.7em;
         border-bottom: 1px solid #ddd; white-space: nowrap; }
th { background: #f4f4f8; }
.tier1 { background: #e8f6e8; } .tier2 { background: #fdf7e2; }
.rfi   { color: #a33; } .known { color: #2563eb; font-weight: 600; }
.tally { display: inline-block; margin-right: 2em; }
.tally b { font-size: 1.6em; display: block; }
svg.prof { vertical-align: middle; }
"""


def _sparkline(values: list[float], w: int = 120, h: int = 24) -> str:
    """Inline SVG profile sparkline for a fold postage stamp."""
    if not values:
        return ""
    lo, hi = min(values), max(values)
    span = (hi - lo) or 1.0
    n = len(values)
    pts = " ".join(
        f"{i * w / max(1, n - 1):.1f},"
        f"{h - (v - lo) / span * (h - 2) - 1:.1f}"
        for i, v in enumerate(values)
    )
    return (
        f'<svg class="prof" width="{w}" height="{h}">'
        f'<polyline points="{pts}" fill="none" stroke="#2563eb" '
        f'stroke-width="1.2"/></svg>'
    )


def _fmt(v, nd=3):
    if v is None:
        return "–"
    if isinstance(v, float):
        return f"{v:.{nd}f}"
    return html.escape(str(v))


def render_html(doc: dict, bowtie_href: str | None = None) -> str:
    """The self-contained survey page. The full report JSON is inlined
    (``</`` escaped so a string can never close the script block) —
    saving the page saves the data. ``bowtie_href`` links the DM-time
    bowtie diagnostic SVG the CLI writes beside the report
    (tools/plotting.py render_bowtie_svg)."""
    run = doc["run"]
    parts = [
        "<!doctype html><html><head><meta charset='utf-8'>",
        f"<title>peasoup-sift survey report {run['run_id']}</title>",
        f"<style>{_CSS}</style></head><body>",
        f"<h1>Survey sifting report <code>{run['run_id']}</code></h1>",
        "<p>",
        f"generated {time.strftime('%Y-%m-%d %H:%M:%S UTC', time.gmtime(doc['generated_unix']))}"
        f" · {doc['observations']} observations"
        + (
            f" · tenant <code>{html.escape(doc['tenant'])}</code>"
            if doc.get("tenant") else ""
        ),
        "</p><div>",
    ]
    score_tiers = doc.get("score_tiers") or {}
    tallies = [
        ("catalogue rows", run["n_catalogue"]),
        ("known sources", run["n_known"]),
        ("RFI vetoed", run["n_rfi"]),
        ("repeat SP sources", run["n_sp_sources"]),
        ("candidates folded", run["n_folded"]),
    ]
    if score_tiers:
        tallies.append(("score tier 1", score_tiers.get("1", 0)))
    for label, n in tallies:
        parts.append(
            f"<span class='tally'><b>{n}</b>{label}</span>"
        )
    parts.append("</div><h2>Candidate catalogue</h2>")
    if doc.get("model_fp"):
        parts.append(
            f"<p>ranked by model <code>"
            f"{html.escape(doc['model_fp'])}</code> (score is the "
            "calibrated P(pulsar); s-tier 1 = review first)</p>"
        )
    parts.append("<table>")
    parts.append(
        "<tr><th>tier</th><th>label</th><th>score</th>"
        "<th>s-tier</th><th>P (s)</th><th>DM</th>"
        "<th>S/N</th><th>folded S/N</th><th>obs</th><th>members</th>"
        "<th>source</th><th>harm</th><th>profile</th></tr>"
    )
    for row in doc["catalogue"]:
        cls = []
        if row["tier"] == 1:
            cls.append("tier1")
        elif row["tier"] == 2:
            cls.append("tier2")
        if row["label"] == "rfi":
            cls.append("rfi")
        prof = (row.get("fold") or {}).get("prof") or []
        src = row.get("known_source")
        stier = row.get("score_tier")
        parts.append(
            f"<tr class='{' '.join(cls)}'>"
            f"<td>{row['tier']}</td><td>{row['label']}</td>"
            f"<td>{_fmt(row.get('score'), 3)}</td>"
            f"<td>{stier if stier is not None else '–'}</td>"
            f"<td>{_fmt(row['period'], 6)}</td>"
            f"<td>{_fmt(row['dm'], 2)}</td>"
            f"<td>{_fmt(row['snr'], 1)}</td>"
            f"<td>{_fmt(row['folded_snr'], 1)}</td>"
            f"<td>{row['n_obs']}</td><td>{row['members']}</td>"
            f"<td>{'<span class=known>' + html.escape(src) + '</span>' if src else '–'}</td>"
            f"<td>{_fmt(row.get('harmonic'))}</td>"
            f"<td>{_sparkline(prof)}</td></tr>"
        )
    parts.append("</table><h2>Known-source tally</h2><table>")
    parts.append(
        "<tr><th>pulsar</th><th>P0 (s)</th><th>DM</th>"
        "<th>matches</th><th>harmonics</th><th>observations</th></tr>"
    )
    for rec in doc["known_sources"]:
        parts.append(
            f"<tr><td class='known'>{html.escape(rec['psr'])}</td>"
            f"<td>{_fmt(rec['psr_period'], 6)}</td>"
            f"<td>{_fmt(rec['psr_dm'], 2)}</td>"
            f"<td>{rec['n_matches']}</td>"
            f"<td>{html.escape(', '.join(rec['harmonics']))}</td>"
            f"<td>{len(rec['job_ids'])}</td></tr>"
        )
    parts.append(
        "</table><h2>Repeat single-pulse sources</h2><table>"
    )
    parts.append(
        "<tr><th>DM</th><th>pulses</th><th>obs</th><th>best S/N</th>"
        "<th>inferred P (s)</th><th>phase resid</th></tr>"
    )
    for s in doc["sp_sources"]:
        parts.append(
            f"<tr><td>{_fmt(s['dm'], 2)}</td><td>{s['n_pulses']}</td>"
            f"<td>{s['n_obs']}</td><td>{_fmt(s['best_snr'], 1)}</td>"
            f"<td>{_fmt(s['period_s'], 6)}</td>"
            f"<td>{_fmt(s['period_frac_resid'], 4)}</td></tr>"
        )
    parts.append("</table>")
    if bowtie_href:
        parts.append(
            f"<p><a href='{html.escape(bowtie_href)}'>DM&#8211;time "
            "bowtie diagnostic</a> (all single-pulse detections, "
            "marker area &#8733; S/N)</p>"
        )
    camp = doc.get("campaign")
    if camp:
        q = camp.get("queue") or {}
        parts.append(
            "<h2>Campaign</h2><p>"
            f"{q.get('done', 0)}/{q.get('total', 0)} observations done, "
            f"{q.get('quarantined', 0)} quarantined · "
            f"{camp.get('candidates_total', 0)} raw candidates</p>"
        )
    payload = json.dumps(doc).replace("</", "<\\/")
    parts.append(
        f'<script type="application/json" id="sift-report">'
        f"{payload}</script>"
    )
    parts.append("</body></html>")
    return "".join(parts)


def write_report(
    doc: dict,
    json_path: str | None,
    html_path: str | None,
    bowtie_href: str | None = None,
) -> None:
    """Validate then write the requested artefacts (atomic rename)."""
    validate_report(doc)
    for path, payload in (
        (json_path, json.dumps(doc, indent=2) + "\n"),
        (html_path, render_html(doc, bowtie_href=bowtie_href)),
    ):
        if not path:
            continue
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(payload)
        os.replace(tmp, path)
