"""Known-pulsar cross-match with harmonic / sub-harmonic ladders.

A blind periodicity search detects a known pulsar not just at its
fundamental: harmonics (P0/n), sub-harmonics (m*P0) and rational
combinations (m/n * P0) all cross the threshold (the GSP pipeline's
known-source filter, arXiv:2110.12749). The match therefore walks a
rational ladder: a candidate period matching ``(num/den) * P0`` within
a fractional tolerance, at a compatible DM, is the catalogue source —
and the ladder identity (e.g. ``1/2`` = second harmonic) is recorded
so a survey team can see *how* the source aliased.

The checked-in convenience catalogue lives in
``peasoup_tpu/sift/data/known_pulsars.json``; a survey substitutes its
own psrcat export in the same shape.
"""

from __future__ import annotations

import json
import math
import os

from ..obs import get_logger

log = get_logger("sift.crossmatch")

CATALOGUE_SCHEMA = "peasoup_tpu.known_pulsars"

DEFAULT_CATALOGUE = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "data",
    "known_pulsars.json",
)


def load_catalogue(path: str | None = None) -> list[dict]:
    """Load + validate an ephemeris catalogue. A malformed catalogue
    fails loudly — silently matching against garbage would launder
    every real candidate into a 'known source'."""
    path = path or DEFAULT_CATALOGUE
    with open(path) as f:
        doc = json.load(f)
    if doc.get("schema") != CATALOGUE_SCHEMA:
        raise ValueError(
            f"{path}: not a {CATALOGUE_SCHEMA} catalogue "
            f"(schema={doc.get('schema')!r})"
        )
    pulsars = doc.get("pulsars")
    if not isinstance(pulsars, list) or not pulsars:
        raise ValueError(f"{path}: empty or missing 'pulsars' list")
    for p in pulsars:
        if (
            not isinstance(p.get("name"), str)
            or not isinstance(p.get("period_s"), (int, float))
            or not isinstance(p.get("dm"), (int, float))
            or p["period_s"] <= 0
        ):
            raise ValueError(
                f"{path}: bad catalogue entry {p!r} (want name, "
                "period_s > 0, dm)"
            )
    return pulsars


def harmonic_identify(
    p_cand: float,
    p_ref: float,
    *,
    max_harm: int = 16,
    tol: float = 2e-3,
) -> tuple[int, int, float] | None:
    """Identify ``p_cand ~= (num/den) * p_ref`` over the reduced
    rational ladder with num, den <= max_harm. Returns the
    lowest-error ``(num, den, frac_err)`` or None. ``den > 1`` rows
    are harmonics (the candidate spins faster than the reference),
    ``num > 1`` sub-harmonics."""
    if p_cand <= 0 or p_ref <= 0:
        return None
    best: tuple[int, int, float] | None = None
    r = p_cand / p_ref
    for den in range(1, max_harm + 1):
        # only the nearest numerators for this denominator can win
        for num in {
            max(1, math.floor(r * den)), math.ceil(r * den),
        }:
            if num > max_harm or math.gcd(num, den) != 1:
                continue
            pred = num / den
            err = abs(r - pred) / pred
            if err <= tol and (best is None or err < best[2]):
                best = (num, den, err)
    return best


def match_candidate(
    period: float,
    dm: float,
    catalogue: list[dict],
    *,
    max_harm: int = 16,
    period_tol: float = 2e-3,
    dm_tol: float = 2.0,
    dm_tol_frac: float = 0.05,
) -> dict | None:
    """Best catalogue match for one candidate, or None.

    The DM gate is ``max(dm_tol, dm_tol_frac * psr_dm)`` — absolute at
    low DM (trial grids are coarse there), fractional at high DM.
    Among DM-compatible pulsars the lowest-fractional-error rung wins.
    """
    best: dict | None = None
    for psr in catalogue:
        gate = max(float(dm_tol), float(dm_tol_frac) * float(psr["dm"]))
        dm_err = abs(float(dm) - float(psr["dm"]))
        if dm_err > gate:
            continue
        rung = harmonic_identify(
            float(period), float(psr["period_s"]),
            max_harm=max_harm, tol=period_tol,
        )
        if rung is None:
            continue
        num, den, err = rung
        if best is None or err < best["period_frac_err"]:
            best = {
                "psr": str(psr["name"]),
                "psr_period": float(psr["period_s"]),
                "psr_dm": float(psr["dm"]),
                "harmonic": f"{num}/{den}",
                "period_frac_err": float(err),
                "dm_err": float(dm_err),
            }
    return best
