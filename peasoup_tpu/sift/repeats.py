"""Repeat single-pulse association + RRAT period inference.

A rotating radio transient (RRAT) shows up as isolated single pulses
in many observations at one DM; the campaign database is the first
place those detections sit side by side. Two steps (the GSP/CRAFTS
repeat-source association, arXiv:2110.12749):

1. **association** — cluster single-pulse candidates across
   observations by DM proximity (and pointing, when positions are
   recorded): a chain-clustering sweep over the DM-sorted rows.

2. **period inference** — pulse arrival times of a rotator differ by
   integer multiples of the spin period, so the period is (close to)
   the greatest common divisor of the TOA differences. The classic
   trial-divisor GCD fit: take the smallest difference, try P =
   d_min/k for k = 1, 2, ..., keep the largest P whose worst phase
   residual over ALL differences stays inside the tolerance, then
   refine by least squares over the implied turn counts.
"""

from __future__ import annotations

import numpy as np

from ..obs import get_logger
from .dedup import position_gate_ok

log = get_logger("sift.repeats")

SECONDS_PER_DAY = 86400.0


def _split_by_position(
    group: list[dict], pos_tol_deg: float
) -> list[list[dict]]:
    """Partition one DM cluster by sky position: greedy anchoring —
    the first unassigned row seeds a source, every row passing the
    position gate against that anchor joins it (rows without recorded
    positions always pass). One DM coincidence across opposite sky
    poles is not one repeating source."""
    out: list[list[dict]] = []
    remaining = list(group)
    while remaining:
        anchor = remaining[0]
        sub = [
            r for r in remaining
            if position_gate_ok(anchor, r, pos_tol_deg)
        ]
        sub_ids = {id(r) for r in sub}
        remaining = [r for r in remaining if id(r) not in sub_ids]
        out.append(sub)
    return out


def associate_repeats(
    sp_cands: list[dict],
    *,
    dm_tol: float = 1.0,
    min_pulses: int = 3,
    min_obs: int = 2,
    pos_tol_deg: float = 0.0,
) -> list[list[dict]]:
    """Cluster single-pulse rows (needing ``dm``, ``job_id``) into
    repeat-source groups: DM chain clustering (adjacent-in-DM rows
    within ``dm_tol`` join one cluster), each cluster then split by
    sky position when ``pos_tol_deg > 0`` (rows need
    ``src_raj``/``src_dej``; missing positions never gate), kept when
    the cluster spans at least ``min_obs`` observations and
    ``min_pulses`` pulses."""
    rows = sorted(sp_cands, key=lambda c: float(c["dm"]))
    groups: list[list[dict]] = []
    cur: list[dict] = []
    for r in rows:
        if cur and float(r["dm"]) - float(cur[-1]["dm"]) > dm_tol:
            groups.append(cur)
            cur = []
        cur.append(r)
    if cur:
        groups.append(cur)
    if pos_tol_deg > 0:
        groups = [
            sub
            for g in groups
            for sub in _split_by_position(g, pos_tol_deg)
        ]
    return [
        g
        for g in groups
        if len(g) >= min_pulses
        and len({r["job_id"] for r in g}) >= min_obs
    ]


def toas_seconds(group: list[dict]) -> np.ndarray:
    """Pulse arrival times on a common clock (seconds since the
    earliest observation start): MJD ``obs_tstart`` plus the in-
    observation ``time_s``."""
    t0 = min(float(r["obs_tstart"]) for r in group)
    return np.sort(
        np.asarray(
            [
                (float(r["obs_tstart"]) - t0) * SECONDS_PER_DAY
                + float(r["time_s"])
                for r in group
            ],
            dtype=np.float64,
        )
    )


def infer_period(
    toas: np.ndarray,
    *,
    min_period: float = 0.05,
    max_harm: int = 1000,
    phase_tol: float = 0.02,
) -> tuple[float, float] | None:
    """TOA-difference GCD fit. Returns ``(period_s, worst_phase_resid)``
    or None when no period under the tolerance exists in the ladder.

    The candidate ladder divides the SMALLEST difference (the most
    constraining one); a trial survives when every difference sits
    within ``phase_tol`` turns of an integer multiple. The largest
    surviving period wins (k smallest) — sub-multiples of the true
    period always survive too, so the search stops at the first hit —
    and a least-squares refinement over the implied turn counts
    (``P = sum(n*d)/sum(n^2)``) polishes it.
    """
    toas = np.sort(np.asarray(toas, dtype=np.float64))
    diffs = np.diff(toas)
    diffs = diffs[diffs > 1e-6]
    if diffs.size == 0:
        return None
    base = float(diffs.min())
    for k in range(1, max_harm + 1):
        p = base / k
        if p < min_period:
            break
        turns = np.rint(diffs / p)
        if np.any(turns < 1):
            continue
        resid = np.abs(diffs / p - turns)
        if float(resid.max()) > phase_tol:
            continue
        # refine: best P for these integer turn counts
        p_ref = float(np.sum(turns * diffs) / np.sum(turns * turns))
        turns2 = np.rint(diffs / p_ref)
        resid2 = float(np.abs(diffs / p_ref - turns2).max())
        return p_ref, resid2
    return None


def repeat_sources(
    sp_cands: list[dict],
    *,
    dm_tol: float = 1.0,
    min_pulses: int = 3,
    min_obs: int = 2,
    min_period: float = 0.05,
    max_harm: int = 1000,
    phase_tol: float = 0.02,
    pos_tol_deg: float = 0.0,
) -> list[dict]:
    """The full pass: associate + infer. Returns one source dict per
    repeat group (period fields None when the GCD fit found nothing —
    a sporadic repeater is still worth a catalogue row)."""
    sources = []
    for group in associate_repeats(
        sp_cands, dm_tol=dm_tol, min_pulses=min_pulses,
        min_obs=min_obs, pos_tol_deg=pos_tol_deg,
    ):
        toas = toas_seconds(group)
        fit = infer_period(
            toas, min_period=min_period, max_harm=max_harm,
            phase_tol=phase_tol,
        )
        dms = np.asarray([float(r["dm"]) for r in group])
        snrs = np.asarray([float(r.get("snr") or 0.0) for r in group])
        sources.append(
            {
                "dm": float(np.median(dms)),
                "n_obs": len({r["job_id"] for r in group}),
                "n_pulses": len(group),
                "best_snr": float(snrs.max()),
                "period_s": None if fit is None else float(fit[0]),
                "period_frac_resid": (
                    None if fit is None else float(fit[1])
                ),
                "job_ids": sorted({r["job_id"] for r in group}),
                "toas_s": [round(float(t), 6) for t in toas],
                "member_ids": [r["id"] for r in group],
            }
        )
    log.info(
        "repeat single-pulse association: %d source(s) from %d "
        "detections", len(sources), len(sp_cands),
    )
    return sources
