"""peasoup-sift: survey-scale batched folding + candidate sifting.

The post-campaign layer that turns the campaign candidate database
(peasoup_tpu/campaign/db.py) into the product a survey team consumes
(the GSP/CRAFTS model, arXiv:2110.12749, with PulsarX-style bulk
folding, arXiv:2309.02544):

- :mod:`~peasoup_tpu.sift.fold` — shape-bucketed batched folding of
  every DB candidate across observations through ONE compiled program
  per bucket (:mod:`peasoup_tpu.ops.survey_fold`).
- :mod:`~peasoup_tpu.sift.crossmatch` — known-pulsar ephemeris
  cross-match with harmonic/sub-harmonic ladders.
- :mod:`~peasoup_tpu.sift.dedup` — campaign-level harmonic/DM dedup
  across observations + multi-beam coincidence vetoing.
- :mod:`~peasoup_tpu.sift.repeats` — repeat single-pulse association
  and RRAT period inference from TOA-difference GCD fitting.
- :mod:`~peasoup_tpu.sift.service` — the ``peasoup-sift run``
  orchestration writing the ``sift_*`` tables.
- :mod:`~peasoup_tpu.sift.report` — the self-contained HTML survey
  report rendered from DB + campaign rollup.
"""

from .service import SiftConfig, SiftRun

__all__ = ["SiftConfig", "SiftRun"]
